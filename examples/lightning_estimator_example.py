"""LightningEstimator example (reference analogue:
examples/spark/pytorch/pytorch_lightning_spark_mnist.py).

The estimator drives the LightningModule *protocol* — training_step,
configure_optimizers (any documented return shape), on_train_epoch_end —
inside horovod_tpu's distributed loop; no pytorch_lightning install is
needed, and a real LightningModule works unchanged.

Run: python examples/lightning_estimator_example.py
"""
import numpy as np
import pandas as pd
import torch

from horovod_tpu.spark import FilesystemStore, LightningEstimator


class LitRegressor(torch.nn.Module):
    """Any nn.Module with the protocol methods qualifies; subclassing
    pl.LightningModule (when installed) gives exactly this surface."""

    def __init__(self, n_in: int = 4):
        super().__init__()
        self.net = torch.nn.Sequential(
            torch.nn.Linear(n_in, 16), torch.nn.ReLU(),
            torch.nn.Linear(16, 1))

    def forward(self, x):
        return self.net(x)[..., 0]

    def training_step(self, batch, batch_idx):
        x, y = batch
        return {"loss": torch.nn.functional.mse_loss(self(x), y)}

    def configure_optimizers(self):
        opt = torch.optim.Adam(self.parameters(), lr=1e-2)
        sched = torch.optim.lr_scheduler.StepLR(opt, step_size=5,
                                                gamma=0.7)
        return {"optimizer": opt,
                "lr_scheduler": {"scheduler": sched, "interval": "epoch"}}


def main():
    rng = np.random.default_rng(0)
    x = rng.standard_normal((256, 4)).astype(np.float32)
    y = np.sin(x[:, 0]) + 0.5 * x[:, 1]
    df = pd.DataFrame({f"f{i}": x[:, i] for i in range(4)} | {"label": y})

    est = LightningEstimator(
        model=LitRegressor(4),
        feature_cols=[f"f{i}" for i in range(4)], label_cols=["label"],
        batch_size=32, epochs=15, num_proc=2,
        store=FilesystemStore("/tmp/hvd_tpu_lit_store"))
    model = est.fit(df)
    print("epoch losses:", [round(h, 4) for h in model.history])
    out = model.transform(df)
    mse = float(np.mean((out["label__output"] - df["label"]) ** 2))
    print(f"transform mse: {mse:.4f}")


if __name__ == "__main__":
    main()
