"""Synthetic SPMD training benchmark (JAX-native path).

TPU-native analogue of the reference's synthetic benchmarks
(reference: examples/pytorch/pytorch_synthetic_benchmark.py): measures
end-to-end training throughput of the compiled train step — forward,
backward, fused gradient allreduce over the mesh, optimizer update.

    python examples/jax_synthetic_benchmark.py --model resnet50
    python examples/jax_synthetic_benchmark.py --model gpt --seq-len 2048
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse
import time

import jax
import optax

from horovod_tpu import models, training
from horovod_tpu.parallel import GradSyncConfig, MeshSpec, build_mesh


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="resnet50",
                   choices=["resnet50", "resnet101", "gpt"])
    p.add_argument("--batch-size", type=int, default=32,
                   help="per-device batch size")
    p.add_argument("--seq-len", type=int, default=2048)
    p.add_argument("--num-iters", type=int, default=10)
    p.add_argument("--num-warmup", type=int, default=3)
    args = p.parse_args()

    n_dev = len(jax.devices())
    mesh = build_mesh(MeshSpec(dp=n_dev))
    on_tpu = jax.default_backend() == "tpu"
    wire = "bf16" if on_tpu else "fp16"

    if args.model == "gpt":
        import jax.numpy as jnp
        cfg = models.gpt_small(
            max_seq_len=args.seq_len, remat=True,
            attention="flash" if on_tpu else "dense",
            dtype=jnp.bfloat16 if on_tpu else jnp.float32)
        model = models.TransformerLM(cfg)
        tx = optax.adamw(3e-4)
        batch = training.synthetic_text_batch(
            max(args.batch_size // 16, 1) * n_dev, seq_len=args.seq_len,
            vocab_size=cfg.vocab_size)
        units = "tokens"
        per_step = batch["input"].size
    else:
        model = {"resnet50": models.ResNet50,
                 "resnet101": models.ResNet101}[args.model](num_classes=1000)
        tx = optax.sgd(0.01, momentum=0.9)
        batch = training.synthetic_image_batch(args.batch_size * n_dev)
        units = "images"
        per_step = batch["image"].shape[0]

    trainer = training.Trainer(
        model, tx, mesh,
        sync=GradSyncConfig(axes=("dp",), op="average", compression=wire))
    state = trainer.init(jax.random.key(0), batch)

    for _ in range(max(args.num_warmup, 1)):  # >=1 keeps compile untimed
        state, metrics = trainer.step(state, batch)
    jax.block_until_ready(metrics)

    t0 = time.perf_counter()
    for _ in range(args.num_iters):
        state, metrics = trainer.step(state, batch)
    jax.block_until_ready(metrics)
    dt = time.perf_counter() - t0

    rate = per_step * args.num_iters / dt
    print(f"Model: {args.model} on {n_dev} device(s) "
          f"[{jax.default_backend()}]")
    print(f"Throughput: {rate:.1f} {units}/sec "
          f"({rate / n_dev:.1f} per device)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
