"""SPMD ResNet-50 training — the reference's
examples/pytorch/pytorch_imagenet_resnet50.py slot, TPU-first: the whole
step (fwd + bwd + fused bf16 gradient allreduce + SGD momentum) compiles
into one XLA program over the chip mesh.

    python examples/spmd_resnet50_train.py --steps 20 --batch-size 128

Multi-host: launch one copy per host under horovodrun-tpu with
HOROVOD_JAX_DISTRIBUTED=1 and the dp axis spans every chip in the pod.
"""
import argparse
import time

import jax
import optax

from horovod_tpu import models, training
from horovod_tpu.parallel import GradSyncConfig, MeshSpec, build_mesh


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--batch-size", type=int, default=128,
                        help="per-chip batch size")
    parser.add_argument("--steps", type=int, default=20)
    parser.add_argument("--image-size", type=int, default=224)
    parser.add_argument("--wire", default="bf16",
                        choices=["bf16", "fp16", "none"],
                        help="gradient wire compression")
    parser.add_argument("--adasum", action="store_true",
                        help="Adasum (scale-adaptive) gradient combine")
    args = parser.parse_args()

    n = len(jax.devices())
    mesh = build_mesh(MeshSpec(dp=n))
    trainer = training.Trainer(
        models.ResNet50(num_classes=1000),
        optax.sgd(0.1, momentum=0.9), mesh,
        sync=GradSyncConfig(
            axes=("dp",),
            op="adasum" if args.adasum else "average",
            compression=None if args.wire == "none" else args.wire))

    batch = training.synthetic_image_batch(args.batch_size * n,
                                           image_size=args.image_size)
    state = trainer.init(jax.random.key(0), batch)
    state, metrics = trainer.step(state, batch)   # compile
    jax.block_until_ready(metrics)

    t0 = time.perf_counter()
    for _ in range(args.steps):
        state, metrics = trainer.step(state, batch)
    jax.block_until_ready(metrics)
    dt = time.perf_counter() - t0
    print(f"{args.batch_size * n * args.steps / dt:.1f} images/sec "
          f"({n} chip(s)); loss {float(metrics['loss']):.4f}")


if __name__ == "__main__":
    main()
