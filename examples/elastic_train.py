"""Elastic training example (reference: examples/elastic/pytorch_*.py).

Run with host discovery so workers can come and go:

    horovodrun-tpu --min-np 1 --max-np 4 \
        --host-discovery-script ./discover_hosts.sh \
        python examples/elastic_train.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


import numpy as np

import horovod_tpu as hvd
from horovod_tpu.elastic import ObjectState
from horovod_tpu.elastic.run import run as elastic_run

EPOCHS = 20


@elastic_run
def train(state):
    while state.epoch < EPOCHS:
        # One "epoch" of synthetic work; every live rank must agree.
        grad = np.ones(1024, np.float32) * (state.epoch + 1)
        avg = hvd.allreduce(grad, average=True,
                            name=f"grad")
        state.weights = state.weights - 0.01 * np.asarray(avg)
        state.epoch += 1
        state.commit()   # checkpoint + surface membership changes
        if hvd.rank() == 0:
            print(f"epoch {state.epoch}/{EPOCHS} on {hvd.size()} workers",
                  flush=True)
    return state.weights


def main() -> int:
    state = ObjectState(epoch=0, weights=np.zeros(1024, np.float32))
    result = train(state)
    if result is not None and hvd.rank() == 0:
        print(f"done: |w| = {np.linalg.norm(result):.3f}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
