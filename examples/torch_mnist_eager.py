"""Eager multi-process training with the Horovod-compatible torch API.

The analogue of the reference's examples/pytorch/pytorch_mnist.py, on
synthetic MNIST-shaped data (no dataset download).  Launch with:

    horovodrun-tpu -np 2 python examples/torch_mnist_eager.py
    # or: python -m horovod_tpu.runner.launch -np 2 python examples/torch_mnist_eager.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


import numpy as np
import torch
import torch.nn as nn
import torch.nn.functional as F

import horovod_tpu.torch as hvd


class Net(nn.Module):
    def __init__(self):
        super().__init__()
        self.conv1 = nn.Conv2d(1, 10, kernel_size=5)
        self.conv2 = nn.Conv2d(10, 20, kernel_size=5)
        self.fc1 = nn.Linear(320, 50)
        self.fc2 = nn.Linear(50, 10)

    def forward(self, x):
        x = F.relu(F.max_pool2d(self.conv1(x), 2))
        x = F.relu(F.max_pool2d(self.conv2(x), 2))
        x = x.flatten(1)
        x = F.relu(self.fc1(x))
        return F.log_softmax(self.fc2(x), dim=1)


def main() -> int:
    hvd.init()
    torch.manual_seed(42 + hvd.rank())

    model = Net()
    optimizer = torch.optim.SGD(model.parameters(), lr=0.01, momentum=0.5)

    # The horovod workflow: broadcast initial state, wrap the optimizer.
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    hvd.broadcast_optimizer_state(optimizer, root_rank=0)
    optimizer = hvd.DistributedOptimizer(
        optimizer, named_parameters=model.named_parameters())

    rng = np.random.default_rng(hvd.rank())
    for epoch in range(2):
        for step in range(10):
            data = torch.tensor(
                rng.standard_normal((32, 1, 28, 28), dtype=np.float32))
            target = torch.tensor(rng.integers(0, 10, 32))
            optimizer.zero_grad()
            loss = F.nll_loss(model(data), target)
            loss.backward()
            optimizer.step()
        avg = hvd.allreduce(loss.detach(), name="epoch_loss")
        if hvd.rank() == 0:
            print(f"epoch {epoch}: loss {avg.item():.4f}")
    hvd.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
