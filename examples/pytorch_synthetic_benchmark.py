"""PyTorch synthetic benchmark over the eager data plane.

TPU-native analogue of the reference's
examples/pytorch/pytorch_synthetic_benchmark.py, same flag surface:
``--fp16-allreduce``, ``--use-adasum``, ``--batches-per-allreduce``.

Launch:  horovodrun-tpu -np 4 python examples/pytorch_synthetic_benchmark.py
"""
import argparse
import timeit

import numpy as np
import torch

import horovod_tpu.torch as hvd


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--batch-size", type=int, default=32)
    parser.add_argument("--num-iters", type=int, default=10)
    parser.add_argument("--num-warmup", type=int, default=3)
    parser.add_argument("--fp16-allreduce", action="store_true")
    parser.add_argument("--use-adasum", action="store_true")
    parser.add_argument("--batches-per-allreduce", type=int, default=1)
    parser.add_argument("--hidden", type=int, default=1024)
    args = parser.parse_args()

    hvd.init()
    torch.manual_seed(42)
    torch.set_num_threads(max(torch.get_num_threads() // hvd.local_size(),
                              1))

    model = torch.nn.Sequential(
        torch.nn.Linear(1024, args.hidden), torch.nn.ReLU(),
        torch.nn.Linear(args.hidden, args.hidden), torch.nn.ReLU(),
        torch.nn.Linear(args.hidden, 128))
    lr = 0.01 * (1 if args.use_adasum else hvd.size())
    optimizer = torch.optim.SGD(model.parameters(), lr=lr)
    compression = hvd.Compression.fp16 if args.fp16_allreduce \
        else hvd.Compression.none
    optimizer = hvd.DistributedOptimizer(
        optimizer, named_parameters=model.named_parameters(),
        compression=compression,
        backward_passes_per_step=args.batches_per_allreduce,
        op=hvd.Adasum if args.use_adasum else hvd.Average)
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)

    data = torch.randn(args.batch_size, 1024)
    target = torch.randn(args.batch_size, 128)

    def benchmark_step() -> None:
        for _ in range(args.batches_per_allreduce):
            loss = torch.nn.functional.mse_loss(model(data), target)
            loss.backward()
        optimizer.step()
        optimizer.zero_grad()

    for _ in range(args.num_warmup):
        benchmark_step()

    img_secs = []
    for _ in range(args.num_iters):
        t = timeit.timeit(benchmark_step, number=1)
        img_secs.append(args.batch_size * args.batches_per_allreduce / t)

    if hvd.rank() == 0:
        mean = np.mean(img_secs)
        print(f"samples/sec per rank: {mean:.1f} +- "
              f"{1.96 * np.std(img_secs):.1f}")
        print(f"total samples/sec on {hvd.size()} rank(s): "
              f"{hvd.size() * mean:.1f}")


if __name__ == "__main__":
    main()
