"""TF2/Keras synthetic benchmark over the eager data plane.

TPU-native analogue of the reference's
examples/tensorflow2/tensorflow2_keras_synthetic_benchmark.py.

Launch:  horovodrun-tpu -np 4 python \
             examples/tensorflow2_keras_synthetic_benchmark.py
"""
import argparse
import timeit

import numpy as np


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--batch-size", type=int, default=32)
    parser.add_argument("--num-iters", type=int, default=10)
    parser.add_argument("--fp16-allreduce", action="store_true")
    args = parser.parse_args()

    import tensorflow as tf

    import horovod_tpu.tensorflow as hvd

    hvd.init()
    tf.keras.utils.set_random_seed(42)

    model = tf.keras.Sequential([
        tf.keras.layers.Input(shape=(1024,)),
        tf.keras.layers.Dense(1024, activation="relu"),
        tf.keras.layers.Dense(1024, activation="relu"),
        tf.keras.layers.Dense(128)])
    opt = hvd.DistributedOptimizer(
        tf.keras.optimizers.SGD(0.01 * hvd.size()),
        compression=hvd.Compression.fp16 if args.fp16_allreduce else None)
    loss_fn = tf.keras.losses.MeanSquaredError()

    data = tf.random.normal((args.batch_size, 1024))
    target = tf.random.normal((args.batch_size, 128))

    first = [True]

    def benchmark_step() -> None:
        with tf.GradientTape() as tape:
            loss = loss_fn(target, model(data, training=True))
        tape = hvd.DistributedGradientTape(tape)
        grads = tape.gradient(loss, model.trainable_variables)
        opt.apply_gradients(zip(grads, model.trainable_variables))
        if first[0]:
            hvd.broadcast_variables(model.variables, root_rank=0)
            hvd.broadcast_variables(opt.variables, root_rank=0)
            first[0] = False

    benchmark_step()   # build + broadcast
    img_secs = []
    for _ in range(args.num_iters):
        t = timeit.timeit(benchmark_step, number=1)
        img_secs.append(args.batch_size / t)

    if hvd.rank() == 0:
        mean = np.mean(img_secs)
        print(f"samples/sec per rank: {mean:.1f}")
        print(f"total samples/sec on {hvd.size()} rank(s): "
              f"{hvd.size() * mean:.1f}")


if __name__ == "__main__":
    main()
