"""Spark-ML-style estimator example (reference analogue:
examples/spark/pytorch/pytorch_spark_mnist.py).

Runs on pandas (pyspark optional): fits a torch model over 2 distributed
workers through the Store, then transforms the frame with predictions.

    python examples/spark_estimator_example.py [--store kv|fs]
"""
import argparse
import functools

import numpy as np
import pandas as pd
import torch

from horovod_tpu.spark import FilesystemStore, TorchEstimator


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--store", default="fs", choices=["fs", "kv"],
                        help="fs: shared-filesystem store; kv: network "
                        "blob store over a rendezvous KV server")
    parser.add_argument("--num-proc", type=int, default=2)
    args = parser.parse_args()

    rng = np.random.default_rng(0)
    x = rng.standard_normal((256, 4)).astype(np.float32)
    w = np.array([1.0, -2.0, 0.5, 3.0], np.float32)
    df = pd.DataFrame({"f0": x[:, 0], "f1": x[:, 1], "f2": x[:, 2],
                       "f3": x[:, 3], "label": x @ w})

    if args.store == "kv":
        from horovod_tpu.runner.network import RendezvousServer
        from horovod_tpu.spark import KVBlobClient, RemoteBlobStore
        server = RendezvousServer()
        port = server.start()
        store = RemoteBlobStore(KVBlobClient("127.0.0.1", port))
    else:
        server = None
        store = FilesystemStore("/tmp/horovod_tpu_example_store")

    torch.manual_seed(0)
    est = TorchEstimator(
        model=torch.nn.Linear(4, 1),
        optimizer=functools.partial(torch.optim.SGD, lr=0.2),
        loss="mse", feature_cols=["f0", "f1", "f2", "f3"],
        label_cols=["label"], batch_size=32, epochs=10,
        num_proc=args.num_proc, store=store)
    model = est.fit(df)
    print("loss history:", [round(h, 4) for h in model.history])

    out = model.transform(df.head(5))
    print(out[["label", "label__output"]])
    if server is not None:
        server.stop()


if __name__ == "__main__":
    main()
