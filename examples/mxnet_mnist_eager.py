"""MXNet binding example (reference analogue:
examples/mxnet/mxnet_mnist.py).

Requires mxnet (EOL upstream; not in this image — the script gates on
import and explains). The binding itself — allreduce/allgather/broadcast/
alltoall, DistributedOptimizer, gluon DistributedTrainer,
broadcast_parameters — is complete and battery-tested against a stub
(tests/mxnet_stub.py); with real mxnet installed this script runs as-is.

Run: horovodrun-tpu -np 2 python examples/mxnet_mnist_eager.py
"""
import sys

try:
    import mxnet as mx
except ImportError:
    sys.exit("mxnet is not installed (EOL upstream). The binding is "
             "complete — install mxnet to run this, or see "
             "tests/mp_worker.py battery_mxnet for the stub-driven "
             "equivalent.")

import numpy as np

import horovod_tpu.mxnet as hvd


def main():
    hvd.init()

    # Synthetic regression batch per rank.
    rng = np.random.default_rng(hvd.rank())
    net = mx.gluon.nn.Dense(1)
    net.initialize()
    trainer = hvd.DistributedTrainer(
        net.collect_params(), "sgd",
        optimizer_params={"learning_rate": 0.05})
    hvd.broadcast_parameters(net.collect_params(), root_rank=0)

    for step in range(50):
        x = mx.nd.array(rng.standard_normal((32, 4)), dtype="float32")
        y = mx.nd.array(x.asnumpy() @ np.array([1., -2., .5, 0.]),
                        dtype="float32")
        with mx.autograd.record():
            loss = ((net(x)[:, 0] - y) ** 2).mean()
        loss.backward()
        trainer.step(batch_size=32)
        if step % 10 == 0 and hvd.rank() == 0:
            print(f"step {step} loss {float(loss.asnumpy()):.4f}")

    hvd.shutdown()


if __name__ == "__main__":
    main()
