"""Headline benchmark: synthetic ResNet-50 training throughput.

TPU-native analogue of the reference's synthetic benchmark
(reference: examples/pytorch/pytorch_synthetic_benchmark.py): time the full
compiled train step (forward + backward + fused gradient allreduce +
SGD-momentum update) on random ImageNet-shaped data, bf16 compute.

Baseline: the reference's published absolute number is 1656.82 images/sec
on 16 P100 GPUs for ResNet-101 tf_cnn_benchmarks (docs/benchmarks.rst:32-43)
= 103.55 images/sec/device. vs_baseline = our images/sec/chip / 103.55.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""
from __future__ import annotations

import argparse
import json
import sys
import time

BASELINE_IMG_PER_SEC_PER_DEVICE = 1656.82 / 16  # reference, P100


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--model", default="resnet50",
                        choices=["resnet50", "gpt"],
                        help="resnet50: headline images/sec benchmark; "
                        "gpt: transformer tokens/sec (flash attention)")
    parser.add_argument("--batch-size", type=int, default=128)
    parser.add_argument("--image-size", type=int, default=224)
    parser.add_argument("--seq-len", type=int, default=2048)
    parser.add_argument("--warmup", type=int, default=3)
    parser.add_argument("--iters", type=int, default=20)
    args = parser.parse_args()
    if args.model == "gpt":
        return bench_gpt(args)

    import jax
    import optax

    from horovod_tpu import models, training
    from horovod_tpu.parallel import GradSyncConfig, MeshSpec, build_mesh

    devices = jax.devices()
    n_dev = len(devices)
    mesh = build_mesh(MeshSpec(dp=n_dev), devices=devices)

    model = models.ResNet50(num_classes=1000)  # bf16 compute by default
    # bf16 wire on TPU; fp16 elsewhere (XLA CPU crashes promoting bf16
    # all-reduces — same guard as __graft_entry__.dryrun_multichip).
    wire = "bf16" if jax.default_backend() == "tpu" else "fp16"
    trainer = training.Trainer(
        model, optax.sgd(0.1, momentum=0.9), mesh,
        sync=GradSyncConfig(axes=("dp",), op="average",
                            compression=wire))

    global_batch = args.batch_size * n_dev
    batch = training.synthetic_image_batch(global_batch,
                                           image_size=args.image_size)
    state = trainer.init(jax.random.key(0), batch)

    for _ in range(max(args.warmup, 1)):   # >=1: excludes compile from timing
        state, metrics = trainer.step(state, batch)
    jax.block_until_ready(metrics)

    t0 = time.perf_counter()
    for _ in range(args.iters):
        state, metrics = trainer.step(state, batch)
    jax.block_until_ready(metrics)
    elapsed = time.perf_counter() - t0

    img_per_sec = global_batch * args.iters / elapsed
    per_chip = img_per_sec / n_dev
    print(json.dumps({
        "metric": "resnet50_synthetic_images_per_sec_per_chip",
        "value": round(per_chip, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(per_chip / BASELINE_IMG_PER_SEC_PER_DEVICE, 3),
    }))
    return 0


def bench_gpt(args) -> int:
    """Transformer LM throughput (tokens/sec/chip) with the Pallas flash
    attention kernel; secondary benchmark covering the long-context path."""
    import jax
    import optax

    from horovod_tpu import models, training
    from horovod_tpu.parallel import GradSyncConfig, MeshSpec, build_mesh

    devices = jax.devices()
    n_dev = len(devices)
    mesh = build_mesh(MeshSpec(dp=n_dev), devices=devices)
    on_tpu = jax.default_backend() == "tpu"

    import jax.numpy as jnp
    cfg = models.gpt_small(
        max_seq_len=args.seq_len,
        attention="flash" if on_tpu else "dense", remat=True,
        # XLA CPU crashes promoting 16-bit all-reduces; bf16 is TPU-only.
        dtype=jnp.bfloat16 if on_tpu else jnp.float32)
    model = models.TransformerLM(cfg)
    trainer = training.Trainer(
        model, optax.adamw(3e-4), mesh,
        sync=GradSyncConfig(axes=("dp",), op="average",
                            compression="bf16" if on_tpu else "fp16"))

    batch_size = max(args.batch_size // 16, 1) * n_dev
    batch = training.synthetic_text_batch(batch_size, seq_len=args.seq_len,
                                          vocab_size=cfg.vocab_size)
    state = trainer.init(jax.random.key(0), batch)
    for _ in range(max(args.warmup, 1)):   # >=1: excludes compile from timing
        state, metrics = trainer.step(state, batch)
    jax.block_until_ready(metrics)

    t0 = time.perf_counter()
    for _ in range(args.iters):
        state, metrics = trainer.step(state, batch)
    jax.block_until_ready(metrics)
    elapsed = time.perf_counter() - t0

    tok_per_sec = batch_size * args.seq_len * args.iters / elapsed
    per_chip = tok_per_sec / n_dev
    print(json.dumps({
        "metric": "gpt_small_tokens_per_sec_per_chip",
        "value": round(per_chip, 1),
        "unit": "tokens/sec/chip",
        "vs_baseline": 0.0,   # no reference LM baseline exists
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
