"""Headline benchmark: synthetic ResNet-50 training throughput.

TPU-native analogue of the reference's synthetic benchmark
(reference: examples/pytorch/pytorch_synthetic_benchmark.py): time the full
compiled train step (forward + backward + fused gradient allreduce +
SGD-momentum update) on random ImageNet-shaped data, bf16 compute.

Baseline: the reference's published absolute number is 1656.82 images/sec
on 16 P100 GPUs for ResNet-101 tf_cnn_benchmarks (docs/benchmarks.rst:32-43)
= 103.55 images/sec/device. vs_baseline = our images/sec/chip / 103.55.

Robustness contract (the driver records rc + the one JSON line):
- Every accelerator run happens in an INNER SUBPROCESS with a hard
  timeout: the experimental axon tunnel can wedge backend discovery or
  die mid-step (`remote_compile: read body`, the BENCH_r02 failure), and
  a dead PJRT client poisons the whole process. The parent retries the
  inner run with backoff, then falls back to a CPU inner run, so a
  structured JSON line is always printed with rc 0 — "backend" records
  what actually ran.
- A persistent JAX compilation cache (JAX_COMPILATION_CACHE_DIR) makes
  retry attempts skip recompilation, shrinking first-compile exposure to
  the flaky tunnel.
- Inside the inner run the backend is additionally probed in a
  sub-subprocess first (a wedged tunnel hangs jax.devices() forever).
- "mfu" reports achieved_flops/peak_flops from XLA cost analysis when the
  chip's peak is known (null otherwise) so "fast" is measurable, not just
  "faster than 2017 P100s".

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

BASELINE_IMG_PER_SEC_PER_DEVICE = 1656.82 / 16  # reference, P100
# Per-model published absolute baselines (images/sec/device). The only
# absolute number the reference publishes is ResNet-101 tf_cnn_benchmarks
# (docs/benchmarks.rst:32-43); resnet50 keeps it as a documented proxy
# (slightly lighter model, conservative ratio). VGG/Inception have only
# scaling-efficiency percentages → no ratio (0.0).
_BASELINES = {"resnet50": BASELINE_IMG_PER_SEC_PER_DEVICE,
              "resnet101": BASELINE_IMG_PER_SEC_PER_DEVICE}

_PROBE_CODE = (
    "import jax; d = jax.devices(); "
    "print('|'.join([str(len(d)), d[0].platform, d[0].device_kind]))"
)


def _probe_backend_status(timeout: float) \
        -> tuple[str, tuple[int, str, str] | None]:
    """Probe jax backend init in a subprocess (a wedged axon tunnel hangs
    jax.devices() forever — never probe in-process first).

    Returns ``(status, result)`` where status is:

    - ``"ok"``: backend is up, result is (device_count, platform, kind);
    - ``"absent"``: the probe ran to its timeout — a wedged/blackholed
      tunnel, i.e. the accelerator genuinely is not reachable right now;
    - ``"crash"``: the probe PROCESS died (rc != 0) or printed garbage —
      a transient init crash (tunnel reset mid-handshake, plugin race),
      NOT evidence the accelerator is gone.  BENCH_r01-05 burned whole
      round windows treating these as terminal; they are retryable.
    """
    # Probe with the IDENTICAL environment the in-process run will use —
    # popping JAX_PLATFORMS here would let the probe see a TPU the real
    # run (honoring the env) never touches, mislabeling the result.
    try:
        out = subprocess.run(
            [sys.executable, "-c", _PROBE_CODE],
            capture_output=True, text=True, timeout=timeout)
    except subprocess.TimeoutExpired:
        print("bench: backend probe timed out", file=sys.stderr)
        return "absent", None
    if out.returncode != 0:
        print(f"bench: backend probe crashed (rc={out.returncode}):\n"
              f"{out.stderr[-2000:]}", file=sys.stderr)
        return "crash", None
    try:
        n, platform, kind = out.stdout.strip().rsplit("\n", 1)[-1].split("|")
        return "ok", (int(n), platform, kind)
    except ValueError:
        print(f"bench: unparseable probe output: {out.stdout!r}",
              file=sys.stderr)
        return "crash", None


def _probe_backend(timeout: float) -> tuple[int, str, str] | None:
    return _probe_backend_status(timeout)[1]


def _init_backend(retries: int = 2, timeout: float = 150.0) -> dict:
    """Probe (with retries) and then initialize the real backend in-process;
    fall back to CPU if the accelerator never comes up."""
    probed = None
    for attempt in range(retries):
        probed = _probe_backend(timeout)
        if probed is not None:
            break
        if attempt + 1 < retries:
            time.sleep(10.0)
    if probed is None:
        if os.environ.get("HVD_BENCH_REQUIRE_ACCEL"):
            # Orchestrator attempt run: fail fast so the parent's retry
            # loop re-probes — do NOT burn minutes on a CPU benchmark
            # whose payload the parent would discard anyway.
            raise RuntimeError("accelerator probe failed "
                               "(HVD_BENCH_REQUIRE_ACCEL set)")
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax
        jax.config.update("jax_platforms", "cpu")
        return {"backend": "cpu-fallback", "device_kind": "cpu",
                "note": "accelerator probe failed; numbers are CPU-only"}
    import jax  # probe succeeded: init the same default backend here
    n, platform, kind = probed
    return {"backend": platform, "device_kind": kind}


def _peak_flops(device_kind: str) -> float | None:
    """Chip peak from the shared perfscope table (the Trainer, the
    serving replica and the bench all read PEAK_FLOPS_TABLE through
    telemetry/perfmodel.py).  None for unknown kinds so the headline
    "mfu" stays null rather than nominal-1TFLOP/s noise."""
    from horovod_tpu.telemetry import perfmodel
    peak = perfmodel.peak_flops(device_kind)
    return None if peak == perfmodel.NOMINAL_PEAK_FLOPS else peak


def _step_flops(trainer, state, batch) -> float | None:
    """Per-device FLOPs of one compiled train step, via XLA cost analysis."""
    try:
        cost = trainer._step_fn.lower(state, batch).compile().cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        return float(cost["flops"])
    except Exception as exc:  # cost analysis is best-effort on all backends
        print(f"bench: cost analysis unavailable: {exc}", file=sys.stderr)
        return None


def _emit(payload: dict) -> None:
    # Every bench payload records WHAT ran, not just how fast: the
    # declared fabric topology and allreduce-algorithm knob ride along so
    # the perf trajectory can attribute a shift to a layout/algo change.
    # Env-sourced (not registry) so even failure payloads from processes
    # that never imported the package carry the stamp; legs that know the
    # runtime-selected value set the keys explicitly and win (setdefault).
    payload.setdefault("topology",
                       os.environ.get("HOROVOD_TOPOLOGY", "") or "flat")
    payload.setdefault("algo", os.environ.get("HOROVOD_ALGO", "") or "auto")
    print(json.dumps(payload))


def _sync(metrics) -> float:
    """Hard timing barrier: fetch the loss scalar to the host.

    jax.block_until_ready is NOT a reliable completion barrier through a
    relayed/tunneled PJRT backend — measured here: a chain of 100
    dependent 268 MB elementwise ops "completed" under block_until_ready
    in 2.4 ms total, while fetching the final value took 1.6 s of real
    execution (docs/PERFORMANCE.md "Timing methodology"). A device->host
    copy of the result cannot return early, so every timed region ends
    with one. The fetched loss doubles as a liveness check: a synthetic
    train step that returns NaN/garbage would be visible in stderr runs.
    """
    import numpy as np
    return float(np.asarray(metrics["loss"]))


_CACHE_DIR = "/tmp/horovod_tpu_jax_cache"


# XLA's deterministic out-of-memory signatures (HBM allocation failure /
# Mosaic scoped-VMEM overflow). Matched against the FULL stderr — the
# returned tail may truncate them away.
_OOM_SIGNATURES = ("Ran out of memory", "exceeded scoped vmem limit")


def _spawn_inner(args, extra_env: dict, timeout: float
                 ) -> tuple[int, dict | None, str, bool]:
    """Run one benchmark attempt in a subprocess; return (rc, parsed JSON
    payload or None, stderr tail, deterministic-OOM flag)."""
    cmd = [sys.executable, os.path.abspath(__file__), "--inner",
           "--model", args.model,
           "--batch-size", str(args.batch_size),
           "--seq-len", str(args.seq_len),
           "--warmup", str(args.warmup),
           "--iters", str(args.iters),
           "--remat", str(args.remat),
           "--remat-policy", args.remat_policy,
           "--block-q", str(args.block_q),
           "--block-k", str(args.block_k),
           "--block-q-bwd", str(args.block_q_bwd),
           "--block-k-bwd", str(args.block_k_bwd),
           "--stem", args.stem,
           "--gpt-preset", args.gpt_preset]
    if args.image_size is not None:
        cmd += ["--image-size", str(args.image_size)]
    env = {**os.environ, **extra_env,
           "JAX_COMPILATION_CACHE_DIR": _CACHE_DIR}
    try:
        out = subprocess.run(cmd, capture_output=True, text=True,
                             timeout=timeout, env=env)
    except subprocess.TimeoutExpired:
        return -1, None, f"inner run timed out after {timeout:.0f}s", False
    payload = None
    for line in reversed(out.stdout.strip().splitlines()):
        try:
            cand = json.loads(line)
        except ValueError:
            continue
        if isinstance(cand, dict) and "metric" in cand:
            payload = cand
            break
    oom = any(sig in out.stderr for sig in _OOM_SIGNATURES)
    return out.returncode, payload, out.stderr[-2000:], oom


_STATE_FILE_DEFAULT = "/tmp/horovod_tpu_bench_probe.json"


def _probe_state_path() -> str:
    return os.environ.get("HOROVOD_BENCH_STATE_FILE", _STATE_FILE_DEFAULT)


def _load_probe_state(window: float) -> dict:
    """Checkpointed watcher state: {"window_start", "attempts",
    "active_s", "last_seen"}.

    The window is measured in ACTIVE watching seconds (``active_s``),
    not wall time: a tunnel outage that also kills the bench process
    for hours must not burn the round's budget while nobody was
    watching (BENCH_r01-05 recorded cpu-fallback rounds exactly this
    way).  A resumed watcher therefore continues the same window no
    matter how long it was dead; only a state whose budget is already
    spent belongs to a finished round and starts fresh."""
    try:
        with open(_probe_state_path()) as f:
            raw = json.load(f)
        ws = float(raw["window_start"])
        state = {"window_start": ws,
                 "attempts": int(raw.get("attempts", 0)),
                 # Old-format states (pre active-time windows) carry no
                 # active_s: resume with a zero budget spent rather
                 # than discarding the round.
                 "active_s": float(raw.get("active_s", 0.0)),
                 "last_seen": float(raw.get("last_seen", ws))}
        if state["active_s"] < window:
            return state
    except (OSError, ValueError, KeyError, TypeError):
        pass
    now = time.time()
    return {"window_start": now, "attempts": 0, "active_s": 0.0,
            "last_seen": now}


def _save_probe_state(state: dict) -> None:
    try:
        tmp = _probe_state_path() + ".tmp"
        with open(tmp, "w") as f:
            json.dump(state, f)
        os.replace(tmp, _probe_state_path())
    except OSError as exc:   # checkpointing is best-effort
        print(f"bench: probe checkpoint failed: {exc}", file=sys.stderr)


def _clear_probe_state() -> None:
    try:
        os.remove(_probe_state_path())
    except OSError:
        pass


def _orchestrate(args) -> int:
    """Resumable probe daemon around the inner accelerator run; CPU
    fallback keeps the robustness contract (structured line, rc 0) when
    the accelerator tunnel is down for the whole round window.

    Five rounds of VERDICT.md recorded `backend: "cpu-fallback"` because
    the old 6-attempt exponential-backoff ladder gave up in ~22 minutes
    while TPU-tunnel outages last hours.  The ladder is now a WATCHER
    with CHECKPOINTED state: probes repeat every
    HOROVOD_BENCH_PROBE_INTERVAL seconds (default 60) across the whole
    round window (HOROVOD_BENCH_WINDOW_SECONDS, default 3600), and the
    watcher's state file (HOROVOD_BENCH_STATE_FILE) survives process
    death — a re-invoked bench RESUMES the same window instead of
    restarting the schedule, so the round keeps watching for the tunnel
    to recover for as long as the driver keeps asking.

    Two BENCH_r01-05 regressions fixed here: (1) a probe CRASH (the
    subprocess exits rc!=0 — a tunnel reset mid-handshake, a plugin
    race) is classified as RETRYABLE and retried on a short capped
    backoff (5 s doubling, capped at the probe interval) instead of
    being treated like "no accelerator" and burning a full interval
    per crash; (2) the window is measured in ACTIVE watching seconds,
    not wall time — a multi-hour tunnel outage that also kills the
    bench process contributes at most one sleep's worth of budget per
    gap, so the resumed watcher still has its round budget and the
    next round records a real payload.  Each probe runs
    in the PARENT with a short timeout (HOROVOD_BENCH_PROBE_BUDGET_S,
    default 25 s — a wedged tunnel costs seconds, not a full inner
    spawn), TWO consecutive timed-out probes are DEFINITIVE (the
    accelerator-free container goes to CPU fallback in under a minute
    instead of re-timing-out across the window), and the inner run
    still fail-fasts via
    HVD_BENCH_REQUIRE_ACCEL if the tunnel dies between probe and run.
    A successful capture clears the checkpoint (the next round starts a
    fresh window); a CPU fallback leaves it — a re-run resumes any
    remaining probe budget, and a spent budget marks the round finished
    so the NEXT invocation starts a fresh window.

    HOROVOD_BENCH_PROBE_ATTEMPTS still caps the TOTAL probes per window
    when set, and a CPU-pinned environment (JAX_PLATFORMS=cpu) skips
    the schedule outright: the accelerator can never appear there, and
    idle probing burned ~13 minutes per bench run in CPU-only
    containers (BENCH_r05)."""
    def _env_float(name: str, default: float) -> float:
        try:
            return float(os.environ.get(name, "") or default)
        except ValueError:
            return default

    window = _env_float("HOROVOD_BENCH_WINDOW_SECONDS", 3600.0)
    interval = max(_env_float("HOROVOD_BENCH_PROBE_INTERVAL", 60.0), 1.0)
    # Per-probe subprocess timeout (registry knob, env fallback when the
    # package is not importable from the bench entrypoint).  A probe that
    # runs to this timeout is a wedged/blackholed tunnel — and TWO
    # consecutive timeouts are DEFINITIVE: in accelerator-free containers
    # the old schedule burned the whole 15->300 s ladder re-timing-out
    # forever; now CPU fallback starts after ~2x this budget (<1 min at
    # the default 25 s).
    try:
        from horovod_tpu.common import config as _hvd_config
        probe_budget = float(_hvd_config.BENCH_PROBE_BUDGET_S.get())
    except Exception:
        probe_budget = _env_float("HOROVOD_BENCH_PROBE_BUDGET_S", 25.0)
    probe_budget = max(probe_budget, 1.0)
    cap_raw = os.environ.get("HOROVOD_BENCH_PROBE_ATTEMPTS", "")
    try:
        attempts_cap = int(cap_raw) if cap_raw else None
    except ValueError:
        attempts_cap = None

    platforms = {p.strip().lower()
                 for p in os.environ.get("JAX_PLATFORMS", "").split(",")
                 if p.strip()}
    cpu_pinned = bool(platforms) and platforms <= {"cpu"}
    if cpu_pinned:
        print("bench: JAX_PLATFORMS pins the cpu backend; skipping the "
              "accelerator probe window", file=sys.stderr)

    state = _load_probe_state(window)
    crash_streak = 0
    absent_streak = 0
    # Failure forensics (never an empty failure round): every probe and
    # attempt outcome lands in a bounded history, and every terminal
    # payload carries the classification + the window accounting below.
    history: list[dict] = []
    exit_reason = "cpu-pinned" if cpu_pinned else "window-exhausted"

    def _window_accounting() -> dict:
        return {"attempts": state["attempts"],
                "probe_window_s": round(
                    time.time() - state["window_start"], 1),
                "probe_active_s": round(state["active_s"], 1)}

    def _note(status: str, delay: float, **extra) -> None:
        history.append({"probe": state["attempts"], "status": status,
                        "delay_s": round(delay, 1), **extra})
        del history[:-20]          # bounded: the last 20 events

    def _tick(cap: float) -> None:
        """Advance the active-time budget: wall time since the last
        checkpoint counts while the watcher was provably alive, but a
        process-death gap (a tunnel outage that killed the driver too)
        contributes at most ``cap`` — the round survives the gap
        instead of expiring during it."""
        now = time.time()
        state["active_s"] += min(max(now - state["last_seen"], 0.0), cap)
        state["last_seen"] = now

    while not cpu_pinned:
        _tick(2.0 * interval)
        if state["active_s"] >= window:
            print(f"bench: round window exhausted "
                  f"({state['active_s']:.0f}s watched of {window:.0f}s)",
                  file=sys.stderr)
            # Checkpoint the spent budget: it marks this round finished,
            # so the NEXT invocation starts a fresh window.
            _save_probe_state(state)
            break
        state["attempts"] += 1
        _save_probe_state(state)
        status, _probed = _probe_backend_status(timeout=probe_budget)
        # the probe itself ran in-process (<= probe_budget)
        _tick(probe_budget + 30.0)
        if status == "ok":
            crash_streak = 0
            absent_streak = 0
            # Attempt runs fail fast on probe failure
            # (HVD_BENCH_REQUIRE_ACCEL) instead of silently completing a
            # CPU benchmark the watcher would discard; CPU execution
            # happens only in the final explicit fallback below.
            rc, payload, err, oom = _spawn_inner(
                args, {"HVD_BENCH_REQUIRE_ACCEL": "1"}, timeout=900.0)
            _tick(1200.0)   # the attempt ran in-process (<= 900 s)
            if rc == 0 and payload and \
                    not str(payload.get("metric", "")
                            ).endswith("_failed") and \
                    payload.get("backend") != "cpu-fallback":
                payload.update(_window_accounting())
                _clear_probe_state()
                _emit(payload)
                return 0
            _note("attempt-failed", interval, rc=rc, oom=oom)
            print(f"bench: attempt {state['attempts']} failed "
                  f"(rc={rc}): {err}", file=sys.stderr)
            if oom:
                # Deterministic config error (XLA's HBM/VMEM OOM
                # signatures, matched on the full stderr): retrying the
                # same shapes can only fail identically — report now.
                # (Matching broad gRPC codes like RESOURCE_EXHAUSTED
                # would misclassify the tunnel's transient flow-control
                # errors, which the watcher exists for.)
                _clear_probe_state()
                _emit({"metric": f"{args.model}_failed", "value": 0.0,
                       "unit": "error", "vs_baseline": 0.0,
                       "backend": "tpu",
                       "error": ("out of memory (deterministic; if the "
                                 "fp32 logits buffer is the culprit, "
                                 "lower HOROVOD_STREAMING_CE_MIN_"
                                 "ELEMENTS — 0 forces the streaming "
                                 "cross-entropy path): "
                                 f"{err[-300:]}"),
                       "failure": {"class": "deterministic-oom",
                                   "retryable": False},
                       "backoff": history,
                       **_window_accounting()})
                return 0
            delay = interval
        elif status == "crash":
            # A transient probe crash is NOT "no accelerator": retry on
            # a short capped backoff instead of burning a full probe
            # interval per crash (the BENCH_r01-05 failure shape).
            crash_streak += 1
            absent_streak = 0
            delay = min(5.0 * (2.0 ** (crash_streak - 1)), interval)
            _note("probe-crash", delay, streak=crash_streak)
            print(f"bench: probe {state['attempts']}: transient probe "
                  f"crash (#{crash_streak} in a row); retrying in "
                  f"{delay:.0f}s", file=sys.stderr)
        else:
            crash_streak = 0
            absent_streak += 1
            _note("probe-absent", 0.0, streak=absent_streak)
            if absent_streak >= 2:
                # Two consecutive full-budget timeouts: the tunnel is not
                # merely resetting, it is absent — classify as definitive
                # and start the CPU fallback NOW instead of re-timing-out
                # across the whole round window (the BENCH_r01-05
                # cpu-fallback rounds each burned the full backoff ladder
                # this way).
                print(f"bench: probe {state['attempts']}: timed out "
                      f"{absent_streak}x in a row "
                      f"(HOROVOD_BENCH_PROBE_BUDGET_S={probe_budget:.0f})"
                      f" — definitive; starting CPU fallback",
                      file=sys.stderr)
                exit_reason = "accelerator-absent"
                _save_probe_state(state)
                break
            # The timeout itself already burned probe_budget seconds of
            # wall time; re-probe immediately to reach the 2-strike
            # verdict fast.
            delay = 0.0
            print(f"bench: probe {state['attempts']}: no accelerator "
                  f"({max(window - state['active_s'], 0):.0f}s of probe "
                  f"budget left in the round window)", file=sys.stderr)
        _save_probe_state(state)
        if attempts_cap is not None and state["attempts"] >= attempts_cap:
            print(f"bench: HOROVOD_BENCH_PROBE_ATTEMPTS cap "
                  f"({attempts_cap}) reached", file=sys.stderr)
            exit_reason = "probe-attempts-cap"
            break
        time.sleep(min(delay, max(window - state["active_s"], 0.0)))

    print("bench: accelerator unavailable; falling back to CPU "
          "(watcher state is kept — a re-run resumes any remaining "
          "probe budget; a spent window starts the next round fresh)",
          file=sys.stderr)
    rc, payload, err, _ = _spawn_inner(args, {"JAX_PLATFORMS": "cpu"},
                                       timeout=900.0)
    state["attempts"] += 1
    if rc == 0 and payload:
        payload["backend"] = "cpu-fallback"
        payload["note"] = ("accelerator unavailable "
                           f"({exit_reason}); numbers are CPU-only")
        payload.update(_window_accounting())
        _emit(payload)
        return 0
    # Even CPU died — still one structured line, rc 0 per the contract,
    # and NEVER an empty round: the payload classifies the failure,
    # carries the probe/backoff history and accounts for the watched
    # window, so the trajectory records WHY instead of a bare zero.
    _note("cpu-fallback-failed", 0.0, rc=rc)
    _emit({"metric": f"{args.model}_failed", "value": 0.0, "unit": "error",
           "vs_baseline": 0.0, "backend": "none",
           "error": f"all attempts failed; last: rc={rc} {err[-500:]}",
           "failure": {"class": "cpu-fallback-crash",
                       "probe_exit": exit_reason,
                       "crash_streak": crash_streak,
                       "absent_streak": absent_streak,
                       "retryable": True},
           "backoff": history,
           **_window_accounting()})
    return 0


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--model", default="resnet50",
                        choices=["resnet50", "resnet101", "vgg16",
                                 "inception3", "gpt", "eager", "serve"],
                        help="resnet50: headline images/sec benchmark; "
                        "resnet101/vgg16/inception3: the reference's "
                        "other headline CNNs (docs/benchmarks.rst:13-43); "
                        "gpt: transformer tokens/sec (flash attention); "
                        "eager: controller/TCP eager-core microbenchmark; "
                        "serve: serving loadgen smoke (goodput + SLO "
                        "latency; report to SERVE_r*.json, "
                        "docs/serving.md)")
    parser.add_argument("--batch-size", type=int, default=128)
    parser.add_argument("--stem", default="conv7",
                        choices=["conv7", "space_to_depth"],
                        help="resnet*: stem layout (space_to_depth folds "
                        "the 7x7/s2 3-channel conv into an equivalent "
                        "4x4/s1 12-channel conv for the MXU)")
    parser.add_argument("--image-size", type=int, default=None,
                        help="default: the model's canonical input "
                        "(299 for inception3, else 224)")
    parser.add_argument("--seq-len", type=int, default=2048)
    parser.add_argument("--gpt-preset", default="small",
                        choices=["small", "medium"],
                        help="gpt: model size (small=124M, medium=350M; "
                        "medium's d_model=1024 shapes map better onto "
                        "the 128x128 MXU)")
    parser.add_argument("--warmup", type=int, default=3)
    parser.add_argument("--iters", type=int, default=20)
    parser.add_argument("--remat", type=int, default=0,
                        help="gpt: rematerialize each block (saves HBM, "
                        "costs recompute; default off for throughput)")
    parser.add_argument("--remat-policy", default="full",
                        choices=["full", "dots"],
                        help="gpt remat granularity: 'dots' saves matmul "
                        "outputs (less recompute, more HBM)")
    # Defaults from the r3 on-TPU sweep (v5e, gpt-small seq 2048):
    # 256/512→66.2k tok/s, 512/1024→78.2k, 1024/1024→79.5k (MFU 0.37);
    # 1024/2048 exceeds the 16M scoped-vmem limit. docs/PERFORMANCE.md.
    parser.add_argument("--block-q", type=int, default=1024)
    parser.add_argument("--block-k", type=int, default=1024)
    # 0 = same as forward; the bwd kernel's VMEM-optimal tiling is often
    # smaller (it holds dq/dk/dv accumulators + the recomputed p block).
    parser.add_argument("--block-q-bwd", type=int, default=0)
    parser.add_argument("--block-k-bwd", type=int, default=0)
    parser.add_argument("--inner", action="store_true",
                        help="internal: run one attempt in-process")
    args = parser.parse_args()
    if args.model.startswith("resnet") and args.stem == "space_to_depth" \
            and (args.image_size or 224) % 2:
        # Validate BEFORE orchestration: a trace-time shape error in the
        # inner process would be indistinguishable from a transient
        # failure and burn the whole retry schedule.
        parser.error(f"--stem space_to_depth needs an even --image-size "
                     f"(got {args.image_size})")
    if args.model == "eager":   # CPU/localhost only — no tunnel exposure
        try:
            return bench_eager(args)
        except Exception as exc:
            import traceback
            traceback.print_exc()
            _emit({"metric": "eager_failed", "value": 0.0, "unit": "error",
                   "vs_baseline": 0.0,
                   "error": f"{type(exc).__name__}: {exc}",
                   "failure": {"class": "harness-exception",
                               "exception": type(exc).__name__,
                               "retryable": True}})
            return 1
    if args.model == "serve":   # CPU/localhost only — no tunnel exposure
        try:
            return bench_serve(args)
        except Exception as exc:
            import traceback
            traceback.print_exc()
            _emit({"metric": "serve_failed", "value": 0.0, "unit": "error",
                   "vs_baseline": 0.0,
                   "error": f"{type(exc).__name__}: {exc}",
                   "failure": {"class": "harness-exception",
                               "exception": type(exc).__name__,
                               "retryable": True}})
            return 1
    if not args.inner:
        return _orchestrate(args)
    try:
        info = _init_backend()
        if args.model == "gpt":
            return bench_gpt(args, info)
        return bench_resnet(args, info)   # all CNN families
    except Exception as exc:  # never a bare traceback: one structured line
        import traceback
        traceback.print_exc()
        _emit({"metric": f"{args.model}_failed", "value": 0.0,
               "unit": "error", "vs_baseline": 0.0,
               "error": f"{type(exc).__name__}: {exc}",
               "failure": {"class": "inner-exception",
                           "exception": type(exc).__name__,
                           "retryable": True}})
        return 1


def bench_serve(args) -> int:
    """Serving loadgen A/B (ISSUE 9 smoke + ISSUE 14 paged leg): the
    open-loop SLO harness runs TWICE at fixed hardware — the dense
    baseline, then the paged+prefix configuration — under the same
    burst arrival profile and the same repeated-prompt pool.  The dense
    numbers keep the trajectory comparable (serve_goodput); the paged
    leg adds serve_goodput_paged / serve_p99_paged and the
    max_concurrent_seqs the block pool sustained next to the dense
    batch bound, so the trajectory finally records a serving perf
    delta."""
    # Saturating burst (4x through the middle fifth) with a tight SLO:
    # below saturation both configs serve everything and the A/B says
    # nothing; at this load the dense leg queues behind prefills while
    # the paged leg's prefix hits + wider slot packing absorb the burst.
    common = ["--requests", "96", "--duration", "5", "--rate", "120",
              "--max-new-tokens", "4", "--prompt-tokens", "8",
              "--profile", "burst", "--prompt-pool", "6",
              "--max-batch", "4", "--slo-ms", "400"]

    def leg(name: str, extra_env: dict, output: str) -> dict | None:
        out = subprocess.run(
            [sys.executable, "-m", "horovod_tpu.serving.loadgen",
             *common, "--output", output],
            capture_output=True, text=True, timeout=600,
            env={**os.environ, "JAX_PLATFORMS": "cpu", **extra_env})
        if out.returncode != 0:
            _emit({"metric": f"serve_{name}_failed", "value": 0.0,
                   "unit": "error", "vs_baseline": 0.0,
                   "error": out.stderr[-500:] or out.stdout[-500:],
                   "failure": {"class": "loadgen-crash", "rc": out.returncode,
                               "retryable": True}})
            return None
        with open(output.replace("{rank}", "0")) as f:
            return json.load(f)

    dense = leg("dense", {"HOROVOD_SERVE_PAGED": "0"},
                "SERVE_r{rank}.json")
    if dense is None:
        return 1
    _emit({"metric": "serve_goodput", "value": dense["goodput_rps"],
           "unit": "req/s", "vs_baseline": 0.0, "backend": "cpu-eager",
           "offered_rps": dense["offered_rps"],
           "served": dense["served"], "shed": dense["shed"],
           "expired": dense["expired"],
           "latency_ms": dense["latency_ms"],
           "step_ms": dense["step_ms"],
           "report": "SERVE_r0.json"})
    # Paged leg at EQUAL memory budget: the pool auto-sizes to the
    # dense layout's token memory (max_batch x max_seq), slots widen to
    # 2 x max_batch — concurrency beyond the dense batch shape comes
    # from residency, not extra HBM.
    paged = leg("paged", {"HOROVOD_SERVE_PAGED": "1"},
                "SERVE_PAGED_r{rank}.json")
    if paged is None:
        return 1
    kv = paged.get("kv") or {}
    _emit({"metric": "serve_goodput_paged",
           "value": paged["goodput_rps"], "unit": "req/s",
           "vs_baseline": (paged["goodput_rps"] / dense["goodput_rps"]
                           if dense["goodput_rps"] else 0.0),
           "backend": "cpu-eager",
           "served": paged["served"], "shed": paged["shed"],
           "latency_ms": paged["latency_ms"],
           "dense_goodput": dense["goodput_rps"],
           "dense_p99_ms": dense["latency_ms"]["p99"],
           "prefix_hits": kv.get("prefix_hits", 0),
           "prefix_misses": kv.get("prefix_misses", 0),
           "report": "SERVE_PAGED_r0.json"})
    _emit({"metric": "serve_p99_paged",
           "value": paged["latency_ms"]["p99"], "unit": "ms",
           "vs_baseline": (paged["latency_ms"]["p99"]
                           / dense["latency_ms"]["p99"]
                           if dense["latency_ms"]["p99"] else 0.0),
           "dense_p99_ms": dense["latency_ms"]["p99"]})
    _emit({"metric": "max_concurrent_seqs",
           "value": float(paged["max_concurrent_seqs"]), "unit": "seqs",
           "vs_baseline": 0.0,
           "dense_max_batch": 4,
           "dense_max_concurrent": dense["max_concurrent_seqs"],
           "pool_blocks": kv.get("pool_blocks", 0),
           "block_tokens": kv.get("block_tokens", 0)})
    return 0


def bench_resnet(args, info: dict) -> int:
    # Telemetry on for the multichip payload (same contract as the eager
    # payload): the trajectory records counters next to the throughput.
    os.environ.setdefault("HOROVOD_METRICS", "on")
    import jax
    import optax

    from horovod_tpu import models, training
    from horovod_tpu.parallel import GradSyncConfig, MeshSpec, build_mesh

    devices = jax.devices()
    n_dev = len(devices)
    mesh = build_mesh(MeshSpec(dp=n_dev), devices=devices)
    on_tpu = jax.default_backend() == "tpu"

    # bf16 compute by default for every CNN family.
    ctor = {"resnet50": models.ResNet50, "resnet101": models.ResNet101,
            "vgg16": models.VGG16, "inception3": models.InceptionV3}
    if args.image_size is None:   # per-model canonical input
        args.image_size = 299 if args.model == "inception3" else 224
    kw = {}
    if args.model.startswith("resnet"):
        kw["stem"] = args.stem
    model = ctor[args.model](num_classes=1000, **kw)
    # bf16 wire on TPU; fp16 elsewhere (XLA CPU crashes promoting bf16
    # all-reduces — same guard as __graft_entry__.dryrun_multichip).
    wire = "bf16" if on_tpu else "fp16"
    trainer = training.Trainer(
        model, optax.sgd(0.1, momentum=0.9), mesh,
        sync=GradSyncConfig(axes=("dp",), op="average",
                            compression=wire))

    batch_size = args.batch_size if on_tpu else 8  # CPU fallback: stay small
    global_batch = batch_size * n_dev
    batch = training.synthetic_image_batch(global_batch,
                                           image_size=args.image_size)
    state = trainer.init(jax.random.key(0), batch)

    for _ in range(max(args.warmup, 1)):   # >=1: excludes compile from timing
        state, metrics = trainer.step(state, batch)
    _sync(metrics)
    flops = _step_flops(trainer, state, batch)

    iters = args.iters if on_tpu else max(args.iters // 4, 2)
    t0 = time.perf_counter()
    for _ in range(iters):
        state, metrics = trainer.step(state, batch)
    _sync(metrics)     # host value fetch: the honest completion barrier
    elapsed = time.perf_counter() - t0

    img_per_sec = global_batch * iters / elapsed
    per_chip = img_per_sec / n_dev
    peak = _peak_flops(info.get("device_kind", ""))
    mfu = (round(flops * iters / elapsed / peak, 4)
           if flops and peak else None)
    baseline = _BASELINES.get(args.model)
    _emit({
        "metric": f"{args.model}_synthetic_images_per_sec_per_chip",
        "value": round(per_chip, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(per_chip / baseline, 3) if baseline else 0.0,
        "mfu": mfu,
        "n_devices": n_dev,
        # Observability rides the multichip payload like the eager one:
        # wire bytes / cache hit rate / stream utilization (empty-ish on
        # the pure-SPMD path, populated whenever the eager runtime is in
        # the loop) — docs/observability.md.
        "metrics": _telemetry_summary(),
        **info,
    })
    return 0


def _telemetry_summary() -> dict:
    try:
        from horovod_tpu import telemetry
        return telemetry.summary()
    except Exception as exc:  # best-effort: never fail a bench for metrics
        print(f"bench: telemetry summary unavailable: {exc}",
              file=sys.stderr)
        return {}


def bench_gpt(args, info: dict) -> int:
    """Transformer LM throughput (tokens/sec/chip) with the Pallas flash
    attention kernel; secondary benchmark covering the long-context path."""
    os.environ.setdefault("HOROVOD_METRICS", "on")
    import jax
    import optax

    from horovod_tpu import models, training
    from horovod_tpu.parallel import GradSyncConfig, MeshSpec, build_mesh

    devices = jax.devices()
    n_dev = len(devices)
    mesh = build_mesh(MeshSpec(dp=n_dev), devices=devices)
    on_tpu = jax.default_backend() == "tpu"

    import jax.numpy as jnp

    def _divisor_block(block: int, seq: int) -> int:
        # The flash kernel requires seq % block == 0 and TPU-tile-aligned
        # blocks; clamp the requested block to the largest 128-multiple
        # divisor of seq. Fail loudly rather than degrade to a tiny
        # unaligned block (prime/odd seq would otherwise clamp to 1).
        hi = max(128, min(block, seq) // 128 * 128)  # 128 = TPU tile min
        for cand in range(hi, 0, -128):
            if seq % cand == 0:
                if cand != block:
                    print(f"bench: flash block {block} -> {cand} "
                          "(blocks must be 128-aligned divisors of "
                          f"seq {seq})", file=sys.stderr)
                return cand
        raise ValueError(
            f"flash attention blocks must be 128-aligned divisors of "
            f"--seq-len; {seq} is not a multiple of 128 (requested "
            f"block {block}).")

    preset = models.gpt_medium if args.gpt_preset == "medium" \
        else models.gpt_small
    cfg = preset(
        max_seq_len=args.seq_len,
        attention="flash" if on_tpu else "dense", remat=bool(args.remat),
        remat_policy=args.remat_policy,
        # Dense attention (off-TPU) ignores blocks — don't validate there.
        block_q=(_divisor_block(args.block_q, args.seq_len)
                 if on_tpu else args.block_q),
        block_k=(_divisor_block(args.block_k, args.seq_len)
                 if on_tpu else args.block_k),
        block_q_bwd=(_divisor_block(args.block_q_bwd, args.seq_len)
                     if on_tpu and args.block_q_bwd else None),
        block_k_bwd=(_divisor_block(args.block_k_bwd, args.seq_len)
                     if on_tpu and args.block_k_bwd else None),
        # XLA CPU crashes promoting 16-bit all-reduces; bf16 is TPU-only.
        dtype=jnp.bfloat16 if on_tpu else jnp.float32)
    model = models.TransformerLM(cfg)
    trainer = training.Trainer(
        model, optax.adamw(3e-4), mesh,
        sync=GradSyncConfig(axes=("dp",), op="average",
                            compression="bf16" if on_tpu else "fp16"))

    batch_size = max(args.batch_size // 16, 1) * n_dev
    seq_len = args.seq_len if on_tpu else min(args.seq_len, 256)
    batch = training.synthetic_text_batch(batch_size, seq_len=seq_len,
                                          vocab_size=cfg.vocab_size)
    state = trainer.init(jax.random.key(0), batch)
    for _ in range(max(args.warmup, 1)):   # >=1: excludes compile from timing
        state, metrics = trainer.step(state, batch)
    _sync(metrics)
    flops = _step_flops(trainer, state, batch)

    iters = args.iters if on_tpu else max(args.iters // 4, 2)
    t0 = time.perf_counter()
    for _ in range(iters):
        state, metrics = trainer.step(state, batch)
    _sync(metrics)     # host value fetch: the honest completion barrier
    elapsed = time.perf_counter() - t0

    tok_per_sec = batch_size * seq_len * iters / elapsed
    per_chip = tok_per_sec / n_dev
    peak = _peak_flops(info.get("device_kind", ""))
    mfu = (round(flops * iters / elapsed / peak, 4)
           if flops and peak else None)
    _emit({
        "metric": f"gpt_{args.gpt_preset}_tokens_per_sec_per_chip",
        "value": round(per_chip, 1),
        "unit": "tokens/sec/chip",
        "vs_baseline": 0.0,   # no reference LM baseline exists
        "mfu": mfu,
        "n_devices": n_dev,
        "metrics": _telemetry_summary(),
        **info,
    })
    return 0


def _eager_worker(payload_mb: int, cycles: int) -> dict:
    """Per-rank body for bench_eager; must be module-level (pickled to
    spawned workers by horovod_tpu.run)."""
    import numpy as np

    import horovod_tpu as hvd

    # Telemetry rides along so the perf trajectory records counters
    # (bytes on wire, cache hit rate, stream utilization) next to the
    # latency numbers (docs/observability.md).
    os.environ["HOROVOD_METRICS"] = "on"
    hvd.init()
    try:
        small = np.ones(64, dtype=np.float32)
        for _ in range(20):  # fill the response cache / steady state
            hvd.allreduce(small, op=hvd.Sum, name="cycle")
        t0 = time.perf_counter()
        for _ in range(cycles):
            hvd.allreduce(small, op=hvd.Sum, name="cycle")
        cycles_per_sec = cycles / (time.perf_counter() - t0)

        big = np.ones(payload_mb * (1 << 20) // 4, dtype=np.float32)
        for _ in range(2):
            hvd.allreduce(big, op=hvd.Sum, name="ring")
        reps = 5
        t0 = time.perf_counter()
        for _ in range(reps):
            hvd.allreduce(big, op=hvd.Sum, name="ring")
        dt = time.perf_counter() - t0
        # Ring allreduce moves 2*(n-1)/n of the payload per rank each op.
        n = hvd.size()
        moved = reps * payload_mb * (1 << 20) * 2 * (n - 1) / n

        # Fused-vs-reference codec A/B (ISSUE 6): the same payload
        # through the int8 quantized plane with the single-pass fused
        # kernels on, then off (= the PR 3 pipelined reference chain).
        # The dispatch flip is safe mid-run: both settings move one
        # frame per peer per leg and reduce bitwise-identically.
        from horovod_tpu import core as _core
        st = _core.global_state()

        def _set_fused(on: bool) -> None:
            for c in st.tcp_collectives:
                c.fused = on
            for mgr in (st.op_managers or
                        ([st.op_manager] if st.op_manager else [])):
                for be in mgr.backends:
                    if be.name == "shm":   # localhost worlds ride shm
                        be.fused = on

        def _time_quantized() -> float:
            t0 = time.perf_counter()
            for _ in range(reps):
                hvd.allreduce(big, op=hvd.Sum, name="qring",
                              compression="int8")
            return (time.perf_counter() - t0) / reps

        _set_fused(True)
        hvd.allreduce(big, op=hvd.Sum, name="qring", compression="int8")
        codec_fused_s = _time_quantized()
        _set_fused(False)
        hvd.allreduce(big, op=hvd.Sum, name="qring", compression="int8")
        codec_reference_s = _time_quantized()
        _set_fused(True)

        from horovod_tpu import telemetry
        return {"cycles_per_sec": cycles_per_sec,
                "ring_gbyte_per_sec": moved / dt / 1e9,
                "codec_fused_ms": codec_fused_s * 1e3,
                "codec_reference_ms": codec_reference_s * 1e3,
                "metrics": telemetry.summary()}
    finally:
        hvd.shutdown()


def _ladder_worker(sizes_bytes: tuple, reps: int) -> dict:
    """Per-rank body for the allreduce size-ladder leg (median latency
    per algorithm × payload size); module-level for pickling."""
    import numpy as np

    import horovod_tpu as hvd
    from horovod_tpu import core as _core

    # Pin the flat TCP plane: the ladder compares ring vs tree SCHEDULES,
    # so the shm/XLA planes (which ignore the algo knob) must not claim
    # the op on localhost worlds.
    os.environ["HOROVOD_SHM_OPERATIONS"] = "0"
    os.environ["HOROVOD_XLA_OPERATIONS"] = "0"
    hvd.init()
    try:
        st = _core.global_state()
        out: dict = {}
        for algo in ("ring", "tree"):
            # Symmetric flip (every rank runs this same line before the
            # same op sequence) — the same mechanism as tuned_algo.
            for c in st.tcp_collectives:
                c.algo = algo
            for nb in sizes_bytes:
                x = np.ones(max(nb // 4, 1), dtype=np.float32)
                name = f"ladder_{algo}_{nb}"
                hvd.allreduce(x, op=hvd.Sum, name=name)   # warm the cache
                samples = []
                for _ in range(reps):
                    t0 = time.perf_counter()
                    hvd.allreduce(x, op=hvd.Sum, name=name)
                    samples.append(time.perf_counter() - t0)
                out[f"{algo}_{nb}"] = sorted(samples)[len(samples) // 2] \
                    * 1e3
        return out
    finally:
        hvd.shutdown()


def bench_eager(args) -> int:
    """Eager-core microbenchmark: steady-state cached negotiation cycle rate
    and TCP-ring allreduce bandwidth (reference analogue: the 1ms
    RunLoopOnce cycle + the NCCL ring, horovod/common/operations.cc:589-647).

    Runs entirely on CPU/localhost — measures the controller + transport
    planes, not XLA."""
    # Force (not setdefault) the CPU backend: the axon TPU tunnel must
    # never be probed for a controller/TCP microbenchmark.
    os.environ["JAX_PLATFORMS"] = "cpu"
    import horovod_tpu

    results = horovod_tpu.run(_eager_worker, args=(16, 200), np=2)
    r = results[0]
    fused_ms = r.get("codec_fused_ms", 0.0)
    ref_ms = r.get("codec_reference_ms", 0.0)

    # Allreduce size ladder (ISSUE 18): median latency per algorithm ×
    # payload size on a 4-rank world (tree degenerates to ring at 2
    # ranks), plus the measured tree/ring crossover — the empirical
    # counterpart of HOROVOD_TREE_THRESHOLD_BYTES.
    ladder_sizes = (4 << 10, 64 << 10, 1 << 20)
    lad = horovod_tpu.run(_ladder_worker, args=(ladder_sizes, 5), np=4)[0]
    ladder = {str(nb): {"ring_ms": round(lad[f"ring_{nb}"], 3),
                        "tree_ms": round(lad[f"tree_{nb}"], 3)}
              for nb in ladder_sizes}
    crossover = 0
    for nb in ladder_sizes:
        if lad[f"tree_{nb}"] < lad[f"ring_{nb}"]:
            crossover = nb
    _emit({
        "metric": "eager_cached_cycles_per_sec",
        "value": round(r["cycles_per_sec"], 1),
        "unit": "cycles/sec (2 ranks, localhost)",
        "vs_baseline": 0.0,
        "ring_gbyte_per_sec": round(r["ring_gbyte_per_sec"], 2),
        # ISSUE 6 A/B: int8 quantized allreduce, fused single-pass
        # kernels vs the PR 3 pipelined reference chain (per-op ms;
        # ratio > 1 means fused is faster).
        "codec_fused_ms": round(fused_ms, 2),
        "codec_reference_ms": round(ref_ms, 2),
        "codec_fused_speedup": round(ref_ms / fused_ms, 3)
        if fused_ms > 0 else 0.0,
        # ISSUE 18 size ladder: per-algo median latency by payload size
        # and the largest size where the tree still beat the ring (0 =
        # the ring won everywhere).
        "allreduce_ladder": ladder,
        "tree_ring_crossover_bytes": crossover,
        # End-of-run telemetry snapshot: the trajectory records counters
        # (wire bytes, cache hit rate, stream utilization) alongside
        # the latency headline.
        "metrics": r.get("metrics", {}),
    })
    return 0


if __name__ == "__main__":
    sys.exit(main())
