#!/bin/bash
# Runs the queued TPU-window experiments in priority order the moment the
# tunnel is green. Appends "<tag> <JSON>" lines like mfu_sweep.sh.
set -u
LOG="${1:-/tmp/window2.log}"
cd "$(dirname "$0")/.."

run() {
  local tag="$1"; shift
  if grep -q "^${tag} " "$LOG" 2>/dev/null; then
    echo "skip ${tag}" >&2; return
  fi
  echo "=== ${tag}: $*" >&2
  local out rc
  out=$("$@" 2>/tmp/window2_err.log); rc=$?
  if [ $rc -ne 0 ] || [ -z "$out" ]; then
    echo "FAILED ${tag} rc=${rc}" >&2; return
  fi
  case "$out" in
    *'"unit": "error"'*)
      echo "${tag} ${out}" >> "${LOG}.failed"
      echo "FAILED ${tag} (structured): ${out}" >&2
      return;;
  esac
  echo "${tag} ${out}" >> "$LOG"
  echo "${tag} ${out}" >&2
}

# 1. s2d stem A/B — back-to-back same window, conv7 first (the default).
run rn50-conv7  python bench.py --model resnet50 --iters 60
run rn50-s2d    python bench.py --model resnet50 --iters 60 --stem space_to_depth
# 2. gpt-medium flagship MFU (d_model=1024 MXU shapes); batch 8 rows/chip
#    = --batch-size 128 default scaling (128//16=8). 350M params, no remat.
run gptmed-bs8  python bench.py --model gpt --gpt-preset medium --iters 30
run gptmed-bs4  python bench.py --model gpt --gpt-preset medium --iters 30 --batch-size 64 --remat 1 --remat-policy dots
# 3. corrected HBM roofline (optimization_barrier between passes);
#    multi-line output -> its own file
if ! [ -s /tmp/window2_roofline.jsonl ]; then
  echo "=== roofline" >&2
  if ! timeout 580 python benchmarks/roofline.py \
      > /tmp/window2_roofline.jsonl 2>/tmp/window2_err.log; then
    echo "FAILED roofline" >&2
    rm -f /tmp/window2_roofline.jsonl   # partial output must not satisfy
  fi                                    # the rerun guard
fi
# 4. gpt default confirm (dense CE now the default path)
run gpt-default python bench.py --model gpt --iters 40
# 5. accuracy-metric cost A/B (argmax over the [B,S,V] logits)
run gpt-noacc env HOROVOD_TRACK_ACCURACY=0 python bench.py --model gpt --iters 40
echo "window2 done" >&2
