"""Practical MXU/HBM roofline of the attached chip, with the honest
host-fetch barrier (docs/PERFORMANCE.md "Timing methodology").

The bench MFU numbers are quoted against the *published* peak
(bench._PEAK_FLOPS). This script measures what fraction of that peak a
pure dependent-chain matmul actually sustains here — the practical roof
every end-to-end MFU should be read against.

Prints one JSON line per experiment.
"""
from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np


def _fetch(x) -> float:
    return float(np.asarray(jax.device_get(x)).ravel()[0])


def bench_matmul(n: int, dtype, iters: int = 30) -> dict:
    a = jax.random.normal(jax.random.key(0), (n, n), dtype)
    b = jax.random.normal(jax.random.key(1), (n, n), dtype)

    @jax.jit
    def chain(a, b):
        # Dependent chain: each matmul consumes the previous result, so
        # the tunnel relay cannot pipeline-hide real execution time.
        x = a
        for _ in range(iters):
            x = jnp.tanh(x @ b)   # tanh keeps values bounded (no inf)
        return x[0, 0]

    r = chain(a, b)
    _fetch(r)                      # compile + warm
    t0 = time.perf_counter()
    r = chain(a, b)
    _fetch(r)
    dt = time.perf_counter() - t0
    flops = 2.0 * n * n * n * iters
    return {"experiment": f"matmul_{n}_{jnp.dtype(dtype).name}",
            "tflops": round(flops / dt / 1e12, 1),
            "iters": iters, "seconds": round(dt, 3)}


def bench_hbm(mb: int = 512, iters: int = 30) -> dict:
    n = mb * (1 << 20) // 2          # bf16 elements
    x = jnp.ones((n,), jnp.bfloat16)

    @jax.jit
    def chain(x):
        # optimization_barrier between passes: without it XLA fuses the
        # whole elementwise chain into ONE kernel (one read, one write)
        # and `moved` would overcount traffic by up to iters×.
        for _ in range(iters):
            x = x * 1.0000001 + 1e-7   # read + write each pass
            (x,) = jax.lax.optimization_barrier((x,))
        return x[0]

    _fetch(chain(x))
    t0 = time.perf_counter()
    _fetch(chain(x))
    dt = time.perf_counter() - t0
    moved = 2.0 * mb * (1 << 20) * iters   # read + write per pass
    return {"experiment": f"hbm_stream_{mb}MB",
            "gbyte_per_sec": round(moved / dt / 1e9, 1),
            "seconds": round(dt, 3)}


def main() -> None:
    assert jax.default_backend() == "tpu", jax.devices()
    print(json.dumps({"device": jax.devices()[0].device_kind}))
    for n in (4096, 8192, 16384):
        print(json.dumps(bench_matmul(n, jnp.bfloat16)))
    print(json.dumps(bench_matmul(8192, jnp.float32, iters=8)))
    print(json.dumps(bench_hbm()))


if __name__ == "__main__":
    main()
