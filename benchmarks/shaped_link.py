"""Shaped-link validation of the cross-host collectives story.

Emulates a 2-host x 2-slot cluster on one machine with network
namespaces: ranks 0-1 live in netns h0, ranks 2-3 in h1, joined by a
veth pair carrying a token-bucket bandwidth cap (tc tbf) — so intra-host
traffic rides each namespace's loopback at memory speed while cross-host
bytes squeeze through the shaped link, the topology the hierarchical
schedule exists for (reference: NCCLHierarchicalAllreduce,
nccl_operations.cc:187-398 — the cross leg carries 1/local_size of the
payload; docs/benchmarks.rst:13-14 measures the reference cross-host).

Measures end-to-end allreduce algorithm bandwidth (payload bytes / wall
time) for:
  flat      — one world-size TCP ring over the shaped link
  hier-tcp  — RS(local) -> AR(cross) -> AG(local), all legs TCP
  hier-shm  — same schedule, intra-host legs on the mmap shm plane

The shm memory-domain fingerprint includes the net-namespace inode, so
the namespace boundary behaves exactly like a host boundary: the global
shm world declines to form across "hosts" (as on real clusters), while
the hierarchical per-host local worlds still form inside each namespace.

Run as root: python benchmarks/shaped_link.py [--rate 1gbit] [--mb 16]
Requires: iproute2 (ip, tc with tbf), CAP_NET_ADMIN.
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = r"""
import os, sys, time
sys.path.insert(0, os.environ["SHAPED_REPO"])
import numpy as np
import horovod_tpu as hvd

hvd.init()
mb = int(os.environ["SHAPED_MB"])
reps = int(os.environ["SHAPED_REPS"])
v = np.ones(mb * (1 << 20) // 4, np.float32)
for _ in range(2):
    hvd.allreduce(v, op=hvd.Sum, name="warm")
t0 = time.perf_counter()
for _ in range(reps):
    hvd.allreduce(v, op=hvd.Sum, name="ar")
dt = time.perf_counter() - t0
out = hvd.allreduce(np.full(4, float(hvd.rank()), np.float32),
                    op=hvd.Sum, name="check")
assert abs(float(out[0]) - sum(range(hvd.size()))) < 1e-6
if hvd.rank() == 0:
    print("RESULT %.4f" % (reps * v.nbytes / dt / 1e9), flush=True)
hvd.shutdown()
"""


def sh(cmd: str) -> None:
    subprocess.run(cmd, shell=True, check=True)


def setup(rate: str) -> None:
    teardown()
    sh("ip netns add h0 && ip netns add h1")
    sh("ip link add veth0 type veth peer name veth1")
    sh("ip link set veth0 netns h0 && ip link set veth1 netns h1")
    for ns, dev, ip in (("h0", "veth0", "10.99.0.1"),
                        ("h1", "veth1", "10.99.0.2")):
        sh(f"ip netns exec {ns} ip addr add {ip}/24 dev {dev}")
        sh(f"ip netns exec {ns} ip link set {dev} up")
        sh(f"ip netns exec {ns} ip link set lo up")
        if rate != "unshaped":
            sh(f"ip netns exec {ns} tc qdisc add dev {dev} root tbf "
               f"rate {rate} burst 256kb latency 100ms")


def teardown() -> None:
    subprocess.run("ip netns del h0; ip netns del h1", shell=True,
                   capture_output=True)


def run_config(name: str, mb: int, reps: int, extra_env: dict) -> float:
    """Launch 4 ranks (2 per namespace) against a rendezvous server that
    itself runs inside h0, bound on the veth address."""
    epoch = f"{name}-{time.time()}"
    server = subprocess.Popen(
        ["ip", "netns", "exec", "h0", sys.executable, "-c",
         "import sys; sys.path.insert(0, %r)\n"
         "from horovod_tpu.runner.network import RendezvousServer\n"
         "import time\n"
         "s = RendezvousServer()\n"
         "print('PORT', s.start(), flush=True)\n"
         "time.sleep(600)" % REPO],
        stdout=subprocess.PIPE)
    line = server.stdout.readline().decode().split()
    assert line and line[0] == "PORT", line
    port = int(line[1])
    procs = []
    for rank in range(4):
        host = rank // 2
        env = dict(os.environ,
                   JAX_PLATFORMS="cpu",
                   PYTHONPATH=REPO,
                   SHAPED_REPO=REPO, SHAPED_MB=str(mb),
                   SHAPED_REPS=str(reps),
                   HOROVOD_RANK=str(rank), HOROVOD_SIZE="4",
                   HOROVOD_LOCAL_RANK=str(rank % 2),
                   HOROVOD_LOCAL_SIZE="2",
                   HOROVOD_CROSS_RANK=str(host), HOROVOD_CROSS_SIZE="2",
                   HOROVOD_GLOO_RENDEZVOUS_ADDR="10.99.0.1",
                   HOROVOD_GLOO_RENDEZVOUS_PORT=str(port),
                   HOROVOD_RENDEZVOUS_EPOCH=epoch,
                   HOROVOD_GLOO_IFACE=f"veth{host}",
                   **{k: str(v) for k, v in extra_env.items()})
        procs.append(subprocess.Popen(
            ["ip", "netns", "exec", f"h{host}", sys.executable, "-c",
             WORKER], env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT))
    result = None
    for r, p in enumerate(procs):
        try:
            out, _ = p.communicate(timeout=600)
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
        text = out.decode(errors="replace")
        if p.returncode != 0:
            print(f"--- rank {r} FAILED (rc={p.returncode}) ---\n{text}",
                  file=sys.stderr)
        for line in text.splitlines():
            if line.startswith("RESULT "):
                result = float(line.split()[1])
    server.kill()
    if result is None:
        raise RuntimeError(f"config {name}: no result")
    return result


CONFIGS = {
    "flat": {"HOROVOD_SHM_OPERATIONS": 0},
    "hier-tcp": {"HOROVOD_SHM_OPERATIONS": 0,
                 "HOROVOD_HIERARCHICAL_ALLREDUCE": 1,
                 "HOROVOD_HIERARCHICAL_ALLGATHER": 1},
    # SHM auto: the global world declines across the netns boundary (as
    # on real clusters); the per-host local-leg worlds form.
    "hier-shm": {"HOROVOD_HIERARCHICAL_ALLREDUCE": 1,
                 "HOROVOD_HIERARCHICAL_ALLGATHER": 1},
}


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rates", default="unshaped,5gbit,1gbit,200mbit")
    ap.add_argument("--mb", type=int, default=16)
    ap.add_argument("--reps", type=int, default=6)
    ap.add_argument("--configs", default="flat,hier-tcp,hier-shm")
    args = ap.parse_args()

    if os.geteuid() != 0:
        sys.exit("needs root (netns + tc)")
    results: dict = {}
    for rate in args.rates.split(","):
        setup(rate)
        try:
            for cfg in args.configs.split(","):
                gbps = run_config(cfg, args.mb, args.reps, CONFIGS[cfg])
                results.setdefault(rate, {})[cfg] = round(gbps, 4)
                print(f"{rate:>10}  {cfg:>9}: {gbps:.3f} GB/s "
                      f"(payload {args.mb} MiB, 4 ranks)", flush=True)
        finally:
            teardown()
    print(json.dumps(results))


if __name__ == "__main__":
    main()
