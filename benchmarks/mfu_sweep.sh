#!/bin/bash
# MFU sweep on the live TPU window.  Appends one line per config to the
# results log: "<tag> <bench.py JSON line>".  Each config is one bench.py
# orchestrated run (probe + retry + compile-cache), so a tunnel blip costs
# one config, not the sweep.
#
# Usage: benchmarks/mfu_sweep.sh [results_log]
set -u
LOG="${1:-/tmp/mfu_sweep_r5.log}"
cd "$(dirname "$0")/.."

run() {
  local tag="$1"; shift
  if grep -q "^${tag} {" "$LOG" 2>/dev/null; then
    echo "skip ${tag} (already in log)" >&2
    return
  fi
  echo "=== ${tag}: python bench.py $*" >&2
  local out rc
  out=$(python bench.py "$@" 2>/tmp/mfu_sweep_err.log)
  rc=$?
  if [ $rc -ne 0 ] || [ -z "$out" ]; then
    # Keep the log parseable as "<tag> <JSON>": failures go to stderr only.
    echo "FAILED ${tag} rc=${rc} (see /tmp/mfu_sweep_err.log)" >&2
    return
  fi
  echo "${tag} ${out}" >> "$LOG"
  echo "${tag} ${out}" >&2
}

# --- GPT: bwd-block tiling x batch x remat (r3 best: 1024/1024 fwd, MFU .37)
run gpt-base          --model gpt --iters 20
run gpt-bwd-512-1024  --model gpt --iters 20 --block-q-bwd 512  --block-k-bwd 1024
run gpt-bwd-1024-512  --model gpt --iters 20 --block-q-bwd 1024 --block-k-bwd 512
run gpt-bwd-512-512   --model gpt --iters 20 --block-q-bwd 512  --block-k-bwd 512
run gpt-bwd-256-1024  --model gpt --iters 20 --block-q-bwd 256  --block-k-bwd 1024
run gpt-bs256         --model gpt --iters 20 --batch-size 256
run gpt-bs512         --model gpt --iters 20 --batch-size 512
run gpt-bs256-dots    --model gpt --iters 20 --batch-size 256 --remat 1 --remat-policy dots
run gpt-bs512-dots    --model gpt --iters 20 --batch-size 512 --remat 1 --remat-policy dots

# --- ResNet-50: batch sweep (r5 first number: bs128 -> 2427 img/s, MFU .295)
run rn50-bs256        --model resnet50 --iters 20 --batch-size 256
run rn50-bs512        --model resnet50 --iters 20 --batch-size 512
run rn50-bs1024       --model resnet50 --iters 20 --batch-size 1024

# --- Other CNN families, one record each
run rn101-bs256       --model resnet101 --iters 15 --batch-size 256
run vgg16-bs128       --model vgg16 --iters 15 --batch-size 128
run incv3-bs256       --model inception3 --iters 15 --batch-size 256

echo "sweep done" >&2
