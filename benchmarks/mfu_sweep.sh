#!/bin/bash
# MFU sweep on the live TPU window.  Appends one line per config to the
# results log: "<tag> <bench.py JSON line>".  Each config is one bench.py
# orchestrated run (probe + retry + compile-cache), so a tunnel blip costs
# one config, not the sweep.  iters are sized so the timed region is
# seconds long — the tunnel's device->host fetch RTT (~0.1s) then biases
# the rate by ~1-2%, not 15%.
#
# Usage: benchmarks/mfu_sweep.sh [results_log]
set -u
LOG="${1:-/tmp/mfu_sweep_r5b.log}"
cd "$(dirname "$0")/.."

run() {
  local tag="$1"; shift
  # Skip only configs with a recorded SUCCESS; *_failed lines (bench.py
  # reports deterministic OOMs with rc 0) go to the .failed side-log so
  # a fixed config re-runs on the next sweep invocation.
  if grep -q "^${tag} {" "$LOG" 2>/dev/null; then
    echo "skip ${tag} (already in log)" >&2
    return
  fi
  echo "=== ${tag}: python bench.py $*" >&2
  local out rc
  out=$(python bench.py "$@" 2>/tmp/mfu_sweep_err.log)
  rc=$?
  if [ $rc -ne 0 ] || [ -z "$out" ]; then
    echo "FAILED ${tag} rc=${rc} (see /tmp/mfu_sweep_err.log)" >&2
    return
  fi
  case "$out" in
    *'"unit": "error"'*)
      echo "${tag} ${out}" >> "${LOG}.failed"
      echo "FAILED ${tag} (structured): ${out}" >&2
      return;;
  esac
  echo "${tag} ${out}" >> "$LOG"
  echo "${tag} ${out}" >&2
}

# --- GPT with the bf16-MXU flash kernels (commit 63a7ce0)
run gpt-base          --model gpt --iters 40
run gpt-bwd-512-1024  --model gpt --iters 40 --block-q-bwd 512 --block-k-bwd 1024
run gpt-fwd-2048      --model gpt --iters 40 --block-q 2048
run gpt-bwd-1024-2048 --model gpt --iters 40 --block-q-bwd 1024 --block-k-bwd 2048
run gpt-bs256         --model gpt --iters 40 --batch-size 256
run gpt-bs256-dots    --model gpt --iters 40 --batch-size 256 --remat 1 --remat-policy dots
run gpt-seq8k         --model gpt --iters 10 --seq-len 8192 --remat 1 --remat-policy dots --batch-size 32

# --- CNN families (bs128 default already recorded in this window: 2427 img/s)
run rn50-bs256        --model resnet50 --iters 60 --batch-size 256
run rn101-bs128       --model resnet101 --iters 40 --batch-size 128
run vgg16-bs128       --model vgg16 --iters 40 --batch-size 128
run incv3-bs128       --model inception3 --iters 40 --batch-size 128

echo "sweep done" >&2
