"""Training-loop callbacks (reference: horovod/keras/callbacks.py 22-151 and
horovod/_keras/callbacks.py).

The reference ships four Keras callbacks; these are their framework-neutral
equivalents for the :class:`horovod_tpu.training.Trainer` fit loop (and any
hand-written loop): metric averaging across ranks, learning-rate warmup /
size-scaled schedules, and rank-0-gated best-model checkpointing.  The
elastic commit callback mirrors horovod/_keras/elastic.py CommitStateCallback.
"""
from __future__ import annotations

import math
from typing import Any, Callable

import numpy as np


class Callback:
    """Lifecycle hooks around the Trainer fit loop."""

    def set_trainer(self, trainer) -> None:
        self.trainer = trainer

    def on_train_begin(self, logs: dict | None = None) -> None: ...

    def on_train_end(self, logs: dict | None = None) -> None: ...

    def on_epoch_begin(self, epoch: int,
                       logs: dict | None = None) -> None: ...

    def on_epoch_end(self, epoch: int, logs: dict | None = None) -> None: ...

    def on_batch_begin(self, batch: int,
                       logs: dict | None = None) -> None: ...

    def on_batch_end(self, batch: int, logs: dict | None = None) -> None: ...


class MetricAverageCallback(Callback):
    """Average epoch metrics over all ranks (reference:
    _keras/callbacks.py:49-92 MetricAverageCallback).

    The SPMD Trainer already returns mesh-averaged metrics; this callback
    matters for the eager multi-process API where each process computes
    local metrics."""

    def on_epoch_end(self, epoch: int, logs: dict | None = None) -> None:
        if not logs:
            return
        import horovod_tpu as hvd
        if not hvd.is_initialized() or hvd.size() == 1:
            return
        keys = sorted(k for k, v in logs.items()
                      if isinstance(v, (int, float, np.floating)))
        if not keys:
            return
        vec = np.array([float(logs[k]) for k in keys], np.float64)
        avg = hvd.allreduce(vec, average=True,
                            name=f"__metric_avg_e{epoch}__")
        for k, v in zip(keys, np.asarray(avg)):
            logs[k] = float(v)


class LearningRateScheduleCallback(Callback):
    """Multiply the base LR by ``multiplier(epoch)`` (reference:
    _keras/callbacks.py LearningRateScheduleCallback).  Works with any
    optimizer object exposing ``lr`` / ``learning_rate`` or torch-style
    ``param_groups``."""

    def __init__(self, optimizer, multiplier: Callable[[int], float] | float,
                 start_epoch: int = 0, end_epoch: int | None = None,
                 staircase: bool = True, steps_per_epoch: int | None = None
                 ) -> None:
        self.optimizer = optimizer
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch
        self.staircase = staircase
        self.steps_per_epoch = steps_per_epoch
        self.current_epoch = 0
        self._initial_lrs: list[float] | None = None
        if callable(multiplier):
            self.multiplier = multiplier
        else:
            self.multiplier = lambda epoch: multiplier

    def _lr_holders(self):
        opt = self.optimizer
        if hasattr(opt, "param_groups"):          # torch
            return opt.param_groups, "lr"
        for attr in ("learning_rate", "lr"):
            if hasattr(opt, attr):
                return [opt], attr
        raise AttributeError(
            "optimizer exposes neither param_groups nor lr/learning_rate")

    def _capture_initial(self):
        holders, attr = self._lr_holders()
        if self._initial_lrs is None:
            self._initial_lrs = [
                (h[attr] if isinstance(h, dict) else getattr(h, attr))
                for h in holders]

    def _adjust(self, epoch: float) -> None:
        if epoch < self.start_epoch or \
                (self.end_epoch is not None and epoch >= self.end_epoch):
            return
        self._capture_initial()
        holders, attr = self._lr_holders()
        mult = self.multiplier(epoch)
        for holder, initial in zip(holders, self._initial_lrs):
            value = initial * mult
            if isinstance(holder, dict):
                holder[attr] = value
            else:
                setattr(holder, attr, value)

    def on_epoch_begin(self, epoch: int, logs: dict | None = None) -> None:
        self.current_epoch = epoch
        # Smooth schedules without steps_per_epoch still adjust at epoch
        # granularity — a schedule must never silently no-op.
        if self.staircase or not self.steps_per_epoch:
            self._adjust(epoch)

    def on_batch_begin(self, batch: int, logs: dict | None = None) -> None:
        if not self.staircase and self.steps_per_epoch:
            self._adjust(self.current_epoch + batch / self.steps_per_epoch)


class LearningRateWarmupCallback(LearningRateScheduleCallback):
    """Gradual LR warmup from ``lr / size`` to ``lr`` over
    ``warmup_epochs``, matching the reference convention that the
    configured optimizer LR is already scaled by the world size
    (reference: _keras/callbacks.py LearningRateWarmupCallback — the
    multiplier interpolates 1/size → 1; the "facebook 1-hour ImageNet"
    recipe)."""

    def __init__(self, optimizer, warmup_epochs: int = 5,
                 momentum_correction: bool = True,
                 steps_per_epoch: int | None = None, verbose: bool = False,
                 initial_lr: float | None = None, size: int | None = None
                 ) -> None:
        if size is None:
            import horovod_tpu as hvd
            size = hvd.size() if hvd.is_initialized() else 1
        self.size = size
        self.warmup_epochs = warmup_epochs
        self.verbose = verbose

        def multiplier(epoch: float) -> float:
            if warmup_epochs <= 0:
                return 1.0
            # epoch/warmup interpolation 1/size → 1.
            frac = min(epoch / warmup_epochs, 1.0)
            return (1.0 + frac * (size - 1)) / size

        # No end_epoch: the multiplier clamps at 1.0, so past the warmup
        # window the configured LR is applied exactly (an exclusive window
        # would freeze just short of it at epoch granularity).
        super().__init__(optimizer, multiplier, start_epoch=0,
                         end_epoch=None, staircase=False,
                         steps_per_epoch=steps_per_epoch)

    def on_epoch_end(self, epoch: int, logs: dict | None = None) -> None:
        if self.verbose and epoch == self.warmup_epochs - 1:
            print(f"Epoch {epoch}: finished gradual learning rate warmup "
                  f"(ramped 1/{self.size} -> 1x of the configured LR).")


class BestModelCheckpoint(Callback):
    """Save the model when the monitored metric improves; rank-0-gated
    (reference: keras/callbacks.py:151 BestModelCheckpoint)."""

    def __init__(self, filepath: str, monitor: str = "loss",
                 mode: str = "min",
                 save_fn: Callable[[str, Any], None] | None = None) -> None:
        self.filepath = filepath
        self.monitor = monitor
        self.mode = mode
        self.best = math.inf if mode == "min" else -math.inf
        self.save_fn = save_fn
        self._state = None

    def set_state(self, state: Any) -> None:
        self._state = state

    def _better(self, value: float) -> bool:
        return value < self.best if self.mode == "min" else value > self.best

    def on_epoch_end(self, epoch: int, logs: dict | None = None) -> None:
        if not logs or self.monitor not in logs:
            return
        import horovod_tpu as hvd
        if hvd.is_initialized() and hvd.rank() != 0:
            return
        value = float(logs[self.monitor])
        if not self._better(value):
            return
        self.best = value
        path = self.filepath.format(epoch=epoch, **logs)
        if self.save_fn is not None:
            self.save_fn(path, self._state)
        else:
            from .checkpoint import save_checkpoint
            save_checkpoint(path, self._state)


class CommitStateCallback(Callback):
    """Commit elastic state every ``batches_per_commit`` batches
    (reference: _keras/elastic.py CommitStateCallback)."""

    def __init__(self, state, batches_per_commit: int = 1) -> None:
        self.state = state
        self.batches_per_commit = batches_per_commit

    def on_batch_end(self, batch: int, logs: dict | None = None) -> None:
        if (batch + 1) % self.batches_per_commit == 0:
            self.state.commit()


class UpdateBatchStateCallback(Callback):
    """Track batch progress in elastic state so a restored worker resumes
    mid-epoch (reference: _keras/elastic.py UpdateBatchStateCallback)."""

    def __init__(self, state) -> None:
        self.state = state

    def on_batch_end(self, batch: int, logs: dict | None = None) -> None:
        self.state.batch = batch

    def on_epoch_end(self, epoch: int, logs: dict | None = None) -> None:
        self.state.epoch = epoch + 1
        self.state.batch = 0
