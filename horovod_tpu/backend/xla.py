"""XLA/ICI data plane for the eager core — the NCCL-ops analogue.

Reference: horovod/common/ops/nccl_operations.cc:61-184 (lazy communicator
creation + fused-buffer ncclAllReduce) and operations.cc:143-252 (backend
priority: NCCL beats MPI beats Gloo; here XLA beats TCP beats Basic).

Design: every Horovod rank is one JAX process in a multi-controller SPMD
world (formed at init by parallel/multihost.py). The fused flat buffer of
each rank becomes one row of a global array G of shape (size, n) sharded
over a 1-D "world" mesh spanning all processes; a cached jitted reduction
over axis 0 makes XLA emit the all-reduce over ICI/DCN. Because the
controller guarantees every rank executes identical ResponseLists in
identical order (SURVEY §5.8), all processes enqueue identical XLA programs
in identical order — the same property that keeps NCCL deadlock-free.

The compiled-program cache keyed by (op, dtype, size) is the analogue of
the reference's lazy `ncclCommInitRank` keyed by device map.
"""
from __future__ import annotations

import threading
from functools import partial

import numpy as np

from ..common.message import Response, ResponseType
from ..common.status import Status
from ..common.tensor_queue import TensorTableEntry
from .base import CollectiveBackend, accum_dtype as _accum_dtype


class XlaCommunicator:
    """Lazily-built world mesh + compiled collective cache."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._mesh = None
        self._cache: dict = {}
        self._shardings: dict = {}
        # Fused host-side encode kernels (lazy; compress/fused.py).
        self._fk = None

    def _world_sharding(self):
        """Cached NamedSharding(mesh, P("world")) — rebuilding these
        objects per call adds measurable dispatch latency on the eager
        hot path."""
        s = self._shardings.get("world")
        if s is None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            s = NamedSharding(self._world_mesh(), P("world"))
            self._shardings["world"] = s
        return s

    def _cached_program(self, key: tuple, build):
        """Double-checked compiled-program cache (the lazy-communicator
        analogue, reference: nccl_operations.cc:61-94)."""
        with self._lock:
            fn = self._cache.get(key)
        if fn is None:
            built = build()
            with self._lock:
                fn = self._cache.setdefault(key, built)
        return fn

    def _world_mesh(self):
        with self._lock:
            if self._mesh is None:
                import jax
                from jax.sharding import Mesh

                rows = []
                for p in range(jax.process_count()):
                    rows.append([d for d in jax.devices()
                                 if d.process_index == p])
                counts = {len(r) for r in rows}
                if len(counts) != 1:
                    raise RuntimeError(
                        "uneven local device counts across processes: "
                        f"{rows}")
                self._mesh = Mesh(np.array(rows), ("world", "local"))
            return self._mesh

    # -- allreduce -------------------------------------------------------
    def _reduce_fn(self, np_dtype: np.dtype, size: int):
        def build():
            import jax
            import jax.numpy as jnp
            from jax.sharding import NamedSharding, PartitionSpec as P

            mesh = self._world_mesh()
            out_sharding = NamedSharding(mesh, P())
            # 16-bit floats accumulate in fp32 (reference:
            # collective_operations.h ScaleBuffer fp16 path; also the XLA
            # CPU backend crashes promoting 16-bit all-reduces). Averaging
            # rides the response's postscale factor, so sum is the only
            # reduction.  accum_dtype (not dtype.kind) so bf16 — numpy
            # kind 'V' — widens too, which the fp16/bf16 wire-cast codecs
            # rely on.
            widen = _accum_dtype(np_dtype) != np_dtype

            @partial(jax.jit, out_shardings=out_sharding,
                     donate_argnums=(0,))
            def _reduce(g):
                acc = g.astype(jnp.float32) if widen else g
                return jnp.sum(acc, axis=0).astype(g.dtype)

            return _reduce

        return self._cached_program(("allreduce", np_dtype.str, size),
                                    build)

    def allreduce(self, buf: np.ndarray) -> np.ndarray:
        import jax

        mesh = self._world_mesh()
        size = mesh.shape["world"]
        sharding = self._world_sharding()
        g = jax.make_array_from_process_local_data(
            sharding, buf[None, :], global_shape=(size, buf.size))
        out = self._reduce_fn(buf.dtype, size)(g)
        return np.asarray(out)

    # -- quantized allreduce (compress/ subsystem) -----------------------
    def _quantized_reduce_fn(self, codec, size: int, n: int,
                             block_size: int):
        def build():
            import jax
            from jax.sharding import NamedSharding, PartitionSpec as P

            from ..compress import jax_ops

            mesh = self._world_mesh()
            rep = NamedSharding(mesh, P())

            @partial(jax.jit, out_shardings=rep)
            def _qar(q, s, zp):
                # Replicate the QUANTIZED rows + block metadata — the
                # resharding is the all-gather, so ICI/DCN moves uint8
                # payload and fp32 scales (~1/4 of the fp32 volume for
                # int8) — then dequantize and sum locally in fp32: the
                # EQuARX shape with the quantize/dequantize fused into
                # the same XLA program as the collective.
                q = jax.lax.with_sharding_constraint(q, rep)
                s = jax.lax.with_sharding_constraint(s, rep)
                zp = jax.lax.with_sharding_constraint(zp, rep)
                deq = jax_ops.dequantize_rows(q, s, zp, codec, block_size)
                return deq.sum(axis=0)[:n]

            return _qar

        return self._cached_program(
            ("qallreduce", int(codec), size, n, block_size), build)

    def quantized_allreduce(self, buf: np.ndarray, codec,
                            block_size: int) -> np.ndarray:
        """Block-quantized allreduce: quantize host-side (one input
        quantization, shared semantics with the tcp/shm planes), exchange
        int8/uint4 payloads device-side, dequantize+sum in fp32.  Unlike
        the socket planes there is no output requantization — the reduced
        fp32 values come straight off the device — so this plane's error
        is strictly within the shared bound.

        The device half is already fused (dequant+sum is one jitted
        program — on TPU, XLA/Mosaic fuses the codec math into the
        collective pass itself); the host half dispatches between the
        single-pass fused encode (compress/fused.py, persistent scratch,
        byte-identical wire image) and the reference quantize() chain
        (HOROVOD_FUSED_KERNELS=0)."""
        import jax

        from ..common import config
        from ..compress import CompressionCodec, num_blocks, quantize

        mesh = self._world_mesh()
        size = mesh.shape["world"]
        n = buf.size
        nb = num_blocks(n, block_size)
        m = nb * block_size
        pb = m // 2 if codec == CompressionCodec.UINT4 else m
        if config.FUSED_KERNELS.get():
            from ..compress.fused import FusedKernels
            fk = self._fk
            if fk is None:
                fk = self._fk = FusedKernels()
            wire = fk.encode(buf.reshape(-1), codec, block_size,
                             ("xla",))
            meta = nb * 4
            scales = wire[:meta].view(np.float32)
            zps = wire[meta:2 * meta].view(np.float32)
            payload = fk.u8(("xla", "pad"), pb)
            pv = wire[2 * meta:]
            payload[:pv.size] = pv
            payload[pv.size:] = 0
        else:
            qb = quantize(buf, codec, block_size)
            scales, zps = qb.scales, qb.zero_points
            payload = np.zeros(pb, np.uint8)
            payload[:qb.payload.size] = qb.payload
        sharding = self._world_sharding()
        # make_array_from_process_local_data device_puts a COPY of each
        # host row, so the persistent fused scratch is safe to reuse on
        # the next op.
        g_q = jax.make_array_from_process_local_data(
            sharding, payload[None, :], global_shape=(size, pb))
        g_s = jax.make_array_from_process_local_data(
            sharding, scales[None, :], global_shape=(size, nb))
        g_z = jax.make_array_from_process_local_data(
            sharding, zps[None, :], global_shape=(size, nb))
        out = self._quantized_reduce_fn(codec, size, n, block_size)(
            g_q, g_s, g_z)
        return np.asarray(out).astype(buf.dtype, copy=False)

    # -- broadcast -------------------------------------------------------
    def _bcast_fn(self, np_dtype: np.dtype, size: int):
        def build():
            import jax
            import jax.numpy as jnp
            from jax.sharding import NamedSharding, PartitionSpec as P

            mesh = self._world_mesh()
            out_sharding = NamedSharding(mesh, P())

            @partial(jax.jit, out_shardings=out_sharding)
            def _bcast(g, root):
                # Masked sum == select the root row, stays shard-friendly
                # (no data-dependent gather across the world axis).
                rows = jnp.arange(g.shape[0])[:, None]
                masked = jnp.where(rows == root, g, jnp.zeros_like(g))
                return masked.sum(axis=0).astype(g.dtype)

            return _bcast

        return self._cached_program(("broadcast", np_dtype.str, size),
                                    build)

    def broadcast(self, buf: np.ndarray, root: int) -> np.ndarray:
        import jax

        mesh = self._world_mesh()
        size = mesh.shape["world"]
        sharding = self._world_sharding()
        g = jax.make_array_from_process_local_data(
            sharding, buf[None, :], global_shape=(size, buf.size))
        out = self._bcast_fn(buf.dtype, size)(g, np.int32(root))
        return np.asarray(out)

    # -- allgather(v) ----------------------------------------------------
    def _gather_fn(self, np_dtype: np.dtype, size: int, n: int):
        def build():
            import jax
            from jax.sharding import NamedSharding, PartitionSpec as P

            mesh = self._world_mesh()
            out_sharding = NamedSharding(mesh, P())

            # Identity with a replicated out-sharding: XLA inserts the
            # all-gather over the world axis (the device analogue of
            # NCCLAllgather, reference: nccl_operations.cc:434-559).
            @partial(jax.jit, out_shardings=out_sharding)
            def _gather(g):
                return g

            return _gather

        return self._cached_program(("allgather", np_dtype.str, size, n),
                                    build)

    def allgatherv(self, local: np.ndarray,
                   first_dims: list[int]) -> np.ndarray:
        """Ragged allgather: per-rank blocks differ in dim 0.  Blocks are
        padded to the max first dim so one dense XLA all-gather moves the
        data; padding is stripped host-side."""
        import jax

        mesh = self._world_mesh()
        size = mesh.shape["world"]
        rest = tuple(local.shape[1:])
        rest_elems = int(np.prod(rest)) if rest else 1
        maxd = max(first_dims)
        padded = np.zeros(maxd * rest_elems, dtype=local.dtype)
        padded[:local.size] = local.reshape(-1)
        sharding = self._world_sharding()
        g = jax.make_array_from_process_local_data(
            sharding, padded[None, :],
            global_shape=(size, maxd * rest_elems))
        full = np.asarray(self._gather_fn(local.dtype, size,
                                          maxd * rest_elems)(g))
        blocks = [full[r, :first_dims[r] * rest_elems]
                  .reshape((first_dims[r],) + rest) for r in range(size)]
        return np.concatenate(blocks, axis=0)

    # -- alltoall(v) -----------------------------------------------------
    def _a2a_fn(self, np_dtype: np.dtype, size: int, blk: int):
        def build():
            import jax
            import jax.numpy as jnp
            from jax.sharding import NamedSharding, PartitionSpec as P

            mesh = self._world_mesh()
            out_sharding = NamedSharding(mesh, P("world"))

            # Sharded transpose of the (sender, receiver, payload) cube:
            # XLA lowers the resharding to an all-to-all over the world
            # axis (reference: nccl_operations.cc:567-619 grouped
            # ncclSend/ncclRecv).
            @partial(jax.jit, out_shardings=out_sharding)
            def _a2a(g):
                return jnp.swapaxes(g, 0, 1)

            return _a2a

        return self._cached_program(("alltoall", np_dtype.str, size, blk),
                                    build)

    def alltoallv(self, local: np.ndarray, splits: list[int]
                  ) -> tuple[np.ndarray, list[int]]:
        """Send splits[j] dim-0 rows to rank j; return (received rows in
        rank order, per-rank received splits).  Ragged splits are padded to
        the global max block so the exchange is one dense device
        all-to-all."""
        import jax

        mesh = self._world_mesh()
        size = mesh.shape["world"]
        my_rank = jax.process_index()
        rest = tuple(local.shape[1:])
        rest_elems = int(np.prod(rest)) if rest else 1

        # Every rank needs the full splits matrix (row r = rank r's
        # splits): received splits + pad bound both come from it.
        matrix = self.allgatherv(
            np.asarray(splits, dtype=np.int64).reshape(size, 1),
            [size] * size).reshape(size, size)
        received_splits = [int(x) for x in matrix[:, my_rank]]
        maxblk = int(matrix.max()) * rest_elems
        if maxblk == 0:
            empty = np.zeros((0,) + rest, dtype=local.dtype)
            return empty, received_splits

        bounds = np.cumsum([0] + list(splits))
        send = np.zeros((size, maxblk), dtype=local.dtype)
        for j in range(size):
            blk = local[bounds[j]:bounds[j + 1]]
            send[j, :blk.size] = blk.reshape(-1)
        sharding = self._world_sharding()
        g = jax.make_array_from_process_local_data(
            sharding, send[None], global_shape=(size, size, maxblk))
        out = self._a2a_fn(local.dtype, size, maxblk)(g)
        shard = np.asarray(out.addressable_shards[0].data)[0]  # [size, blk]
        blocks = [shard[r, :received_splits[r] * rest_elems]
                  .reshape((received_splits[r],) + rest)
                  for r in range(size)]
        return np.concatenate(blocks, axis=0), received_splits

    # -- reducescatter ---------------------------------------------------
    def _rs_fn(self, np_dtype: np.dtype, size: int, dim0: int,
               rest_elems: int):
        def build():
            import jax
            import jax.numpy as jnp
            from jax.sharding import NamedSharding, PartitionSpec as P

            mesh = self._world_mesh()
            out_sharding = NamedSharding(mesh, P("world"))
            widen = np_dtype.kind == "f" and np_dtype.itemsize <= 2

            # Sum over the world axis with a world-sharded output: XLA
            # emits a true reduce-scatter (half the bytes of
            # allreduce+slice; reference: nccl ReduceScatter leg of
            # NCCLHierarchicalAllreduce, nccl_operations.cc:187-398).
            @partial(jax.jit, out_shardings=out_sharding,
                     donate_argnums=(0,))
            def _rs(g):
                acc = g.astype(jnp.float32) if widen else g
                red = jnp.sum(acc, axis=0).astype(g.dtype)
                return red.reshape(dim0, rest_elems)

            return _rs

        return self._cached_program(
            ("reducescatter", np_dtype.str, size, dim0, rest_elems), build)

    def reducescatter(self, local: np.ndarray) -> np.ndarray:
        """Reduce over ranks, scatter dim-0 slices; local: [dim0, ...] with
        dim0 divisible by the world size.  Returns this rank's slice."""
        import jax

        mesh = self._world_mesh()
        size = mesh.shape["world"]
        dim0 = local.shape[0]
        rest = tuple(local.shape[1:])
        rest_elems = int(np.prod(rest)) if rest else 1
        sharding = self._world_sharding()
        g = jax.make_array_from_process_local_data(
            sharding, local.reshape(1, -1),
            global_shape=(size, dim0 * rest_elems))
        out = self._rs_fn(local.dtype, size, dim0, rest_elems)(g)
        shard = np.asarray(out.addressable_shards[0].data)
        return shard.reshape((dim0 // size,) + rest)


class XlaBackend(CollectiveBackend):
    """Device data plane: fused allreduce/broadcast via XLA collectives.

    Sits ahead of TcpBackend in the op-manager chain; `enabled()` is the
    Enabled()-priority contract (reference: operations.cc:143-252) — it
    claims a response only when the JAX world spans the full Horovod world
    and the op+dtype are supported, otherwise the response falls through
    to the TCP ring.
    """

    name = "xla"

    _SUPPORTED = (ResponseType.ALLREDUCE, ResponseType.BROADCAST,
                  ResponseType.ALLGATHER, ResponseType.ALLTOALL,
                  ResponseType.REDUCESCATTER)

    def __init__(self, comm: XlaCommunicator, world_size: int) -> None:
        self.comm = comm
        self.world_size = world_size

    def enabled(self, response: Response,
                entries: list[TensorTableEntry]) -> bool:
        if response.response_type not in self._SUPPORTED:
            return False
        try:
            import jax
            if jax.process_count() != self.world_size:
                return False
        except Exception:  # noqa: BLE001
            return False
        from ..common.dtypes import to_numpy
        np_dtype = np.dtype(to_numpy(response.tensor_type))
        if np_dtype.kind not in "fiu":
            return False
        if np_dtype.itemsize == 8:
            # Without jax_enable_x64, device_put silently canonicalizes
            # 64-bit dtypes to 32-bit — wrapping int64s and truncating
            # float64s. Decline so they ride the (exact) TCP ring.
            import jax
            if not jax.config.jax_enable_x64:
                return False
        if response.response_type == ResponseType.ALLGATHER:
            # Degenerate all-empty gathers fall through to the TCP plane
            # (a zero-size device program buys nothing).
            return bool(response.tensor_sizes) and \
                max(response.tensor_sizes) > 0
        if response.response_type == ResponseType.REDUCESCATTER:
            # The device reduce-scatter shards dim 0 evenly over the
            # world; ragged splits ride the TCP plane.
            for e in entries:
                if e.tensor is None:
                    return False
                if np.asarray(e.tensor).shape[0] % self.world_size:
                    return False
        if response.response_type == ResponseType.ALLTOALL:
            if any(e.tensor is None for e in entries):
                return False
        return True

    def allreduce(self, response: Response,
                  entries: list[TensorTableEntry]) -> Status:
        buf = self.pack_fusion_buffer(response, entries)
        buf = self.scale_buffer(buf, response.prescale_factor)
        np_dtype = buf.dtype
        codec = self.quantized_codec(response)
        if codec is not None:
            self._act_start(entries, "XLA_QUANTIZED_ALLREDUCE")
            try:
                buf = self.comm.quantized_allreduce(
                    np.ascontiguousarray(buf), codec,
                    self.codec_block_size(response))
            finally:
                self._act_end(entries)
        else:
            wire_dt = self.wire_cast_dtype(response)
            if wire_dt is not None:
                buf = buf.astype(wire_dt)
            self._act_start(entries, "XLA_ALLREDUCE")
            try:
                buf = self.comm.allreduce(np.ascontiguousarray(buf))
            finally:
                self._act_end(entries)
            buf = buf.astype(np_dtype, copy=False)
        buf = self.scale_buffer(buf, response.postscale_factor)
        self.unpack_fusion_buffer(buf, response, entries)
        return Status.ok()

    def broadcast(self, response: Response,
                  entries: list[TensorTableEntry]) -> Status:
        self._act_start(entries, "XLA_BCAST")
        try:
            return self._broadcast_traced(response, entries)
        finally:
            self._act_end(entries)

    def _broadcast_traced(self, response, entries) -> Status:
        from ..common.dtypes import to_numpy
        dtype = np.dtype(to_numpy(response.tensor_type))
        for i, e in enumerate(entries):
            n = response.tensor_sizes[i] if i < len(response.tensor_sizes) \
                else int(np.asarray(e.tensor).size)
            if e.tensor is not None:
                local = np.ascontiguousarray(
                    np.asarray(e.tensor, dtype=dtype).reshape(-1))
                shape = np.asarray(e.tensor).shape
            else:
                local = np.zeros(n, dtype=dtype)
                shape = (n,)
            out = self.comm.broadcast(local, response.root_rank)
            e.output = out.reshape(shape)
        return Status.ok()

    def allgather(self, response: Response,
                  entries: list[TensorTableEntry]) -> Status:
        from ..common.dtypes import to_numpy
        self._act_start(entries, "XLA_ALLGATHER")
        try:
            dtype = np.dtype(to_numpy(response.tensor_type))
            size = self.world_size
            if len(entries) == 1:
                dims = self.allgather_entry_dims(response, 1, size)
                local = np.ascontiguousarray(
                    np.asarray(entries[0].tensor, dtype=dtype))
                entries[0].output = self.comm.allgatherv(local, dims[0])
                return Status.ok()
            # Fused response: one padded device all-gather moves every
            # entry's packed bytes (same layout as the TCP plane).
            locals_, dims, rests, per_rank, payload = \
                self.pack_fused_allgather(response, entries, dtype, size)
            full = self.comm.allgatherv(payload, per_rank)
            self.unpack_fused_allgather(full, entries, locals_, dims,
                                        rests, dtype, per_rank)
            return Status.ok()
        finally:
            self._act_end(entries)

    def alltoall(self, response: Response,
                 entries: list[TensorTableEntry]) -> Status:
        from ..common.dtypes import to_numpy
        self._act_start(entries, "XLA_ALLTOALL")
        try:
            dtype = np.dtype(to_numpy(response.tensor_type))
            for e in entries:
                local = np.ascontiguousarray(
                    np.asarray(e.tensor, dtype=dtype))
                splits = self.resolve_alltoall_splits(e, local.shape[0],
                                                      self.world_size)
                if isinstance(splits, Status):
                    return splits
                e.output, e.received_splits = self.comm.alltoallv(local,
                                                                  splits)
            return Status.ok()
        finally:
            self._act_end(entries)

    def reducescatter(self, response: Response,
                      entries: list[TensorTableEntry]) -> Status:
        from ..common.dtypes import to_numpy
        self._act_start(entries, "XLA_REDUCESCATTER")
        try:
            dtype = np.dtype(to_numpy(response.tensor_type))
            prescale = response.prescale_factor
            postscale = response.postscale_factor
            for e in entries:
                local = np.ascontiguousarray(
                    np.asarray(e.tensor, dtype=dtype))
                buf = self.scale_buffer(local.reshape(-1),
                                        prescale).reshape(local.shape)
                out = self.comm.reducescatter(buf)
                e.output = self.scale_buffer(out.reshape(-1),
                                             postscale).reshape(out.shape)
            return Status.ok()
        finally:
            self._act_end(entries)
