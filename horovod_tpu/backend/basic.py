"""Single-process (world size 1) backend.

With one rank every collective degenerates: allreduce = scale-by-factors
copy, allgather/broadcast = identity copy, alltoall = split passthrough.
This is the terminal fallback in the priority chain, mirroring how the
reference always has a CPU op available (reference: operations.cc:143-252).
"""
from __future__ import annotations

import numpy as np

from ..common.message import Response
from ..common.status import Status
from ..common.tensor_queue import TensorTableEntry
from .base import CollectiveBackend


class BasicBackend(CollectiveBackend):
    name = "basic"
    # Purely rank-local (no shared wire/protocol state beyond the
    # per-instance fusion buffers core.init builds per stream).
    stream_safe = True

    def __init__(self, size: int = 1) -> None:
        self._size = size
        # Telemetry (no-op when HOROVOD_METRICS=off): single-rank worlds
        # still show their degenerate collectives in the same counters.
        from ..telemetry import metrics as _tm_metrics
        self._m_ops = _tm_metrics().counter(
            "horovod_basic_ops_total",
            "Degenerate single-rank collectives executed locally")

    def enabled(self, response, entries) -> bool:
        return self._size == 1

    def allreduce(self, response: Response,
                  entries: list[TensorTableEntry]) -> Status:
        buf = self.pack_fusion_buffer(response, entries)
        factor = response.prescale_factor * response.postscale_factor
        buf = self.scale_buffer(buf, factor)
        self.unpack_fusion_buffer(buf, response, entries)
        self._m_ops.inc()
        return Status.ok()

    def allgather(self, response, entries) -> Status:
        for e in entries:
            e.output = np.asarray(e.tensor)
        return Status.ok()

    def broadcast(self, response, entries) -> Status:
        for e in entries:
            e.output = np.asarray(e.tensor)
        return Status.ok()

    def alltoall(self, response, entries) -> Status:
        for e in entries:
            e.output = np.asarray(e.tensor)
            e.received_splits = list(e.splits) if e.splits else \
                [np.asarray(e.tensor).shape[0]]
        return Status.ok()

    def reducescatter(self, response, entries) -> Status:
        buf = self.pack_fusion_buffer(response, entries)
        factor = response.prescale_factor * response.postscale_factor
        buf = self.scale_buffer(buf, factor)
        self.unpack_fusion_buffer(buf, response, entries)
        return Status.ok()
