"""Hierarchical (two-level) TCP collectives over the local/cross topology.

Eager-plane analogue of the reference's NCCLHierarchicalAllreduce
(reference: horovod/common/ops/nccl_operations.cc:187-398 — ReduceScatter
over the intra-node communicator, cross-node allreduce of the owned shard,
AllGather over the intra-node communicator) and MPIHierarchicalAllgather
(reference: horovod/common/ops/mpi_operations.cc — node-local gather, then
cross-node exchange of whole node blocks).

On TPU pods the intra-host leg rides loopback/ICI-adjacent links and the
cross leg rides DCN, so the two-level schedule moves only 1/local_size of
the payload across the slow axis.  Enabled by HOROVOD_HIERARCHICAL_ALLREDUCE
/ HOROVOD_HIERARCHICAL_ALLGATHER (launcher flags --hierarchical-allreduce /
--hierarchical-allgather); requires a homogeneous host-major rank layout
(rank == cross_rank * local_size + local_rank), which is what the launcher
assigns.  The compiled/SPMD plane has its own equivalent
(parallel/grad_sync.py hierarchical=True); this backend covers the eager
op chain.
"""
from __future__ import annotations

import numpy as np

from ..common.message import Response, ResponseType
from ..common.status import Status
from ..common.tensor_queue import TensorTableEntry
from ..common.dtypes import to_numpy
from .base import CollectiveBackend
from .tcp import TcpCollectives


class HierarchicalTcpBackend(CollectiveBackend):
    """Two-leg allreduce/allgather over (local, cross) TCP sub-meshes.

    Sits between the XLA plane and the flat TCP ring in the op-manager
    priority chain: it refines the TCP data plane when the knobs are on,
    and never claims ops the knobs don't cover.
    """

    name = "tcp-hierarchical"

    def __init__(self, local: TcpCollectives, cross: TcpCollectives, *,
                 allreduce_on: bool, allgather_on: bool,
                 shm_local=None,
                 levels: list[TcpCollectives] | None = None) -> None:
        # Generalized reduction ladder, innermost (fastest links) first.
        # The classic host×slot case is exactly two levels [local, cross];
        # a torus is [row, col]; deeper fabrics (slot×host×pod) pass more.
        # Every rank descends the SAME ladder (level sizes come from the
        # launcher-uniform topology), so shard bounds stay rank-symmetric.
        self.levels = list(levels) if levels else [local, cross]
        assert len(self.levels) >= 2, "hierarchical needs >= 2 levels"
        self.local = self.levels[0] if levels else local
        self.cross = self.levels[-1] if levels else cross
        self._level_names = ["local", "cross"] if len(self.levels) == 2 \
            else [f"l{i}" for i in range(len(self.levels) - 1)] + ["top"]
        # Optional same-host shm world over the LOCAL ranks: the
        # intra-host legs then ride mmap regions instead of TCP loopback
        # (the NCCL-intra-node analogue; ~2x on multi-rank hosts).  The
        # decision is per-host — hosts with and without shm interoperate
        # because the cross-leg traffic pattern is identical either way.
        # Only meaningful for the two-level ladder (its 3-barrier protocol
        # assumes exactly one descend leg).
        self.shm_local = shm_local if len(self.levels) == 2 else None
        self.allreduce_on = allreduce_on
        self.allgather_on = allgather_on
        # Per-leg observability: op counts and analytic payload volumes.
        # Tests (and PERFORMANCE.md) use these to prove the knob changes
        # the executed path, independent of whether a leg took the native
        # C++ ring or the Python fallback.  Two-level keys are unchanged
        # from the pre-multi-level backend (local_rs/cross_ar/local_ag).
        self.leg_ops = {}
        for name in self._level_names[:-1]:
            self.leg_ops[f"{name}_rs"] = 0
            self.leg_ops[f"{name}_ag"] = 0
        self.leg_ops[f"{self._level_names[-1]}_ar"] = 0
        self.leg_ops["local_gather"] = 0
        self.leg_ops["cross_gather"] = 0
        self.leg_bytes = dict(self.leg_ops)

    def enabled(self, response: Response,
                entries: list[TensorTableEntry]) -> bool:
        rt = response.response_type
        if rt == ResponseType.ALLREDUCE:
            return self.allreduce_on
        if rt == ResponseType.ALLGATHER:
            return self.allgather_on
        return False

    def _use_shm_legs(self, wire_dtype: np.dtype, nbytes: int) -> bool:
        from .base import accum_dtype as _accum_dtype
        # poison_seen (not bare `formed`): after any host-local rank
        # poisons — e.g. its cross leg threw after op t — EVERY local
        # rank must decline the shm legs for op t+1 together, or the
        # fallen-back rank blocks in the TCP local legs while its peers
        # error inside the shm protocol (the same unanimous-decline rule
        # as ShmBackend.enabled()).
        return (self.shm_local is not None
                and not self.shm_local.poison_seen()
                and nbytes <= self.shm_local.capacity
                # 16-bit wires keep the TCP legs: those stay in one fp32
                # accumulation across all three legs, which the wire-dtype
                # shm regions cannot represent.
                and _accum_dtype(wire_dtype) == wire_dtype)

    # -- allreduce: RS(levels 0..k-1) -> AR(top) -> AG(k-1..0) ------------
    def allreduce(self, response: Response,
                  entries: list[TensorTableEntry]) -> Status:
        from .base import accum_dtype as _accum_dtype

        self.last_algo = "hierarchical"
        buf = self.pack_fusion_buffer(response, entries)
        buf = self.scale_buffer(buf, response.prescale_factor)
        wire_dtype = buf.dtype
        nbytes = buf.size * wire_dtype.itemsize
        # Plane selection is world-symmetric (the shm world forms only
        # when every rank attached the identical region at init, and
        # (dtype, nbytes) come from the negotiated response) and both
        # arms' collectives run through sub-mesh receivers — hvdflow's
        # symmetric-per-submesh demotion (SUBMESH_ATTRS) documents this
        # as a warning instead of an HVD601 error, so no suppression.
        if self._use_shm_legs(wire_dtype, nbytes):
            return self._allreduce_shm_local(response, entries, buf)
        # Accumulate ALL legs in the widened dtype: each leg's round-trip
        # through TcpCollectives returns its input dtype, so a 16-bit wire
        # buffer would otherwise be rounded between legs — numerics
        # diverging from the flat ring's single fp32 accumulation.
        buf = np.ascontiguousarray(buf.astype(_accum_dtype(wire_dtype),
                                              copy=False))
        names = self._level_names
        item = wire_dtype.itemsize

        # Descend: reduce-scatter through every inner level; after level i
        # this rank owns shard index levels[i].rank of the previous shard.
        # Shard bounds at each level are a pure function of (payload size,
        # level sizes), so every member of each sub-mesh runs an identical
        # leg set — symmetric-per-submesh, demoted by hvdflow's
        # SUBMESH_ATTRS rule rather than suppressed.
        shard = buf
        sizes_stack: list[list[int]] = []
        for i, level in enumerate(self.levels[:-1]):
            base, rem = divmod(shard.size, level.size)
            sizes = [base + (1 if j < rem else 0)
                     for j in range(level.size)]
            bounds = np.cumsum([0] + sizes)
            self._act_start(entries, f"{names[i].upper()}_REDUCESCATTER")
            try:
                shard = level.reduce_scatter(
                    np.ascontiguousarray(shard), bounds)
            finally:
                self._act_end(entries)
            sizes_stack.append(sizes)
            self.leg_ops[f"{names[i]}_rs"] += 1
            self.leg_bytes[f"{names[i]}_rs"] += \
                int(bounds[-1]) * item  # analytic wire volume of the leg

        # Top leg: allreduce the owned shard across the slowest axis; only
        # 1/prod(inner sizes) of the payload crosses it — the point of the
        # schedule.  (Empty shards — more inner ranks than elements — skip
        # the exchange but still count the leg, matching 2-level behavior.)
        cross = self.levels[-1]
        if shard.size:
            self._act_start(entries, f"{names[-1].upper()}_ALLREDUCE")
            try:
                shard = cross.allreduce(np.ascontiguousarray(shard))
            finally:
                self._act_end(entries)
        self.leg_ops[f"{names[-1]}_ar"] += 1
        self.leg_bytes[f"{names[-1]}_ar"] += shard.size * item

        # Ascend: allgather the reduced shards back out, innermost last,
        # mirroring the descend exactly.
        for i in range(len(self.levels) - 2, -1, -1):
            level = self.levels[i]
            self._act_start(entries, f"{names[i].upper()}_ALLGATHER")
            try:
                shard = level.allgatherv(shard.reshape(-1), sizes_stack[i])
            finally:
                self._act_end(entries)
            self.leg_ops[f"{names[i]}_ag"] += 1
            self.leg_bytes[f"{names[i]}_ag"] += shard.size * item

        full = self.scale_buffer(shard, response.postscale_factor)
        full = full.astype(wire_dtype, copy=False)
        self.unpack_fusion_buffer(full, response, entries)
        return Status.ok()

    def _allreduce_shm_local(self, response: Response,
                             entries: list[TensorTableEntry],
                             buf: np.ndarray) -> Status:
        """Local legs over the per-host shm world, cross leg over TCP.

        Same 3-barrier sequence-word protocol as ShmBackend's chunked
        path (disjoint chunk ownership makes the in-place writes safe);
        the cross-host TCP allreduce of the owned shard slots between the
        reduce and gather phases.  Deliberately NOT shared with
        ShmBackend._allreduce_locked: that protocol has no fallible I/O
        between publishes (and a 2-rank fused fast path that cannot host
        a cross leg — hierarchical needs per-rank shard ownership), while
        this one must poison the world if the cross leg throws
        mid-protocol."""
        w = self.shm_local
        try:
            return self._shm_local_protocol(response, entries, buf)
        except BaseException:
            # A cross-leg failure between barrier publishes would leave
            # local peers spinning: poison so every rank on this host
            # raises now and falls back to the TCP planes afterwards.
            w.poison()
            raise

    def _shm_local_protocol(self, response: Response,
                            entries: list[TensorTableEntry],
                            buf: np.ndarray) -> Status:
        w = self.shm_local
        rank, size = w.rank, w.size
        np_dtype = buf.dtype
        n = buf.size
        nbytes = n * np_dtype.itemsize
        t = w._t
        w._t += 1

        base, rem = divmod(n, size)
        sizes = [base + (1 if i < rem else 0) for i in range(size)]
        bounds = np.cumsum([0] + sizes)
        lo, hi = int(bounds[rank]), int(bounds[rank + 1])

        w.wait_all(3 * t)
        my_region = w.data(rank)[:nbytes].view(np_dtype)
        my_region[:] = buf
        w.publish(3 * t + 1)

        # Leg 1 (shm): reduce my chunk across the local ranks' regions.
        self._act_start(entries, "LOCAL_REDUCESCATTER")
        try:
            w.wait_all(3 * t + 1)
            mine = my_region[lo:hi]
            for r in range(size):
                if r != rank:
                    mine += w.data(r)[lo * np_dtype.itemsize:
                                      hi * np_dtype.itemsize].view(np_dtype)
        finally:
            self._act_end(entries)
        self.leg_ops["local_rs"] += 1
        self.leg_bytes["local_rs"] += nbytes

        # Leg 2 (TCP): allreduce the host-reduced shard across hosts,
        # writing the result back into my chunk (peers only read their
        # OWN chunk index before the 3t+2 barrier, never mine).
        # Chunk bounds are a pure function of (payload size, local_size):
        # peers sharing this chunk index run the identical cross leg,
        # beneath one already-negotiated response — symmetric-per-submesh
        # (SUBMESH_ATTRS demotion), not suppressed.
        if hi > lo:
            self._act_start(entries, "CROSS_ALLREDUCE")
            try:
                my_region[lo:hi] = self.cross.allreduce(
                    np.ascontiguousarray(my_region[lo:hi]))
            finally:
                self._act_end(entries)
        self.leg_ops["cross_ar"] += 1
        self.leg_bytes["cross_ar"] += (hi - lo) * np_dtype.itemsize
        w.publish(3 * t + 2)

        # Leg 3 (shm): gather the fully reduced chunks from their owners.
        self._act_start(entries, "LOCAL_ALLGATHER")
        try:
            w.wait_all(3 * t + 2)
            out = np.empty(n, dtype=np_dtype)
            for r in range(size):
                rlo, rhi = int(bounds[r]), int(bounds[r + 1])
                if rhi > rlo:
                    out[rlo:rhi] = w.data(r)[rlo * np_dtype.itemsize:
                                             rhi * np_dtype.itemsize
                                             ].view(np_dtype)
            w.publish(3 * t + 3)
        finally:
            self._act_end(entries)
        self.leg_ops["local_ag"] += 1
        self.leg_bytes["local_ag"] += nbytes

        out = self.scale_buffer(out, response.postscale_factor)
        self.unpack_fusion_buffer(out, response, entries)
        return Status.ok()

    # -- allgather: gather(local) -> gather node blocks (cross) ------------
    def allgather(self, response: Response,
                  entries: list[TensorTableEntry]) -> Status:
        """Node-local gather, then one exchange of whole node blocks —
        and for a fused response the packing happens ONCE: every entry's
        block rides a single local gather and a single cross exchange
        (reference: mpi_operations.cc MPIHierarchicalAllgather, which
        likewise moves the node block as one unit), instead of 2×N
        collectives for N fused tensors.

        Packed byte layout (shared with the flat planes'
        unpack_fused_allgather): rank-major, entry-major within a rank;
        the global rank order is host-major × local-rank-major, so
        concatenating host blocks reproduces it."""
        self.last_algo = "hierarchical"
        lsize = self.local.size
        csize = self.cross.size
        crank = self.cross.rank
        np_dtype = to_numpy(response.tensor_type)
        locals_, dims, rests, per_rank, payload = \
            self.pack_fused_allgather(response, entries, np_dtype,
                                      lsize * csize)

        # Leg 1: gather this host's packed rank blocks over the local
        # mesh (shm-free path rides the TCP ring; byte-level so fused
        # entries with different trailing shapes share the exchange).
        node_bytes = per_rank[crank * lsize:(crank + 1) * lsize]
        self._act_start(entries, "LOCAL_GATHER")
        try:
            node_block = self.local.allgatherv(payload, node_bytes)
        finally:
            self._act_end(entries)
        self.leg_ops["local_gather"] += 1
        self.leg_bytes["local_gather"] += node_block.size

        # Leg 2: exchange whole node blocks across hosts; only the cross
        # axis pays per-host traffic (the point of the hierarchy).
        host_bytes = [sum(per_rank[h * lsize:(h + 1) * lsize])
                      for h in range(csize)]
        self._act_start(entries, "CROSS_GATHER")
        try:
            full = self.cross.allgatherv(node_block, host_bytes)
        finally:
            self._act_end(entries)
        self.leg_ops["cross_gather"] += 1
        self.leg_bytes["cross_gather"] += full.size

        self.unpack_fused_allgather(full, entries, locals_, dims, rests,
                                    np_dtype, per_rank)
        return Status.ok()

    # Never selected (enabled() is False for these response types).
    def broadcast(self, response, entries) -> Status:
        return Status.unknown_error(
            "hierarchical backend does not implement broadcast")

    def alltoall(self, response, entries) -> Status:
        return Status.unknown_error(
            "hierarchical backend does not implement alltoall")
