"""Hierarchical (two-level) TCP collectives over the local/cross topology.

Eager-plane analogue of the reference's NCCLHierarchicalAllreduce
(reference: horovod/common/ops/nccl_operations.cc:187-398 — ReduceScatter
over the intra-node communicator, cross-node allreduce of the owned shard,
AllGather over the intra-node communicator) and MPIHierarchicalAllgather
(reference: horovod/common/ops/mpi_operations.cc — node-local gather, then
cross-node exchange of whole node blocks).

On TPU pods the intra-host leg rides loopback/ICI-adjacent links and the
cross leg rides DCN, so the two-level schedule moves only 1/local_size of
the payload across the slow axis.  Enabled by HOROVOD_HIERARCHICAL_ALLREDUCE
/ HOROVOD_HIERARCHICAL_ALLGATHER (launcher flags --hierarchical-allreduce /
--hierarchical-allgather); requires a homogeneous host-major rank layout
(rank == cross_rank * local_size + local_rank), which is what the launcher
assigns.  The compiled/SPMD plane has its own equivalent
(parallel/grad_sync.py hierarchical=True); this backend covers the eager
op chain.
"""
from __future__ import annotations

import numpy as np

from ..common.message import Response, ResponseType
from ..common.status import Status
from ..common.tensor_queue import TensorTableEntry
from ..common.dtypes import to_numpy
from .base import CollectiveBackend
from .tcp import TcpCollectives


class HierarchicalTcpBackend(CollectiveBackend):
    """Two-leg allreduce/allgather over (local, cross) TCP sub-meshes.

    Sits between the XLA plane and the flat TCP ring in the op-manager
    priority chain: it refines the TCP data plane when the knobs are on,
    and never claims ops the knobs don't cover.
    """

    name = "tcp-hierarchical"

    def __init__(self, local: TcpCollectives, cross: TcpCollectives, *,
                 allreduce_on: bool, allgather_on: bool) -> None:
        self.local = local
        self.cross = cross
        self.allreduce_on = allreduce_on
        self.allgather_on = allgather_on
        # Per-leg observability: op counts and analytic payload volumes.
        # Tests (and PERFORMANCE.md) use these to prove the knob changes
        # the executed path, independent of whether a leg took the native
        # C++ ring or the Python fallback.
        self.leg_ops = {"local_rs": 0, "cross_ar": 0, "local_ag": 0,
                        "local_gather": 0, "cross_gather": 0}
        self.leg_bytes = dict(self.leg_ops)

    def enabled(self, response: Response,
                entries: list[TensorTableEntry]) -> bool:
        rt = response.response_type
        if rt == ResponseType.ALLREDUCE:
            return self.allreduce_on
        if rt == ResponseType.ALLGATHER:
            return self.allgather_on
        return False

    # -- allreduce: RS(local) -> AR(cross) -> AG(local) -------------------
    def allreduce(self, response: Response,
                  entries: list[TensorTableEntry]) -> Status:
        from .base import accum_dtype as _accum_dtype

        buf = self.pack_fusion_buffer(response, entries)
        buf = self.scale_buffer(buf, response.prescale_factor)
        wire_dtype = buf.dtype
        nbytes = buf.size * wire_dtype.itemsize
        # Accumulate ALL THREE legs in the widened dtype: each leg's
        # round-trip through TcpCollectives returns its input dtype, so a
        # 16-bit wire buffer would otherwise be rounded between legs —
        # numerics diverging from the flat ring's single fp32 accumulation.
        buf = np.ascontiguousarray(buf.astype(_accum_dtype(wire_dtype),
                                              copy=False))

        lsize = self.local.size
        base, rem = divmod(buf.size, lsize)
        sizes = [base + (1 if i < rem else 0) for i in range(lsize)]
        bounds = np.cumsum([0] + sizes)

        # Leg 1: reduce-scatter across the local (intra-host) mesh; this
        # rank ends up owning the fully host-reduced shard local_rank.
        self._act_start(entries, "LOCAL_REDUCESCATTER")
        try:
            shard = self.local.reduce_scatter(buf, bounds)
        finally:
            self._act_end(entries)
        self.leg_ops["local_rs"] += 1
        self.leg_bytes["local_rs"] += nbytes

        # Leg 2: allreduce the owned shard across hosts (same local_rank on
        # every host holds the same shard index, so the cross mesh is
        # exactly the set of peers sharing this shard).  Only 1/local_size
        # of the payload crosses the slow axis — the point of the schedule.
        if shard.size:
            self._act_start(entries, "CROSS_ALLREDUCE")
            try:
                shard = self.cross.allreduce(np.ascontiguousarray(shard))
            finally:
                self._act_end(entries)
        self.leg_ops["cross_ar"] += 1
        self.leg_bytes["cross_ar"] += \
            shard.size * wire_dtype.itemsize  # analytic wire volume

        # Leg 3: allgather the reduced shards back across the local mesh.
        self._act_start(entries, "LOCAL_ALLGATHER")
        try:
            full = self.local.allgatherv(shard.reshape(-1), sizes)
        finally:
            self._act_end(entries)
        self.leg_ops["local_ag"] += 1
        self.leg_bytes["local_ag"] += nbytes

        full = self.scale_buffer(full, response.postscale_factor)
        full = full.astype(wire_dtype, copy=False)
        self.unpack_fusion_buffer(full, response, entries)
        return Status.ok()

    # -- allgather: gather(local) -> gather node blocks (cross) ------------
    def allgather(self, response: Response,
                  entries: list[TensorTableEntry]) -> Status:
        lsize = self.local.size
        csize = self.cross.size
        crank = self.cross.rank
        dims = list(response.tensor_sizes)  # per-rank first dims, rank order
        np_dtype = to_numpy(response.tensor_type)
        for e in entries:
            local_arr = np.asarray(e.tensor, dtype=np_dtype)
            # Host-major rank layout: host h owns dims[h*lsize:(h+1)*lsize].
            node_dims = dims[crank * lsize:(crank + 1) * lsize]
            node_block = self.local.allgatherv(local_arr, node_dims)
            self.leg_ops["local_gather"] += 1
            self.leg_bytes["local_gather"] += \
                node_block.size * node_block.dtype.itemsize
            # Cross leg: exchange whole node blocks; concatenation in host
            # order reproduces global rank order.
            host_dims = [sum(dims[h * lsize:(h + 1) * lsize])
                         for h in range(csize)]
            e.output = self.cross.allgatherv(node_block, host_dims)
            self.leg_ops["cross_gather"] += 1
            self.leg_bytes["cross_gather"] += \
                e.output.size * e.output.dtype.itemsize
        return Status.ok()

    # Never selected (enabled() is False for these response types).
    def broadcast(self, response, entries) -> Status:
        return Status.unknown_error(
            "hierarchical backend does not implement broadcast")

    def alltoall(self, response, entries) -> Status:
        return Status.unknown_error(
            "hierarchical backend does not implement alltoall")
