"""TCP/numpy data plane — the Gloo-replacement CPU backend.

Reference: horovod/common/ops/gloo_operations.{cc,h} (ring / halving-doubling
CPU collectives) and gloo's connectFullMesh bootstrap.  Used when the world
has multiple processes but no shared XLA mesh: multi-process CPU tests and
the control-plane-only deployments.  Bulk payloads ride a dedicated
full-mesh socket set (PeerMesh) so they never interleave with controller
messages.

Pipelined zero-copy engine: sends are enqueued on the mesh's persistent
per-peer sender lanes (no per-step thread spawn — enforced by hvdlint
HVD1001) straight from the accumulator's memory (no tobytes), and receives
land either directly in the destination buffer or in reusable scratch,
consumed in HOROVOD_SEGMENT_BYTES slices so the fp32 accumulate of segment
k overlaps the wire time of segment k+1 (numerics bit-identical to the
monolithic path — same elementwise adds, same order).

Fused computation-collective kernels (compress/fused.py): the quantized
and cast codec legs dispatch per codec between the single-pass fused
kernels (decode+accumulate straight off the wire into the fp32
accumulator, requantize straight into a persistent wire image — zero
steady-state allocations) and the reference per-chunk
dequantize/from_bytes/add/quantize chain kept as the A/B baseline and
fallback (HOROVOD_FUSED_KERNELS; the autotuner sweeps it).  Both paths
are bitwise identical: same IEEE fp32 ops, same rank-order accumulation.

Algorithms:
- allreduce: ring reduce-scatter + ring allgather (bandwidth-optimal,
  2(N-1)/N · bytes per link) with fp32 accumulation for 16-bit dtypes;
- allgatherv: ring rotation of variable-size blocks;
- broadcast: binomial tree from the root (O(log N) latency);
- alltoall: pairwise exchange over the sender lanes (cycle-deadlock free).
"""
from __future__ import annotations

import time

import numpy as np

from ..common import config
from ..common.message import Response, ResponseType
from ..common.status import Status
from ..common.tensor_queue import TensorTableEntry
from ..common.dtypes import to_numpy
from ..runner.network import PeerMesh
from .base import (CollectiveBackend, accum_dtype as _accum_dtype,
                   dim0_row_bounds)


def _bv(arr: np.ndarray) -> memoryview:
    """Flat byte view of a C-contiguous array — the zero-copy payload/
    destination handed to the mesh's send lanes and recv_into."""
    return memoryview(arr.reshape(-1).view(np.uint8))


class TcpCollectives:
    """Raw collective algorithms over a PeerMesh (rank-symmetric calls)."""

    def __init__(self, mesh: PeerMesh,
                 segment_bytes: int | None = None,
                 fused: bool | None = None,
                 ring_order: list[int] | None = None,
                 torus: tuple[int, int] | None = None,
                 algo: str | None = None,
                 tree_threshold: int | None = None) -> None:
        self.mesh = mesh
        self.rank = mesh.rank
        self.size = mesh.size
        # Pipeline granularity for the segmented receive+accumulate (the
        # autotuner may retune this at runtime through
        # ResponseList.tuned_segment_bytes); 0 = monolithic receives.
        self.segment_bytes = config.SEGMENT_BYTES.get() \
            if segment_bytes is None else int(segment_bytes)
        # Topology-aware ring order (common/topology.py): a permutation
        # of ranks in ring-walk order.  The allreduce ring sends to the
        # NEXT position and chunk ownership follows position, so a torus
        # snake / host-grouped order keeps every hop on a neighbor link.
        # Identity (the default) reproduces the pre-topology schedule
        # bit-for-bit.  The permutation is launcher-uniform
        # (HOROVOD_TOPOLOGY), so positions are rank-symmetric.
        if ring_order is not None:
            order = [int(r) for r in ring_order]
            assert sorted(order) == list(range(self.size)), order
            self._order = order
            self._pos = order.index(self.rank)
        else:
            self._order = list(range(self.size))
            self._pos = self.rank
        # Declared torus shape (rows, cols) with rank = row*cols + col;
        # None = no torus, the two-phase algorithm is ineligible.
        self._torus = None
        if torus is not None and torus[0] * torus[1] == self.size:
            self._torus = (int(torus[0]), int(torus[1]))
        # Allreduce algorithm selection (HOROVOD_ALGO) and the small-
        # tensor crossover (HOROVOD_TREE_THRESHOLD_BYTES).  Both are
        # runtime-tunable through ResponseList.tuned_algo /
        # tuned_tree_threshold — applied before dispatch on every rank,
        # so selection (a pure function of these fields and the
        # negotiated payload size) can never diverge across ranks.
        self.algo = config.ALGO.get() if algo is None else str(algo)
        self.tree_threshold = config.TREE_THRESHOLD_BYTES.get() \
            if tree_threshold is None else int(tree_threshold)
        # Algorithm the last allreduce actually executed (telemetry's
        # algo= label reads it through the owning backend).
        self.last_algo = "ring"
        # Fused single-pass codec kernels (compress/fused.py) vs the
        # reference per-chunk dequant/requant chain — runtime-tunable
        # through ResponseList.tuned_fused, swept by the autotuner.
        self.fused = config.FUSED_KERNELS.get() if fused is None \
            else bool(fused)
        from ..compress.fused import FusedKernels
        self._fk = FusedKernels()
        # Per-(peer, dtype) ndarray views over the channels' scratch
        # bytearrays: the segmented accumulate reuses ONE typed view per
        # channel instead of a fresh np.frombuffer wrapper per segment
        # (allocation churn visible in the per-plane latency histograms).
        self._seg_views: dict = {}
        # Segment-overlap efficiency (telemetry/): bytes whose fp32
        # accumulate overlapped the wire (segmented path) vs bytes that
        # arrived monolithically.  No-op metrics when HOROVOD_METRICS=off.
        from ..telemetry import metrics as _tm_metrics
        _tm = _tm_metrics()
        self._m_seg_bytes = _tm.counter(
            "horovod_tcp_segmented_recv_bytes_total",
            "Ring-chunk bytes consumed through the segmented "
            "receive+accumulate (comm/compute overlapped)")
        self._m_mono_bytes = _tm.counter(
            "horovod_tcp_monolithic_recv_bytes_total",
            "Ring-chunk bytes consumed in one monolithic receive "
            "(chunk below segment size, or segmentation off)")
        # Per-leg fused-vs-reference latency histograms: the codec legs
        # record wall time under {leg, fused} labels so the fusion win
        # (or regression) is visible straight in the metrics dump.
        self._tm_on = getattr(_tm, "enabled", False)
        self._m_leg = {
            (leg, fused): _tm.histogram(
                "horovod_tcp_codec_leg_ms",
                "Wall time of one codec-collective leg (gather = "
                "contributions in + fp32 accumulate, return = reduced "
                "chunks out), split by fused-kernel vs reference "
                "dispatch",
                labels={"leg": leg, "fused": "on" if fused else "off"})
            for leg in ("gather", "return") for fused in (True, False)}

    # -- helpers --------------------------------------------------------
    def _sendrecv(self, to_rank: int, payload: bytes,
                  from_rank: int) -> bytearray:
        """Concurrent send+recv so rings/pairwise exchanges cannot deadlock
        on filled socket buffers: the send streams on the peer's
        persistent sender lane while this thread blocks in recv."""
        self.mesh.send_async(to_rank, payload)
        return self.mesh.recv(from_rank)  # hvdlint: disable=unbounded-blocking-wait -- bounded inside the peer channel (socket poll timeout + op deadline under HOROVOD_FAULT_TOLERANCE)

    def _scratch_view(self, frm: int, view: memoryview,
                      dtype: np.dtype) -> np.ndarray:
        """Persistent typed ndarray over the peer channel's scratch
        bytearray (satellite of the fused-kernel work: one cached view
        per (peer, dtype) instead of an np.frombuffer wrapper per
        segment).  Invalidated automatically when the channel grows its
        scratch — the underlying bytearray object changes identity."""
        base = view.obj
        key = (frm, dtype.str)
        cached = self._seg_views.get(key)
        if cached is None or cached[0] is not base:
            arr = np.frombuffer(base, dtype=dtype,
                                count=len(base) // dtype.itemsize)
            self._seg_views[key] = (base, arr)
            return arr
        return cached[1]

    def _recv_accum(self, frm: int, acc_slice: np.ndarray) -> None:
        """Receive one ring chunk from `frm`, adding it into `acc_slice`
        in segment_bytes slices so the adds of segment k run while the
        kernel receives segment k+1.  Elementwise adds in ascending index
        order — bit-identical to one monolithic add."""
        nbytes = self.mesh.recv_begin(frm)
        assert nbytes == acc_slice.nbytes, (nbytes, acc_slice.nbytes)
        if nbytes == 0:
            return
        itemsize = acc_slice.dtype.itemsize
        seg_elems = self.segment_bytes // itemsize
        total = acc_slice.size
        if seg_elems <= 0 or seg_elems >= total:
            view = self.mesh.scratch(frm, nbytes)
            self.mesh.recv_raw_into(frm, view)
            arr = self._scratch_view(frm, view, acc_slice.dtype)
            np.add(acc_slice, arr[:total], out=acc_slice)
            self._m_mono_bytes.inc(nbytes)
            return
        self._m_seg_bytes.inc(nbytes)
        scratch = self.mesh.scratch(frm, seg_elems * itemsize)
        arr = self._scratch_view(frm, scratch, acc_slice.dtype)
        pos = 0
        while pos < total:
            k = min(seg_elems, total - pos)
            view = scratch[:k * itemsize]
            self.mesh.recv_raw_into(frm, view)
            np.add(acc_slice[pos:pos + k], arr[:k],
                   out=acc_slice[pos:pos + k])
            pos += k

    def _recv_into(self, frm: int, arr: np.ndarray) -> None:
        """Receive one framed message from `frm` straight into `arr`
        (no staging copy; `arr` must be C-contiguous)."""
        nbytes = self.mesh.recv_begin(frm)
        assert nbytes == arr.nbytes, (nbytes, arr.nbytes)
        if nbytes:
            self.mesh.recv_raw_into(frm, _bv(arr))

    def _recv_scratch(self, frm: int) -> memoryview:
        """Receive one framed message into the peer's reusable scratch;
        the view is valid until the next receive from `frm`."""
        nbytes = self.mesh.recv_begin(frm)
        view = self.mesh.scratch(frm, nbytes)
        if nbytes:
            self.mesh.recv_raw_into(frm, view)
        return view

    # -- algorithm selection --------------------------------------------
    def _select_algo(self, nbytes: int) -> str:
        """Pick the allreduce algorithm for an `nbytes` payload.

        A pure function of rank-symmetric inputs only: the negotiated
        payload size, the launcher-uniform HOROVOD_ALGO /
        HOROVOD_TREE_THRESHOLD_BYTES / HOROVOD_TOPOLOGY knobs, and the
        coordinator-broadcast tuned_algo / tuned_tree_threshold fields —
        so every rank of a response picks the identical algorithm (the
        deadlock-freedom invariant).  Feasibility fallbacks are
        themselves symmetric (world size and torus declaration are
        world-constant)."""
        algo = self.algo
        if algo == "auto":
            if 0 < self.tree_threshold and nbytes <= self.tree_threshold \
                    and self.size > 2:
                algo = "tree"
            elif self._torus is not None:
                algo = "torus"
            else:
                algo = "ring"
        if algo == "rhd" and (self.size & (self.size - 1)) != 0:
            algo = "tree"      # halving/doubling needs a power-of-two world
        if algo == "torus" and self._torus is None:
            algo = "ring"
        if self.size <= 2 and algo in ("tree", "rhd", "torus"):
            # Two ranks: every schedule degenerates to the same single
            # exchange; keep the ring (native fast path, fewer frames).
            algo = "ring"
        return algo

    # -- allreduce ------------------------------------------------------
    def allreduce(self, buf: np.ndarray) -> np.ndarray:
        """In-place-style allreduce; returns the reduced buffer.

        Dispatches per payload size (see _select_algo): segmented ring
        for bandwidth-bound tensors, binomial tree / recursive
        halving-doubling for latency-bound ones, the two-phase torus
        schedule on a declared torus.  All variants reduce in the
        widened accumulation dtype end-to-end; fp32 results may differ
        from the ring in the last ulp where the accumulation ORDER
        differs (tree root adds in rank order, rhd adds pairwise) —
        integer dtypes are exact everywhere."""
        n, size = buf.size, self.size
        if size == 1:
            return buf
        algo = self._select_algo(buf.size * buf.dtype.itemsize)
        self.last_algo = algo
        if algo != "ring":
            acc = np.ascontiguousarray(
                buf.astype(_accum_dtype(buf.dtype), copy=True))
            if algo == "tree":
                acc = self._allreduce_tree(acc)
            elif algo == "rhd":
                acc = self._allreduce_rhd(acc)
            else:
                acc = self._allreduce_torus(acc)
            return acc.astype(buf.dtype, copy=False)
        pos = self._pos
        acc = buf.astype(_accum_dtype(buf.dtype), copy=True)
        # Chunk boundaries: chunk i = [bounds[i], bounds[i+1]), owned by
        # ring POSITION i (identity order: position == rank, the
        # pre-topology schedule unchanged).
        base, rem = divmod(n, size)
        sizes = [base + (1 if i < rem else 0) for i in range(size)]
        bounds = np.cumsum([0] + sizes)
        nxt = self._order[(pos + 1) % size]
        prv = self._order[(pos - 1) % size]

        # Native C++ ring (same schedule, GIL released, SIMD adds); falls
        # through to the Python ring for unsupported dtypes/toolchains.
        # It writes the raw fds directly, so queued frames from a previous
        # op's final leg must drain first.  EXCLUDED under fault
        # tolerance/chaos: the C loop blocks on raw fds (it cannot honor
        # the per-op deadline, and the resilience socket timeouts put the
        # fds in non-blocking mode), and chaos send hooks never see its
        # traffic — the deadline-bounded Python ring is the resilient
        # path (docs/resilience.md).
        from .. import native
        acc = np.ascontiguousarray(acc)
        self.mesh.flush()
        native_ok = (self.mesh._resilience is None
                     and self.mesh._chaos is None)
        if native_ok and \
                native.ring_allreduce(self.mesh._socks[nxt].fileno(),
                                      self.mesh._socks[prv].fileno(),
                                      acc, pos, size):
            # The native path writes the raw fds directly; account its
            # known ring volume so the mesh byte counters stay truthful
            # (2(N-1) chunk sends per rank, uneven chunk split).  The C
            # loop's schedule is indexed by ring position: handing it
            # `pos` and the permuted neighbor fds IS the topology ring.
            sent = sum(sizes[(pos - s) % size] +
                       sizes[(pos + 1 - s) % size]
                       for s in range(size - 1)) * acc.dtype.itemsize
            rcvd = sum(sizes[(pos - s - 1) % size] +
                       sizes[(pos - s) % size]
                       for s in range(size - 1)) * acc.dtype.itemsize
            with self.mesh._lock:
                self.mesh.bytes_sent += sent
                self.mesh.bytes_received += rcvd
            if self.mesh._tm_on:   # per-peer attribution for the raw-fd ring
                self.mesh._tm_count_sent(nxt, sent)
                self.mesh._tm_count_recv(prv, rcvd)
            return acc.astype(buf.dtype, copy=False)

        # Reduce-scatter: after step s, this position owns-partial chunk
        # (pos - s) % size.  Send the chunk we just accumulated straight
        # from the accumulator (zero copy — never re-mutated while queued:
        # step s writes chunk (pos-s-1), which is not sent until s+1) and
        # accumulate the incoming chunk segment-by-segment.
        for step in range(size - 1):
            send_idx = (pos - step) % size
            recv_idx = (pos - step - 1) % size
            self.mesh.send_async(
                nxt, _bv(acc[bounds[send_idx]:bounds[send_idx + 1]]))
            self._recv_accum(prv, acc[bounds[recv_idx]:bounds[recv_idx + 1]])

        # Ring allgather of the fully reduced chunks, received straight
        # into their final position in the accumulator.
        for step in range(size - 1):
            send_idx = (pos + 1 - step) % size
            recv_idx = (pos - step) % size
            self.mesh.send_async(
                nxt, _bv(acc[bounds[send_idx]:bounds[send_idx + 1]]))
            self._recv_into(prv, acc[bounds[recv_idx]:bounds[recv_idx + 1]])

        # Queued frames must reach the kernel before the caller may mutate
        # the result (the pre-channel code's per-step join guaranteed it).
        self.mesh.flush()
        return acc.astype(buf.dtype, copy=False)

    # -- binomial tree primitives (small-tensor allreduce) --------------
    def _tree_low(self) -> int:
        """My subtree stride in the rank-0-rooted binomial tree: lowbit
        of the rank, or the covering power of two at the root (the same
        vrank schedule as broadcast(), with root pinned to 0)."""
        if self.rank == 0:
            low = 1
            while low < self.size:
                low <<= 1
            return low
        return self.rank & -self.rank

    def _tree_gather(self, payload, item: int) -> bytearray | None:
        """Binomial gather of one fixed-size `payload` per rank to rank
        0: internal ranks concatenate their subtree's contributions
        (subtree of rank r = ranks [r, r+lowbit(r)), so child r+m's
        block lands at slot offset m) and forward the whole block to the
        parent — log N rounds, and the root ends holding all N
        contributions ordered BY RANK.  Returns the slot buffer on rank
        0, None elsewhere.  Latency-path only: the root's O(N·item)
        memory is exactly why selection caps this at the tree
        threshold."""
        size, rank = self.size, self.rank
        low = self._tree_low()
        span = min(low, size - rank)        # my subtree = [rank, rank+span)
        block: bytearray | None = None
        if span > 1:
            block = bytearray(span * item)
            block[0:item] = payload
        # Children rank+m, ascending m: the shallow subtrees drain first
        # while the deepest (largest m) is still gathering.
        m = 1
        while m < low:
            child = rank + m
            if child < size:
                cspan = min(m, size - child)
                view = memoryview(block)[m * item:(m + cspan) * item]
                nb = self.mesh.recv_begin(child)
                assert nb == cspan * item, (nb, cspan, item)
                self.mesh.recv_raw_into(child, view)
            m <<= 1
        if rank == 0:
            return block
        parent = rank - low
        self.mesh.send_async(
            parent, payload if block is None else memoryview(block))
        return None

    def _tree_bcast_into(self, view: memoryview) -> None:
        """Binomial broadcast of rank 0's `view` into every rank's view
        (the broadcast() schedule with root pinned to 0); flushes the
        lanes so the caller may mutate the buffer on return."""
        size, rank = self.size, self.rank
        low = self._tree_low()
        if rank != 0:
            parent = rank - low
            nb = self.mesh.recv_begin(parent)
            assert nb == len(view), (nb, len(view))
            self.mesh.recv_raw_into(parent, view)
        m = low >> 1
        while m:
            child = rank + m
            if child < size:
                self.mesh.send_async(child, view)
            m >>= 1
        self.mesh.flush()

    def _allreduce_tree(self, acc: np.ndarray) -> np.ndarray:
        """Binomial-tree allreduce for latency-bound payloads: 2·log N
        rounds instead of the ring's 2(N-1).  Contributions ride the
        binomial gather to rank 0, the root accumulates all N in RANK
        ORDER in the widened dtype (the same order — hence the same fp32
        bit pattern — as the codec planes' owner-reduce), and the
        reduced buffer returns on the mirrored binomial broadcast."""
        n = acc.size
        item = acc.nbytes
        block = self._tree_gather(_bv(acc), item)
        if block is not None:               # root: rank-order accumulate
            for j in range(1, self.size):
                arr = np.frombuffer(block, dtype=acc.dtype,
                                    count=n, offset=j * item)
                np.add(acc, arr, out=acc)
        self._tree_bcast_into(_bv(acc))
        return acc

    # -- recursive halving-doubling (power-of-two worlds) ---------------
    def _allreduce_rhd(self, acc: np.ndarray) -> np.ndarray:
        """Recursive vector-halving/distance-doubling allreduce
        (reference: gloo's CPU halving-doubling, Rabenseifner): log N
        exchange rounds each moving half the live window — latency
        O(log N) like the tree with no gather hotspot at the root.
        Power-of-two worlds only (selection falls back to tree
        otherwise).  Partner pairs at mask m share an identical window
        (they agree on all lower bits), so the halves line up by
        construction."""
        size, rank = self.size, self.rank
        lo, hi = 0, acc.size
        steps: list[tuple[int, int, int]] = []
        mask = 1
        while mask < size:
            partner = rank ^ mask
            mid = (lo + hi) // 2
            steps.append((lo, hi, mid))
            if rank & mask:
                # Keep the upper half: ship [lo, mid) and fold the
                # partner's upper contribution into [mid, hi).  The sent
                # region is never re-mutated before the partner consumed
                # it (the mirrored doubling recv below happens only
                # after the partner progressed past this very frame).
                self.mesh.send_async(partner, _bv(acc[lo:mid]))
                self._recv_accum(partner, acc[mid:hi])
                lo = mid
            else:
                self.mesh.send_async(partner, _bv(acc[mid:hi]))
                self._recv_accum(partner, acc[lo:mid])
                hi = mid
            mask <<= 1
        # Distance-doubling allgather: replay the halving in reverse,
        # exchanging my fully reduced window for the partner's.
        for plo, phi, mid in reversed(steps):
            mask >>= 1
            partner = rank ^ mask
            self.mesh.send_async(partner, _bv(acc[lo:hi]))
            if lo == mid:                   # I kept the upper half
                self._recv_into(partner, acc[plo:mid])
            else:
                self._recv_into(partner, acc[mid:phi])
            lo, hi = plo, phi
        self.mesh.flush()
        return acc

    # -- two-phase torus allreduce --------------------------------------
    def _group_ring_reduce_scatter(self, group: list[int], k: int,
                                   acc: np.ndarray,
                                   bounds: np.ndarray) -> int:
        """Ring reduce-scatter among `group` (I am group[k]) over the
        caller's chunk bounds; returns the chunk index this member ends
        up owning fully reduced — the flat ring's (k+1) % len(group)."""
        m = len(group)
        nxt, prv = group[(k + 1) % m], group[(k - 1) % m]
        for step in range(m - 1):
            si = (k - step) % m
            ri = (k - step - 1) % m
            self.mesh.send_async(nxt, _bv(acc[bounds[si]:bounds[si + 1]]))
            self._recv_accum(prv, acc[bounds[ri]:bounds[ri + 1]])
        return (k + 1) % m

    def _group_ring_allgather(self, group: list[int], k: int,
                              acc: np.ndarray, bounds: np.ndarray,
                              own: int) -> None:
        """Ring allgather among `group` of the fully reduced chunks,
        starting from each member's owned chunk index."""
        m = len(group)
        nxt, prv = group[(k + 1) % m], group[(k - 1) % m]
        for step in range(m - 1):
            si = (own - step) % m
            ri = (own - step - 1) % m
            self.mesh.send_async(nxt, _bv(acc[bounds[si]:bounds[si + 1]]))
            self._recv_into(prv, acc[bounds[ri]:bounds[ri + 1]])

    def _group_ring_allreduce(self, group: list[int], k: int,
                              seg: np.ndarray) -> None:
        """In-place ring allreduce of `seg` among `group` (RS + AG over
        sub-chunks of the segment)."""
        m = len(group)
        base, rem = divmod(seg.size, m)
        sizes = [base + (1 if i < rem else 0) for i in range(m)]
        bounds = np.cumsum([0] + sizes)
        own = self._group_ring_reduce_scatter(group, k, seg, bounds)
        self._group_ring_allgather(group, k, seg, bounds, own)

    def _allreduce_torus(self, acc: np.ndarray) -> np.ndarray:
        """Two-phase torus allreduce on a declared R×C grid (reference:
        arXiv:1909.09756's 2-D schedule): ring reduce-scatter along my
        ROW, ring allreduce of the owned chunk along my COLUMN, ring
        allgather back along the row.  Every hop stays on a grid-
        neighbor link, and each phase's ring spans only one axis —
        2(C-1)/C + 2(R-1)/(R·C) bytes per link instead of the flat
        ring's 2(N-1)/N over arbitrary-distance hops."""
        rows, cols = self._torus
        row, col = divmod(self.rank, cols)
        row_group = [row * cols + j for j in range(cols)]
        col_group = [i * cols + col for i in range(rows)]
        base, rem = divmod(acc.size, cols)
        sizes = [base + (1 if j < rem else 0) for j in range(cols)]
        bounds = np.cumsum([0] + sizes)
        own = self._group_ring_reduce_scatter(row_group, col, acc, bounds)
        seg = acc[bounds[own]:bounds[own + 1]]
        if seg.size and rows > 1:
            self._group_ring_allreduce(col_group, row, seg)
        self._group_ring_allgather(row_group, col, acc, bounds, own)
        self.mesh.flush()
        return acc

    # -- cast-codec allreduce (compress/ subsystem) ---------------------
    def cast_allreduce(self, buf: np.ndarray,
                       wire_dtype: np.dtype) -> np.ndarray:
        """Allreduce with a narrow wire dtype (fp16/bf16) that ACTUALLY
        halves socket bytes: the plain ring widens 16-bit payloads to the
        fp32 accumulation dtype before the wire, so a cast codec there
        saves nothing.  Same owner-reduce shape as the quantized path —
        each rank ships its wire-cast chunks to their owners, owners
        accumulate in fp32 and round ONCE, reduced chunks return in the
        wire dtype — so numerics match the planes' one-rounding contract
        instead of the reference's per-hop fp16 rounding.

        Dispatch: fused single-pass widen+accumulate kernels
        (compress/fused.py) when enabled, else the reference per-chunk
        astype chain.  Bitwise-identical results either way."""
        if self.size == 1:
            return buf
        # Small-tensor leg: the binomial tree composes with the codec
        # (whole-buffer contributions gather encoded, the root
        # accumulates in rank order and rounds ONCE — bitwise identical
        # to the owner-reduce below).  rhd/torus stay on the owner-
        # reduce exchange: their windowed hops would need per-hop
        # re-rounding, breaking the one-rounding contract.
        wire_dtype = np.dtype(wire_dtype)
        if self.size > 2 and self._select_algo(
                buf.size * wire_dtype.itemsize) == "tree":
            self.last_algo = "tree"
            return self._cast_allreduce_tree(buf, wire_dtype)
        self.last_algo = "ring"
        if self.fused:
            return self._cast_allreduce_fused(buf, wire_dtype)
        return self._cast_allreduce_reference(buf, wire_dtype)

    def _cast_allreduce_fused(self, buf: np.ndarray,
                              wire_dtype: np.dtype) -> np.ndarray:
        """Fused gather leg: every destination chunk is posted on the
        persistent sender lanes UP FRONT (one frame per peer — far below
        the lane queue bound), which frees this thread to receive
        contributions in ASCENDING RANK ORDER and fold each one into the
        fp32 accumulator the moment it arrives (compress/fused.py
        cast_add: one widening pass in scratch + one in-place add).
        Accumulation order is therefore exactly the reference path's
        rank-order sum — bitwise identical — without the per-peer
        astype allocations or the deferred contribution list."""
        n, rank, size = buf.size, self.rank, self.size
        from ..compress import chunk_bounds
        fk = self._fk
        wire_dtype = np.dtype(wire_dtype)
        x = np.ascontiguousarray(buf).astype(wire_dtype, copy=False)
        bounds = chunk_bounds(n, size)
        my_len = int(bounds[rank + 1] - bounds[rank])

        t0 = time.perf_counter() if self._tm_on else 0.0
        for offset in range(1, size):
            to = (rank + offset) % size
            self.mesh.send_async(to, _bv(x[bounds[to]:bounds[to + 1]]))
        acc = fk.f32(("cacc",), my_len)
        acc[:] = 0.0
        for j in range(size):                  # rank-order accumulate
            if j == rank:
                fk.cast_add(_bv(x[bounds[rank]:bounds[rank + 1]]),
                            wire_dtype, acc, ("cin",))
            else:
                view = self._recv_scratch(j)
                fk.cast_add(view, wire_dtype, acc, ("cin",))
        reduced = acc.astype(wire_dtype)       # the ONE rounding
        if self._tm_on:
            self._m_leg[("gather", True)].observe(
                (time.perf_counter() - t0) * 1e3)

        # Return leg: reduced chunks land straight in their output slice
        # (already zero-copy in the reference shape).
        t0 = time.perf_counter() if self._tm_on else 0.0
        out = np.empty(n, dtype=wire_dtype)
        out[bounds[rank]:bounds[rank + 1]] = reduced
        payload = _bv(reduced)
        for offset in range(1, size):
            to = (rank + offset) % size
            frm = (rank - offset) % size
            self.mesh.send_async(to, payload)
            self._recv_into(frm, out[bounds[frm]:bounds[frm + 1]])
        self.mesh.flush()
        if self._tm_on:
            self._m_leg[("return", True)].observe(
                (time.perf_counter() - t0) * 1e3)
        return out.astype(buf.dtype, copy=False)

    def _cast_allreduce_reference(self, buf: np.ndarray,
                                  wire_dtype: np.dtype) -> np.ndarray:
        """Reference cast path (pre-fusion): per-peer astype widening into
        a deferred contribution list, rank-order sum at the end.  Kept as
        the A/B baseline and the HOROVOD_FUSED_KERNELS=0 fallback."""
        n, rank, size = buf.size, self.rank, self.size
        from ..compress import chunk_bounds
        wire_dtype = np.dtype(wire_dtype)
        x = np.ascontiguousarray(buf).astype(wire_dtype, copy=False)
        bounds = chunk_bounds(n, size)
        my_len = int(bounds[rank + 1] - bounds[rank])

        # Owner-reduce gather leg: each peer's wire-dtype contribution is
        # widened to fp32 AS IT ARRIVES (the decode overlaps the next
        # peer's in-flight bytes); the accumulation below stays in rank
        # order, so numerics are bit-identical to decode-after-gather.
        t0 = time.perf_counter() if self._tm_on else 0.0
        contrib32: list = [None] * size
        contrib32[rank] = x[bounds[rank]:bounds[rank + 1]].astype(
            np.float32)
        for offset in range(1, size):
            to = (rank + offset) % size
            frm = (rank - offset) % size
            self.mesh.send_async(to, _bv(x[bounds[to]:bounds[to + 1]]))
            view = self._recv_scratch(frm)
            contrib32[frm] = np.frombuffer(
                view, dtype=wire_dtype, count=my_len).astype(np.float32)
        acc = np.zeros(my_len, np.float32)
        for c in contrib32:                    # rank order (see above)
            acc += c
        reduced = acc.astype(wire_dtype)
        if self._tm_on:
            self._m_leg[("gather", False)].observe(
                (time.perf_counter() - t0) * 1e3)

        # Return leg: reduced chunks land straight in their output slice.
        t0 = time.perf_counter() if self._tm_on else 0.0
        out = np.empty(n, dtype=wire_dtype)
        out[bounds[rank]:bounds[rank + 1]] = reduced
        payload = _bv(reduced)
        for offset in range(1, size):
            to = (rank + offset) % size
            frm = (rank - offset) % size
            self.mesh.send_async(to, payload)
            self._recv_into(frm, out[bounds[frm]:bounds[frm + 1]])
        self.mesh.flush()
        if self._tm_on:
            self._m_leg[("return", False)].observe(
                (time.perf_counter() - t0) * 1e3)
        return out.astype(buf.dtype, copy=False)

    # -- quantized allreduce (compress/ subsystem) ----------------------
    def quantized_allreduce(self, buf: np.ndarray, codec,
                            block_size: int) -> np.ndarray:
        """Block-quantized allreduce — the EQuARX owner-reduce shape on
        sockets (PAPERS.md, arxiv 2506.17615):

          1. quantize each destination chunk of my buffer independently;
          2. pairwise-exchange the QUANTIZED chunks (scales+zp+payload)
             so each owner holds every rank's contribution to its chunk;
          3. dequantize + sum in fp32 (including my own contribution's
             dequantized form, so every rank reconstructs the identical
             value regardless of ownership);
          4. requantize the reduced chunk ONCE and exchange it pairwise.

        Wire bytes: 2(N-1)/N · quantized-size — the ring-allreduce
        structure at ~1/4 (int8) / ~1/8 (uint4) of the fp32 volume.

        Dispatch: single-pass fused dequant+accumulate+requant kernels
        (compress/fused.py) when enabled, else the reference per-chunk
        chain.  Bitwise-identical results either way (same fp32 ops,
        same rank-order accumulation), so fused and reference ranks even
        interoperate — both sides move one frame per peer per leg."""
        if self.size == 1:
            return buf
        # Small-tensor tree leg (see cast_allreduce): selection keys on
        # the LOGICAL fp32 bytes — the negotiated size every rank
        # shares, independent of codec framing.  Auto-selection is
        # additionally gated on chunk bounds aligning to quantization
        # blocks: only then do the ring's per-chunk block stats equal the
        # tree's whole-buffer stats, keeping the tree BITWISE identical
        # to the owner-reduce (and to the shm plane's schedule — the
        # cross-plane contract asserted in tests/test_compress.py).  The
        # gate is a pure function of (n, size, block_size), all
        # world-symmetric.  An explicitly pinned algo="tree" skips it —
        # the operator traded last-ulp block-stat drift for latency.
        aligned = buf.size % (self.size * block_size) == 0
        if self.size > 2 and (aligned or self.algo == "tree") and \
                self._select_algo(buf.size * 4) == "tree":
            self.last_algo = "tree"
            return self._quantized_allreduce_tree(buf, codec, block_size)
        self.last_algo = "ring"
        if self.fused:
            return self._quantized_allreduce_fused(buf, codec, block_size)
        return self._quantized_allreduce_reference(buf, codec, block_size)

    def _quantized_allreduce_fused(self, buf: np.ndarray, codec,
                                   block_size: int) -> np.ndarray:
        """Fused EQuARX legs: requantize straight into persistent wire
        images (no QuantizedBlocks objects, no to_bytes copies), each
        destination chunk posted on its sender lane the moment it is
        encoded — the encode of chunk k+1 overlaps the wire of chunk k.
        With every send in flight, contributions are received in
        ASCENDING RANK ORDER and folded into the fp32 accumulator the
        moment their bytes land (decode_add: one fused dequant in
        scratch + one in-place add), so accumulation order is exactly
        the reference path's rank-order sum — bitwise identical.  The
        return leg decodes the owners' reduced chunks straight into
        their final output slices — no deferred part list, no
        concatenate."""
        n, rank, size = buf.size, self.rank, self.size
        from ..compress import chunk_bounds
        fk = self._fk
        x = np.ascontiguousarray(buf).astype(np.float32, copy=False)
        bounds = chunk_bounds(n, size)
        my_len = int(bounds[rank + 1] - bounds[rank])

        t0 = time.perf_counter() if self._tm_on else 0.0
        for offset in range(1, size):          # encode k+1 overlaps wire k
            to = (rank + offset) % size
            self.mesh.send_async(
                to, fk.encode(x[bounds[to]:bounds[to + 1]], codec,
                              block_size, ("enc", to)))
        my_wire = fk.encode(x[bounds[rank]:bounds[rank + 1]], codec,
                            block_size, ("enc", rank))
        acc = fk.f32(("qacc",), my_len)
        acc[:] = 0.0
        for j in range(size):                  # rank-order accumulate
            if j == rank:
                fk.decode_add(my_wire, my_len, codec, block_size,
                              acc, ("qin",))
            else:
                view = self._recv_scratch(j)
                fk.decode_add(view, my_len, codec, block_size,
                              acc, ("qin",))
        reduced = fk.encode(acc, codec, block_size, ("red",))
        if self._tm_on:
            self._m_leg[("gather", True)].observe(
                (time.perf_counter() - t0) * 1e3)

        t0 = time.perf_counter() if self._tm_on else 0.0
        out = np.empty(n, np.float32)
        fk.decode_into(reduced, my_len, codec, block_size,
                       out[bounds[rank]:bounds[rank + 1]], ("qout",))
        for offset in range(1, size):
            to = (rank + offset) % size
            frm = (rank - offset) % size
            self.mesh.send_async(to, reduced)
            view = self._recv_scratch(frm)
            fk.decode_into(view, int(bounds[frm + 1] - bounds[frm]),
                           codec, block_size,
                           out[bounds[frm]:bounds[frm + 1]], ("qout",))
        self.mesh.flush()
        if self._tm_on:
            self._m_leg[("return", True)].observe(
                (time.perf_counter() - t0) * 1e3)
        return out.astype(buf.dtype, copy=False)

    def _quantized_allreduce_reference(self, buf: np.ndarray, codec,
                                       block_size: int) -> np.ndarray:
        """Reference quantized path (pre-fusion): per-chunk
        quantize/to_bytes on the way out, from_bytes/dequantize + a
        deferred rank-order sum on the way in.  Kept as the A/B baseline
        and the HOROVOD_FUSED_KERNELS=0 fallback."""
        from ..compress import (chunk_bounds, dequantize, from_bytes,
                                quantize, to_bytes)
        n, rank, size = buf.size, self.rank, self.size
        x = np.ascontiguousarray(buf).astype(np.float32, copy=False)
        bounds = chunk_bounds(n, size)

        t0 = time.perf_counter() if self._tm_on else 0.0
        my_chunks = [quantize(x[bounds[j]:bounds[j + 1]], codec,  # hvdlint: disable=per-segment-codec-loop -- this IS the reference chain the fused kernels replace; kept for the fused-vs-reference A/B and as the dispatch fallback
                              block_size) for j in range(size)]
        my_len = int(bounds[rank + 1] - bounds[rank])
        # Gather leg: dequantize each contribution AS IT ARRIVES (the
        # decode overlaps the next peer's in-flight bytes); the
        # accumulation below stays in RANK order — fp32 addition is
        # order-sensitive and the shm plane reduces in rank order, so
        # this keeps the two planes' reconstructions bit-identical (they
        # interoperate).
        contrib32: list = [None] * size
        contrib32[rank] = dequantize(my_chunks[rank])
        for offset in range(1, size):
            to = (rank + offset) % size
            frm = (rank - offset) % size
            self.mesh.send_async(to, to_bytes(my_chunks[to]))  # hvdlint: disable=per-segment-codec-loop -- reference A/B baseline (see above)
            view = self._recv_scratch(frm)
            contrib32[frm] = dequantize(from_bytes(  # hvdlint: disable=per-segment-codec-loop -- reference A/B baseline (see above)
                np.frombuffer(view, np.uint8), my_len, codec, block_size))
        acc = np.zeros(my_len, np.float32)
        for c in contrib32:
            acc += c
        reduced = quantize(acc, codec, block_size)
        if self._tm_on:
            self._m_leg[("gather", False)].observe(
                (time.perf_counter() - t0) * 1e3)

        t0 = time.perf_counter() if self._tm_on else 0.0
        out_parts: list = [None] * size
        out_parts[rank] = dequantize(reduced)
        payload = to_bytes(reduced)
        for offset in range(1, size):
            to = (rank + offset) % size
            frm = (rank - offset) % size
            self.mesh.send_async(to, payload)
            view = self._recv_scratch(frm)
            out_parts[frm] = dequantize(from_bytes(  # hvdlint: disable=per-segment-codec-loop -- reference A/B baseline (see above)
                np.frombuffer(view, np.uint8),
                int(bounds[frm + 1] - bounds[frm]), codec, block_size))
        self.mesh.flush()
        if self._tm_on:
            self._m_leg[("return", False)].observe(
                (time.perf_counter() - t0) * 1e3)
        out = np.concatenate(out_parts) if size > 1 else out_parts[0]
        return out.astype(buf.dtype, copy=False)

    # -- small-tensor codec legs on the binomial tree -------------------
    def _cast_allreduce_tree(self, buf: np.ndarray,
                             wire_dtype: np.dtype) -> np.ndarray:
        """Cast-codec allreduce on the binomial tree: whole-buffer
        wire-cast contributions gather to rank 0 in log N rounds, the
        root widens + accumulates all N in RANK ORDER in fp32
        (fk.cast_add — bitwise equal to the reference astype chain) and
        rounds ONCE to the wire dtype, and the reduced wire image
        returns on the binomial broadcast.  Same accumulation order and
        single rounding as the owner-reduce gather leg — results are
        bitwise identical to the flat codec path."""
        n, size = buf.size, self.size
        fk = self._fk
        x = np.ascontiguousarray(buf).astype(wire_dtype, copy=False)
        item = x.nbytes
        t0 = time.perf_counter() if self._tm_on else 0.0
        block = self._tree_gather(_bv(x), item)
        if block is not None:               # root: rank-order accumulate
            acc = fk.f32(("tcacc",), n)
            acc[:] = 0.0
            mv = memoryview(block)
            for j in range(size):
                fk.cast_add(mv[j * item:(j + 1) * item], wire_dtype,
                            acc, ("tcin",))
            out = acc.astype(wire_dtype)    # the ONE rounding
        else:
            out = np.empty(n, dtype=wire_dtype)
        if self._tm_on:
            self._m_leg[("gather", self.fused)].observe(
                (time.perf_counter() - t0) * 1e3)
        t0 = time.perf_counter() if self._tm_on else 0.0
        self._tree_bcast_into(_bv(out))
        if self._tm_on:
            self._m_leg[("return", self.fused)].observe(
                (time.perf_counter() - t0) * 1e3)
        return out.astype(buf.dtype, copy=False)

    def _quantized_allreduce_tree(self, buf: np.ndarray, codec,
                                  block_size: int) -> np.ndarray:
        """Quantized allreduce on the binomial tree: whole-buffer
        ENCODED contributions gather to rank 0, the root dequantizes +
        accumulates all N in RANK ORDER in fp32 (fk.decode_add — the
        fused kernels are bitwise equal to the reference chain) and
        requantizes ONCE, and every rank decodes the broadcast reduced
        image.  Same accumulation order and single rounding as the
        owner-reduce path; additionally bitwise identical to it when
        the flat path's chunk bounds fall on quantization-block
        boundaries (blockwise scales then agree — e.g. payloads
        divisible by size × block_size), documented fp32 tolerance
        otherwise."""
        n, size = buf.size, self.size
        fk = self._fk
        x = np.ascontiguousarray(buf).astype(np.float32, copy=False)
        t0 = time.perf_counter() if self._tm_on else 0.0
        wire = fk.encode(x, codec, block_size, ("tqenc",))
        item = wire.nbytes                  # deterministic in (n, codec)
        block = self._tree_gather(_bv(wire), item)
        if block is not None:               # root: rank-order accumulate
            acc = fk.f32(("tqacc",), n)
            acc[:] = 0.0
            mv = memoryview(block)
            for j in range(size):
                fk.decode_add(mv[j * item:(j + 1) * item], n, codec,
                              block_size, acc, ("tqin",))
            reduced = np.ascontiguousarray(
                fk.encode(acc, codec, block_size, ("tqred",)))
        else:
            reduced = np.empty(item, np.uint8)
        if self._tm_on:
            self._m_leg[("gather", self.fused)].observe(
                (time.perf_counter() - t0) * 1e3)
        t0 = time.perf_counter() if self._tm_on else 0.0
        self._tree_bcast_into(_bv(reduced))
        out = np.empty(n, np.float32)
        fk.decode_into(reduced, n, codec, block_size, out, ("tqout",))
        if self._tm_on:
            self._m_leg[("return", self.fused)].observe(
                (time.perf_counter() - t0) * 1e3)
        return out.astype(buf.dtype, copy=False)

    # -- reduce-scatter --------------------------------------------------
    def reduce_scatter(self, buf: np.ndarray,
                       bounds: "np.ndarray") -> np.ndarray:
        """Ring reduce-scatter with caller-provided chunk bounds
        (bounds[r]..bounds[r+1] = rank r's output slice): the first half
        of the ring allreduce only, (N-1)/N · bytes per link — half the
        traffic of allreduce+slice.  Schedule shifted by one vs the
        allreduce's reduce-scatter phase so rank r finishes owning chunk
        r (not r+1)."""
        rank, size = self.rank, self.size
        if size == 1:
            return np.asarray(buf)
        acc = buf.astype(_accum_dtype(buf.dtype), copy=True)
        nxt, prv = (rank + 1) % size, (rank - 1) % size
        for step in range(size - 1):
            send_idx = (rank - step - 1) % size
            recv_idx = (rank - step - 2) % size
            self.mesh.send_async(
                nxt, _bv(acc[bounds[send_idx]:bounds[send_idx + 1]]))
            self._recv_accum(prv, acc[bounds[recv_idx]:bounds[recv_idx + 1]])
        self.mesh.flush()
        return acc[bounds[rank]:bounds[rank + 1]].astype(buf.dtype,
                                                         copy=False)

    # -- allgatherv -----------------------------------------------------
    def allgatherv(self, local: np.ndarray,
                   first_dims: list[int]) -> np.ndarray:
        """Gather variable-first-dim blocks from every rank, rank order."""
        size, rank = self.size, self.rank
        if size == 1:
            return np.asarray(local)
        local = np.ascontiguousarray(local)
        blocks: list[np.ndarray | None] = [None] * size
        blocks[rank] = local
        rest_shape = local.shape[1:]
        nxt, prv = (rank + 1) % size, (rank - 1) % size
        # Ring rotation: at step s we forward the block of rank (rank-s)%size
        # zero-copy off its array, and receive the next block straight
        # into its own freshly-sized destination.
        for step in range(size - 1):
            send_idx = (rank - step) % size
            recv_idx = (rank - step - 1) % size
            self.mesh.send_async(
                nxt, _bv(np.ascontiguousarray(blocks[send_idx])))
            block = np.empty((first_dims[recv_idx],) + rest_shape,
                             dtype=local.dtype)
            self._recv_into(prv, block)
            blocks[recv_idx] = block
        self.mesh.flush()
        return np.concatenate([np.asarray(b) for b in blocks], axis=0)

    # -- broadcast ------------------------------------------------------
    def broadcast(self, buf: np.ndarray | None, root: int,
                  nbytes: int, dtype: np.dtype,
                  shape: tuple[int, ...]) -> np.ndarray:
        """Binomial-tree broadcast (reference: MPIBroadcast over
        MPI_Bcast's binomial algorithm): O(log N) latency instead of the
        root's O(N) serialized star, with zero per-call thread spawn —
        forwards ride the persistent sender lanes.  Tree edges: vrank v
        receives from v - lowbit(v) and forwards to v + m for descending
        powers m < lowbit(v) (largest subtree first), all relative to the
        root."""
        size, rank = self.size, self.rank
        if size == 1:
            assert buf is not None
            return np.asarray(buf)
        vrank = (rank - root) % size
        if vrank == 0:
            data = np.ascontiguousarray(buf)
            low = 1
            while low < size:
                low <<= 1      # root forwards every power below 2^ceil(log2 N)
        else:
            low = vrank & -vrank
            parent = ((vrank - low) + root) % size
            data = np.empty(shape if shape else
                            (nbytes // max(dtype.itemsize, 1),), dtype=dtype)
            self._recv_into(parent, data)
        payload = _bv(data)
        m = low >> 1
        while m:
            child = vrank + m
            if child < size:
                self.mesh.send_async((child + root) % size, payload)
            m >>= 1
        self.mesh.flush()
        return np.asarray(data)

    # -- alltoall -------------------------------------------------------
    def alltoallv(self, local: np.ndarray,
                  splits: list[int]) -> tuple[np.ndarray, list[int]]:
        """Send splits[j] rows to rank j; return concatenated received rows
        and the per-rank received splits."""
        size, rank = self.size, self.rank
        local = np.ascontiguousarray(local)
        bounds = np.cumsum([0] + list(splits))
        my_block = local[bounds[rank]:bounds[rank + 1]]
        received: list[np.ndarray | None] = [None] * size
        received[rank] = my_block
        rest_shape = local.shape[1:]
        row_bytes = max(1, int(np.prod(rest_shape)) * local.dtype.itemsize)
        for offset in range(1, size):
            to_peer = (rank + offset) % size
            from_peer = (rank - offset) % size
            self.mesh.send_async(
                to_peer,
                _bv(np.ascontiguousarray(
                    local[bounds[to_peer]:bounds[to_peer + 1]])))
            nbytes = self.mesh.recv_begin(from_peer)
            block = np.empty((nbytes // row_bytes,) + rest_shape,
                             dtype=local.dtype)
            assert nbytes == block.nbytes, (nbytes, block.nbytes)
            if nbytes:
                self.mesh.recv_raw_into(from_peer, _bv(block))
            received[from_peer] = block
        self.mesh.flush()
        received_splits = [int(np.asarray(b).shape[0]) for b in received]
        out = np.concatenate([np.asarray(b) for b in received], axis=0) \
            if any(s for s in received_splits) else my_block[:0]
        return out, received_splits

    def barrier(self) -> None:
        token = np.zeros(1, dtype=np.uint8)
        self.allreduce(token)


class TcpBackend(CollectiveBackend):
    """CollectiveBackend adapter over TcpCollectives."""

    name = "tcp"
    # Per-stream instances each own a dedicated PeerMesh channel set and
    # fusion buffers, so independent responses execute concurrently
    # without interleaving bytes on a shared socket.
    stream_safe = True

    def __init__(self, collectives: TcpCollectives) -> None:
        self.coll = collectives

    def enabled(self, response, entries) -> bool:
        return self.coll.size > 1

    def allreduce(self, response: Response,
                  entries: list[TensorTableEntry]) -> Status:
        buf = self.pack_fusion_buffer(response, entries)
        buf = self.scale_buffer(buf, response.prescale_factor)
        np_dtype = buf.dtype
        wire_dt = self.wire_cast_dtype(response)
        if response.response_type == ResponseType.ADASUM:
            from ..ops.adasum import adasum_tcp
            # Adasum semantics are per-tensor: the reference computes
            # per-layer dot products even inside fused buffers
            # (adasum.h:38-552), so a fused response must not mix norms
            # across tensor boundaries — run VHDD per segment.  Cast
            # codecs shrink the exchanged payload; quantized codecs were
            # rejected at negotiation.
            if wire_dt is not None:
                buf = buf.astype(wire_dt)
            self._act_start(entries, "TCP_ADASUM")
            try:
                offset, parts = 0, []
                for n in response.tensor_sizes:
                    parts.append(adasum_tcp(self.coll,
                                            buf[offset:offset + n]))
                    offset += n
                buf = np.concatenate(parts) if len(parts) > 1 else parts[0]
            finally:
                self._act_end(entries)
            buf = buf.astype(np_dtype, copy=False)
            self.last_algo = "adasum"
        elif self.quantized_codec(response) is not None:
            self._act_start(entries, "TCP_QUANTIZED_ALLREDUCE")
            try:
                buf = self.coll.quantized_allreduce(
                    buf, self.quantized_codec(response),
                    self.codec_block_size(response))
            finally:
                self._act_end(entries)
            self.last_algo = self.coll.last_algo
        elif wire_dt is not None:
            self._act_start(entries, "TCP_CAST_ALLREDUCE")
            try:
                buf = self.coll.cast_allreduce(buf, wire_dt)
            finally:
                self._act_end(entries)
            self.last_algo = self.coll.last_algo
        else:
            self._act_start(entries, "TCP_RING_ALLREDUCE")
            try:
                buf = self.coll.allreduce(buf)
            finally:
                self._act_end(entries)
            self.last_algo = self.coll.last_algo
        buf = self.scale_buffer(buf, response.postscale_factor)
        self.unpack_fusion_buffer(buf, response, entries)
        return Status.ok()

    def allgather(self, response: Response,
                  entries: list[TensorTableEntry]) -> Status:
        self.last_algo = "ring"
        self._act_start(entries, "TCP_ALLGATHERV")
        try:
            dtype = to_numpy(response.tensor_type)
            size = self.coll.size
            if len(entries) == 1:
                dims = self.allgather_entry_dims(response, 1, size)
                local = np.ascontiguousarray(
                    np.asarray(entries[0].tensor, dtype=dtype))
                entries[0].output = self.coll.allgatherv(local, dims[0])
                return Status.ok()
            # Fused response: ONE ring exchange for all entries
            # (reference: MPI_Allgatherv over the fusion buffer,
            # mpi_operations.cc MPIAllgather::Execute).
            locals_, dims, rests, per_rank, payload = \
                self.pack_fused_allgather(response, entries, dtype, size)
            full = self.coll.allgatherv(payload, per_rank)
            self.unpack_fused_allgather(full, entries, locals_, dims,
                                        rests, dtype, per_rank)
            return Status.ok()
        finally:
            self._act_end(entries)

    def broadcast(self, response: Response,
                  entries: list[TensorTableEntry]) -> Status:
        dtype = to_numpy(response.tensor_type)
        self.last_algo = "tree"            # binomial broadcast schedule
        self._act_start(entries, "TCP_BCAST")
        try:
            for e in entries:
                local = None if e.tensor is None else \
                    np.asarray(e.tensor, dtype=dtype)
                shape = local.shape if local is not None else ()
                e.output = self.coll.broadcast(local, response.root_rank,
                                               response.tensor_sizes[0]
                                               * dtype.itemsize, dtype,
                                               shape)
            return Status.ok()
        finally:
            self._act_end(entries)

    def alltoall(self, response: Response,
                 entries: list[TensorTableEntry]) -> Status:
        self.last_algo = "pairwise"
        self._act_start(entries, "TCP_ALLTOALLV")
        try:
            for e in entries:
                local = np.asarray(e.tensor,
                                   dtype=to_numpy(response.tensor_type))
                splits = self.resolve_alltoall_splits(e, local.shape[0],
                                                      self.coll.size)
                if isinstance(splits, Status):
                    return splits
                e.output, e.received_splits = self.coll.alltoallv(local,
                                                                  splits)
            return Status.ok()
        finally:
            self._act_end(entries)

    def reducescatter(self, response: Response,
                      entries: list[TensorTableEntry]) -> Status:
        # True ring reduce-scatter: chunk bounds follow the per-rank dim-0
        # split (uneven allowed), (N-1)/N bytes per link (reference: the
        # ReduceScatter leg of nccl_operations.cc:187-398).
        self.last_algo = "ring"
        size = self.coll.size
        if len(entries) > 1:
            # Multi-entry responses keep ONE fused ring (2(N-1) rounds on
            # the whole buffer) instead of a latency-bound ring per
            # tensor; byte volume doubles but round count stays constant.
            self._act_start(entries, "TCP_RING_ALLREDUCE")
            try:
                return self._reducescatter_fused(response, entries)
            finally:
                self._act_end(entries)
        self._act_start(entries, "TCP_RING_REDUCESCATTER")
        try:
            return self._reducescatter_single(response, entries, size)
        finally:
            self._act_end(entries)

    def _reducescatter_single(self, response: Response,
                              entries: list[TensorTableEntry],
                              size: int) -> Status:
        for e in entries:
            local = np.ascontiguousarray(
                np.asarray(e.tensor, dtype=to_numpy(response.tensor_type)))
            shape = local.shape
            rest = int(np.prod(shape[1:])) if len(shape) > 1 else 1
            rows = dim0_row_bounds(shape[0], size)
            bounds = np.asarray(rows) * rest
            buf = self.scale_buffer(local.reshape(-1),
                                    response.prescale_factor)
            out = self.coll.reduce_scatter(np.ascontiguousarray(buf),
                                           bounds)
            out = self.scale_buffer(out, response.postscale_factor)
            my_rows = rows[self.coll.rank + 1] - rows[self.coll.rank]
            e.output = out.reshape((my_rows,) + shape[1:])
        return Status.ok()

    def _reducescatter_fused(self, response: Response,
                             entries: list[TensorTableEntry]) -> Status:
        # Allreduce the fused buffer, slice per entry (the pre-r3 path).
        buf = self.pack_fusion_buffer(response, entries)
        buf = self.scale_buffer(buf, response.prescale_factor)
        buf = self.coll.allreduce(buf)
        buf = self.scale_buffer(buf, response.postscale_factor)
        offset = 0
        for i, e in enumerate(entries):
            n = response.tensor_sizes[i]
            chunk = buf[offset:offset + n]
            offset += n
            shape = np.asarray(e.tensor).shape
            full = chunk.reshape(shape)
            starts = dim0_row_bounds(shape[0], self.coll.size)
            sliced = full[starts[self.coll.rank]:
                          starts[self.coll.rank + 1]]
            e.output = sliced.copy() if self.fusion_buffers.owns(buf) \
                else sliced
        return Status.ok()

    def barrier(self, response, entries) -> Status:
        self.coll.barrier()
        return Status.ok()
