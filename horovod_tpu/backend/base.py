"""Backend interface + priority dispatch.

Reference: horovod/common/ops/operation_manager.{cc,h}:27-66 and
collective_operations.h:38-288.  `OperationManager` walks backends in
registration priority order; the first whose `enabled()` returns True for a
given Response executes it — this is how NCCL beats MPI beats Gloo in the
reference, and how XLA beats TCP beats basic here.
"""
from __future__ import annotations

from abc import ABC, abstractmethod

import numpy as np

from ..common.dtypes import to_numpy
from ..common.message import Response, ResponseType
from ..common.status import Status
from ..common.tensor_queue import TensorTableEntry


class CollectiveBackend(ABC):
    """One data-plane implementation of the collective ops."""

    name = "abstract"

    @abstractmethod
    def enabled(self, response: Response, entries: list[TensorTableEntry]) -> bool:
        ...

    def execute(self, response: Response,
                entries: list[TensorTableEntry]) -> Status:
        rt = response.response_type
        if rt in (ResponseType.ALLREDUCE, ResponseType.ADASUM):
            return self.allreduce(response, entries)
        if rt == ResponseType.ALLGATHER:
            return self.allgather(response, entries)
        if rt == ResponseType.BROADCAST:
            return self.broadcast(response, entries)
        if rt == ResponseType.ALLTOALL:
            return self.alltoall(response, entries)
        if rt == ResponseType.REDUCESCATTER:
            return self.reducescatter(response, entries)
        if rt == ResponseType.BARRIER:
            return self.barrier(response, entries)
        return Status.unknown_error(f"Unsupported response type {rt}")

    @abstractmethod
    def allreduce(self, response, entries) -> Status: ...

    @abstractmethod
    def allgather(self, response, entries) -> Status: ...

    @abstractmethod
    def broadcast(self, response, entries) -> Status: ...

    @abstractmethod
    def alltoall(self, response, entries) -> Status: ...

    def reducescatter(self, response, entries) -> Status:
        return Status.unknown_error("reducescatter not supported by "
                                    f"backend {self.name}")

    def barrier(self, response, entries) -> Status:
        return Status.ok()

    # ------------------------------------------------------------------
    # Fusion-buffer staging helpers (reference:
    # collective_operations.h:89-125 MemcpyInFusionBuffer / ScaleBuffer).
    # ------------------------------------------------------------------
    @staticmethod
    def pack_fusion_buffer(response: Response,
                           entries: list[TensorTableEntry]) -> np.ndarray:
        """Concatenate flattened entry payloads into one fused buffer."""
        np_dtype = to_numpy(response.tensor_type)
        if len(entries) == 1:
            e = entries[0]
            if e.tensor is None:
                return np.zeros(response.tensor_sizes[0], dtype=np_dtype)
            return np.ascontiguousarray(
                np.asarray(e.tensor, dtype=np_dtype).reshape(-1))
        parts: list[np.ndarray | None] = []
        for i, e in enumerate(entries):
            if e.tensor is None:   # joined-rank zero stand-in
                parts.append(None)
            else:
                parts.append(np.ascontiguousarray(
                    np.asarray(e.tensor, dtype=np_dtype)).reshape(-1))
        from .. import native
        fused = native.pack(parts, list(response.tensor_sizes), np_dtype)
        if fused is not None:
            return fused
        return np.concatenate([
            p if p is not None else np.zeros(response.tensor_sizes[i],
                                             dtype=np_dtype)
            for i, p in enumerate(parts)])

    @staticmethod
    def unpack_fusion_buffer(buf: np.ndarray, response: Response,
                             entries: list[TensorTableEntry]) -> None:
        """Slice the fused result back into per-entry outputs, restoring
        original shapes."""
        offset = 0
        for i, e in enumerate(entries):
            n = response.tensor_sizes[i]
            chunk = buf[offset:offset + n]
            offset += n
            if e.tensor is not None:
                shape = np.asarray(e.tensor).shape
                e.output = chunk.reshape(shape)
            else:
                e.output = chunk

    @staticmethod
    def scale_buffer(buf: np.ndarray, factor: float) -> np.ndarray:
        if factor == 1.0:
            return buf
        # fp16/bf16 buffers scale in fp32 to avoid precision loss
        # (reference: collective_operations.h:89-125 ScaleBuffer fp16 path).
        if buf.dtype.itemsize <= 2 and buf.dtype.kind == "f":
            return (buf.astype(np.float32) * factor).astype(buf.dtype)
        if buf.dtype.kind in "iu":
            return (buf * factor).astype(buf.dtype)
        return buf * buf.dtype.type(factor)


class OperationManager:
    """Priority dispatch over registered backends
    (reference: ops/operation_manager.cc)."""

    def __init__(self, backends: list[CollectiveBackend]) -> None:
        self._backends = backends

    @property
    def backends(self) -> list[CollectiveBackend]:
        return list(self._backends)

    def execute_operation(self, response: Response,
                          entries: list[TensorTableEntry]) -> Status:
        if response.response_type == ResponseType.ERROR:
            return Status.precondition_error(response.error_message)
        if response.response_type == ResponseType.JOIN:
            return Status.ok()
        for backend in self._backends:
            if backend.enabled(response, entries):
                return backend.execute(response, entries)
        return Status.unknown_error(
            f"No enabled backend for response type "
            f"{response.response_type.name}")
