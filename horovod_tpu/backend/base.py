"""Backend interface + priority dispatch.

Reference: horovod/common/ops/operation_manager.{cc,h}:27-66 and
collective_operations.h:38-288.  `OperationManager` walks backends in
registration priority order; the first whose `enabled()` returns True for a
given Response executes it — this is how NCCL beats MPI beats Gloo in the
reference, and how XLA beats TCP beats basic here.
"""
from __future__ import annotations

from abc import ABC, abstractmethod

import numpy as np

from ..common.dtypes import to_numpy
from ..common.message import Response, ResponseType
from ..common.status import Status
from ..common.tensor_queue import TensorTableEntry


def dim0_row_bounds(n_rows: int, size: int) -> list[int]:
    """Uneven dim-0 reducescatter split: rank r owns rows
    [bounds[r], bounds[r+1]); the first ``rem`` ranks get one extra row.
    MUST stay identical across the TCP/shm/XLA planes — they interoperate
    (fallbacks, hierarchical mixes) and must scatter the same rows."""
    base, rem = divmod(n_rows, size)
    return [r * base + min(r, rem) for r in range(size + 1)]


def accum_dtype(dtype: np.dtype) -> np.dtype:
    """Accumulation dtype for reductions: 16-bit floats widen to fp32,
    everything else reduces in place (the numerics contract shared by the
    TCP, shm and hierarchical planes; reference: common/half.cc fp16 sum).
    NOTE: ml_dtypes.bfloat16 reports dtype.kind 'V', so the float test
    goes through finfo, not kind."""
    dtype = np.dtype(dtype)
    if dtype.itemsize <= 2:
        try:
            return np.dtype(np.float32) if np.finfo(dtype).bits <= 16 \
                else dtype
        except ValueError:
            pass   # int/bool — or bf16, which np.finfo rejects too
        try:
            import ml_dtypes
            if ml_dtypes.finfo(dtype).bits <= 16:
                return np.dtype(np.float32)
        except (ImportError, ValueError, TypeError):
            pass
    return dtype


class FusionBufferManager:
    """Persistent fusion staging buffers — the analogue of the reference's
    one-per-(device, framework) buffer (fusion_buffer_manager.cc): lazily
    allocated, grown geometrically, reused every cycle so steady-state
    fused responses pay zero allocations."""

    def __init__(self) -> None:
        self._buffers: dict[tuple[str, str], np.ndarray] = {}

    def get(self, tag: str, dtype, n: int) -> np.ndarray:
        key = (tag, np.dtype(dtype).str)
        buf = self._buffers.get(key)
        if buf is None or buf.size < n:
            cap = max(n, 0 if buf is None else 2 * buf.size)
            buf = np.empty(cap, dtype=dtype)
            self._buffers[key] = buf
        return buf[:n]

    def owns(self, arr: np.ndarray) -> bool:
        """True if ``arr`` is (a view of) a managed buffer — such results
        must be copied out before the next cycle clobbers them."""
        return any(arr is b or arr.base is b
                   for b in self._buffers.values())


class CollectiveBackend(ABC):
    """One data-plane implementation of the collective ops."""

    name = "abstract"
    # Attached by core.init so ops can emit sub-activity spans
    # (MEMCPY_IN_FUSION_BUFFER / <PLANE>_<OP> / MEMCPY_OUT_FUSION_BUFFER —
    # reference: timeline activities emitted from inside ops, e.g.
    # nccl_operations.cc:143).
    timeline = None
    # Multi-stream dispatch contract (core._dispatch_cycle): True means
    # independent responses may execute concurrently on per-stream
    # instances of this backend, each over its own channel set.  Planes
    # with process-global protocol state (shm lockstep, XLA program
    # order, the hierarchical sub-meshes) stay False and always run on
    # stream 0.
    stream_safe = False
    # Which dispatch stream this instance serves (annotates timeline
    # activities; per-stream instances are built by core.init).
    stream = 0
    # Algorithm used by the most recent collective on this backend
    # instance ("ring", "tree", "rhd", "torus", "adasum", "pairwise",
    # "hierarchical", ...).  Telemetry reads it right after execute() to
    # label the per-plane latency histogram; single dispatch thread per
    # stream instance, so a plain attribute is race-free.
    last_algo = "none"

    def _act_start(self, entries, activity: str) -> None:
        tl = self.timeline
        if tl is not None and tl.enabled:
            tl.activity_start_all(entries, activity, stream=self.stream)

    def _act_end(self, entries) -> None:
        tl = self.timeline
        if tl is not None and tl.enabled:
            tl.activity_end_all(entries)

    @property
    def fusion_buffers(self) -> FusionBufferManager:
        fb = getattr(self, "_fusion_buffers", None)
        if fb is None:
            fb = self._fusion_buffers = FusionBufferManager()
        return fb

    @abstractmethod
    def enabled(self, response: Response, entries: list[TensorTableEntry]) -> bool:
        ...

    def execute(self, response: Response,
                entries: list[TensorTableEntry]) -> Status:
        rt = response.response_type
        if rt in (ResponseType.ALLREDUCE, ResponseType.ADASUM):
            return self.allreduce(response, entries)
        if rt == ResponseType.ALLGATHER:
            return self.allgather(response, entries)
        if rt == ResponseType.BROADCAST:
            return self.broadcast(response, entries)
        if rt == ResponseType.ALLTOALL:
            return self.alltoall(response, entries)
        if rt == ResponseType.REDUCESCATTER:
            return self.reducescatter(response, entries)
        if rt == ResponseType.BARRIER:
            return self.barrier(response, entries)
        return Status.unknown_error(f"Unsupported response type {rt}")

    @abstractmethod
    def allreduce(self, response, entries) -> Status: ...

    @abstractmethod
    def allgather(self, response, entries) -> Status: ...

    @abstractmethod
    def broadcast(self, response, entries) -> Status: ...

    @abstractmethod
    def alltoall(self, response, entries) -> Status: ...

    def reducescatter(self, response, entries) -> Status:
        return Status.unknown_error("reducescatter not supported by "
                                    f"backend {self.name}")

    def barrier(self, response, entries) -> Status:
        return Status.ok()

    # ------------------------------------------------------------------
    # Fusion-buffer staging helpers (reference:
    # collective_operations.h:89-125 MemcpyInFusionBuffer / ScaleBuffer).
    # ------------------------------------------------------------------
    def pack_fusion_buffer(self, response: Response,
                           entries: list[TensorTableEntry]) -> np.ndarray:
        """Concatenate flattened entry payloads into the backend's
        persistent staging buffer (single entries pass through without a
        copy — the data plane stages them itself)."""
        np_dtype = to_numpy(response.tensor_type)
        if len(entries) == 1:
            e = entries[0]
            if e.tensor is None:
                return np.zeros(response.tensor_sizes[0], dtype=np_dtype)
            return np.ascontiguousarray(
                np.asarray(e.tensor, dtype=np_dtype).reshape(-1))
        parts: list[np.ndarray | None] = []
        for i, e in enumerate(entries):
            if e.tensor is None:   # joined-rank zero stand-in
                parts.append(None)
            else:
                parts.append(np.ascontiguousarray(
                    np.asarray(e.tensor, dtype=np_dtype)).reshape(-1))
        sizes = list(response.tensor_sizes)
        self._act_start(entries, "MEMCPY_IN_FUSION_BUFFER")
        try:
            fused = self.fusion_buffers.get("pack", np_dtype, sum(sizes))
            from .. import native
            if native.pack(parts, sizes, np_dtype, out=fused) is not None:
                return fused
            offset = 0
            for i, p in enumerate(parts):
                n = sizes[i]
                view = fused[offset:offset + n]
                if p is None:
                    view[:] = 0
                else:
                    view[:] = p
                offset += n
            return fused
        finally:
            self._act_end(entries)

    def unpack_fusion_buffer(self, buf: np.ndarray, response: Response,
                             entries: list[TensorTableEntry]) -> None:
        """Slice the fused result back into per-entry outputs, restoring
        original shapes.  Results living in a persistent buffer are copied
        out (the next cycle reuses the buffer); fresh backend results are
        sliced zero-copy."""
        owned = self.fusion_buffers.owns(buf)
        if len(entries) > 1:
            self._act_start(entries, "MEMCPY_OUT_FUSION_BUFFER")
        try:
            offset = 0
            for i, e in enumerate(entries):
                n = response.tensor_sizes[i]
                chunk = buf[offset:offset + n]
                offset += n
                if e.tensor is not None:
                    shape = np.asarray(e.tensor).shape
                    out = chunk.reshape(shape)
                else:
                    out = chunk
                e.output = out.copy() if owned else out
        finally:
            # finally-guarded end (hvdlint HVD1005): a reshape error here
            # must not leave the MEMCPY span open — an unbalanced B
            # corrupts every later span on the tensor's trace lane.
            if len(entries) > 1:
                self._act_end(entries)

    @staticmethod
    def resolve_alltoall_splits(entry: TensorTableEntry, dim0: int,
                                world_size: int) -> list[int] | Status:
        """Explicit splits, or an even division of dim 0; a Status error
        when neither applies (shared by the XLA, TCP and shm planes)."""
        if entry.splits:
            if len(entry.splits) != world_size:
                return Status.invalid_argument(
                    f"alltoall splits must have one entry per rank "
                    f"(got {len(entry.splits)} for world size "
                    f"{world_size})")
            splits = [int(s) for s in entry.splits]
            if any(s < 0 for s in splits):
                return Status.invalid_argument(
                    f"alltoall splits must be non-negative (got {splits})")
            # Reference rejects split tables inconsistent with the tensor
            # (operations.cc:1176 "Sum of splits entries is greater than
            # the first dimension"); we require exact coverage so no plane
            # can silently read stale or truncated bytes.
            if sum(splits) != dim0:
                return Status.invalid_argument(
                    f"alltoall splits must sum to the first dimension "
                    f"(sum {sum(splits)} != dim0 {dim0})")
            return splits
        if dim0 % world_size != 0:
            return Status.invalid_argument(
                "alltoall first dimension must be divisible by the "
                "world size when splits are not given")
        return [dim0 // world_size] * world_size

    @staticmethod
    def allgather_entry_dims(response: Response, n_entries: int,
                             world_size: int) -> list[list[int]]:
        """Per-entry per-rank first dims of a (possibly fused) allgather
        response: tensor_sizes holds one world_size block per entry
        (reference: message.cc:380-388 Response::add_allgather_response)."""
        sizes = list(response.tensor_sizes)
        assert len(sizes) == n_entries * world_size, \
            (len(sizes), n_entries, world_size)
        return [sizes[i * world_size:(i + 1) * world_size]
                for i in range(n_entries)]

    @staticmethod
    def _fused_allgather_layout(dims: list[list[int]], rests: list[int],
                                itemsize: int) -> tuple[np.ndarray,
                                                        np.ndarray]:
        """(bytes[i][r], exclusive per-rank entry prefix[i][r]) for the
        rank-major/entry-major packed layout — one cumsum instead of an
        O(entries) Python sum per (entry, rank) in the hot unpack path."""
        nbytes = np.asarray(dims, dtype=np.int64) * \
            (np.asarray(rests, dtype=np.int64)[:, None] * itemsize)
        return nbytes, np.cumsum(nbytes, axis=0) - nbytes

    @staticmethod
    def pack_fused_allgather(response: Response,
                             entries: list[TensorTableEntry],
                             dtype: np.dtype, world_size: int):
        """Encode the fused-allgather wire layout shared by the TCP, XLA,
        shm and hierarchical planes: each rank's packed payload is the
        concatenation of its block of every entry (entry-major), as raw
        bytes so entries with different trailing shapes share one
        exchange.  Returns (locals_, dims, rests, per_rank_bytes,
        payload)."""
        dims = CollectiveBackend.allgather_entry_dims(
            response, len(entries), world_size)
        locals_ = [np.ascontiguousarray(np.asarray(e.tensor, dtype=dtype))
                   for e in entries]
        rests = [int(np.prod(a.shape[1:])) if a.ndim > 1 else 1
                 for a in locals_]
        nbytes, _ = CollectiveBackend._fused_allgather_layout(
            dims, rests, dtype.itemsize)
        per_rank = nbytes.sum(axis=0).tolist()
        payload = np.concatenate([a.reshape(-1).view(np.uint8)
                                  for a in locals_])
        return locals_, dims, rests, per_rank, payload

    @staticmethod
    def unpack_fused_allgather(full: np.ndarray,
                               entries: list[TensorTableEntry],
                               locals_: list[np.ndarray],
                               dims: list[list[int]],
                               rests: list[int],
                               dtype: np.dtype,
                               per_rank: list[int]) -> None:
        """Slice a rank-major/entry-major packed byte exchange back into
        per-entry outputs in global rank order (the decoder paired with
        pack_fused_allgather)."""
        size = len(per_rank)
        rank_off = np.cumsum([0] + list(per_rank))
        nbytes, ent_off = CollectiveBackend._fused_allgather_layout(
            dims, rests, dtype.itemsize)
        for i, e in enumerate(entries):
            blocks = []
            rest_shape = locals_[i].shape[1:]
            for r in range(size):
                off = int(rank_off[r] + ent_off[i, r])
                blk = full[off:off + int(nbytes[i, r])].view(dtype) \
                    .reshape((dims[i][r],) + rest_shape)
                blocks.append(blk)
            e.output = np.concatenate(blocks, axis=0)

    # ------------------------------------------------------------------
    # Wire-compression codec helpers (compress/ subsystem).  Shared by
    # the planes so every backend interprets Response.codec identically.
    # ------------------------------------------------------------------
    @staticmethod
    def quantized_codec(response: Response):
        """The response's quantized codec (int8/uint4) when it applies —
        floating payloads only — else None."""
        from ..common.dtypes import is_floating
        from ..compress import QUANTIZED_CODECS, CompressionCodec
        codec = CompressionCodec(response.codec)
        if codec in QUANTIZED_CODECS and is_floating(response.tensor_type):
            return codec
        return None

    @staticmethod
    def codec_block_size(response: Response) -> int:
        """Negotiated quantization block size (falls back to the config
        default for hand-built responses that omitted it)."""
        if response.codec_block_size > 0:
            return response.codec_block_size
        from ..compress import default_block_size
        return default_block_size()

    @staticmethod
    def wire_cast_dtype(response: Response):
        """Wire dtype for the cast codecs (fp16/bf16) when the payload is
        a wider float, else None.  The planes reduce 16-bit wires with
        fp32 accumulation already (accum_dtype), so the cast alone
        reproduces the legacy Compression.fp16 semantics."""
        from ..common.dtypes import element_size, is_floating
        from ..compress import CompressionCodec
        codec = CompressionCodec(response.codec)
        if not is_floating(response.tensor_type) or \
                element_size(response.tensor_type) <= 2:
            return None
        if codec == CompressionCodec.FP16:
            return np.dtype(np.float16)
        if codec == CompressionCodec.BF16:
            try:
                import ml_dtypes
                return np.dtype(ml_dtypes.bfloat16)
            except ImportError:   # bf16 wire unavailable: ship fp16
                return np.dtype(np.float16)
        return None

    @staticmethod
    def scale_buffer(buf: np.ndarray, factor: float) -> np.ndarray:
        if factor == 1.0:
            return buf
        # fp16/bf16 buffers scale in fp32 to avoid precision loss
        # (reference: collective_operations.h:89-125 ScaleBuffer fp16 path).
        if accum_dtype(buf.dtype) != buf.dtype:
            return (buf.astype(np.float32) * factor).astype(buf.dtype)
        if buf.dtype.kind in "iu":
            return (buf * factor).astype(buf.dtype)
        return buf * buf.dtype.type(factor)


class OperationManager:
    """Priority dispatch over registered backends
    (reference: ops/operation_manager.cc)."""

    def __init__(self, backends: list[CollectiveBackend]) -> None:
        self._backends = backends

    @property
    def backends(self) -> list[CollectiveBackend]:
        return list(self._backends)

    def resolve(self, response: Response,
                entries: list[TensorTableEntry]) -> CollectiveBackend | None:
        """First enabled backend for this response, or None.  Every
        enabled() check is rank-symmetric by contract (world size, knob
        env, unanimous KV-store formation), so all ranks resolve the same
        plane — the invariant the multi-stream assignment relies on."""
        for backend in self._backends:
            if backend.enabled(response, entries):
                return backend
        return None

    def execute_operation(self, response: Response,
                          entries: list[TensorTableEntry]) -> Status:
        if response.response_type == ResponseType.ERROR:
            return Status.precondition_error(response.error_message)
        if response.response_type == ResponseType.JOIN:
            return Status.ok()
        backend = self.resolve(response, entries)
        if backend is not None:
            return backend.execute(response, entries)
        return Status.unknown_error(
            f"No enabled backend for response type "
            f"{response.response_type.name}")
