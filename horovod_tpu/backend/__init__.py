"""Collective data-plane backends.

Reference analogue: horovod/common/ops/* — op implementations are registered
in priority order and the first enabled one executes each Response
(reference: operations.cc:143-252 CreateOperationManager).  The TPU rebuild
keeps the same contract with these backends:

- ``xla``: fused collectives compiled by XLA over the device mesh (the
  NCCL-replacement; jitted psum/all_gather/all_to_all/ppermute riding ICI).
- ``tcp``: pure-CPU numpy collectives over TCP sockets between processes
  (the Gloo-replacement; keeps CPU-only paths working without TPUs).
- ``basic``: single-process world — identity semantics with scaling.
"""
from .base import CollectiveBackend, OperationManager

__all__ = ["CollectiveBackend", "OperationManager"]
