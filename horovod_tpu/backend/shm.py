"""Shared-memory data plane for same-host worlds.

The eager analogue of the reference's intra-node shared-memory paths —
Gloo's shm transport and MPIHierarchicalAllgather's node-shared window
(reference: horovod/common/ops/mpi_operations.cc) — rebuilt for the
multi-process-per-host layout of TPU VM hosts: ranks that share a machine
exchange bulk payloads through mmap'd /dev/shm regions instead of the TCP
loopback ring, cutting per-byte work from ~6 copies (user→kernel→user each
way plus staging) to ~3 (pack, reduce, copy-out) and roughly tripling
effective allreduce bandwidth on localhost worlds.

Protocol (per collective, lockstep across ranks — the identical-response-
order invariant guarantees every rank runs the same op sequence); this is
the allreduce shape, with broadcast/allgather using a 2-barrier variant
(stage, publish 3t+1, read peers, publish 3t+3 — monotonic ``>=`` waits
make the skipped middle word equivalent):

  wait all seq >= 3t      (peers finished reading my previous result)
  pack payload into my region;            publish seq = 3t+1
  wait all seq >= 3t+1    (everyone's payload visible)
  reduce chunk `rank` across all regions; publish seq = 3t+2
  wait all seq >= 3t+2    (all chunks reduced)
  gather chunks from owners, unpack;      publish seq = 3t+3

Sequence counters are 8-byte aligned words in each rank's region header;
aligned word stores/loads are atomic on the host ISAs we target and mmap
shared mappings are cache-coherent.  Liveness: each rank publishes its PID
at formation and waiters poll peer PIDs, so a dead peer surfaces as a
structured error in ~liveness-interval, not a transport timeout (SURVEY
§5.2 "mismatch → structured error, not hang").

SYMMETRIC-CALL CONTRACT: the barrier words above are sequence-counted
like multihost.kv_barrier — the protocol is only safe because every rank
executes the identical ResponseList in identical order, so a
rank-asymmetric collective upstream of this plane would wedge a peer at
``wait all seq >= 3t``.  That contract is proven statically by hvdlint
(``python -m horovod_tpu.analysis.lint``; rank-gated-collective /
collective-under-lock rules) and checked at runtime by
``HOROVOD_FINGERPRINT`` — which names the first divergent op in a
structured error before this plane's barrier deadline or the stall
inspector ever fire.  See docs/analysis.md.
"""
from __future__ import annotations

import mmap
import os
import time

import numpy as np

from ..common.dtypes import element_size, to_numpy
from ..common.message import Response, ResponseType
from ..common.status import Status
from ..common.tensor_queue import TensorTableEntry
from .base import (CollectiveBackend, accum_dtype as _accum_dtype,
                   dim0_row_bounds)

_HEADER = 4096          # one page: seq word + splits table + padding
_SEQ_OFFSET = 0
# Alltoall publishes the sender-side split row-counts in the header (the
# receiver needs the sender's offsets to find its slice): int64 count at
# +8, then up to _MAX_SPLITS int64 entries at +16.
_SPLITS_OFFSET = 16
_MAX_SPLITS = (_HEADER - _SPLITS_OFFSET) // 8
# Poison flag bit, OR'd onto the failing rank's LAST PUBLISHED sequence
# value (e.g. a rank failing after publishing 3t+1 poisons to
# _POISON + 3t+1).  Carrying the high-water mark matters: a rank that
# fails AFTER completing op t must not error a slow peer still inside op
# t's last wait — everything that peer needs was already published — so
# wait_all honors published progress below the mark and raises only for
# barriers beyond it (data that will never arrive).  The whole host then
# declines shm unanimously at the next op via ``poison_seen``.
_POISON = 1 << 62


def _boot_fingerprint() -> str:
    """Same-memory-domain fingerprint.  Hostname alone lies inside
    containers sharing a hostname on one box; the kernel boot id pins the
    machine, and the mount/IPC namespace inodes pin the /dev/shm tmpfs —
    two containers on one host share a boot id but NOT a mount ns, and a
    private /dev/shm must disqualify formation up front (the attach
    verdict round below is the backstop).  The NET namespace is included
    deliberately: it never splits ranks that could otherwise share
    /dev/shm in practice (container setups split mnt/ipc too), and it
    makes a network-namespace boundary behave exactly like a host
    boundary — which is what netns-based cross-host emulation
    (benchmarks/shaped_link.py) relies on."""
    parts = []
    try:
        with open("/proc/sys/kernel/random/boot_id") as f:
            parts.append(f.read().strip())
    except OSError:
        parts.append("noboot")
    for ns in ("mnt", "ipc", "net"):
        try:
            parts.append(str(os.stat(f"/proc/self/ns/{ns}").st_ino))
        except OSError:
            parts.append("nons")
    import socket
    return socket.gethostname() + "." + ".".join(parts)


def _tune_malloc() -> None:
    """Keep multi-MB result buffers on the heap: glibc mmap()s allocations
    above the default threshold and munmap()s them on free, so every
    allreduce output repays ~4k page faults.  Raising the mmap/trim
    thresholds lets freed gradient-sized buffers be reused fault-free
    (measured: 16 MB op 17.9 ms -> 13.8 ms on one core).  Trade-off is
    retained RSS up to the threshold — right for bulk-data workers."""
    try:
        import ctypes
        libc = ctypes.CDLL("libc.so.6")
        libc.mallopt(-3, 256 << 20)   # M_MMAP_THRESHOLD
        libc.mallopt(-1, 256 << 20)   # M_TRIM_THRESHOLD
    except Exception:  # noqa: BLE001 - musl/macOS: no mallopt
        pass


def _shm_dir() -> str | None:
    for cand in ("/dev/shm", os.environ.get("TMPDIR", "/tmp")):
        if cand and os.path.isdir(cand) and os.access(cand, os.W_OK):
            return cand
    return None


class ShmWorld:
    """mmap'd per-rank regions + sequence-word lockstep for one world.

    Formation is collective through the rendezvous KV store and
    UNANIMOUS: every rank publishes (fingerprint, shm-usable, pid) and
    the world forms only if all ranks share one memory domain — so the
    backend chain stays rank-symmetric without extra negotiation.
    """

    def __init__(self, rank: int, size: int, kv, scope: str,
                 capacity: int, timeout: float = 30.0,
                 resilience=None) -> None:
        self.rank = rank
        self.size = size
        self.capacity = capacity
        self.timeout = timeout
        # Resilience (HOROVOD_FAULT_TOLERANCE): when on, the lockstep
        # barrier deadline derives from the per-op ResilienceContext
        # (one fault window) instead of the 600 s default, and the
        # liveness poll additionally consults the heartbeat monitor so a
        # WEDGED peer (PID alive, collective abandoned) is detected too.
        from ..resilience import active_state
        self._res = resilience if resilience is not None \
            else active_state()
        # Inter-op barrier deadline is deliberately MUCH larger than the
        # formation timeout: a live-but-slow peer (rank-0 checkpointing,
        # evaluation, CPU starvation) must not kill training — the 0.5 s
        # PID-liveness poll is the fail-fast path for actual death, and
        # one-sided submissions are the stall inspector's job upstream.
        self.barrier_timeout = float(os.environ.get(
            "HOROVOD_SHM_BARRIER_TIMEOUT_SECONDS", "600")) \
            if self._res is None else self._res.op_timeout()
        self._maps: list[mmap.mmap | None] = [None] * size
        self._seqs: list[np.ndarray | None] = [None] * size
        self._splits: list[np.ndarray | None] = [None] * size
        self._datas: list[np.ndarray | None] = [None] * size
        self._pids: list[int] = [0] * size
        self._paths: list[str] = [""] * size
        self.formed = False
        self._t = 0

        # Phase 1 — advertise (memory-domain fingerprint, capacity,
        # usability, pid); unanimity on domain AND capacity is required:
        # heterogeneous capacities would mmap past a smaller peer file
        # (SIGBUS on first touch) or desync enabled() across ranks.
        shm_dir = _shm_dir()
        usable = shm_dir is not None
        me = f"{_boot_fingerprint()}|{capacity}|{int(usable)}|{os.getpid()}"
        kv.put(scope, f"peer:{rank}", me.encode())
        peers = []
        for r in range(size):
            raw = kv.wait(scope, f"peer:{r}", timeout).decode()
            fp, cap, ok, pid = raw.rsplit("|", 3)
            peers.append((fp, int(cap), ok == "1", int(pid)))
        if not all(ok for _, _, ok, _ in peers) or \
                len({fp for fp, _, _, _ in peers}) != 1 or \
                len({cap for _, cap, _, _ in peers}) != 1:
            return   # not one memory domain: every rank skips unanimously

        # Phase 2 — create + attach, crash-proof: every rank ALWAYS
        # publishes a path (or "!") and then an attach verdict, so a
        # filesystem surprise on one rank degrades the whole world to the
        # TCP plane unanimously instead of crashing init or hanging peers.
        self._pids = [pid for _, _, _, pid in peers]
        attached = False
        try:
            path = os.path.join(shm_dir,
                                f"hvd_{scope}_{rank}_{os.getpid()}")
            try:   # stale region from a crashed same-pid predecessor
                os.unlink(path)
            except OSError:
                pass
            fd = os.open(path, os.O_CREAT | os.O_RDWR | os.O_EXCL, 0o600)
            try:
                os.ftruncate(fd, _HEADER + capacity)
                mm = mmap.mmap(fd, _HEADER + capacity)
            finally:
                os.close(fd)
            self._own_path = path
            self._attach(rank, mm, path)
            kv.put(scope, f"path:{rank}", path.encode())
        except OSError:
            kv.put(scope, f"path:{rank}", b"!")
        else:
            try:
                for r in range(size):
                    if r == rank:
                        continue
                    rpath = kv.wait(scope, f"path:{r}", timeout).decode()
                    if rpath == "!":
                        raise OSError("peer region unavailable")
                    fd = os.open(rpath, os.O_RDWR)
                    try:
                        mm = mmap.mmap(fd, _HEADER + capacity)
                    finally:
                        os.close(fd)
                    self._attach(r, mm, rpath)
                attached = True
            except OSError:
                attached = False

        # Phase 3 — unanimous attach verdict.
        kv.put(scope, f"att:{rank}", b"1" if attached else b"0")
        all_attached = all(
            kv.wait(scope, f"att:{r}", timeout) == b"1"
            for r in range(size))
        if not all_attached:
            self.close()
            return
        # Every peer holds an mmap now: unlink the file immediately so the
        # region becomes anonymous — a SIGKILLed job cannot leak
        # capacity-sized tmpfs files (the kernel frees the pages when the
        # last mapping dies with the processes).
        try:
            os.unlink(self._own_path)
        except OSError:
            pass
        self._own_path = ""
        _tune_malloc()
        self.formed = True

    def _attach(self, r: int, mm: mmap.mmap, path: str) -> None:
        self._maps[r] = mm
        self._paths[r] = path
        self._seqs[r] = np.frombuffer(mm, dtype=np.uint64, count=1,
                                      offset=_SEQ_OFFSET)
        self._splits[r] = np.frombuffer(mm, dtype=np.int64,
                                        count=1 + _MAX_SPLITS, offset=8)
        self._datas[r] = np.frombuffer(mm, dtype=np.uint8,
                                       count=self.capacity, offset=_HEADER)

    # -- lockstep ------------------------------------------------------
    def publish(self, value: int) -> None:
        self._seqs[self.rank][0] = value

    def poison(self) -> None:
        """Mark this world failed: peers blocked on data we never staged
        raise instead of timing out, peers merely draining barriers we
        already satisfied complete normally, and every rank declines shm
        for the next op (``poison_seen``), keeping the backend chain
        rank-symmetric."""
        self.formed = False
        try:
            cur = int(self._seqs[self.rank][0])   # type: ignore[index]
            if cur < _POISON:   # idempotent: keep the original mark
                self._seqs[self.rank][0] = _POISON + cur
        except Exception:  # noqa: BLE001 - already closed
            pass

    def poison_seen(self) -> bool:
        """Cross-rank poison probe for ``enabled()``.  A rank that fails
        AFTER its peers' last wait of op t (e.g. MemoryError during
        unpack) poisons and runs op t+1 on TCP — but peers that already
        finished op t would only notice inside op t+1's shm wait, a
        one-op plane desync that leaves the fallen-back rank blocked in
        the TCP ring until transport timeout.  Reading every seq word
        BEFORE claiming an op makes the decline unanimous.

        Residual race (accepted, bounded): a fast peer can pass this
        probe and enter op t+1's shm protocol before the failing rank
        writes its mark.  Outcome: the peer's first data wait (>= 3t+4)
        exceeds the decliner's boundary mark (3t+3) and raises a
        structured error for op t+1, while the decliner waits out the
        TCP transport timeout for the same op; from op t+2 every rank is
        on TCP.  Blast radius is ONE op, surfaced as
        HorovodInternalError on every affected rank (elastic recovery's
        trigger) — never stale data (see the freshness invariant in
        wait_all).  A TCP retry inside the raising op would be unsound:
        the mark cannot distinguish "declined to TCP" from "claimed op
        t+1 on shm and died before its first publish", and retrying
        against the latter mis-pairs payloads on the persistent TCP
        sockets."""
        if not self.formed:
            return True
        try:
            if any(int(s[0]) >= _POISON  # type: ignore[index]
                   for s in self._seqs):
                self.formed = False
                return True
        except Exception:  # noqa: BLE001 - region torn down under us
            self.formed = False
            return True
        return False

    def wait_all(self, target: int) -> None:
        start = time.monotonic()
        deadline = start + self.barrier_timeout
        next_liveness = start + 0.5
        while True:
            seqs = [int(s[0]) for s in self._seqs]  # type: ignore[index]
            # Published progress counts even from a poisoned rank (the
            # mark is its last publish + _POISON): barriers the failing
            # rank already satisfied complete; only barriers past its
            # high-water mark — data that will never arrive — raise.
            # A LIVE rank below the target is simply slow: keep waiting
            # (PID liveness and the barrier deadline cover death/stalls)
            # rather than letting a covering poison mark error an op the
            # slow rank is about to finish.  Freshness invariant: every
            # data-guarded wait in the five protocols targets >= 3t+1 of
            # its own op, while a rank that completed op t-1 and then
            # declined marks at exactly the 3t boundary — so a poison
            # mark can never satisfy a wait that would read data the
            # marked rank never staged.
            if all((s - _POISON if s >= _POISON else s) >= target
                   for s in seqs):
                return
            if any(s >= _POISON and s - _POISON < target for s in seqs):
                self.formed = False
                raise ConnectionError(
                    "shm world poisoned by a peer failure")
            now = time.monotonic()
            if now >= next_liveness:
                next_liveness = now + 0.5
                for r, pid in enumerate(self._pids):
                    if r == self.rank:
                        continue
                    try:
                        os.kill(pid, 0)
                    except OSError:
                        self._peer_died(r, pid)
                if self._res is not None:
                    # Heartbeat-declared failures (a peer wedged with its
                    # PID alive, or a death another rank witnessed first)
                    # convert this barrier too — same detection window as
                    # the socket planes.
                    failed = self._res.failed_ranks()
                    if failed:
                        self.poison()
                        from ..common.exceptions import RanksFailedError
                        from ..resilience import current_op
                        raise RanksFailedError(
                            failed, op=current_op(), phase="shm_barrier")
                if now > deadline:
                    self._barrier_deadline(target, seqs)
            # Small-op barriers resolve within a scheduling quantum:
            # yield-spin briefly.  Past that, the peer is mid-copy on a
            # core we may share — REALLY sleep (escalating to 1 ms) so it
            # gets whole quanta instead of alternating with our spin.
            waited = now - start
            if waited < 0.0003:
                time.sleep(0)
            else:
                time.sleep(min(max(waited / 4, 0.0004), 0.001))

    def _peer_died(self, r: int, pid: int) -> None:
        """PID-liveness verdict: always a RanksFailedError (a
        ConnectionError subclass, so pre-resilience handlers and the
        elastic loop both keep working); with fault tolerance on the
        death is also published to the liveness table so distant ranks
        attribute their own stalls to rank `r` within one poll."""
        from ..common.exceptions import RanksFailedError
        from ..resilience import current_op
        if self._res is not None:
            self._res.mark_failed(r, f"shm peer pid {pid} died")
        raise RanksFailedError(
            frozenset({r}), op=current_op(), phase="shm_barrier",
            message=f"shm peer rank {r} (pid {pid}) died")

    def _barrier_deadline(self, target: int, seqs: list[int]) -> None:
        """Deadline expiry: attribute the stall to the ranks still below
        the barrier target instead of a bare timeout (with resilience
        off this keeps the historical TimeoutError type)."""
        lagging = sorted(
            r for r, s in enumerate(seqs)
            if r != self.rank
            and (s - _POISON if s >= _POISON else s) < target)
        if self._res is None:
            raise TimeoutError(
                f"shm barrier target {target} not reached within "
                f"{self.barrier_timeout}s (lagging ranks: {lagging})")
        from ..common.exceptions import RanksFailedError
        from ..resilience import current_op
        for r in lagging:
            self._res.mark_failed(
                r, f"shm barrier target {target} missed for "
                   f"{self.barrier_timeout:g}s", confirmed=False)
        raise RanksFailedError(
            frozenset(lagging), op=current_op(), phase="shm_barrier",
            message=f"shm barrier target {target} not reached within "
                    f"{self.barrier_timeout:g}s; lagging ranks {lagging} "
                    f"are alive but absent from the collective (wedged).")

    def data(self, r: int) -> np.ndarray:
        return self._datas[r]   # type: ignore[return-value]

    def close(self) -> None:
        self._seqs = [None] * self.size
        self._splits = [None] * self.size
        self._datas = [None] * self.size
        for mm in self._maps:
            if mm is not None:
                try:
                    mm.close()
                except BufferError:   # outstanding views: leak, don't crash
                    pass
        self._maps = [None] * self.size
        own = getattr(self, "_own_path", None)
        if own:
            try:
                os.unlink(own)
            except OSError:
                pass


class ShmBackend(CollectiveBackend):
    """Same-host allreduce, broadcast, ragged allgather and alltoall over
    a ShmWorld; fused allreduce/allgather responses ride it natively
    (entry-major packed staging), other fused shapes fall through to the
    TCP/XLA planes via ``enabled()``.  Broadcast/allgather/alltoall use a
    2-barrier variant of the protocol (publish 3t+1 after staging, jump
    straight to 3t+3 after reading — the monotonic ``>=`` waits make the
    skipped middle word equivalent); alltoall additionally publishes its
    split table in the region header, with sentinel flags that delegate
    oversized payloads to TCP or surface invalid splits symmetrically."""

    name = "shm"

    def __init__(self, world: ShmWorld) -> None:
        self.world = world
        self.ops_executed = 0   # observability for tests/PERFORMANCE.md
        # Telemetry (no-op metrics when HOROVOD_METRICS=off): ops claimed
        # by this plane and bytes staged through the shared region.
        from ..telemetry import metrics as _tm_metrics
        _tm = _tm_metrics()
        self._m_ops = _tm.counter(
            "horovod_shm_ops_total",
            "Collectives executed on the shared-memory plane")
        self._m_staged = _tm.counter(
            "horovod_shm_staged_bytes_total",
            "Payload bytes staged into /dev/shm regions")
        # TcpBackend delegate for alltoall payloads that exceed the
        # region capacity: per-rank dim-0 sizes are not in the response,
        # so the fit decision can only be made mid-protocol — an
        # oversized rank raises a header flag and EVERY rank delegates
        # (set by core.init).
        self.tcp = None

    def enabled(self, response: Response,
                entries: list[TensorTableEntry]) -> bool:
        if self.world.poison_seen():
            return False
        rt = response.response_type
        if rt == ResponseType.ALLREDUCE:
            # Fused payload must fit one region.  All inputs to the
            # sizing decision come from the response, so it stays
            # rank-symmetric whatever the codec.
            n = sum(response.tensor_sizes)
            if self.quantized_codec(response) is not None:
                from ..compress import staged_nbytes
                per_chunk, stage_total = staged_nbytes(
                    n, self.world.size, self.quantized_codec(response),
                    self.codec_block_size(response))
                # Staged contribution chunks + the owner's requantized
                # result chunk live in one region concurrently.
                nbytes = stage_total + (max(per_chunk) if per_chunk
                                        else 0)
            else:
                wire_dt = self.wire_cast_dtype(response)
                itemsize = wire_dt.itemsize if wire_dt is not None \
                    else element_size(response.tensor_type)
                nbytes = n * itemsize
        elif rt == ResponseType.BROADCAST and len(entries) == 1:
            nbytes = response.tensor_sizes[0] * \
                element_size(response.tensor_type)
        elif rt == ResponseType.REDUCESCATTER and len(entries) == 1 \
                and entries[0].tensor is not None:
            # Shapes are cross-rank validated for reducescatter, so the
            # local staging size is a rank-symmetric decision.
            nbytes = np.asarray(entries[0].tensor).size * \
                element_size(response.tensor_type)
        elif rt == ResponseType.ALLTOALL:
            # Every clause is rank-symmetric (alltoall with a joined rank
            # is rejected upstream, so tensors are present everywhere);
            # capacity is checked mid-protocol via the header flag.
            return (self.world.formed and self.tcp is not None
                    and len(entries) == 1
                    and entries[0].tensor is not None
                    and self.world.size <= _MAX_SPLITS)
        elif rt == ResponseType.ALLGATHER \
                and all(e.tensor is not None for e in entries):
            # Each rank stages only its OWN blocks (entry-major packed
            # for fused responses); capacity must hold the LARGEST
            # per-rank packed payload anywhere so the decision is
            # rank-symmetric (dims come from the response, trailing
            # shapes from our own entries — cross-rank validated equal).
            esz = element_size(response.tensor_type)
            dims = self.allgather_entry_dims(response, len(entries),
                                             self.world.size)
            rests = []
            for e in entries:
                shape = np.asarray(e.tensor).shape
                rests.append(int(np.prod(shape[1:]))
                             if len(shape) > 1 else 1)
            per_rank, _ = self._fused_allgather_layout(dims, rests, esz)
            nbytes = int(per_rank.sum(axis=0).max())
        else:
            return False
        return self.world.formed and nbytes <= self.world.capacity

    @staticmethod
    def _stage_except(region: np.ndarray, flat_u8: np.ndarray,
                      lo_byte: int, hi_byte: int) -> None:
        """Stage a payload into this rank's region, skipping the
        [lo_byte, hi_byte) range destined to self: no peer ever reads it
        (the own slice is copied straight from the local buffer), so two
        writes save 1/size of the staging traffic."""
        region[:lo_byte] = flat_u8[:lo_byte]
        region[hi_byte:flat_u8.nbytes] = flat_u8[hi_byte:]

    def allreduce(self, response: Response,
                  entries: list[TensorTableEntry]) -> Status:
        t = self.world._t
        self.world._t += 1
        self._act_start(entries, "SHM_ALLREDUCE")
        try:
            return self._allreduce_locked(response, entries, t)
        except BaseException:
            # Leave no peer spinning on a barrier we will never publish.
            self.world.poison()
            raise
        finally:
            self._act_end(entries)

    def _allreduce_locked(self, response: Response,
                          entries: list[TensorTableEntry],
                          t: int) -> Status:
        w = self.world
        rank, size = w.rank, w.size
        result_dtype = to_numpy(response.tensor_type)
        codec = self.quantized_codec(response)
        if codec is not None:
            return self._allreduce_quantized(response, entries, t, codec)
        # Cast codecs (fp16/bf16) stage and reduce in the wire dtype —
        # the fp32-accumulation contract below already widens 16-bit
        # wires, so this reproduces the legacy cast-compression exactly
        # while shrinking the staged bytes 2x.
        np_dtype = self.wire_cast_dtype(response) or result_dtype
        n = sum(response.tensor_sizes)

        # Peers must be done READING my previous result before I repack.
        w.wait_all(3 * t)
        my_region = w.data(rank)[:n * np_dtype.itemsize].view(np_dtype)
        packed = self.pack_fusion_buffer(response, entries)
        packed = self.scale_buffer(packed, response.prescale_factor)
        my_region[:] = packed.astype(np_dtype, copy=False)
        w.publish(3 * t + 1)
        nbytes = n * np_dtype.itemsize
        self._m_ops.inc()
        self._m_staged.inc(nbytes)

        if size == 2:
            # Two ranks: one fused full-sum pass per rank beats the
            # chunked reduce+gather (2 barriers instead of 3, 2n touched
            # instead of 2.5n).
            w.wait_all(3 * t + 1)
            peer = w.data(1 - rank)[:nbytes].view(np_dtype)
            out = self._full_sum(my_region, peer, np_dtype)
            # 3t+2 and 3t+3 both published: peers wait on 3(t+1) before
            # repacking, so the skipped middle barrier stays consistent
            # with the general protocol.
            w.publish(3 * t + 3)
            out = out.astype(result_dtype, copy=False)
            out = self.scale_buffer(out, response.postscale_factor)
            self.unpack_fusion_buffer(out, response, entries)
            self.ops_executed += 1
            return Status.ok()

        # Reduce chunk `rank` across every rank's region (fp32 widening
        # for 16-bit wire dtypes, one rounding at the end — the flat-ring
        # numerics contract).
        base, rem = divmod(n, size)
        sizes = [base + (1 if i < rem else 0) for i in range(size)]
        bounds = np.cumsum([0] + sizes)
        lo, hi = int(bounds[rank]), int(bounds[rank + 1])
        w.wait_all(3 * t + 1)
        if hi > lo:
            acc_dt = _accum_dtype(np_dtype)
            mine = my_region[lo:hi]
            if acc_dt is np_dtype:
                # In-place accumulation into my chunk: peers only ever
                # read their OWN chunk index from my region, never mine,
                # so the read/write sets are disjoint.
                for r in range(size):
                    if r != rank:
                        mine += w.data(r)[lo * np_dtype.itemsize:
                                          hi * np_dtype.itemsize
                                          ].view(np_dtype)
            else:
                # 16-bit wire dtypes: widen once, round once.
                acc = mine.astype(acc_dt, copy=True)
                for r in range(size):
                    if r != rank:
                        acc += w.data(r)[lo * np_dtype.itemsize:
                                         hi * np_dtype.itemsize
                                         ].view(np_dtype).astype(acc_dt)
                mine[:] = acc.astype(np_dtype, copy=False)
        w.publish(3 * t + 2)

        # Gather the reduced chunks straight out of their owners' regions
        # into a FRESH private array (the regions are recycled next op;
        # entry outputs alias this array zero-copy and must outlive it).
        w.wait_all(3 * t + 2)
        out = np.empty(n, dtype=np_dtype)
        for r in range(size):
            rlo, rhi = int(bounds[r]), int(bounds[r + 1])
            if rhi > rlo:
                src = w.data(r)[rlo * np_dtype.itemsize:
                                rhi * np_dtype.itemsize].view(np_dtype)
                out[rlo:rhi] = src
        w.publish(3 * t + 3)

        out = out.astype(result_dtype, copy=False)
        out = self.scale_buffer(out, response.postscale_factor)
        self.unpack_fusion_buffer(out, response, entries)
        self.ops_executed += 1
        return Status.ok()

    def _allreduce_quantized(self, response: Response,
                             entries: list[TensorTableEntry],
                             t: int, codec) -> Status:
        """Quantized allreduce over the shm regions — the same
        owner-reduce math as TcpCollectives.quantized_allreduce (one
        input quantization, fp32 accumulation, one requantization of the
        reduced chunk), expressed in the 3-barrier lockstep:

          stage   serialized quantized chunks, one per destination rank,
                  at deterministic offsets;          publish 3t+1
          reduce  my chunk: dequantize every rank's contribution
                  (including my own) + sum in fp32, requantize once into
                  the region's RESULT area;          publish 3t+2
          gather  owners' requantized chunks, dequantize into a fresh
                  private array;                     publish 3t+3

        Regions carry ~1/4 (int8) / ~1/8 (uint4) of the fp32 bytes, and
        the reconstruction matches the tcp plane bit-for-bit (identical
        quantize/dequantize order — the fused kernels execute the same
        fp32 ops in the same rank order), so planes stay
        interchangeable.  Dispatch (HOROVOD_FUSED_KERNELS / the
        autotuned ``fused`` attribute): single-pass fused kernels
        (compress/fused.py — requantize straight into the shm region,
        dequantize+accumulate in place off the staged bytes) vs the
        reference per-chunk chain.  Bitwise identical either way."""
        fused = getattr(self, "fused", None)
        if fused is None:
            from ..common import config
            fused = self.fused = bool(config.FUSED_KERNELS.get())
        if fused:
            return self._allreduce_quantized_fused(response, entries, t,
                                                   codec)
        return self._allreduce_quantized_reference(response, entries, t,
                                                   codec)

    def _allreduce_quantized_fused(self, response: Response,
                                   entries: list[TensorTableEntry],
                                   t: int, codec) -> Status:
        from ..compress import chunk_bounds, staged_nbytes
        from ..compress.fused import FusedKernels
        fk = getattr(self, "_fk", None)
        if fk is None:
            fk = self._fk = FusedKernels()
        w = self.world
        rank, size = w.rank, w.size
        result_dtype = to_numpy(response.tensor_type)
        block_size = self.codec_block_size(response)
        n = sum(response.tensor_sizes)
        per_chunk, stage_total = staged_nbytes(n, size, codec, block_size)
        chunk_off = np.cumsum([0] + per_chunk)
        bounds = chunk_bounds(n, size)

        w.wait_all(3 * t)
        packed = self.pack_fusion_buffer(response, entries)
        packed = self.scale_buffer(packed, response.prescale_factor)
        x = packed.astype(np.float32, copy=False)
        region = w.data(rank)
        for j in range(size):
            wire = fk.encode(x[bounds[j]:bounds[j + 1]], codec,
                             block_size, ("enc",))
            region[int(chunk_off[j]):int(chunk_off[j]) + wire.size] = wire
        w.publish(3 * t + 1)

        w.wait_all(3 * t + 1)
        my_len = int(bounds[rank + 1] - bounds[rank])
        lo = int(chunk_off[rank])
        acc = fk.f32(("acc",), my_len)
        acc[:] = 0.0
        for r in range(size):                  # rank-order accumulate
            fk.decode_add(w.data(r)[lo:lo + per_chunk[rank]], my_len,
                          codec, block_size, acc, ("in",))
        reduced = fk.encode(acc, codec, block_size, ("red",))
        region[stage_total:stage_total + reduced.size] = reduced
        w.publish(3 * t + 2)

        w.wait_all(3 * t + 2)
        out = np.empty(n, np.float32)
        for r in range(size):
            fk.decode_into(w.data(r)[stage_total:stage_total
                                     + per_chunk[r]],
                           int(bounds[r + 1] - bounds[r]), codec,
                           block_size, out[bounds[r]:bounds[r + 1]],
                           ("out",))
        w.publish(3 * t + 3)

        out = out.astype(result_dtype, copy=False)
        out = self.scale_buffer(out, response.postscale_factor)
        self.unpack_fusion_buffer(out, response, entries)
        self.ops_executed += 1
        return Status.ok()

    def _allreduce_quantized_reference(self, response: Response,
                                       entries: list[TensorTableEntry],
                                       t: int, codec) -> Status:
        """Reference quantized lockstep (pre-fusion): per-chunk
        quantize/to_bytes into the region, from_bytes/dequantize out.
        Kept as the fused-vs-reference A/B baseline and the
        HOROVOD_FUSED_KERNELS=0 fallback."""
        from ..compress import (chunk_bounds, dequantize, from_bytes,
                                quantize, staged_nbytes, to_bytes)
        w = self.world
        rank, size = w.rank, w.size
        result_dtype = to_numpy(response.tensor_type)
        block_size = self.codec_block_size(response)
        n = sum(response.tensor_sizes)
        per_chunk, stage_total = staged_nbytes(n, size, codec, block_size)
        chunk_off = np.cumsum([0] + per_chunk)
        bounds = chunk_bounds(n, size)

        w.wait_all(3 * t)
        packed = self.pack_fusion_buffer(response, entries)
        packed = self.scale_buffer(packed, response.prescale_factor)
        x = packed.astype(np.float32, copy=False)
        region = w.data(rank)
        for j in range(size):
            raw = to_bytes(quantize(x[bounds[j]:bounds[j + 1]], codec,  # hvdlint: disable=per-segment-codec-loop -- this IS the reference chain the fused kernels replace; kept for the fused-vs-reference A/B and as the dispatch fallback
                                    block_size))
            region[int(chunk_off[j]):int(chunk_off[j]) + len(raw)] = \
                np.frombuffer(raw, np.uint8)
        w.publish(3 * t + 1)

        w.wait_all(3 * t + 1)
        my_len = int(bounds[rank + 1] - bounds[rank])
        lo = int(chunk_off[rank])
        acc = np.zeros(my_len, np.float32)
        for r in range(size):
            raw = w.data(r)[lo:lo + per_chunk[rank]]
            acc += dequantize(from_bytes(raw, my_len, codec, block_size))  # hvdlint: disable=per-segment-codec-loop -- reference A/B baseline (see above)
        reduced = to_bytes(quantize(acc, codec, block_size))
        region[stage_total:stage_total + len(reduced)] = \
            np.frombuffer(reduced, np.uint8)
        w.publish(3 * t + 2)

        w.wait_all(3 * t + 2)
        out = np.empty(n, np.float32)
        for r in range(size):
            raw = w.data(r)[stage_total:stage_total + per_chunk[r]]
            out[bounds[r]:bounds[r + 1]] = dequantize(  # hvdlint: disable=per-segment-codec-loop -- reference A/B baseline (see above)
                from_bytes(raw, int(bounds[r + 1] - bounds[r]), codec,  # hvdlint: disable=per-segment-codec-loop -- reference A/B baseline (see above)
                           block_size))
        w.publish(3 * t + 3)

        out = out.astype(result_dtype, copy=False)
        out = self.scale_buffer(out, response.postscale_factor)
        self.unpack_fusion_buffer(out, response, entries)
        self.ops_executed += 1
        return Status.ok()

    @staticmethod
    def _full_sum(a: np.ndarray, b: np.ndarray,
                  np_dtype: np.dtype) -> np.ndarray:
        acc_dt = _accum_dtype(np_dtype)
        if acc_dt is np_dtype:
            out = np.empty(a.shape, dtype=np_dtype)
            np.add(a, b, out=out)
            return out
        return (a.astype(acc_dt) + b.astype(acc_dt)).astype(np_dtype)

    def broadcast(self, response: Response,
                  entries: list[TensorTableEntry]) -> Status:
        """Root writes its payload once; every peer reads it straight out
        of the root's region — one copy in, one copy out per rank,
        vs the TCP star's per-peer socket round trips (big win for
        broadcast_parameters at model startup)."""
        w = self.world
        t = w._t
        w._t += 1
        self._act_start(entries, "SHM_BCAST")
        try:
            np_dtype = to_numpy(response.tensor_type)
            root = response.root_rank
            (entry,) = entries
            w.wait_all(3 * t)
            if w.rank == root:
                shape = np.asarray(entry.tensor).shape
                # NB: ascontiguousarray promotes 0-d to 1-d — restore the
                # original shape on the output.
                local = np.ascontiguousarray(
                    np.asarray(entry.tensor, dtype=np_dtype))
                w.data(root)[:local.nbytes] = \
                    local.reshape(-1).view(np.uint8)
                w.publish(3 * t + 1)
                entry.output = local.copy().reshape(shape)
            else:
                w.publish(3 * t + 1)
                w.wait_all(3 * t + 1)
                n = response.tensor_sizes[0]
                src = w.data(root)[:n * np_dtype.itemsize].view(np_dtype)
                shape = np.asarray(entry.tensor).shape \
                    if entry.tensor is not None else (n,)
                entry.output = src.reshape(shape).copy()
            w.publish(3 * t + 3)
            self.ops_executed += 1
            return Status.ok()
        except BaseException:
            w.poison()
            raise
        finally:
            self._act_end(entries)

    def allgather(self, response: Response,
                  entries: list[TensorTableEntry]) -> Status:
        """Each rank stages its (ragged dim-0) blocks in its own region —
        entry-major packed for fused responses — and peers assemble the
        rank-ordered concatenation directly from the owners' regions:
        one staging pass and one read pass regardless of how many
        tensors the response fused."""
        w = self.world
        t = w._t
        w._t += 1
        self._act_start(entries, "SHM_ALLGATHER")
        try:
            np_dtype = to_numpy(response.tensor_type)
            dims = self.allgather_entry_dims(response, len(entries),
                                             w.size)
            locals_ = [np.ascontiguousarray(
                np.asarray(e.tensor, dtype=np_dtype)) for e in entries]
            rests = [int(np.prod(a.shape[1:])) if a.ndim > 1 else 1
                     for a in locals_]
            itemsize = np_dtype.itemsize
            # bytes[i][r] and each entry's exclusive prefix inside rank
            # r's entry-major region (shared layout with the flat planes).
            nbytes, ent_off = self._fused_allgather_layout(dims, rests,
                                                           itemsize)
            w.wait_all(3 * t)
            staged = 0
            for a in locals_:
                w.data(w.rank)[staged:staged + a.nbytes] = \
                    a.reshape(-1).view(np.uint8)
                staged += a.nbytes
            w.publish(3 * t + 1)
            w.wait_all(3 * t + 1)
            for i, entry in enumerate(entries):
                total = sum(dims[i])
                out = np.empty(total * rests[i], dtype=np_dtype)
                offset = 0
                for r in range(w.size):
                    count = dims[i][r] * rests[i]
                    if r == w.rank:   # own block: skip the region trip
                        out[offset:offset + count] = \
                            locals_[i].reshape(-1)
                    else:
                        lo = int(ent_off[i, r])
                        out[offset:offset + count] = \
                            w.data(r)[lo:lo + count * itemsize
                                      ].view(np_dtype)
                    offset += count
                entry.output = out.reshape((total,)
                                           + locals_[i].shape[1:])
            w.publish(3 * t + 3)
            self.ops_executed += 1
            return Status.ok()
        except BaseException:
            w.poison()
            raise
        finally:
            self._act_end(entries)

    def reducescatter(self, response: Response,
                      entries: list[TensorTableEntry]) -> Status:
        """Stage the full buffer; reduce only my dim-0 row range across
        all regions (same uneven row split as the TCP plane) — no gather
        phase at all, 2 barriers, (size-1)/size of the payload read."""
        w = self.world
        t = w._t
        w._t += 1
        self._act_start(entries, "SHM_REDUCESCATTER")
        try:
            np_dtype = to_numpy(response.tensor_type)
            (entry,) = entries
            local = np.ascontiguousarray(
                np.asarray(entry.tensor, dtype=np_dtype))
            shape = local.shape
            rest = int(np.prod(shape[1:])) if len(shape) > 1 else 1
            rows = dim0_row_bounds(shape[0], w.size)
            lo = rows[w.rank] * rest
            hi = rows[w.rank + 1] * rest

            w.wait_all(3 * t)
            flat = self.scale_buffer(local.reshape(-1),
                                     response.prescale_factor)
            self._stage_except(w.data(w.rank), flat.view(np.uint8),
                               lo * np_dtype.itemsize,
                               hi * np_dtype.itemsize)
            w.publish(3 * t + 1)
            w.wait_all(3 * t + 1)
            acc_dt = _accum_dtype(np_dtype)
            acc = flat[lo:hi].astype(acc_dt, copy=True)
            for r in range(w.size):
                if r != w.rank:
                    peer = w.data(r)[lo * np_dtype.itemsize:
                                     hi * np_dtype.itemsize].view(np_dtype)
                    acc += peer.astype(acc_dt) if acc_dt != np_dtype \
                        else peer
            w.publish(3 * t + 3)
            out = self.scale_buffer(acc.astype(np_dtype, copy=False),
                                    response.postscale_factor)
            my_rows = rows[w.rank + 1] - rows[w.rank]
            entry.output = out.reshape((my_rows,) + shape[1:])
            self.ops_executed += 1
            return Status.ok()
        except BaseException:
            w.poison()
            raise
        finally:
            self._act_end(entries)

    def alltoall(self, response: Response,
                 entries: list[TensorTableEntry]) -> Status:
        """Each rank stages its full send buffer + its split row-counts
        (header table); peers pull exactly their targeted slice from each
        sender's region — no pairwise socket exchange."""
        w = self.world
        t = w._t
        w._t += 1
        self._act_start(entries, "SHM_ALLTOALL")
        try:
            np_dtype = to_numpy(response.tensor_type)
            (entry,) = entries
            local = np.ascontiguousarray(
                np.asarray(entry.tensor, dtype=np_dtype))
            splits = self.resolve_alltoall_splits(entry, local.shape[0],
                                                  w.size)
            rest = int(np.prod(local.shape[1:])) if local.ndim > 1 else 1
            w.wait_all(3 * t)
            table = w._splits[w.rank]
            if isinstance(splits, Status):
                # Rank-local argument error: the sentinel keeps every
                # peer IN the lockstep (a bare return would strand them
                # at the barrier) and makes the failure symmetric — an
                # improvement over pairwise planes, where one bad rank
                # can stall its partners.
                table[0] = -2
            elif local.nbytes > w.capacity:
                table[0] = -1   # too big: ask every rank to delegate
            else:
                own_lo = sum(splits[:w.rank]) * rest * np_dtype.itemsize
                own_hi = own_lo + splits[w.rank] * rest * np_dtype.itemsize
                self._stage_except(w.data(w.rank),
                                   local.reshape(-1).view(np.uint8),
                                   own_lo, own_hi)
                table[0] = len(splits)
                table[1:1 + len(splits)] = splits
            w.publish(3 * t + 1)
            w.wait_all(3 * t + 1)
            flags = [int(w._splits[r][0]) for r in range(w.size)]
            if any(f == -2 for f in flags):
                w.publish(3 * t + 3)
                return splits if isinstance(splits, Status) else \
                    Status.invalid_argument(
                        "a peer submitted invalid alltoall splits")
            if any(f == -1 for f in flags):
                # Unanimous fallback: some rank's buffer exceeds the
                # region; all ranks run the pairwise TCP exchange.
                w.publish(3 * t + 3)
                return self.tcp.alltoall(response, entries)
            recv_splits = []
            slices = []
            for r in range(w.size):
                peer_table = w._splits[r]
                peer_splits = [int(x)
                               for x in peer_table[1:1 + int(peer_table[0])]]
                start = sum(peer_splits[:w.rank]) * rest
                rows = peer_splits[w.rank]
                slices.append((start, rows * rest))
                recv_splits.append(rows)
            out = np.empty(sum(n for _, n in slices), dtype=np_dtype)
            offset = 0
            for r, (start, count) in enumerate(slices):
                if r == w.rank:   # own block: skip the region round-trip
                    out[offset:offset + count] = \
                        local.reshape(-1)[start:start + count]
                else:
                    lo = start * np_dtype.itemsize
                    out[offset:offset + count] = \
                        w.data(r)[lo:lo + count * np_dtype.itemsize
                                  ].view(np_dtype)
                offset += count
            w.publish(3 * t + 3)
            entry.output = out.reshape((sum(recv_splits),)
                                       + local.shape[1:])
            entry.received_splits = recv_splits
            self.ops_executed += 1
            return Status.ok()
        except BaseException:
            w.poison()
            raise
        finally:
            self._act_end(entries)
