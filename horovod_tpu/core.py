"""Global state, background coordination thread, and the enqueue API.

TPU-native rebuild of the reference core runtime
(reference: horovod/common/operations.cc — InitializeHorovodOnce at 651-699,
BackgroundThreadLoop at 589-647, RunLoopOnce + PerformOperation at 256-329,
EnqueueTensor* at 919-1226) plus the handle/future layer
(reference: horovod/torch/handle_manager.cc).

Design: user threads enqueue TensorTableEntries + Requests; a single
background thread runs the controller protocol every CycleTime ms, receives
the identical fused ResponseList on every rank, and executes each Response
through the backend priority chain.  Completion flows back through per-entry
callbacks into Handle futures, never blocking the background thread.
"""
from __future__ import annotations

import os
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Sequence

import numpy as np

from .analysis.hvdshard.specs import spec_token
from .backend.base import OperationManager
from .backend.basic import BasicBackend
from .common import config
from .common.controller import Controller, LocalTransport
from .common.dtypes import from_any
from .common.group_table import GroupTable
from .common.logging import configure as configure_logging
from .common.logging import logger
from .common.message import (Request, RequestType, Response, ResponseType)
from .common.response_cache import ResponseCache
from .common.stall_inspector import StallInspector
from .common.status import Status
from .common.tensor_queue import TensorQueue, TensorTableEntry
from .common.timeline import Timeline

JOIN_TENSOR_NAME = "__join__"


class Handle:
    """Future for one (possibly grouped) async collective
    (reference: torch/handle_manager.cc)."""

    __slots__ = ("_event", "status", "entries", "_pending", "_hid",
                 "wrap_refs", "inplace_targets", "wants_recv_splits")

    def __init__(self, entries: list[TensorTableEntry]) -> None:
        self._event = threading.Event()
        self.status: Status | None = None
        self.entries = entries
        self._pending = len(entries)
        self._hid = -1
        # Original framework tensors (torch/jax/...) so async results can be
        # returned in the caller's framework, same as the sync API.
        self.wrap_refs: list[Any] = []
        # torch binding extras: in-place copy-back targets, alltoall
        # received-splits flag (see horovod_tpu/torch/mpi_ops.py).
        self.inplace_targets: list[Any] = []
        self.wants_recv_splits = False

    def done(self) -> bool:
        return self._event.is_set()

    def wait(self, timeout: float | None = None) -> Status:
        if not self._event.wait(timeout):
            raise TimeoutError("collective did not complete in time")
        assert self.status is not None
        return self.status

    def outputs(self) -> list[Any]:
        return [e.output for e in self.entries]


class HandleManager:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._next = 0
        self._handles: dict[int, Handle] = {}

    def allocate(self, entries: list[TensorTableEntry]) -> tuple[int, Handle]:
        handle = Handle(entries)
        with self._lock:
            hid = self._next
            self._next += 1
            handle._hid = hid
            self._handles[hid] = handle
        return hid, handle

    def get(self, hid: int) -> Handle:
        with self._lock:
            return self._handles[hid]

    def entry_done(self, handle: Handle, status: Status) -> None:
        with self._lock:
            handle._pending -= 1
            # First error wins; OK only recorded if nothing failed.
            if handle.status is None or (handle.status.ok_p()
                                         and not status.ok_p()):
                handle.status = status
            if handle._pending <= 0:
                # Auto-release: once complete, the caller's Handle reference
                # is the only owner — the table must not pin tensors forever.
                self._handles.pop(handle._hid, None)
                handle._event.set()

    def release(self, hid: int) -> None:
        with self._lock:
            self._handles.pop(hid, None)


class StreamDispatcher:
    """HOROVOD_NUM_STREAMS persistent worker threads executing the
    independent responses of one cycle concurrently — the multi-stream
    analogue of the reference's per-stream NCCL queues
    (HOROVOD_NUM_NCCL_STREAMS).  Workers live for the whole run (no
    per-cycle/per-response thread spawn); the background loop enqueues a
    cycle's responses with their deterministic stream assignment and
    blocks on the cycle latch, so the controller protocol still advances
    one fully-executed cycle at a time."""

    def __init__(self, num_streams: int) -> None:
        self.num_streams = num_streams
        self._queues: list[queue.Queue] = [queue.Queue()
                                           for _ in range(num_streams)]
        self._threads = [
            threading.Thread(target=self._worker, args=(k,), daemon=True,
                             name=f"hvd-stream-{k}")
            for k in range(num_streams)]
        for t in self._threads:
            t.start()

    def run_cycle(self, work: list[tuple[int, Any]]) -> None:
        """Execute [(stream, thunk)] concurrently across the stream
        workers; returns when every thunk finished."""
        if not work:
            return
        remaining = len(work)
        lock = threading.Lock()
        done = threading.Event()

        def _count_down() -> None:
            nonlocal remaining
            with lock:
                remaining -= 1
                if remaining == 0:
                    done.set()

        for stream, thunk in work:
            self._queues[stream].put((thunk, _count_down))
        done.wait()

    def _worker(self, k: int) -> None:
        q = self._queues[k]
        while True:
            item = q.get()
            if item is None:
                return
            thunk, count_down = item
            try:
                thunk()
            except Exception as exc:  # noqa: BLE001 - entry.finish reports
                logger.error("stream %d execution failed: %s", k, exc)
            finally:
                count_down()

    def stop(self) -> None:
        for q in self._queues:
            q.put(None)
        for t in self._threads:
            t.join(timeout=5)


@dataclass
class GlobalState:
    rank: int = 0
    size: int = 1
    local_rank: int = 0
    local_size: int = 1
    cross_rank: int = 0
    cross_size: int = 1
    initialized: bool = False
    shutdown_requested: bool = False
    background_thread: threading.Thread | None = None
    tensor_queue: TensorQueue = field(default_factory=TensorQueue)
    group_table: GroupTable = field(default_factory=GroupTable)
    controller: Controller | None = None
    op_manager: OperationManager | None = None
    # Multi-stream response dispatch (HOROVOD_NUM_STREAMS): op_managers[k]
    # is stream k's backend chain (stream 0 = the full chain above;
    # streams 1.. carry per-stream TCP/basic instances over their own
    # PeerMesh channel sets).  active_streams <= len(op_managers) is the
    # runtime width (autotuner-adjustable through the ResponseList).
    op_managers: list[OperationManager] = field(default_factory=list)
    stream_dispatcher: StreamDispatcher | None = None
    tcp_collectives: list[Any] = field(default_factory=list)
    active_streams: int = 1
    handle_manager: HandleManager = field(default_factory=HandleManager)
    timeline: Timeline | None = None
    # Metrics registry (telemetry/; HOROVOD_METRICS).  Null when off so
    # hot paths test one attribute and skip all instrumentation.
    telemetry: Any = None
    # Flight recorder (telemetry/flight.py; HOROVOD_FLIGHT).  Null when
    # off; records a bounded ring of trace events and dumps it on every
    # structured failure.
    flight: Any = None
    # Chaos engine (resilience/chaos.py; HOROVOD_CHAOS).  None when off;
    # the background loop fires its deterministic response-level actions.
    chaos: Any = None
    parameter_manager: Any = None
    cycle_time_ms: float = 1.0
    joined: bool = False
    elastic_enabled: bool = False
    # Runtime default wire codec (autotuner override via the ResponseList
    # tuned_codec field); None = honor HOROVOD_COMPRESSION.
    codec_override: str | None = None
    # Resolved fabric layout (common/topology.Topology) from
    # HOROVOD_TOPOLOGY + the launcher env; drives ring orders, the torus
    # allreduce eligibility and the hierarchical level ladder.
    topology: Any = None
    # resources to close at shutdown (sockets, rendezvous server, ...)
    resources: list[Any] = field(default_factory=list)

    def mark_done_callback(self, handle: Handle):
        def _cb(status: Status) -> None:
            self.handle_manager.entry_done(handle, status)
        return _cb


_global = GlobalState()
_init_lock = threading.Lock()
_atexit_registered = False


def global_state() -> GlobalState:
    return _global


# ---------------------------------------------------------------------------
# Initialization / shutdown (reference: operations.cc:651-769)
# ---------------------------------------------------------------------------
def init(*, rank: int | None = None, size: int | None = None,
         rendezvous_addr: str | None = None,
         rendezvous_port: int | None = None,
         local_rank: int | None = None, local_size: int | None = None,
         cross_rank: int | None = None, cross_size: int | None = None) -> None:
    """Initialize the runtime: discover the world from env/args, connect the
    control plane, build backends, spawn the background thread."""
    with _init_lock:
        if _global.initialized:
            return

        # Under jsrun (LSF) every rank receives the same environment; rank
        # identity arrives via JSM/PMIx vars instead of per-slot env.
        from .runner.js_run import adopt_jsm_env
        adopt_jsm_env()

        def _resolve(kwarg, knob, fallback):
            if kwarg is not None:
                return kwarg
            env = knob.get()
            return env if env >= 0 else fallback

        rank = _resolve(rank, config.RANK, 0)
        size = _resolve(size, config.SIZE, 1)
        # Topology default: one host holding every rank (local == global),
        # matching single-host launches without explicit env.
        local_rank = _resolve(local_rank, config.LOCAL_RANK, rank)
        local_size = _resolve(local_size, config.LOCAL_SIZE, size)
        cross_rank = _resolve(cross_rank, config.CROSS_RANK, 0)
        cross_size = _resolve(cross_size, config.CROSS_SIZE, 1)

        configure_logging(rank)
        # Telemetry registry BEFORE any mesh/controller construction —
        # they cache metric handles from the configured registry.
        from . import telemetry as _telemetry
        _global.telemetry = _telemetry.configure(rank)
        _global.flight = _telemetry.flight.configure(rank)
        if _global.telemetry.enabled:
            # Every elastic transition re-inits, so the gauge tracks
            # grow/shrink without statesync having to be loaded.
            _global.telemetry.gauge(
                "horovod_world_size",
                "Live world size as seen by this rank's statesync "
                "service (tracks every elastic grow/shrink transition)"
            ).set(size)
        _global.rank, _global.size = rank, size
        _global.local_rank, _global.local_size = local_rank, local_size
        _global.cross_rank, _global.cross_size = cross_rank, cross_size
        # Fabric layout (HOROVOD_TOPOLOGY; common/topology.py).  The knob
        # is launcher-uniform, so every rank resolves the same Topology —
        # the ring orders / torus shape derived below are rank-symmetric
        # by construction.
        from .common import topology as _topology
        # The launcher exports the full rank→host-index map (hosts.py
        # host_ids_env) for layouts that break the homogeneous host-major
        # assumption behind local/cross-size auto-detection; a map whose
        # length doesn't match the world (stale env across an elastic
        # resize) is ignored rather than trusted.
        host_ids = config.HOST_IDS.get()
        hosts = None
        if host_ids:
            try:
                parsed = tuple(int(x) for x in host_ids.split(","))
            except ValueError:
                parsed = ()
            if len(parsed) == size:
                hosts = parsed
        topo = _topology.resolve(size, local_size, cross_size, hosts=hosts)
        _global.topology = topo
        _global.cycle_time_ms = config.CYCLE_TIME.get()
        _global.shutdown_requested = False
        _global.tensor_queue.reset()
        _global.joined = False
        _global.elastic_enabled = config.ELASTIC.get()
        _global.tcp_collectives = []
        _global.stream_dispatcher = None
        _global.active_streams = 1

        timeline_path = config.TIMELINE.get()
        # EVERY rank records its own trace file (cross-rank stitching,
        # telemetry/trace.py): rank 0 keeps the exact configured path,
        # ranks > 0 get the '.r<rank>' suffix (timeline.rank_path) —
        # pre-PR behavior gave only rank 0 a file, so a merged trace and
        # critical-path attribution were structurally impossible.
        _global.timeline = Timeline(
            timeline_path,
            mark_cycles=config.TIMELINE_MARK_CYCLES.get(), rank=rank)

        backends = []
        if size > 1:
            addr = rendezvous_addr or config.RENDEZVOUS_ADDR.get()
            port = rendezvous_port if rendezvous_port is not None \
                else config.RENDEZVOUS_PORT.get()
            if not addr or port <= 0:
                raise RuntimeError(
                    "Multi-process world requires a rendezvous server: set "
                    "HOROVOD_GLOO_RENDEZVOUS_ADDR/PORT (the launcher does "
                    "this automatically).")
            from .common.tcp_transport import TcpTransport
            from .backend.tcp import TcpBackend, TcpCollectives
            from .runner.network import PeerMesh, RendezvousClient

            timeout = config.GLOO_TIMEOUT_SECONDS.get()
            kv = RendezvousClient(addr, port, timeout)
            epoch = os.environ.get("HOROVOD_RENDEZVOUS_EPOCH", "0")
            # Resilience BEFORE any mesh/shm formation: every PeerMesh
            # and ShmWorld captures the process ResilienceState (and the
            # chaos engine) at construction.  None when
            # HOROVOD_FAULT_TOLERANCE is off — the zero-overhead mode.
            from . import resilience
            _global.chaos = resilience.chaos.configure(rank)
            resilience.configure(rank, size, kv, epoch)
            # Form the multi-process JAX world FIRST (before any backend
            # below touches jax) — the analogue of GlooContext rendezvous
            # at init (reference: gloo/gloo_context.cc:136-152).
            from .parallel import multihost
            if multihost.should_init(size):
                multihost.init_jax_distributed(
                    rank, size, kv=kv,
                    timeout=max(timeout, 120.0))
            # XLA/ICI data plane (the NCCL-ops slot, reference:
            # operations.cc:143-252): first in the chain; enabled() falls
            # through to TCP when the JAX world doesn't span the ranks.
            xla_mode = config.parse_tristate(config.XLA_OPERATIONS.get())
            if xla_mode is True and not multihost.is_initialized():
                # Required mode must fail loudly, not silently degrade to
                # the TCP ring at a fraction of the bandwidth.
                raise RuntimeError(
                    "HOROVOD_XLA_OPERATIONS=1 requires the multi-process "
                    "JAX world; it did not form (check "
                    "HOROVOD_JAX_DISTRIBUTED and coordinator logs).")
            if xla_mode is not False and multihost.is_initialized():
                from .backend.xla import XlaBackend, XlaCommunicator
                backends.append(XlaBackend(XlaCommunicator(), size))
            # Same-host shared-memory plane (reference: Gloo shm transport
            # / MPI shared-memory windows): beats the TCP loopback ring
            # ~2x on intra-host worlds; formation is collective and
            # unanimous through the KV store.  Appended to the chain
            # AFTER the hierarchical backend below: an explicit
            # --hierarchical-* knob is a user decision and outranks the
            # auto-formed plane.
            shm_backend = None
            shm_mode = config.parse_tristate(config.SHM_OPERATIONS.get())
            shm_capacity = config.SHM_CAPACITY.get() or \
                max(config.FUSION_THRESHOLD.get(), 64 * 1024 * 1024)
            if shm_mode is not False:
                from .backend.shm import ShmBackend, ShmWorld
                shm_world = ShmWorld(
                    rank, size, kv, scope=f"shm{epoch}",
                    capacity=shm_capacity, timeout=timeout)
                if shm_world.formed:
                    _global.resources.append(shm_world)
                    shm_backend = ShmBackend(shm_world)
                elif shm_mode is True:
                    raise RuntimeError(
                        "HOROVOD_SHM_OPERATIONS=1 requires every rank on "
                        "one host/memory domain; formation failed.")
            ctrl_mesh = PeerMesh(rank, size, kv, scope=f"ctrl{epoch}",
                                 timeout=timeout)
            data_mesh = PeerMesh(rank, size, kv, scope=f"data{epoch}",
                                 timeout=timeout)
            _global.resources.extend([ctrl_mesh, data_mesh])
            transport = TcpTransport(ctrl_mesh)
            # Per-rank clock-offset estimate against the coordinator
            # (round-trip probes; the FIRST frames on the ctrl mesh, so
            # they precede every protocol frame on all ranks).  Recorded
            # as trace metadata — never applied to live timestamps.
            clock_offset_us, clock_rtt_us = transport.estimate_clock_offset()
            _global.timeline.set_clock_sync(clock_offset_us, clock_rtt_us)
            _global.flight.set_metadata(
                rank=rank, size=size, clock_offset_us=clock_offset_us,
                clock_rtt_us=clock_rtt_us)
            # Two-level eager path (reference: NCCLHierarchicalAllreduce,
            # nccl_operations.cc:187-398): refine the TCP plane with
            # local/cross sub-meshes when the knobs are on and the rank
            # layout is the launcher's homogeneous host-major assignment.
            hier_ar = config.HIERARCHICAL_ALLREDUCE.get()
            hier_ag = config.HIERARCHICAL_ALLGATHER.get()
            if (hier_ar or hier_ag) and topo.kind == "torus":
                # Declared torus (HOROVOD_TOPOLOGY=torus:RxC): the
                # hierarchical ladder follows the grid axes — RS along
                # the row, AR along the column, AG back along the row —
                # so every leg rides neighbor links.  The knob is
                # launcher-uniform and topology.parse degrades invalid
                # shapes to flat identically on every rank, so the
                # build decision is symmetric without a KV verdict.
                from .backend.hierarchical import HierarchicalTcpBackend
                t_row, t_col = divmod(rank, topo.cols)
                row_mesh = PeerMesh(
                    t_col, topo.cols, kv,
                    scope=f"htor{epoch}.r{t_row}", timeout=timeout)
                col_mesh = PeerMesh(
                    t_row, topo.rows, kv,
                    scope=f"htor{epoch}.c{t_col}", timeout=timeout)
                _global.resources.extend([row_mesh, col_mesh])
                backends.append(HierarchicalTcpBackend(
                    TcpCollectives(row_mesh),
                    TcpCollectives(col_mesh),
                    allreduce_on=hier_ar, allgather_on=hier_ag))
            elif hier_ar or hier_ag:
                # Every rank must make the SAME build-or-skip decision: a
                # rank skipping while peers form the sub-meshes would hang
                # their rendezvous.  The knob env is launcher-set (uniform),
                # and EVERY rank publishes a layout verdict — the verdict
                # itself carries per-rank eligibility (topology must be
                # two-level homogeneous host-major on every rank), so
                # heterogeneous slot counts unanimously fall back flat.
                layout_ok = (local_size > 1 and cross_size > 1 and
                             local_size * cross_size == size and
                             rank == cross_rank * local_size + local_rank)
                kv.put(f"hier{epoch}", f"ok:{rank}",
                       b"1" if layout_ok else b"0")
                all_ok = all(
                    kv.wait(f"hier{epoch}", f"ok:{r}", timeout) == b"1"
                    for r in range(size))
                if not all_ok:
                    logger.warning(
                        "hierarchical collectives requested but the rank "
                        "layout is not homogeneous host-major on every "
                        "rank (here: rank=%d local=%d/%d cross=%d/%d); "
                        "using the flat path", rank, local_rank,
                        local_size, cross_rank, cross_size)
                else:
                    from .backend.hierarchical import HierarchicalTcpBackend
                    local_mesh = PeerMesh(
                        local_rank, local_size, kv,
                        scope=f"hloc{epoch}.{cross_rank}", timeout=timeout)
                    cross_mesh = PeerMesh(
                        cross_rank, cross_size, kv,
                        scope=f"hcross{epoch}.{local_rank}", timeout=timeout)
                    _global.resources.extend([local_mesh, cross_mesh])
                    # Intra-host legs ride shm when the local ranks share
                    # a memory domain (per-host decision: the cross-leg
                    # pattern is identical either way, so hosts with and
                    # without shm interoperate).
                    hier_shm = None
                    if shm_mode is not False:
                        from .backend.shm import ShmWorld
                        hier_shm = ShmWorld(
                            local_rank, local_size, kv,
                            scope=f"hshm{epoch}.{cross_rank}",
                            capacity=shm_capacity, timeout=timeout)
                        if hier_shm.formed:
                            _global.resources.append(hier_shm)
                        else:
                            hier_shm = None
                    backends.append(HierarchicalTcpBackend(
                        TcpCollectives(local_mesh),
                        TcpCollectives(cross_mesh),
                        allreduce_on=hier_ar, allgather_on=hier_ag,
                        shm_local=hier_shm))
            # Topology-aware ring order + torus shape for the flat data
            # plane: a non-flat layout permutes the ring walk (grid
            # neighbors / host-adjacent slots) and, for a torus, enables
            # the two-phase row×column allreduce.  Identity order keeps
            # the pre-topology schedule bit-for-bit.
            ring_order = topo.ring_order() if topo.kind != "flat" else None
            torus_shape = (topo.rows, topo.cols) \
                if topo.kind == "torus" else None
            tcp_coll = TcpCollectives(data_mesh, ring_order=ring_order,
                                      torus=torus_shape)
            tcp_backend = TcpBackend(tcp_coll)
            _global.tcp_collectives = [tcp_coll]
            if shm_backend is not None:
                shm_backend.tcp = tcp_backend   # oversized-alltoall delegate
                backends.append(shm_backend)
            backends.append(tcp_backend)
            # Multi-stream response dispatch (HOROVOD_NUM_STREAMS): one
            # additional PeerMesh channel set + TCP backend chain per
            # stream, so concurrent responses never interleave bytes on a
            # shared socket and fusion staging buffers are per-stream.
            # Mesh formation is collective — the knob is launcher-set and
            # identical on every rank.
            num_streams = max(config.NUM_STREAMS.get(), 1)
            stream_managers: list[OperationManager] = []
            for s in range(1, num_streams):
                stream_mesh = PeerMesh(rank, size, kv,
                                       scope=f"data{epoch}.s{s}",
                                       timeout=timeout)
                _global.resources.append(stream_mesh)
                coll_s = TcpCollectives(stream_mesh,
                                        ring_order=ring_order,
                                        torus=torus_shape)
                _global.tcp_collectives.append(coll_s)
                tcp_s = TcpBackend(coll_s)
                basic_s = BasicBackend(size)
                tcp_s.stream = basic_s.stream = s
                tcp_s.timeline = basic_s.timeline = _global.timeline
                stream_managers.append(OperationManager([tcp_s, basic_s]))
            _global.active_streams = num_streams
            if num_streams > 1:
                _global.stream_dispatcher = StreamDispatcher(num_streams)
        else:
            transport = LocalTransport()
            stream_managers = []
            from . import resilience
            _global.chaos = resilience.chaos.configure(rank)
            _global.timeline.set_clock_sync(0.0, 0.0)
            _global.flight.set_metadata(rank=rank, size=size,
                                        clock_offset_us=0.0,
                                        clock_rtt_us=0.0)
        backends.append(BasicBackend(size))

        # Runtime collective-symmetry fingerprinting (HOROVOD_FINGERPRINT;
        # analysis/fingerprint.py): divergent ranks get a structured error
        # naming the first divergent op instead of a stall.
        from .analysis.fingerprint import FingerprintTracker
        _global.controller = Controller(
            rank=rank, size=size, transport=transport,
            tensor_queue=_global.tensor_queue,
            group_table=_global.group_table,
            response_cache=ResponseCache(config.CACHE_CAPACITY.get()),
            stall_inspector=StallInspector(),
            local_rank=local_rank, local_size=local_size,
            cross_rank=cross_rank, cross_size=cross_size,
            timeline=_global.timeline,
            fingerprint=FingerprintTracker.from_config())
        for backend in backends:
            backend.timeline = _global.timeline
        _global.op_manager = OperationManager(backends)
        _global.op_managers = [_global.op_manager] + stream_managers

        if config.AUTOTUNE.get():
            from .common.parameter_manager import ParameterManager
            _global.parameter_manager = ParameterManager(
                _global.controller, rank == 0)

        if _global.telemetry.enabled and config.METRICS_PORT.get() > 0:
            from .telemetry import MetricsExporter
            _global.resources.append(MetricsExporter(
                _global.telemetry, rank, config.METRICS_PORT.get()))

        _global.background_thread = threading.Thread(
            target=_background_loop, daemon=True, name="hvd-background")
        _global.initialized = True
        _global.background_thread.start()
        # Finalize on interpreter exit like the reference (its library
        # destructor shuts Horovod down when the process ends): a script
        # that returns without calling hvd.shutdown() still flushes the
        # timeline writer and tears sockets/regions down cleanly.
        global _atexit_registered
        if not _atexit_registered:
            import atexit
            atexit.register(shutdown)
            _atexit_registered = True
        # hvdlife census witness (HOROVOD_LIFE_CENSUS): snapshot the
        # live thread/fd/socket/mmap fabric of the freshly formed world
        # — the elastic batteries diff these around grow/shrink cycles
        # (off mode: one cached knob read, nothing else).
        from .analysis.hvdlife import census as _census
        w = _census.witness()
        if w.enabled:
            w.note(f"world:{epoch if size > 1 else '0'}:{size}",
                   rank=rank)
        logger.debug("horovod_tpu initialized: rank=%d size=%d", rank, size)


def shutdown() -> None:
    with _init_lock:
        if not _global.initialized:
            return
        _global.shutdown_requested = True
        thread = _global.background_thread
    if thread is not None:
        thread.join(timeout=60)
    with _init_lock:
        if not _global.initialized:
            return   # a concurrent shutdown won the race past the join
        # Under the lock: only state flips and the queue abort (its
        # callbacks are event sets, never blocking).  The teardown that
        # can WAIT — stream-worker joins, the timeline writer join,
        # metrics dump file I/O, channel-close joins on possibly wedged
        # peers — runs below, outside the lock: hvdsan's HVD502 showed
        # that holding _init_lock across those joins lets one dead peer
        # stall every later init()/shutdown() caller for the full
        # close grace (docs/analysis.md, lock-hold manifest).
        _global.tensor_queue.finalize()
        dispatcher = _global.stream_dispatcher
        _global.stream_dispatcher = None
        timeline = _global.timeline
        telemetry = _global.telemetry
        resources = list(_global.resources)
        _global.resources.clear()
        # Drop the per-epoch object graph NOW, not at the next init():
        # the backend chains pin the TcpCollectives' per-(peer, dtype)
        # scratch views, which pin every closed channel's receive
        # scratch (multi-MB bytearrays) — without these resets one full
        # epoch's staging memory survived each reinit_world until the
        # next world happened to form (hvdlife's epoch-leak census
        # motivated the sweep, same shape as the HVD704 rule).
        _global.controller = None
        _global.op_manager = None
        _global.op_managers = []
        _global.tcp_collectives = []
        _global.parameter_manager = None
        _global.active_streams = 1
        _global.initialized = False
        _global.background_thread = None
    if dispatcher is not None:
        dispatcher.stop()
    if timeline is not None:
        timeline.stop()
    if telemetry is not None and telemetry.enabled:
        metrics_file = config.METRICS_FILE.get()
        if metrics_file:
            from .telemetry import dump_json
            try:
                dump_json(telemetry, metrics_file, _global.rank)
            except OSError as exc:
                logger.warning("telemetry: metrics dump to %s "
                               "failed: %s", metrics_file, exc)
    for res in resources:
        try:
            res.close()
        except Exception:  # noqa: BLE001 - best-effort cleanup
            pass
    from . import resilience
    resilience.shutdown()   # stop the heartbeat monitor (if any)
    from .parallel import multihost
    multihost.shutdown_jax_distributed()
    from .analysis.hvdlife import census as _census
    w = _census.witness()
    if w.enabled:
        w.note("down:%s" % os.environ.get("HOROVOD_RENDEZVOUS_EPOCH",
                                          "0"))


def reinit_world(*, rank: int, size: int, epoch: str) -> None:
    """Tear the world down and re-form it under a new rendezvous epoch
    with a (possibly) different rank/size — the elastic transition
    primitive shared by the serving shrink path (serving/replica.py)
    and the statesync grow/preemption transitions (statesync/service.py).
    Every mesh/shm/heartbeat scope keys on the epoch, so no stale state
    from the previous membership is ever touched; the env writes make
    the new identity survive any later env-driven re-init."""
    shutdown()
    os.environ["HOROVOD_RENDEZVOUS_EPOCH"] = epoch
    os.environ["HOROVOD_RANK"] = str(rank)
    os.environ["HOROVOD_SIZE"] = str(size)
    init()


def is_initialized() -> bool:
    return _global.initialized


def _require_init() -> GlobalState:
    if not _global.initialized:
        raise RuntimeError(
            "horovod_tpu has not been initialized; call hvd.init().")
    return _global


def rank() -> int:
    return _require_init().rank


def size() -> int:
    return _require_init().size


def local_rank() -> int:
    return _require_init().local_rank


def local_size() -> int:
    return _require_init().local_size


def cross_rank() -> int:
    return _require_init().cross_rank


def cross_size() -> int:
    return _require_init().cross_size


def is_homogeneous() -> bool:
    """True when every host runs the same number of ranks
    (reference: mpi_controller.cc:30-82 homogeneity check)."""
    st = _require_init()
    return st.size % max(st.local_size, 1) == 0 and \
        st.cross_size * st.local_size == st.size


def start_timeline(path: str, mark_cycles: bool = False) -> None:
    st = _require_init()
    if st.timeline is not None:
        st.timeline._mark_cycles = mark_cycles
        st.timeline.start(path)


def stop_timeline() -> None:
    st = _require_init()
    if st.timeline is not None:
        st.timeline.stop()


# ---------------------------------------------------------------------------
# Background loop (reference: operations.cc:589-647 RunLoopOnce)
# ---------------------------------------------------------------------------
def _background_loop() -> None:
    st = _global
    tm = st.telemetry
    tm_on = tm is not None and tm.enabled
    if tm_on:
        # Metric handles resolved once — the per-cycle cost is the update
        # itself (one uncontended per-metric lock), nothing else.
        m_cycle = tm.histogram(
            "horovod_controller_cycle_ms",
            "Background-loop cycle wall time (pop + sync + dispatch)")
        m_qdepth = tm.gauge(
            "horovod_controller_tensor_queue_depth",
            "Pending tensor-table entries after dispatch")
        m_fill = tm.histogram(
            "horovod_fusion_fill_ratio",
            "Fused-response payload bytes / fusion threshold")
    while True:
        t0 = time.monotonic()
        try:
            response_list = st.controller.compute_response_list(
                st.shutdown_requested)
        except Exception as exc:  # noqa: BLE001 - control-plane failure
            logger.error("controller failure: %s", exc)
            st.tensor_queue.finalize()
            return
        if st.timeline is not None:
            st.timeline.mark_cycle()

        # Pipeline autotune parameters apply BEFORE this cycle's dispatch:
        # they ride the identical broadcast ResponseList, so every rank
        # flips segment size / stream width on the same cycle and the
        # round-robin stream assignment below stays rank-symmetric.
        if response_list.tuned_segment_bytes >= 0:
            for coll in st.tcp_collectives:
                coll.segment_bytes = response_list.tuned_segment_bytes
        if response_list.tuned_num_streams > 0:
            st.active_streams = min(response_list.tuned_num_streams,
                                    max(len(st.op_managers), 1))
        if response_list.tuned_fused >= 0:
            # Fused-kernel dispatch flips on the same cycle on every rank
            # (both settings are bitwise identical AND frame-compatible,
            # so even a straggling flip cannot corrupt a reduce).  The
            # shm plane carries the same dispatch attribute.
            for coll in st.tcp_collectives:
                coll.fused = bool(response_list.tuned_fused)
            for mgr in (st.op_managers or
                        ([st.op_manager] if st.op_manager else [])):
                for be in mgr.backends:
                    if be.name == "shm":
                        be.fused = bool(response_list.tuned_fused)
        # Allreduce-algorithm autotune applies BEFORE dispatch for the
        # same reason as the pipeline knobs: all ranks flip on the same
        # broadcast cycle, so _select_algo stays rank-symmetric.
        if response_list.tuned_algo >= 0:
            from .common.topology import algo_name
            for coll in st.tcp_collectives:
                coll.algo = algo_name(response_list.tuned_algo)
        if response_list.tuned_tree_threshold >= 0:
            for coll in st.tcp_collectives:
                coll.tree_threshold = response_list.tuned_tree_threshold

        # Chaos harness (HOROVOD_CHAOS): deterministic response-level
        # fault injection fires HERE, on the coordinator-ordered
        # ResponseList — the global collective index is identical on
        # every rank, so a kill/freeze/fail at index N is replayable and
        # (for rank=*) rank-symmetric.
        if st.chaos is not None:
            for i, response in enumerate(response_list.responses):
                if response.response_type in (ResponseType.JOIN,
                                              ResponseType.ERROR):
                    continue
                if st.chaos.on_response(response.tensor_names) == "fail":
                    # REPLACE, never mutate: the original Response object
                    # may be held by the response cache, and an in-place
                    # flip to ERROR would poison every later cache hit.
                    response_list.responses[i] = Response(
                        response_type=ResponseType.ERROR,
                        tensor_names=list(response.tensor_names),
                        error_message=(
                            "chaos: injected collective failure "
                            f"(HOROVOD_CHAOS, tensors "
                            f"{response.tensor_names})"))

        if st.stream_dispatcher is not None \
                and len(response_list.responses) > 1:
            _dispatch_cycle(st, response_list.responses)
        else:
            for response in response_list.responses:
                _perform_operation(st, response)

        total_bytes = 0
        tensor_names: list[str] = []
        fusion_threshold = st.controller.fusion_threshold_bytes() \
            if tm_on else 0
        for response in response_list.responses:
            if response.response_type in (ResponseType.ALLREDUCE,
                                          ResponseType.ADASUM):
                from .common.dtypes import element_size
                resp_bytes = sum(response.tensor_sizes) * \
                    element_size(response.tensor_type)
                total_bytes += resp_bytes
                tensor_names.extend(response.tensor_names)
                if tm_on and fusion_threshold > 0 and \
                        len(response.tensor_names) > 1:
                    m_fill.observe(resp_bytes / fusion_threshold)

        # Autotune: coordinator scores the window and proposes new params;
        # every rank applies parameters broadcast through the ResponseList.
        if response_list.tuned_cycle_time_ms > 0:
            st.cycle_time_ms = response_list.tuned_cycle_time_ms
        if response_list.tuned_codec >= 0:
            from .compress import CompressionCodec, codec_name
            st.codec_override = codec_name(
                CompressionCodec(response_list.tuned_codec))
        if st.parameter_manager is not None:
            st.parameter_manager.observe(tensor_names, total_bytes)

        if response_list.shutdown:
            # Flip the visible flag: ranks that never submitted anything
            # (e.g. the stalled side of a one-sided collective) must be
            # able to observe that the world shut down around them.
            st.shutdown_requested = True
            st.tensor_queue.finalize()
            return

        elapsed = time.monotonic() - t0
        if tm_on:
            m_cycle.observe(elapsed * 1e3)
            st.controller.record_cycle(elapsed * 1e3)
            m_qdepth.set(st.tensor_queue.size())
        timeline = st.timeline
        if timeline is not None and timeline.enabled \
                and response_list.responses:
            # Counter tracks ("ph":"C") render queue depth and cumulative
            # wire bytes as series in the trace, next to the op spans.
            timeline.counter("tensor_queue_depth",
                             {"depth": st.tensor_queue.size()})
            if st.tcp_collectives:
                timeline.counter(
                    "wire_bytes",
                    {"sent": sum(c.mesh.bytes_sent
                                 for c in st.tcp_collectives),
                     "received": sum(c.mesh.bytes_received
                                     for c in st.tcp_collectives)})
        sleep_s = st.cycle_time_ms / 1000.0 - elapsed
        if sleep_s > 0:
            # Wake early on fresh enqueues (cached single-op latency is
            # otherwise dominated by this sleep), then grant a short
            # batching grace so bursts — per-gradient hooks firing during
            # backward — still fuse into one response like the
            # reference's fixed cadence achieves.
            if st.tensor_queue.wait_for_work(sleep_s):
                time.sleep(min(0.0003, st.cycle_time_ms / 5000.0))


def _perform_join(st: GlobalState, response: Response) -> None:
    st.joined = False
    if st.tensor_queue.has_tensor_entry(JOIN_TENSOR_NAME):
        entry = st.tensor_queue.pop_tensor_entry(JOIN_TENSOR_NAME)
        entry.output = np.int32(response.last_joined_rank)
        entry.finish(Status.ok())
        if st.timeline is not None and st.timeline.enabled:
            st.timeline.queue_end(JOIN_TENSOR_NAME,
                                  trace=response.trace_id())


def _pop_entries(st: GlobalState,
                 response: Response) -> list[TensorTableEntry]:
    """Pop the response's entries from the tensor table (background
    thread only — the queue has a single consumer) and close their
    negotiation spans."""
    entries: list[TensorTableEntry] = []
    for name in response.tensor_names:
        if st.tensor_queue.has_tensor_entry(name):
            entries.append(st.tensor_queue.pop_tensor_entry(name))
        else:
            # Joined rank: participate with a zero stand-in
            # (reference: controller.cc:254-308 joined-rank handling).
            entries.append(TensorTableEntry(tensor_name=name))
    # Stamp the response's cross-rank trace id on every entry: backend
    # sub-activity spans and the flight recorder read it from there, so
    # the planes need no extra plumbing (telemetry/trace.py).
    trace = response.trace_id()
    for e in entries:
        e.trace = trace
    timeline = st.timeline
    if timeline is not None and timeline.enabled:
        for e in entries:
            timeline.negotiate_end(e.tensor_name, trace=trace)
    return entries


def _execute_response(st: GlobalState, response: Response,
                      entries: list[TensorTableEntry],
                      stream: int = 0) -> None:
    """Execute one response on stream `stream`'s backend chain and finish
    its entries (runs on the background thread when streams == 1, on a
    stream worker otherwise)."""
    timeline = st.timeline
    trace = response.trace_id()
    if timeline is not None and timeline.enabled:
        for e in entries:
            timeline.activity_start(e.tensor_name,
                                    response.response_type.name,
                                    stream=stream, trace=trace)
    fl = st.flight
    fl_on = fl is not None and fl.enabled
    if fl_on:
        head = response.tensor_names[0] if response.tensor_names else ""
        fl.record("dispatch", head, trace=trace,
                  detail=f"{response.response_type.name.lower()}"
                         f" x{len(entries)} stream={stream}")

    if response.response_type == ResponseType.ERROR:
        status = Status.precondition_error(response.error_message)
    else:
        tm = st.telemetry
        tm_on = tm is not None and tm.enabled
        from .resilience import active_state, op_scope
        res = active_state()
        try:
            manager = st.op_managers[stream] if st.op_managers \
                else st.op_manager
            if tm_on:
                backend = manager.resolve(response, entries)
                plane = backend.name if backend is not None else "none"
                t0 = time.monotonic()
            if res is not None:
                # Label the blocking waits below for failure attribution
                # (RanksFailedError.op); off mode skips the string build.
                # The tightest propagated request deadline of the fused
                # entries bounds every transport wait of this op
                # (resilience.deadline_scope -> entry.deadline).
                deadlines = [e.deadline for e in entries
                             if e.deadline is not None]
                with op_scope(f"{response.response_type.name.lower()}"
                              f"({response.tensor_names[0]}"
                              f"{'…' if len(response.tensor_names) > 1 else ''})"
                              if response.tensor_names else
                              response.response_type.name.lower(),
                              deadline=min(deadlines) if deadlines
                              else None):
                    status = manager.execute_operation(response, entries)
            else:
                status = manager.execute_operation(response, entries)
            if tm_on:
                algo = getattr(backend, "last_algo", "none") \
                    if backend is not None else "none"
                _observe_collective(tm, response, plane, stream,
                                    (time.monotonic() - t0) * 1e3, algo,
                                    st)
        except Exception as exc:  # noqa: BLE001 - backend failure
            logger.error("collective execution failed: %s", exc)
            status = Status.unknown_error(str(exc))
            from .common.exceptions import RanksFailedError
            if fl_on and isinstance(exc, RanksFailedError):
                # A data-plane wait converted a dead/wedged peer into
                # the structured error: ship the evidence — the dump's
                # tail is the "dispatch" event of this in-flight op.
                fl.record("ranks-failed", head, trace=trace,
                          detail=str(exc)[:200])
                fl.dump(reason=str(exc))

    if timeline is not None and timeline.enabled:
        for e in entries:
            timeline.activity_end(e.tensor_name)

    if fl_on:
        fl.record("done" if status.ok_p() else "error", head,
                  trace=trace,
                  detail="" if status.ok_p() else status.reason[:200])

    # Release explicit groups everywhere — the coordinator deregisters
    # during response construction, but worker ranks would otherwise leak
    # one group per grouped collective.
    st.group_table.deregister_groups(response.tensor_names)

    for e in entries:
        e.finish(status)
    if timeline is not None and timeline.enabled:
        # Close the enqueue->callback spans AFTER the callbacks ran —
        # the span covers the waiter's full latency, not just dispatch.
        for e in entries:
            timeline.queue_end(e.tensor_name, trace=trace)


def _observe_collective(tm, response: Response, plane: str, stream: int,
                        latency_ms: float, algo: str = "none",
                        st: GlobalState | None = None) -> None:
    """Per-plane/per-codec collective latency+bytes, per-stream busy
    time, and the perfscope busbw observation (registry lookups are
    dict hits; metric objects are cached by the registry itself)."""
    from .common.dtypes import element_size
    from .compress import CompressionCodec, codec_name
    from .telemetry import perfmodel
    op = response.response_type.name.lower()
    codec = codec_name(CompressionCodec(response.codec))
    nbytes = sum(response.tensor_sizes) * element_size(response.tensor_type)
    tm.histogram(
        "horovod_collective_latency_ms",
        "End-to-end latency of one executed response, by data plane, "
        "op, wire codec and collective algorithm",
        labels={"plane": plane, "op": op, "codec": codec, "algo": algo}
    ).observe(latency_ms)
    tm.counter(
        "horovod_collective_algo_total",
        "Executed responses by collective algorithm (ring / tree / rhd "
        "/ torus / hierarchical / ... — the per-size selection verdict)",
        labels={"algo": algo}).inc(1)
    tm.counter(
        "horovod_collective_bytes_total",
        "Uncompressed payload bytes of executed responses (allgather "
        "counts per-rank first dims as elements)",
        labels={"plane": plane, "op": op}).inc(nbytes)
    tm.counter(
        "horovod_stream_busy_ms_total",
        "Cumulative execution time on each dispatch stream",
        labels={"stream": str(stream)}).inc(latency_ms)
    # perfscope (ISSUE 19): bus bandwidth per (plane, op, codec, algo,
    # size-bucket) — the nccl-tests normalization, so the ledger compares
    # cells across algorithms and world sizes on one scale.
    size = st.size if st is not None else 1
    if size > 1 and nbytes > 0 and latency_ms > 0.0:
        busbw = perfmodel.busbw_mbps(op, nbytes, latency_ms, size)
        bucket = perfmodel.size_bucket(nbytes)
        tm.histogram(
            "horovod_collective_busbw_mbps",
            "Bus bandwidth of one executed collective (busbw = algbw x "
            "op factor, MB/s) by data plane, op, wire codec, algorithm "
            "and payload size bucket — the perf ledger's raw table "
            "(telemetry/perfmodel.py)",
            labels={"plane": plane, "op": op, "codec": codec,
                    "algo": algo, "size_bucket": bucket}
        ).observe(busbw)
        peak = tm.gauge(
            "horovod_collective_busbw_peak_mbps",
            "Best bus bandwidth any collective demonstrated on this "
            "rank's data planes (the self-calibrated roofline when "
            "HOROVOD_PERF_PEAK_MBPS is unset)")
        if busbw > peak.value:
            peak.set(busbw)
        roof = float(config.PERF_PEAK_MBPS.get()) or peak.value
        tm.gauge(
            "horovod_collective_efficiency",
            "Roofline-relative bus-bandwidth efficiency of the most "
            "recent collective in each (plane, algo, size-bucket) cell: "
            "busbw / peak (HOROVOD_PERF_PEAK_MBPS, else the "
            "self-calibrated peak gauge)",
            labels={"plane": plane, "algo": algo, "size_bucket": bucket}
        ).set(busbw / roof if roof > 0.0 else 0.0)


def _perform_operation(st: GlobalState, response: Response) -> None:
    """Reference: operations.cc:256-329 PerformOperation."""
    if response.response_type == ResponseType.JOIN:
        _perform_join(st, response)
        return
    _execute_response(st, response, _pop_entries(st, response), stream=0)


def _dispatch_cycle(st: GlobalState, responses: list[Response]) -> None:
    """Multi-stream dispatch of one cycle's responses.

    Stream assignment is round-robin over the coordinator-ordered
    ResponseList, counting only stream-safe responses — both the order
    and each response's resolved backend are identical on every rank
    (enabled() checks are rank-symmetric by contract), so rank R's
    stream-k worker exchanges bytes exactly with every peer's stream-k
    worker and hvdlint's symmetric-call contract holds.  Responses whose
    plane keeps process-global protocol state (shm lockstep, XLA program
    order, hierarchical sub-meshes) all ride stream 0, preserving their
    relative execution order."""
    work: list[tuple[int, Any]] = []
    rr = 0
    for response in responses:
        if response.response_type == ResponseType.JOIN:
            _perform_join(st, response)
            continue
        entries = _pop_entries(st, response)
        stream = 0
        if response.response_type != ResponseType.ERROR:
            backend = st.op_managers[0].resolve(response, entries)
            if backend is not None and backend.stream_safe:
                stream = rr % max(st.active_streams, 1)
                rr += 1

        def _thunk(response=response, entries=entries, stream=stream):
            _execute_response(st, response, entries, stream=stream)

        work.append((stream, _thunk))
    st.stream_dispatcher.run_cycle(work)


# ---------------------------------------------------------------------------
# Enqueue API (reference: operations.cc:919-1226)
# ---------------------------------------------------------------------------
def _as_array(tensor) -> np.ndarray:
    """Stage a framework tensor as a numpy array (zero-copy where the
    framework allows it; torch CPU and jax host arrays both support the
    buffer protocol / __array__)."""
    return np.asarray(tensor)


def _enqueue(entries: list[TensorTableEntry],
             requests: list[Request]) -> tuple[int, Handle]:
    st = _require_init()
    hid, handle = st.handle_manager.allocate(entries)
    cb = st.mark_done_callback(handle)
    for e in entries:
        e.callback = cb
    # Open the enqueue->callback trace span BEFORE submission: the
    # background loop may pop and finish an entry before this thread
    # runs again, and a queue_end without its begin would be dropped.
    timeline = st.timeline
    tl_on = timeline is not None and timeline.enabled
    fl = st.flight
    # Per-request deadline propagation (serving SLOs): the enqueuing
    # thread's deadline_scope rides the entries to the dispatch thread,
    # which re-raises it through op_scope around the transport waits.
    from .resilience.context import pending_deadline
    deadline = pending_deadline()
    for e in entries:
        if deadline is not None:
            e.deadline = deadline
        if tl_on:
            timeline.queue_start(e.tensor_name)
        if fl is not None and fl.enabled:
            fl.record("enqueue", e.tensor_name)
    status = st.tensor_queue.add_to_tensor_queue_multi(entries, requests)
    if not status.ok_p():
        # Fail synchronously (duplicate name / shut down).
        for e in entries:
            e.callback = None
            if tl_on:
                timeline.queue_end(e.tensor_name)
        handle.status = status
        st.handle_manager.release(hid)
        handle._event.set()
    return hid, handle


def _resolve_codec(codec) -> tuple[int, int]:
    """(codec id, block size) for a Request: explicit argument beats the
    autotuner's runtime override beats the HOROVOD_COMPRESSION knob."""
    from .common import config as _config
    from .compress import (QUANTIZED_CODECS, CompressionCodec,
                           codec_from_name, default_block_size)
    if codec is None:
        codec = _global.codec_override
    if codec is None:
        codec = _config.COMPRESSION.get()
    c = codec_from_name(codec)
    if c not in QUANTIZED_CODECS:
        return int(c), 0
    bs = default_block_size()
    if bs <= 0:
        raise ValueError(
            f"HOROVOD_COMPRESSION_BLOCK_SIZE must be positive (got {bs})")
    if c == CompressionCodec.UINT4 and bs % 2:
        raise ValueError(
            "uint4 compression requires an even "
            f"HOROVOD_COMPRESSION_BLOCK_SIZE (got {bs})")
    return int(c), int(bs)


def enqueue_allreduce(name: str, tensor, *, op: str = "sum",
                      prescale_factor: float = 1.0,
                      postscale_factor: float = 1.0,
                      adasum: bool = False,
                      codec=None, spec=None) -> tuple[int, Handle]:
    return enqueue_grouped_allreduce([name], [tensor], op=op,
                                     prescale_factor=prescale_factor,
                                     postscale_factor=postscale_factor,
                                     adasum=adasum, register_group=False,
                                     codec=codec, spec=spec)


def enqueue_grouped_allreduce(names: Sequence[str], tensors: Sequence[Any], *,
                              op: str = "sum",
                              prescale_factor: float = 1.0,
                              postscale_factor: float = 1.0,
                              adasum: bool = False,
                              register_group: bool = True,
                              codec=None, spec=None) -> tuple[int, Handle]:
    """``spec`` annotates the tensor's sharding layout (a PartitionSpec,
    an axis-entry iterable, or an already-canonical token string); it
    rides the Request as the sp_spec wire field and joins the
    collective's fingerprint identity — op×name×dtype×dims×spec — when
    the mesh negotiated FEATURE_SHARDING (hvdshard; docs/analysis.md)."""
    st = _require_init()
    if op == "average":
        postscale_factor = postscale_factor / st.size
    elif op != "sum":
        raise ValueError(f"Unknown allreduce op: {op}")
    rtype = RequestType.ADASUM if adasum else RequestType.ALLREDUCE
    codec_id, codec_bs = _resolve_codec(codec)
    sp = spec_token(spec)
    entries, requests = [], []
    if register_group and len(names) > 1:
        st.group_table.register_group(list(names))
    for name, tensor in zip(names, tensors):
        arr = _as_array(tensor)
        entries.append(TensorTableEntry(tensor_name=name, tensor=arr))
        requests.append(Request(
            request_rank=st.rank, request_type=rtype,
            tensor_type=from_any(arr.dtype), tensor_name=name,
            tensor_shape=tuple(arr.shape),
            prescale_factor=prescale_factor,
            postscale_factor=postscale_factor,
            codec=codec_id, codec_block_size=codec_bs,
            sp_spec=sp))
    return _enqueue(entries, requests)


def enqueue_reducescatter(name: str, tensor, *, op: str = "sum",
                          prescale_factor: float = 1.0,
                          postscale_factor: float = 1.0,
                          spec=None) -> tuple[int, Handle]:
    """Reduce over all ranks, scatter dim-0 slices back (the eager analogue
    of upstream Horovod's reducescatter; rides the XLA device plane when
    dim 0 divides evenly, the TCP plane otherwise)."""
    st = _require_init()
    if op == "average":
        postscale_factor = postscale_factor / st.size
    elif op != "sum":
        raise ValueError(f"Unknown reducescatter op: {op}")
    arr = _as_array(tensor)
    entry = TensorTableEntry(tensor_name=name, tensor=arr)
    request = Request(request_rank=st.rank,
                      request_type=RequestType.REDUCESCATTER,
                      tensor_type=from_any(arr.dtype), tensor_name=name,
                      tensor_shape=tuple(arr.shape),
                      prescale_factor=prescale_factor,
                      postscale_factor=postscale_factor,
                      sp_spec=spec_token(spec))
    return _enqueue([entry], [request])


def enqueue_allgather(name: str, tensor, *, spec=None) -> tuple[int, Handle]:
    st = _require_init()
    arr = _as_array(tensor)
    entry = TensorTableEntry(tensor_name=name, tensor=arr)
    request = Request(request_rank=st.rank,
                      request_type=RequestType.ALLGATHER,
                      tensor_type=from_any(arr.dtype), tensor_name=name,
                      tensor_shape=tuple(arr.shape),
                      sp_spec=spec_token(spec))
    return _enqueue([entry], [request])


def enqueue_broadcast(name: str, tensor, root_rank: int, *,
                      spec=None) -> tuple[int, Handle]:
    st = _require_init()
    arr = _as_array(tensor)
    entry = TensorTableEntry(tensor_name=name, tensor=arr,
                             root_rank=root_rank)
    request = Request(request_rank=st.rank,
                      request_type=RequestType.BROADCAST,
                      tensor_type=from_any(arr.dtype), tensor_name=name,
                      root_rank=root_rank, tensor_shape=tuple(arr.shape),
                      sp_spec=spec_token(spec))
    return _enqueue([entry], [request])


def enqueue_alltoall(name: str, tensor,
                     splits=None) -> tuple[int, Handle]:
    st = _require_init()
    arr = _as_array(tensor)
    split_list = [int(x) for x in np.asarray(splits).reshape(-1)] \
        if splits is not None else []
    # Validate at ENQUEUE like the reference (operations.cc:1176): the
    # submitting rank fails fast before negotiation, so an invalid table
    # never reaches a pairwise exchange where a rank-local rejection
    # would strand peers mid-protocol.  resolve_alltoall_splits repeats
    # these checks defensively for internal callers.
    if split_list:
        if len(split_list) != st.size:
            raise ValueError(
                f"alltoall splits must have one entry per rank (got "
                f"{len(split_list)} for world size {st.size})")
        if any(s < 0 for s in split_list):
            raise ValueError(
                f"alltoall splits must be non-negative (got {split_list})")
        if sum(split_list) != arr.shape[0]:
            raise ValueError(
                f"alltoall splits sum to {sum(split_list)} but tensor "
                f"first dimension is {arr.shape[0]}")
    entry = TensorTableEntry(tensor_name=name, tensor=arr,
                             splits=split_list)
    request = Request(request_rank=st.rank,
                      request_type=RequestType.ALLTOALL,
                      tensor_type=from_any(arr.dtype), tensor_name=name,
                      tensor_shape=tuple(arr.shape))
    return _enqueue([entry], [request])


def enqueue_barrier() -> tuple[int, Handle]:
    st = _require_init()
    name = "__barrier__"
    entry = TensorTableEntry(tensor_name=name)
    request = Request(request_rank=st.rank, request_type=RequestType.BARRIER,
                      tensor_name=name)
    return _enqueue([entry], [request])


def enqueue_join() -> tuple[int, Handle]:
    """Graceful uneven-data exit (reference: operations.cc:1202-1226).

    After join() this rank keeps participating in negotiated collectives
    with zero stand-ins until every rank has joined."""
    st = _require_init()
    st.joined = True
    entry = TensorTableEntry(tensor_name=JOIN_TENSOR_NAME)
    request = Request(request_rank=st.rank, request_type=RequestType.JOIN,
                      tensor_name=JOIN_TENSOR_NAME)
    return _enqueue([entry], [request])
