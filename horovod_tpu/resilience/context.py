"""Per-process resilience state: fault timeout, per-op deadlines, and the
liveness view the transport's bounded waits consult.

The reference's elastic layer (horovod/common/elastic.py, PAPER.md L7)
only reacts AFTER a collective has failed; the gap this module closes is
that on our socket/shm planes a dead or wedged peer previously produced
no failure at all — every survivor blocked forever in ``recv_into`` /
``kv_barrier`` / the 3-barrier shm lockstep.  A :class:`ResilienceState`
turns those blocking waits into deadline-bounded ones:

- every transport wait polls in short slices (``poll_interval``) and asks
  :meth:`ResilienceState.check` between slices;
- ``check`` raises :class:`RanksFailedError` the moment the heartbeat
  monitor declares any rank failed, or when the wait itself exceeds the
  per-op deadline (``op_timeout``, default ``HOROVOD_FAULT_TIMEOUT``) —
  the wedged-rank detector heartbeats alone cannot provide (a stuck main
  thread keeps heartbeating from its monitor thread);
- a transport-level death observation (peer socket closed mid-message)
  is fed back through :meth:`mark_failed`, which publishes a ``dead:``
  key to the rendezvous KV so every OTHER rank's next poll attributes
  its own stall to the true culprit instead of its silent neighbor.

Zero-overhead off mode: ``active_state()`` returns None unless
``HOROVOD_FAULT_TOLERANCE`` is on and a multi-rank world configured it,
and every instrumentation point reduces to one ``is None`` test.
"""
from __future__ import annotations

import threading
import time

from ..common import config
from ..common.exceptions import RanksFailedError
from ..common.logging import logger

__all__ = ["RanksFailedError", "ResilienceState", "active_state",
           "configure", "shutdown", "current_op", "op_scope",
           "current_op_deadline", "deadline_scope", "pending_deadline"]

# Name of the collective currently blocking this thread, for error
# attribution (set only when resilience is enabled — see op_scope).
_current_op = threading.local()

# Deadline a CALLER thread attaches to the collectives it is about to
# enqueue (serving/ per-request SLOs; see deadline_scope).  Read once at
# enqueue and stamped on the TensorTableEntry, which carries it to the
# background/stream thread that actually blocks — thread-locals do not
# cross that boundary on their own.
_pending_deadline = threading.local()


def current_op() -> str:
    return getattr(_current_op, "name", "")


def current_op_deadline() -> float | None:
    """Absolute monotonic deadline of the op this thread is executing,
    or None (set by op_scope on the dispatch thread)."""
    return getattr(_current_op, "deadline", None)


class op_scope:
    """Label the collective the calling thread is about to block in, so a
    RanksFailedError raised from a transport wait names it.  An optional
    absolute monotonic ``deadline`` additionally tightens the per-op
    deadline every bounded wait under this scope consults
    (:meth:`ResilienceState.op_timeout`) — the serving path's per-request
    SLO propagation."""

    __slots__ = ("_name", "_deadline", "_prev", "_prev_deadline")

    def __init__(self, name: str, deadline: float | None = None) -> None:
        self._name = name
        self._deadline = deadline

    def __enter__(self) -> "op_scope":
        self._prev = getattr(_current_op, "name", "")
        self._prev_deadline = getattr(_current_op, "deadline", None)
        _current_op.name = self._name
        _current_op.deadline = self._deadline
        return self

    def __exit__(self, *exc) -> None:
        _current_op.name = self._prev
        _current_op.deadline = self._prev_deadline


class deadline_scope:
    """Caller-side half of per-request deadline propagation: collectives
    enqueued by this thread inside the scope carry ``deadline`` (absolute
    ``time.monotonic()`` seconds) on their TensorTableEntries; the
    dispatch thread re-raises it through :class:`op_scope` so every
    transport wait of that op is bounded by the request SLO instead of
    the full HOROVOD_FAULT_TIMEOUT.  No-op overhead when fault tolerance
    is off (the entry field rides along but nothing reads it)."""

    __slots__ = ("_deadline", "_prev")

    def __init__(self, deadline: float | None) -> None:
        self._deadline = deadline

    def __enter__(self) -> "deadline_scope":
        self._prev = getattr(_pending_deadline, "value", None)
        _pending_deadline.value = self._deadline
        return self

    def __exit__(self, *exc) -> None:
        _pending_deadline.value = self._prev


def pending_deadline() -> float | None:
    """Deadline the calling thread attached via deadline_scope, if any
    (read by core at enqueue time)."""
    return getattr(_pending_deadline, "value", None)


class ResilienceState:
    """Liveness view + deadline policy for one world membership."""

    def __init__(self, rank: int, size: int, monitor,
                 fault_timeout: float | None = None) -> None:
        self.rank = rank
        self.size = size
        self.monitor = monitor          # HeartbeatMonitor (never None here)
        # Flight recorder (telemetry/flight.py): failure observations
        # land in the ring so the eventual RanksFailedError dump shows
        # WHEN this rank first suspected whom (Null when off).
        from ..telemetry import flight as _flight
        self.flight = _flight.recorder()
        self.fault_timeout = config.FAULT_TIMEOUT.get() \
            if fault_timeout is None else float(fault_timeout)
        # Transport waits poll in slices of this size between liveness
        # checks; short enough that a KV-propagated death mark is acted
        # on promptly, long enough that the off-CPU cost is negligible.
        self.poll_interval = max(0.05, min(0.25, self.fault_timeout / 8.0))

    # -- deadline policy -------------------------------------------------
    def op_timeout(self) -> float:
        """Per-op deadline for one blocking transport wait.  One fault
        window by default: a peer that neither completes its part of the
        op nor is declared dead within it is treated as wedged or
        unreachable.  When the executing op carries a propagated request
        deadline (serving SLOs, op_scope(deadline=...)), the window
        tightens to the remaining SLO budget — floored at a couple of
        poll slices so a healthy-but-busy peer is never declared wedged
        by an already-hopeless request alone."""
        deadline = current_op_deadline()
        if deadline is None:
            return self.fault_timeout
        remaining = deadline - time.monotonic()
        return min(self.fault_timeout,
                   max(remaining, 2.0 * self.poll_interval))

    # -- liveness --------------------------------------------------------
    def failed_ranks(self) -> frozenset[int]:
        return self.monitor.failed_ranks()

    def rank_failed(self, r: int) -> bool:
        return r in self.monitor.failed_ranks()

    def confirmed_dead(self, ranks) -> frozenset[int]:
        """Subset of `ranks` with CONFIRMED death evidence — the retry
        policy refuses to retry over these (a dead rank cannot rejoin a
        fixed-size world; that is shrink's job), while deadline-suspect
        ranks — alive but slow/wedged — stay retriable."""
        return frozenset(ranks) & self.monitor.confirmed_failed_ranks()

    def mark_failed(self, r: int, reason: str,
                    confirmed: bool = True) -> None:
        if self.flight.enabled:
            self.flight.record(
                "mark-failed", f"rank {r}",
                detail=f"{'confirmed' if confirmed else 'suspect'}: "
                       f"{reason[:160]}")
        self.monitor.mark_failed(r, reason, confirmed=confirmed)

    # -- the bounded-wait probe -----------------------------------------
    def check(self, peer: int, waited: float, phase: str) -> None:
        """Called by a transport wait after each expired poll slice.
        Raises RanksFailedError when the monitor has declared ANY rank
        failed (attributing the stall to the true culprit, which may not
        be the silent direct neighbor), or when this wait exceeded the
        per-op deadline (the peer is wedged: alive per heartbeat, absent
        from the collective)."""
        failed = self.monitor.failed_ranks()
        if failed:
            if self.flight.enabled:
                self.flight.record(
                    "deadline-convert", current_op(),
                    detail=f"phase={phase} failed="
                           f"{sorted(failed)} after {waited:.1f}s")
            raise RanksFailedError(failed, op=current_op(), phase=phase)
        if waited >= self.op_timeout():
            self.mark_failed(peer, f"unresponsive for {waited:.1f}s in "
                                   f"{phase}", confirmed=False)
            raise RanksFailedError(
                frozenset({peer}), op=current_op(), phase=phase,
                message=(f"rank {peer} sent no bytes for {waited:.1f}s "
                         f"(>= HOROVOD_FAULT_TIMEOUT="
                         f"{self.fault_timeout:g}s) while this rank "
                         f"blocked in {phase}; peer heartbeat still "
                         f"present — likely wedged mid-collective."))

    def peer_connection_lost(self, peer: int, phase: str,
                             detail: str) -> RanksFailedError:
        """A socket to `peer` closed/reset mid-message: record the
        failure (KV-propagated so distant ranks attribute correctly) and
        return the error for the caller to raise.  Marked SUSPECT, not
        confirmed: a peer that raised its own structured error and tore
        its mesh down also closes this socket — only heartbeat silence
        or a vanished PID confirms actual death (what the retry policy's
        refusal gate keys on).

        Forces one liveness poll FIRST: when a survivor detects the root
        failure, raises and exits, its ring neighbor sees the SURVIVOR's
        socket close — without the poll it would blame the messenger;
        the true culprit's dead-mark is already on the KV by then (marks
        publish before any raise), so one read attributes correctly."""
        try:
            self.monitor.poll_once()
        except Exception:  # noqa: BLE001 - attribution must never mask
            pass
        self.mark_failed(peer, f"connection lost: {detail}",
                         confirmed=False)
        return RanksFailedError(
            frozenset({peer}) | self.monitor.failed_ranks(),
            op=current_op(), phase=phase,
            message=f"connection to rank {peer} lost mid-collective "
                    f"({detail}).")

    def close(self) -> None:
        self.monitor.stop()


_lock = threading.Lock()
_state: ResilienceState | None = None


def active_state() -> ResilienceState | None:
    """The live ResilienceState, or None when fault tolerance is off or
    no multi-rank world has configured it (the zero-overhead off mode)."""
    return _state


def configure(rank: int, size: int, kv, epoch: str) -> ResilienceState | None:
    """Build (or rebuild, under elastic/retry re-init) the process
    resilience state.  Returns None — and tears down any previous state —
    unless HOROVOD_FAULT_TOLERANCE is on and the world is multi-rank."""
    global _state
    with _lock:
        if _state is not None:
            _state.close()
            _state = None
        if size <= 1 or kv is None or not config.FAULT_TOLERANCE.get():
            return None
        from .heartbeat import HeartbeatMonitor
        fault_timeout = config.FAULT_TIMEOUT.get()
        monitor = HeartbeatMonitor(rank, size, kv, epoch,
                                   fault_timeout=fault_timeout)
        monitor.start()
        _state = ResilienceState(rank, size, monitor,
                                 fault_timeout=fault_timeout)
        logger.debug("resilience: fault tolerance on (rank=%d size=%d "
                     "timeout=%.1fs)", rank, size, fault_timeout)
        return _state


def shutdown() -> None:
    global _state
    with _lock:
        if _state is not None:
            _state.close()
            _state = None
