"""Heartbeat-based failure detection over the rendezvous liveness table.

Every rank runs ONE daemon monitor thread (only when
``HOROVOD_FAULT_TOLERANCE`` is on — the off-mode thread census is zero)
that each interval:

1. publishes its own heartbeat ``hb/<epoch>:<rank> = <seq>|<pid>`` to the
   rendezvous KV store — the coordinator liveness table (the KV server
   already exists for mesh bootstrap, so detection adds no new service);
2. reads every peer's heartbeat and records, in LOCAL monotonic time,
   when each peer's value last ADVANCED — staleness is judged by local
   observation of progress, never by comparing cross-host clocks;
3. reads the ``dead/<epoch>`` scope, where any rank that has direct
   transport evidence of a death (socket closed mid-message, shm PID
   gone) published the victim's rank — so failure knowledge reaches
   ranks that are several ring hops away from the broken socket within
   one poll interval instead of one fault timeout.

A peer is declared failed when its heartbeat has not advanced for
``fault_timeout`` seconds (grace: never before one full window after
monitor start, so slow-importing peers are not condemned at formation),
or immediately when a ``dead:`` mark for it appears.

Telemetry (no-op when ``HOROVOD_METRICS`` is off): per-peer
``horovod_liveness`` gauge (1 alive / 0 failed), ``horovod_failures_total``
counter by kind, and a ``horovod_failure_detection_ms`` histogram of
heartbeat-silence length at declaration time.
"""
from __future__ import annotations

import threading
import time

from ..common.logging import logger

_DEAD_SCOPE = "dead"
_HB_SCOPE = "hb"


class HeartbeatMonitor:
    """One background thread maintaining this rank's view of peer
    liveness.  All reads from the data path (`failed_ranks`) are plain
    attribute/dict reads of state the thread replaces atomically."""

    def __init__(self, rank: int, size: int, kv, epoch: str,
                 fault_timeout: float = 30.0,
                 interval: float | None = None,
                 registry=None) -> None:
        self.rank = rank
        self.size = size
        self.kv = kv
        self.epoch = epoch
        self.fault_timeout = float(fault_timeout)
        self.interval = max(0.1, self.fault_timeout / 8.0) \
            if interval is None else float(interval)
        self._seq = 0
        self._failed: frozenset[int] = frozenset()
        # Subset of _failed with CONFIRMED-death evidence (socket closed,
        # PID gone, heartbeat silent) as opposed to deadline-expiry
        # suspicion — the retry policy may rebuild over a suspect (slow
        # but alive) rank, never over a confirmed-dead one.
        self._confirmed: frozenset[int] = frozenset()
        self._reasons: dict[int, str] = {}
        # peer -> (last observed value, local monotonic time it changed)
        self._last_progress: dict[int, tuple[str, float]] = {}
        self._started_at = 0.0
        # True while the rendezvous KV itself is unreachable: peer
        # staleness windows are paused (nobody can stamp), so a
        # coordinator failover never reads as mass peer death.
        self._kv_outage = False
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # `registry` overrides the process registry — fleetsim passes a
        # NullRegistry to non-leader virtual ranks so 500 monitors do
        # not mint 500×499 per-peer liveness gauges in one process.
        if registry is None:
            from ..telemetry import metrics as _tm_metrics
            registry = _tm_metrics()
        tm = registry
        self._tm_on = tm.enabled
        self._m_liveness = {}
        if self._tm_on:
            self._m_liveness = {
                r: tm.gauge("horovod_liveness",
                            "1 while the peer's heartbeat advances, 0 "
                            "once it is declared failed",
                            labels={"rank": str(r)})
                for r in range(size) if r != rank}
            for g in self._m_liveness.values():
                g.set(1)
            self._m_failures = tm.counter(
                "horovod_failures_total",
                "Ranks declared failed, by detection kind",
                labels={"kind": "heartbeat"})
            self._m_marked = tm.counter(
                "horovod_failures_total",
                "Ranks declared failed, by detection kind",
                labels={"kind": "transport"})
            self._m_latency = tm.histogram(
                "horovod_failure_detection_ms",
                "Heartbeat silence observed when a rank was declared "
                "failed (detection latency upper bound)")

    # -- lifecycle -------------------------------------------------------
    def start(self) -> None:
        self._started_at = time.monotonic()
        self._publish()   # first stamp before any wait can consult us
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="hvd-heartbeat")
        self._thread.start()

    def stop(self, silent: bool = False) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=self.interval + 5.0)
            if t.is_alive():
                logger.warning("resilience: heartbeat monitor thread did "
                               "not stop within grace (rank=%d)", self.rank)
        self._thread = None
        if silent:
            # A simulated hard kill (fleetsim chaos `kill`): the rank
            # must fall silent WITHOUT a goodbye — peers are supposed to
            # detect the death from heartbeat staleness.
            return
        # Orderly departure stamp: peers still watching THIS epoch (e.g.
        # mid-retry, about to rebuild under a new one) must not read the
        # coming heartbeat silence as death — a rank that leaves the
        # epoch deliberately says goodbye; only a killed/frozen rank
        # falls silent without one.
        try:
            self.kv.put(_HB_SCOPE, f"{self.epoch}:{self.rank}",
                        f"bye|{self._seq}".encode())
        except Exception:  # noqa: BLE001 - KV already gone at teardown
            pass

    # -- data-path reads -------------------------------------------------
    def failed_ranks(self) -> frozenset[int]:
        return self._failed

    def confirmed_failed_ranks(self) -> frozenset[int]:
        return self._confirmed

    def failure_reason(self, r: int) -> str:
        return self._reasons.get(r, "")

    # -- transport evidence ---------------------------------------------
    def mark_failed(self, r: int, reason: str,
                    confirmed: bool = True) -> None:
        """Direct evidence of a failure.  ``confirmed=True`` means death
        evidence (shm PID gone, heartbeat silence); ``False`` means the
        rank is unreachable but possibly alive — deadline expiry, or a
        closed socket that an errored-but-alive peer produces too (the
        retriable cases).  Publishes a dead-mark so every other rank's
        next poll converges on the same verdict."""
        if r in self._failed and (not confirmed or r in self._confirmed):
            return
        self._declare(r, reason, kind="transport", confirmed=confirmed)
        try:
            prefix = "confirmed" if confirmed else "suspect"
            self.kv.put(_DEAD_SCOPE, f"{self.epoch}:{r}",
                        f"{prefix}|by {self.rank}: {reason}".encode())
        except Exception:  # noqa: BLE001 - KV gone: local verdict stands
            pass

    def _declare(self, r: int, reason: str, kind: str,
                 confirmed: bool = True) -> None:
        self._failed = self._failed | {r}
        if confirmed:
            self._confirmed = self._confirmed | {r}
        self._reasons.setdefault(r, reason)
        logger.warning("resilience: rank %d declared FAILED (%s, %s): %s",
                       r, kind, "confirmed" if confirmed else "suspect",
                       reason)
        if self._tm_on:
            g = self._m_liveness.get(r)
            if g is not None:
                g.set(0)
            (self._m_failures if kind == "heartbeat"
             else self._m_marked).inc()

    # -- monitor thread --------------------------------------------------
    def _publish(self) -> None:
        self._seq += 1
        try:
            import os
            self.kv.put(_HB_SCOPE, f"{self.epoch}:{self.rank}",
                        f"{self._seq}|{os.getpid()}".encode())
        except Exception:  # noqa: BLE001 - KV hiccup: next beat retries
            pass

    def _note_kv_outage(self, now: float, was_down: bool) -> None:
        """Restart every peer's staleness window at `now` (the liveness
        table itself is down; one structured warning per outage)."""
        if not was_down and not self._kv_outage:
            logger.warning(
                "resilience: rendezvous KV unreachable — heartbeat "
                "staleness clock paused until an endpoint answers "
                "(coordinator restart/failover window)")
        self._kv_outage = True
        for r, (value, _t) in list(self._last_progress.items()):
            self._last_progress[r] = (value, now)

    def poll_once(self) -> None:
        """One detection pass (also called directly by tests)."""
        now = time.monotonic()
        kv_was_down = self._kv_outage
        self._kv_outage = False
        for r in range(self.size):
            # Suspect ranks keep being polled — heartbeat silence (or a
            # peer's confirmed mark) may upgrade them to confirmed.
            if r == self.rank or r in self._confirmed:
                continue
            # Fast path: a peer's direct transport evidence.
            try:
                mark = self.kv.get(_DEAD_SCOPE, f"{self.epoch}:{r}")
            except Exception:  # noqa: BLE001 - KV hiccup
                mark = None
            if mark is not None:
                text = mark.decode(errors="replace")
                kind_tag, _, reason = text.partition("|")
                confirmed = kind_tag != "suspect"
                if confirmed or r not in self._failed:
                    self._declare(r, reason or text, kind="transport",
                                  confirmed=confirmed)
                if confirmed:
                    continue
                # Suspect mark only: FALL THROUGH to the staleness check
                # — heartbeat silence must still be able to upgrade the
                # suspicion to confirmed death (a SIGKILLed rank whose
                # socket closed first would otherwise stay suspect
                # forever, and shrink-style recovery keys on
                # confirmation).
            try:
                raw = self.kv.get(_HB_SCOPE, f"{self.epoch}:{r}")
            except Exception:  # noqa: BLE001
                # KV unreachable (coordinator death / failover window):
                # nobody's stamp can advance, so observed silence says
                # nothing about the PEER.  Pause the staleness clock —
                # every peer's window restarts when the control plane
                # answers again — instead of condemning the whole world
                # for the coordinator's outage.
                self._note_kv_outage(now, kv_was_down)
                continue
            value = raw.decode(errors="replace") if raw is not None else ""
            if value.startswith("bye|"):
                # Orderly departure (shutdown or epoch rebuild): not
                # death evidence — the transport's own socket errors
                # cover the rank's absence from live collectives.
                self._last_progress[r] = (value, now)
                continue
            prev = self._last_progress.get(r)
            if prev is None or prev[0] != value:
                if value:
                    self._last_progress[r] = (value, now)
                continue
            silence = now - prev[1]
            grace_over = now - self._started_at > self.fault_timeout
            if silence > self.fault_timeout and grace_over:
                self._declare(
                    r, f"heartbeat silent for {silence:.1f}s "
                       f"(> {self.fault_timeout:g}s)", kind="heartbeat")
                if self._tm_on:
                    self._m_latency.observe(silence * 1e3)
        if kv_was_down and not self._kv_outage:
            logger.warning("resilience: rendezvous KV reachable again; "
                           "heartbeat staleness clock resumed")

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            self._publish()
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 - never kill the monitor
                logger.debug("resilience: liveness poll failed",
                             exc_info=True)
