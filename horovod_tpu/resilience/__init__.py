"""resilience/ — failure detection, deadline-bounded collectives, and the
deterministic fault-injection (chaos) harness (ISSUE 5;
docs/resilience.md).

Module surface:

- :func:`configure` / :func:`active_state` — process resilience state
  (heartbeat monitor + deadline policy); None in the zero-overhead off
  mode (``HOROVOD_FAULT_TOLERANCE`` unset).
- :class:`~..common.exceptions.RanksFailedError` — the structured,
  attributed error every survivor raises instead of deadlocking when a
  peer dies, becomes unreachable, or misses a collective deadline.
- :func:`run_with_recovery` — applies ``HOROVOD_ON_FAILURE``
  (raise | retry-with-rebuilt-channels | shrink-via-elastic).
- :mod:`.chaos` — ``HOROVOD_CHAOS`` deterministic fault injection
  (kill/freeze/fail at a collective index, delay/drop/dup a specific
  peer-channel send), seeded and replayable so every failure path above
  is exercised by ordinary pytest workers.
"""
from __future__ import annotations

from ..common.exceptions import RanksFailedError
from . import chaos
from .context import (ResilienceState, active_state, configure, current_op,
                      current_op_deadline, deadline_scope, op_scope,
                      pending_deadline, shutdown)
from .policy import (apply_shrink, converge_confirmed_dead, rebuild_world,
                     run_with_recovery)

__all__ = [
    "RanksFailedError", "ResilienceState", "active_state", "apply_shrink",
    "chaos", "configure", "converge_confirmed_dead", "current_op",
    "current_op_deadline", "deadline_scope", "op_scope",
    "pending_deadline", "rebuild_world", "run_with_recovery", "shutdown",
]
