"""Recovery policy: what happens AFTER a collective raised
RanksFailedError (``HOROVOD_ON_FAILURE=raise|shrink|retry``).

- ``raise`` (default): propagate — the safe behavior for fixed-size
  jobs, and what a surrounding elastic loop (``hvd.elastic.run``) needs
  to see to trigger its own restore/re-rendezvous.
- ``retry``: for *idempotent eager collectives* only.  Transport state
  after a deadline expiry is unrecoverable in place (a late frame from
  the slow rank would desync the byte stream), so a retry is a full
  channel rebuild: ``hvd.shutdown()``, a deterministic epoch bump every
  rank computes identically, ``hvd.init()`` against fresh mesh scopes,
  then the collective re-runs.  Exponential backoff between attempts;
  ranks the liveness monitor confirms DEAD are never retried over
  (a dead rank cannot rejoin a fixed-size world — that is shrink's job).
- ``shrink``: hand the surviving-rank set to the elastic driver: the
  dead ranks' hosts are blacklisted (reference: horovod/runner/elastic/
  driver.py host blacklist) and the next rendezvous round forms on the
  survivors.  Inside ``hvd.elastic.run`` this happens by re-raising —
  RanksFailedError IS a HorovodInternalError, so the elastic loop's
  restore + re-rendezvous path fires; :func:`apply_shrink` is the
  driver-side half that records the failures and lets the round resolve
  at the smaller world size.
"""
from __future__ import annotations

import os
import time

from ..common import config
from ..common.exceptions import HorovodInternalError, RanksFailedError
from ..common.logging import logger
from . import context as _context

__all__ = ["apply_shrink", "converge_confirmed_dead", "rebuild_world",
           "run_with_recovery"]

# Attempts taken by the most recent run_with_recovery call (observability
# for tests and post-mortems; single-threaded write from the caller).
last_attempts = 0


def _retry_epoch(base: str, attempt: int) -> str:
    """Deterministic epoch for retry attempt N: every rank computes the
    same value from the same base, so the rebuilt meshes' KV scopes
    agree without any extra coordination."""
    root = base.split("~r", 1)[0]
    return f"{root}~r{attempt}"


def _await_control_plane(deadline_s: float = 10.0) -> bool:
    """Block (bounded) until some rendezvous endpoint answers its
    ``/.ctl/role`` probe.  A retry that races a coordinator failover
    window would otherwise burn its attempts on mesh formation
    timeouts while the standby is still promoting; waiting here costs
    one probe loop instead of a full rebuild cycle."""
    from ..common import config as _config
    from ..runner.network import RendezvousClient

    addr = _config.RENDEZVOUS_ADDR.get()
    port = _config.RENDEZVOUS_PORT.get()
    if not addr:
        return True                      # single-process world: no KV
    client = RendezvousClient(addr, port, timeout=2.0)
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        if client.find_primary() is not None:
            return True
        time.sleep(0.1)
    logger.warning("resilience: no rendezvous primary answered within "
                   "%.1fs; proceeding with the rebuild anyway",
                   deadline_s)
    return False


def rebuild_world(attempt: int) -> None:
    """Tear the runtime down and re-form every channel under a fresh
    rendezvous epoch (mesh scopes, shm regions, heartbeat table all key
    on it, so no stale state from the failed world is ever touched)."""
    from .. import core
    base = os.environ.get("HOROVOD_RENDEZVOUS_EPOCH", "0")
    core.shutdown()
    _await_control_plane()
    os.environ["HOROVOD_RENDEZVOUS_EPOCH"] = _retry_epoch(base, attempt)
    core.init()


def run_with_recovery(fn, *, policy: str | None = None,
                      max_retries: int | None = None,
                      base_backoff: float | None = None):
    """Run ``fn`` (an idempotent eager collective, or a closure of them)
    under the configured failure policy.  Returns ``fn()``'s result."""
    global last_attempts
    policy = (policy or config.ON_FAILURE.get()).strip().lower()
    if policy not in ("raise", "retry", "shrink"):
        raise ValueError(f"HOROVOD_ON_FAILURE must be raise|shrink|retry "
                         f"(got {policy!r})")
    retries = config.FAULT_RETRIES.get() if max_retries is None \
        else int(max_retries)
    backoff = config.FAULT_BACKOFF_SECONDS.get() if base_backoff is None \
        else float(base_backoff)
    attempt = 0
    while True:
        try:
            result = fn()
            last_attempts = attempt + 1
            return result
        except HorovodInternalError as exc:
            last_attempts = attempt + 1
            if policy in ("raise", "shrink"):
                # shrink: the surrounding elastic loop owns the resize —
                # RanksFailedError is a HorovodInternalError, so
                # hvd.elastic.run restores state and re-rendezvouses on
                # the post-blacklist host set (see apply_shrink).
                raise
            if attempt >= retries:
                logger.error("resilience: giving up after %d retry "
                             "attempt(s): %s", attempt, exc)
                raise
            state = _context.active_state()
            if isinstance(exc, RanksFailedError) and state is not None:
                dead = state.confirmed_dead(exc.failed_ranks)
                if dead:
                    logger.error(
                        "resilience: not retrying — rank(s) %s are "
                        "confirmed dead (retry cannot resize the world; "
                        "use HOROVOD_ON_FAILURE=shrink under elastic)",
                        sorted(dead))
                    raise
            delay = backoff * (2 ** attempt)
            logger.warning("resilience: attempt %d failed (%s); "
                           "rebuilding channels and retrying in %.2fs",
                           attempt, exc, delay)
            time.sleep(delay)
            attempt += 1
            rebuild_world(attempt)


def converge_confirmed_dead(exc: RanksFailedError) -> frozenset[int]:
    """Converge on the heartbeat-CONFIRMED dead set after a collective
    raised RanksFailedError: every survivor must compute the same
    membership before any of them renumbers the world, and suspicion
    alone (a slow-but-alive peer) must never shrink it — an
    unconfirmable failure re-raises ``exc`` instead.

    Shared by the serving shrink path (serving/replica.py) and the
    statesync failure-shrink transition (statesync/service.py): both
    poll the liveness monitor until the confirmed set is stable across
    two polls, bounded by two fault windows."""
    from . import context as _ctx

    state = _ctx.active_state()
    if state is None:
        raise exc
    suspects = set(exc.failed_ranks)
    deadline = time.monotonic() + 2.0 * state.fault_timeout
    confirmed: frozenset[int] = frozenset()
    while time.monotonic() < deadline:
        try:
            state.monitor.poll_once()
        except Exception:  # noqa: BLE001 - convergence must not mask
            pass
        suspects |= state.failed_ranks()
        now_confirmed = state.confirmed_dead(suspects)
        if now_confirmed and now_confirmed == confirmed:
            return confirmed           # stable across two polls
        confirmed = now_confirmed
        time.sleep(state.poll_interval)
    if confirmed:
        return confirmed
    raise exc                          # alive-but-wedged: not shrinkable


def apply_shrink(driver, failed_ranks) -> dict[int, str]:
    """Driver-side shrink: blacklist every failed rank's host and record
    the slot failures so the current rendezvous round can resolve and
    :meth:`ElasticDriver.resume` re-forms the world on the survivors.
    Returns {failed rank: host} for logging/telemetry."""
    slots = driver.rank_to_slot()
    shrunk: dict[int, str] = {}
    for r in sorted(set(failed_ranks)):
        slot = slots.get(r)
        if slot is None:
            continue
        shrunk[r] = slot.hostname
        driver.record_failure(slot.hostname, slot.local_rank)
    if shrunk:
        logger.warning("resilience: shrink — blacklisted %s; elastic "
                       "driver will resume on the survivors",
                       {r: h for r, h in shrunk.items()})
    return shrunk
