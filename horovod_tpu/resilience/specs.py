"""Protocol spec for the hard-failure shrink-convergence path (hvdmc).

Co-located with ``policy.py``: a collective raised ``RanksFailedError``
on some (possibly different) step of every survivor; before any of them
renumbers the world they must converge on the heartbeat-CONFIRMED dead
set — suspicion alone (a slow-but-alive peer) must never shrink the
world — and realign replicated state afterwards
(``resync_replicated``).  Shared by the serving shrink handler
(``serving/replica.py``) and the statesync failure-shrink transition.
"""
from __future__ import annotations

from ..analysis.hvdmc.spec import ProtocolSpec, Transition, Verb

__all__ = ["shrink_spec"]

_POLICY = "resilience.policy"
_SVC = "statesync.service.StateSyncService"
_REPLICA = "serving.replica.ReplicaExecutor"


def shrink_spec() -> ProtocolSpec:
    transitions = (
        Transition("vic.crash", "victim", "run", "crashed",
                   "fault:crash"),
        Transition("vic.freeze", "victim", "run", "frozen",
                   "fault:freeze",
                   doc="alive but wedged: suspect, never confirmable"),
        Transition("hb.confirm", "victim", "crashed", "crashed",
                   "internal:heartbeat-confirms",
                   doc="stale stamps + transport evidence upgrade the "
                       "suspect to CONFIRMED"),
        Transition("sur.fail", "survivor", "run", "failcaught",
                   "internal:ranks-failed",
                   binds=(f"{_SVC}.shrink_on_failure",
                          f"{_REPLICA}._shrink_and_resume"),
                   doc="survivors can catch the failure on DIFFERENT "
                       "steps (one applied the last update, a neighbor "
                       "did not)"),
        Transition("sur.converge-poll", "survivor", "failcaught",
                   "converging", "internal:poll",
                   requires_calls=("poll_once",),
                   binds=(f"{_POLICY}.converge_confirmed_dead",)),
        Transition("sur.confirm-shrink", "survivor", "converging",
                   "shrunk", "internal:confirmed-stable",
                   guard="confirmed-only",
                   requires_calls=("reinit_world",), observe="shrink",
                   binds=(f"{_SVC}.shrink_on_failure",)),
        Transition("sur.reraise-suspect", "survivor", "converging",
                   "raised", "internal:unconfirmable",
                   guard="confirmed-only",
                   binds=(f"{_POLICY}.converge_confirmed_dead",),
                   doc="no confirmation inside two fault windows: "
                       "re-raise rather than shrink over a live peer"),
        Transition("sur.resync", "survivor", "shrunk", "run",
                   "internal:resync",
                   requires_calls=("broadcast_object",),
                   binds=("statesync.service.resync_replicated",),
                   doc="the most-advanced survivor broadcasts; every "
                       "rank adopts its state version"),
    )
    return ProtocolSpec(
        name="resilience-shrink",
        doc="hard-failure shrink convergence (docs/resilience.md)",
        roles=("victim", "survivor"),
        states={"victim": ("run", "crashed", "frozen"),
                "survivor": ("run", "failcaught", "converging",
                             "shrunk", "raised")},
        verbs=(Verb("BYE", "kv", "bye|",
                    doc="orderly-shutdown liveness stamp: an epoch-"
                        "rebuilding rank is never mistaken for dead"),),
        transitions=transitions,
        anchor_modules=(_POLICY,),
        properties={
            "never-shrink-live":
                "a frozen (alive) peer is never in any committed dead "
                "set — convergence re-raises instead",
            "dead-set-agreement":
                "every survivor commits the identical dead set",
            "resync-equal":
                "after resync every survivor holds the same state "
                "version",
        })
