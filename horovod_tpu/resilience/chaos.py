"""Deterministic fault injection (the chaos harness).

``HOROVOD_CHAOS`` holds a ``';'``-separated list of actions, each
``kind:key=val,key=val``.  Matching is deterministic — actions fire at a
global collective index or a per-peer send index, both of which are
identical run-to-run (and, for the op index, identical across ranks: it
counts responses of the coordinator-ordered ResponseList) — so every
failure path has a replayable pytest reproduction.  An optional
``seed=`` enables the one stochastic matcher (``prob=``) with its own
private, replayable ``random.Random`` stream.

Response-level actions (fired by the background loop before dispatch;
``op=`` is the global response index, ``name=`` a tensor-name prefix,
``rank=`` the injecting rank or ``*``):

- ``kill:rank=2,op=5[,exit=43]``       — ``os._exit`` at response 5;
- ``freeze:rank=1,op=3,ms=5000``       — sleep mid-collective;
- ``fail:op=4[,rank=*][,count=2]``     — convert the response to a
  structured ERROR before any byte moves (rank ``*`` makes the failure
  symmetric on every rank — the retriable case);
- ``preempt:rank=2,op=7``              — deliver SIGTERM (NOT SIGKILL)
  to self at the global collective index and keep running: the
  preemption-notice grace path (HOROVOD_PREEMPT_GRACE_S) is then
  testable under the same deterministic harness as a kill.  Like every
  spec, ``rank=`` names the LAUNCH-TIME rank, so a survivor renumbered
  by an earlier shrink never inherits another rank's preemption.
- ``coordkill:at=5[,rank=0]``          — SIGKILL the rendezvous
  PRIMARY (pid resolved through the client's ``/.ctl/pid`` endpoint)
  at the global collective index: the coordinator-death shape the
  replicated control plane's standby promotion must absorb.  Fires
  from launch rank 0 by default so an N-rank world kills once.
- ``coordpause:at=5,ms=800[,rank=0]``  — SIGSTOP the rendezvous
  primary and SIGCONT it ``ms`` later: the lease-lapse-then-return
  split-brain shape — the resumed primary must fence itself on the
  WAL's higher leader epoch instead of acking stale writes.

Send-level actions (fired by ``PeerMesh`` at enqueue; ``send=`` is the
per-(mesh-scope, peer) send index, ``mesh=`` a scope prefix like
``data``):

- ``delay:rank=1,peer=2,send=0,ms=6000[,count=1]`` — sleep before the
  frame is handed to the sender lane (the caller thread stalls, exactly
  like a wedged producer);
- ``drop:rank=1,peer=2,send=3``        — swallow the frame;
- ``dup:rank=1,peer=2,send=3``         — enqueue the frame twice.

Every action consumes ``count`` firings (default: unlimited for
kill/freeze — they end the process or merely stall — and 1 for
fail/delay/drop/dup, so a retried op runs clean).
"""
from __future__ import annotations

import os
import random
import threading

from ..common import config
from ..common.logging import logger


def _sigcont(pid: int) -> None:
    """Resume a coordpause victim (fire-and-forget Timer body)."""
    import signal
    try:
        os.kill(pid, signal.SIGCONT)
    except OSError:
        pass

__all__ = ["ChaosAction", "ChaosEngine", "ChaosInjectedError", "active",
           "configure", "parse_spec"]

_RESPONSE_KINDS = frozenset({"kill", "freeze", "fail", "preempt",
                             "coordkill", "coordpause"})
_SEND_KINDS = frozenset({"delay", "drop", "dup"})
_DEFAULT_COUNTS = {"fail": 1, "preempt": 1, "delay": 1, "drop": 1,
                   "dup": 1, "coordkill": 1, "coordpause": 1}


class ChaosInjectedError(RuntimeError):
    """A chaos ``fail`` action converted this collective into an error."""


class ChaosAction:
    __slots__ = ("kind", "rank", "op", "name", "peer", "send", "mesh",
                 "ms", "exit_code", "sig", "count", "prob", "_rng",
                 "fired")

    def __init__(self, kind: str, params: dict[str, str]) -> None:
        if kind not in _RESPONSE_KINDS | _SEND_KINDS:
            raise ValueError(f"unknown chaos action kind {kind!r}")
        self.kind = kind
        # coordkill/coordpause fire from ONE rank (default launch rank
        # 0): the victim is the shared coordinator process, and N ranks
        # each delivering the signal would consume N standby promotions.
        raw_rank = params.get("rank",
                              "0" if kind.startswith("coord") else "*")
        self.rank = None if raw_rank == "*" else int(raw_rank)
        if "at" in params:              # coord* spelling of the op index
            params = dict(params, op=params["at"])
        self.op = int(params["op"]) if "op" in params else None
        self.name = params.get("name")
        self.peer = int(params["peer"]) if "peer" in params else None
        self.send = int(params["send"]) if "send" in params else None
        self.mesh = params.get("mesh")
        self.ms = float(params.get("ms", 0.0))
        self.exit_code = int(params.get("exit", 43))
        # kill delivery: sig=9 sends a REAL signal (the acceptance
        # criterion's SIGKILL mid-allreduce); default is os._exit.
        self.sig = int(params["sig"]) if "sig" in params else None
        self.count = int(params.get(
            "count", _DEFAULT_COUNTS.get(kind, -1)))   # -1 = unlimited
        self.prob = float(params["prob"]) if "prob" in params else None
        self._rng = random.Random(int(params.get("seed", 0))) \
            if self.prob is not None else None
        self.fired = 0
        if kind in _SEND_KINDS and self.peer is None:
            raise ValueError(f"chaos {kind} action requires peer=")
        if kind in _RESPONSE_KINDS and self.op is None \
                and self.name is None:
            raise ValueError(f"chaos {kind} action requires op= or name=")

    # -- matching --------------------------------------------------------
    def _consume(self) -> bool:
        if self.count == 0:
            return False
        if self.prob is not None and self._rng.random() >= self.prob:
            return False
        if self.count > 0:
            self.count -= 1
        self.fired += 1
        return True

    def matches_response(self, rank: int, op_index: int,
                         tensor_names) -> bool:
        if self.kind not in _RESPONSE_KINDS or self.count == 0:
            return False
        if self.rank is not None and self.rank != rank:
            return False
        if self.op is not None and self.op != op_index:
            return False
        if self.name is not None and not any(
                n.startswith(self.name) for n in tensor_names):
            return False
        return self._consume()

    def matches_send(self, rank: int, scope: str, peer: int,
                     send_index: int) -> bool:
        if self.kind not in _SEND_KINDS or self.count == 0:
            return False
        if self.rank is not None and self.rank != rank:
            return False
        if self.peer != peer:
            return False
        if self.mesh is not None and not scope.startswith(self.mesh):
            return False
        if self.send is not None and self.send != send_index:
            return False
        return self._consume()


def parse_spec(spec: str) -> list[ChaosAction]:
    actions: list[ChaosAction] = []
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        if ":" not in part:
            raise ValueError(f"chaos action {part!r} lacks 'kind:' prefix")
        kind, rest = part.split(":", 1)
        params: dict[str, str] = {}
        for kv in rest.split(","):
            kv = kv.strip()
            if not kv:
                continue
            if "=" not in kv:
                raise ValueError(f"chaos parameter {kv!r} lacks '='")
            k, v = kv.split("=", 1)
            params[k.strip()] = v.strip()
        actions.append(ChaosAction(kind.strip(), params))
    return actions


def _coordinator_pid() -> int | None:
    """Resolve the rendezvous PRIMARY's pid through the seed list's
    ``/.ctl/pid`` endpoint (same-host chaos harness contract: the
    coordinator process must be signalable from this rank)."""
    from ..common import config as _config
    from ..runner.network import RendezvousClient

    from urllib import request as urlrequest

    addr = _config.RENDEZVOUS_ADDR.get()
    port = _config.RENDEZVOUS_PORT.get()
    if not addr:
        return None
    endpoint = RendezvousClient(addr, port, timeout=5.0).find_primary()
    if endpoint is None:
        return None
    try:
        with urlrequest.urlopen(
                f"http://{endpoint}/.ctl/pid", timeout=2.0) as resp:
            return int(resp.read())
    except (OSError, ValueError):
        return None


class ChaosEngine:
    """Process-wide injector.  Survives core shutdown/re-init on purpose:
    consumed ``count``s persist, so a retried collective after a world
    rebuild runs clean — the replayable half of the retry battery."""

    def __init__(self, spec: str, rank: int) -> None:
        self.spec = spec
        self.rank = rank
        self.actions = parse_spec(spec)
        self._op_index = 0
        self._send_index: dict[tuple[str, int], int] = {}
        self._lock = threading.Lock()

    # -- response hook (background loop, pre-dispatch) -------------------
    def on_response(self, tensor_names) -> str | None:
        """Advance the global collective index; fire any matching
        response action.  Returns "fail" when the caller must convert
        this response into a structured ERROR."""
        idx = self._op_index
        self._op_index += 1
        verdict: str | None = None
        for act in self.actions:
            if not act.matches_response(self.rank, idx, tensor_names):
                continue
            if act.kind == "kill":
                self._fire_kill(act, idx)
            elif act.kind == "preempt":
                self._fire_preempt(act, idx)
            elif act.kind in ("coordkill", "coordpause"):
                self._fire_coord(act, idx)
            elif act.kind == "freeze":
                logger.warning("chaos: freezing rank %d at collective %d "
                               "for %.0f ms", self.rank, idx, act.ms)
                import time
                time.sleep(act.ms / 1e3)
            elif act.kind == "fail":
                logger.warning("chaos: failing collective %d (%s)",
                               idx, list(tensor_names))
                verdict = "fail"
        return verdict

    def _fire_kill(self, act: ChaosAction, idx: int) -> None:
        """Deliver a kill to THIS process.  A seam on purpose: fleetsim's
        virtual engine overrides it to end one virtual rank instead of
        the host process that carries 500 of them."""
        logger.warning("chaos: killing rank %d at collective %d "
                       "(%s)", self.rank, idx,
                       f"signal {act.sig}" if act.sig is not None
                       else f"exit {act.exit_code}")
        import os
        if act.sig is not None:
            import time
            os.kill(os.getpid(), act.sig)
            time.sleep(5.0)   # SIGKILL lands before this expires
        os._exit(act.exit_code)

    def _fire_preempt(self, act: ChaosAction, idx: int) -> None:
        """SIGTERM to self (virtualized by fleetsim the same way)."""
        logger.warning("chaos: preempting rank %d at collective "
                       "%d (SIGTERM)", self.rank, idx)
        import os
        import signal
        os.kill(os.getpid(), signal.SIGTERM)
        # NOT followed by an exit: the grace path owns the
        # departure; without a grace handler the default
        # disposition (or flight's chained handler) fires.

    def _fire_coord(self, act: ChaosAction, idx: int) -> None:
        """SIGKILL (coordkill) or SIGSTOP+delayed-SIGCONT (coordpause)
        the rendezvous primary.  The victim pid is resolved through the
        seed list at fire time, so the action targets whichever replica
        CURRENTLY leads — a second firing after a failover exercises
        the next promotion."""
        import signal

        pid = _coordinator_pid()
        if pid is None:
            logger.warning("chaos: %s at collective %d: no rendezvous "
                           "primary reachable; skipping", act.kind, idx)
            return
        if act.kind == "coordkill":
            logger.warning("chaos: SIGKILL rendezvous primary pid %d "
                           "at collective %d", pid, idx)
            os.kill(pid, signal.SIGKILL)
            return
        pause_s = (act.ms or 1000.0) / 1e3
        logger.warning("chaos: SIGSTOP rendezvous primary pid %d at "
                       "collective %d for %.0f ms (lease-lapse-then-"
                       "return)", pid, idx, pause_s * 1e3)
        os.kill(pid, signal.SIGSTOP)
        timer = threading.Timer(pause_s, _sigcont, args=(pid,))
        timer.daemon = True
        timer.name = "hvd-chaos-cont"
        timer.start()

    # -- send hook (PeerMesh enqueue path) -------------------------------
    def on_send(self, scope: str, peer: int) -> str | None:
        """Advance the per-(scope, peer) send index; fire any matching
        send action.  Returns "drop"/"dup"/None; delays sleep inline
        (the caller thread stalls like a wedged producer)."""
        with self._lock:
            key = (scope, peer)
            idx = self._send_index.get(key, 0)
            self._send_index[key] = idx + 1
        verdict: str | None = None
        for act in self.actions:
            if not act.matches_send(self.rank, scope, peer, idx):
                continue
            if act.kind == "delay":
                logger.warning("chaos: delaying send %d to peer %d on "
                               "%s by %.0f ms", idx, peer, scope, act.ms)
                import time
                time.sleep(act.ms / 1e3)
            else:
                logger.warning("chaos: %s send %d to peer %d on %s",
                               act.kind, idx, peer, scope)
                verdict = act.kind
        return verdict


_engine: ChaosEngine | None = None
_lock = threading.Lock()


def active() -> ChaosEngine | None:
    return _engine


def configure(rank: int) -> ChaosEngine | None:
    """Install the engine from HOROVOD_CHAOS.  Reuses the existing engine
    when the spec is unchanged (consumed counts AND the global collective
    index must survive the shutdown+init cycle a retry or an elastic
    shrink performs — and a spec's ``rank=`` refers to the LAUNCH-TIME
    rank, so a survivor renumbered by a shrink keeps its original chaos
    identity instead of inheriting a dead rank's); clears it when the
    spec is."""
    global _engine
    spec = config.CHAOS.get().strip()
    with _lock:
        if not spec:
            _engine = None
        elif _engine is None or _engine.spec != spec:
            _engine = ChaosEngine(spec, rank)
        return _engine
