"""Checkpoint save/restore.

The reference has no core checkpoint format (SURVEY §5.4) — it relies on
``broadcast_parameters`` for start-of-training consistency and rank-0-gated
framework checkpoints.  The TPU-native equivalent: orbax for sharded-array
pytrees (params/optimizer state survive any mesh relayout), with the same
rank-0 gating semantics for the eager multi-process API.

Ring-sharded (ZeRO) optimizer state — PR 6's ``sync_and_apply`` keeps
1/world of the optimizer state per rank — needs its own round trip:
the replicated ``save_checkpoint`` path silently stores only THIS
rank's shard.  :func:`save_ring_checkpoint` writes one stamped shard
file per rank; :func:`restore_ring_checkpoint` reads every shard,
digest-verifies each, and re-cuts the concatenated state for the
CURRENT world size (statesync/snapshot.py ``reshard_ring_state``), so
a 4-rank run restores cleanly on 2 ranks (or 8) — the file layout is
world-size-agnostic.
"""
from __future__ import annotations

import glob as _glob
import json
import os
import re
from typing import Any

import jax
import numpy as np


def _checkpointer():
    import orbax.checkpoint as ocp
    return ocp.PyTreeCheckpointer()


def save_checkpoint(path: str, state: Any, *, force: bool = True) -> None:
    """Write a pytree checkpoint (sharded arrays handled by orbax).

    In multi-process (eager API) worlds only rank 0 writes, matching the
    reference's rank-0 gating (keras/callbacks.py BestModelCheckpoint).
    Under single-process SPMD every process calls this once anyway.
    """
    from . import core
    if core.is_initialized() and core.global_state().rank != 0 \
            and jax.process_count() == 1:
        return
    path = os.path.abspath(path)
    _checkpointer().save(path, state, force=force)


def restore_checkpoint(path: str, target: Any | None = None) -> Any:
    """Restore a pytree checkpoint; ``target`` (a matching pytree of arrays
    or ShapeDtypeStructs) restores with the target's shardings/dtypes."""
    path = os.path.abspath(path)
    ckpt = _checkpointer()
    if target is None:
        return ckpt.restore(path)
    import orbax.checkpoint as ocp
    try:
        return ckpt.restore(path, ocp.args.PyTreeRestore(target))
    except (TypeError, AttributeError):
        return ckpt.restore(path, item=target)


# ---------------------------------------------------------------------------
# Ring-sharded (ZeRO) optimizer-state round trip (ISSUE 10 satellite)
# ---------------------------------------------------------------------------
_RING_RE = re.compile(r"ring-(\d+)-of-(\d+)\.state$")


def _ring_paths(directory: str, rank: int, world: int) -> tuple[str, str]:
    base = os.path.join(os.path.abspath(directory),
                        f"ring-{rank}-of-{world}")
    return base + ".state", base + ".json"


def save_ring_checkpoint(directory: str, opt_state: Any, *, rank: int,
                         world: int, n_params: int, step: int = 0,
                         config=None) -> str:
    """Write THIS rank's ring-sharded optimizer-state shard (PR 6
    ``init_ring_optimizer_state`` layout) as a stamped flat image.

    Every rank calls this with its own shard — the directory ends up
    holding ``ring-<r>-of-<world>.state`` for every rank, which is the
    gather: restore reads them all and re-shards for whatever world
    size is current.  No collective runs here, so the save works from
    a failure handler or a preemption-grace window."""
    from .statesync.snapshot import flatten_state, state_digest

    os.makedirs(os.path.abspath(directory), exist_ok=True)
    image = flatten_state(opt_state)
    state_path, meta_path = _ring_paths(directory, rank, world)
    with open(state_path, "wb") as f:
        f.write(image)
    with open(meta_path, "w") as f:
        json.dump({"rank": rank, "world": world, "n_params": int(n_params),
                   "step": int(step), "nbytes": len(image),
                   "digest": state_digest(image)}, f)
    return state_path


def restore_ring_checkpoint(directory: str, tx, *, rank: int, world: int,
                            n_params: int | None = None,
                            config=None) -> tuple[Any, int]:
    """Restore THIS rank's optimizer-state shard for the CURRENT world
    size from a ring checkpoint written at ANY world size.

    Reads every saved shard, digest-verifies each against its stamp
    (and all stamps against each other's step — shards from different
    steps are a torn checkpoint), concatenates them back to the full
    flat state, and re-cuts ``rank``'s shard for ``world`` ranks.
    Returns ``(opt_state_shard, step)``; the shard pytree matches
    ``init_ring_optimizer_state(tx, ..., world, ...)``."""
    import jax.numpy as jnp

    from .parallel.grad_sync import GradSyncConfig, ring_chunk_size
    from .statesync.snapshot import (reshard_ring_state, state_digest,
                                     unflatten_state)

    directory = os.path.abspath(directory)
    files = sorted(_glob.glob(os.path.join(directory,
                                           "ring-*-of-*.state")))
    if not files:
        raise FileNotFoundError(
            f"no ring checkpoint shards under {directory}")
    cfg = config if config is not None else GradSyncConfig()
    by_rank: dict[int, str] = {}
    world_old = None
    for path in files:
        m = _RING_RE.search(path)
        if not m:
            continue
        r, w = int(m.group(1)), int(m.group(2))
        if world_old is None:
            world_old = w
        if w != world_old:
            raise ValueError(
                f"mixed world sizes in {directory}: found shards of "
                f"{w} and {world_old}")
        by_rank[r] = path
    if world_old is None or sorted(by_rank) != list(range(world_old)):
        raise ValueError(
            f"incomplete ring checkpoint: have shards {sorted(by_rank)} "
            f"of a {world_old}-rank world")
    shards = []
    step = None
    meta0 = None
    for r in range(world_old):
        with open(by_rank[r][:-len(".state")] + ".json") as f:
            meta = json.load(f)
        with open(by_rank[r], "rb") as f:
            image = f.read()
        if state_digest(image) != int(meta["digest"]) or \
                len(image) != int(meta["nbytes"]):
            raise ValueError(
                f"ring shard {by_rank[r]} failed its digest check — "
                f"refusing to restore corrupt optimizer state")
        if step is None:
            step, meta0 = int(meta["step"]), meta
        elif int(meta["step"]) != step:
            raise ValueError(
                f"torn ring checkpoint: shard {r} is from step "
                f"{meta['step']}, shard 0 from step {step}")
        n = int(meta["n_params"]) if n_params is None else int(n_params)
        chunk_old = ring_chunk_size(n, world_old, cfg)
        template = tx.init(jnp.zeros((chunk_old,), jnp.float32))
        shards.append(unflatten_state(image, template))
    n = int(meta0["n_params"]) if n_params is None else int(n_params)
    return reshard_ring_state(shards, n, world, rank, cfg), step


def latest_checkpoint(directory: str) -> str | None:
    """Newest checkpoint subdirectory by mtime (step-named dirs)."""
    if not os.path.isdir(directory):
        return None
    entries = [os.path.join(directory, e) for e in os.listdir(directory)]
    dirs = [e for e in entries if os.path.isdir(e)]
    return max(dirs, key=os.path.getmtime) if dirs else None
