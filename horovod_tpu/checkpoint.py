"""Checkpoint save/restore.

The reference has no core checkpoint format (SURVEY §5.4) — it relies on
``broadcast_parameters`` for start-of-training consistency and rank-0-gated
framework checkpoints.  The TPU-native equivalent: orbax for sharded-array
pytrees (params/optimizer state survive any mesh relayout), with the same
rank-0 gating semantics for the eager multi-process API.
"""
from __future__ import annotations

import os
from typing import Any

import jax


def _checkpointer():
    import orbax.checkpoint as ocp
    return ocp.PyTreeCheckpointer()


def save_checkpoint(path: str, state: Any, *, force: bool = True) -> None:
    """Write a pytree checkpoint (sharded arrays handled by orbax).

    In multi-process (eager API) worlds only rank 0 writes, matching the
    reference's rank-0 gating (keras/callbacks.py BestModelCheckpoint).
    Under single-process SPMD every process calls this once anyway.
    """
    from . import core
    if core.is_initialized() and core.global_state().rank != 0 \
            and jax.process_count() == 1:
        return
    path = os.path.abspath(path)
    _checkpointer().save(path, state, force=force)


def restore_checkpoint(path: str, target: Any | None = None) -> Any:
    """Restore a pytree checkpoint; ``target`` (a matching pytree of arrays
    or ShapeDtypeStructs) restores with the target's shardings/dtypes."""
    path = os.path.abspath(path)
    ckpt = _checkpointer()
    if target is None:
        return ckpt.restore(path)
    import orbax.checkpoint as ocp
    try:
        return ckpt.restore(path, ocp.args.PyTreeRestore(target))
    except (TypeError, AttributeError):
        return ckpt.restore(path, item=target)


def latest_checkpoint(directory: str) -> str | None:
    """Newest checkpoint subdirectory by mtime (step-named dirs)."""
    if not os.path.isdir(directory):
        return None
    entries = [os.path.join(directory, e) for e in os.listdir(directory)]
    dirs = [e for e in entries if os.path.isdir(e)]
    return max(dirs, key=os.path.getmtime) if dirs else None
