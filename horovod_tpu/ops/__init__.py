"""Collective-op algorithms and TPU kernels (adasum, compression, fused ops)."""
