"""Collective-op algorithms and TPU kernels (adasum, compression, fused ops)."""
from .flash_attention import (flash_attention, flash_attention_with_lse,
                              mha_reference)

__all__ = ["flash_attention", "flash_attention_with_lse", "mha_reference"]
