"""Streaming (vocab-chunked) softmax cross entropy.

Large-vocab LM heads pay more for the loss than for the matmul that
produced the logits: the naive path materializes a second fp32
``[tokens, vocab]`` tensor for ``log_softmax`` (6.6 GB at
batch 16 x seq 2048 x vocab 50304) and its fp32 gradient — all pure HBM
traffic. Measured on the v5e benchmark config, the naive loss costs
18.7 ms of a 411 ms step (docs/PERFORMANCE.md "Step decomposition").

This op computes the same mean cross entropy (with optional label
smoothing) without ever materializing an fp32 logits-sized tensor:

- forward: one streamed pass over vocab chunks with an online
  max/sum-exp (the flash-attention trick applied to the vocab axis),
  carrying three ``[tokens]`` fp32 vectors; the label logit comes from
  one gather.
- backward: ``d_logits = (softmax * target_mass - target) * g / tokens``
  is emitted chunk-by-chunk straight into the logits' own (usually
  bf16) dtype — one read of the logits, one write of the gradient,
  nothing fp32 of logits size.

Out-of-range labels (e.g. -1 as an ignore/padding index) follow the
dense ``jax.nn.one_hot`` semantics exactly: the one-hot target mass for
such rows is zero, so without smoothing they contribute nothing to loss
or gradient; with smoothing they still receive the uniform eps/V target
component (that is what the dense path computes).

Reference analogue: none — the reference's benchmarks stop at the
framework boundary (tf_cnn_benchmarks / synthetic torch models,
reference: docs/benchmarks.rst:20-43); this exists because on TPU the
loss epilogue is a first-class HBM-bandwidth consumer.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

def _pick_chunk(vocab: int, target: int) -> int:
    """Largest divisor of ``vocab`` <= target; ``vocab`` itself when the
    only such divisors are degenerately small (< target/8 — a prime
    vocab would otherwise degenerate to chunk=1: ~50k sequential
    one-column scan slices, in an op built to be fast)."""
    if vocab <= target:
        return vocab
    floor = max(1, target // 8)
    for n_chunks in range(2, vocab // floor + 1):
        if vocab % n_chunks == 0 and vocab // n_chunks <= target:
            return vocab // n_chunks
    return vocab


@partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _streaming_ce(logits2d: jax.Array, labels1d: jax.Array,
                  label_smoothing: float, chunk: int) -> jax.Array:
    loss, _ = _streaming_ce_fwd(logits2d, labels1d, label_smoothing, chunk)
    return loss


def _lse_scan(logits2d: jax.Array, chunk: int, need_total: bool):
    """One streamed pass: per-row logsumexp (and, for label smoothing,
    the per-row sum of logits)."""
    tokens, vocab = logits2d.shape
    n_chunks = vocab // chunk

    def body(carry, i):
        m, s, tot = carry
        xc = lax.dynamic_slice_in_dim(
            logits2d, i * chunk, chunk, axis=1).astype(jnp.float32)
        mc = jnp.max(xc, axis=-1)
        m_new = jnp.maximum(m, mc)
        s = s * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(xc - m_new[:, None]), axis=-1)
        if need_total:
            tot = tot + jnp.sum(xc, axis=-1)
        return (m_new, s, tot), None

    init = (jnp.full((tokens,), -jnp.inf, jnp.float32),
            jnp.zeros((tokens,), jnp.float32),
            jnp.zeros((tokens,), jnp.float32))
    (m, s, tot), _ = lax.scan(body, init, jnp.arange(n_chunks))
    return m + jnp.log(s), tot


def _streaming_ce_fwd(logits2d, labels1d, label_smoothing, chunk):
    tokens, vocab = logits2d.shape
    eps = label_smoothing
    lse, tot = _lse_scan(logits2d, chunk, need_total=bool(eps))
    valid = ((labels1d >= 0) & (labels1d < vocab))
    label_logit = jnp.take_along_axis(
        logits2d, jnp.clip(labels1d, 0, vocab - 1)[:, None],
        axis=1)[:, 0].astype(jnp.float32)
    # one_hot semantics: out-of-range labels carry zero one-hot mass.
    nll = jnp.where(valid, lse - label_logit, 0.0)
    if eps:
        nll = (1.0 - eps) * nll + eps * (lse - tot / vocab)
    return jnp.mean(nll), (logits2d, labels1d, lse)


def _streaming_ce_bwd(label_smoothing, chunk, res, g):
    logits2d, labels1d, lse = res
    tokens, vocab = logits2d.shape
    n_chunks = vocab // chunk
    eps = label_smoothing
    scale = (g / tokens).astype(jnp.float32)
    valid = ((labels1d >= 0) & (labels1d < vocab)).astype(jnp.float32)
    # d(-sum(target*logp))/dx = softmax * sum(target) - target.
    # sum(target) per row: (1-eps)*valid + eps  (eps/V rides every row).
    target_mass = (1.0 - eps) * valid + eps if eps else valid

    def body(dl, i):
        xc = lax.dynamic_slice_in_dim(
            logits2d, i * chunk, chunk, axis=1).astype(jnp.float32)
        p = jnp.exp(xc - lse[:, None])
        local = labels1d - i * chunk
        onehot = (local[:, None] == jnp.arange(chunk)[None, :]).astype(
            jnp.float32) * valid[:, None]
        target = (1.0 - eps) * onehot + eps / vocab if eps else onehot
        dchunk = ((p * target_mass[:, None] - target) * scale).astype(
            logits2d.dtype)
        return lax.dynamic_update_slice_in_dim(dl, dchunk, i * chunk,
                                               axis=1), None

    dlogits, _ = lax.scan(body, jnp.zeros_like(logits2d),
                          jnp.arange(n_chunks))
    return dlogits, None


_streaming_ce.defvjp(_streaming_ce_fwd, _streaming_ce_bwd)


def streaming_softmax_cross_entropy(logits: jax.Array, labels: jax.Array,
                                    label_smoothing: float = 0.0,
                                    chunk_target: int = 8192) -> jax.Array:
    """Mean softmax cross entropy over integer labels, streamed over the
    vocab axis so no fp32 logits-sized tensor is ever materialized.

    Numerically identical to the dense
    ``-mean(sum(one_hot(labels) * log_softmax(logits)))`` with fp32
    accumulation (same math, chunked), including one_hot's zero-mass
    treatment of out-of-range labels; gradients flow to ``logits`` in
    the logits' own dtype. ``chunk_target`` bounds the fp32 working
    chunk to ``[tokens, <=chunk_target]``.
    """
    vocab = logits.shape[-1]
    logits2d = logits.reshape(-1, vocab)
    labels1d = labels.reshape(-1).astype(jnp.int32)
    chunk = _pick_chunk(vocab, chunk_target)
    return _streaming_ce(logits2d, labels1d, float(label_smoothing), chunk)
