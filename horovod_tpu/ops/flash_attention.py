"""Fused flash attention for TPU (Pallas).

The reference framework has no attention code at all (SURVEY §5.7) — long
context on TPU is a first-class goal of this rebuild, so the hot op is a
native MXU kernel: blockwise attention with online softmax, FlashAttention-2
style forward and backward, streaming KV blocks through VMEM so memory is
O(block) instead of O(seq²).

Layout: [batch*heads, seq, head_dim] inside the kernels; the public API
takes [batch, seq, heads, head_dim] (BTHD, the framework-wide convention).

On non-TPU backends a numerically identical pure-JAX blockwise path runs
instead (same online-softmax math, differentiable); the Pallas kernels can
also be exercised anywhere via ``interpret=True`` (used by the unit tests).
"""
from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp

NEG_INF = -1e30
_LANE = 128   # TPU lane width: last-dim tile alignment


def _cdiv(a: int, b: int) -> int:
    return (a + b - 1) // b


def _out_struct(shape, dtype, *like):
    """ShapeDtypeStruct carrying the union of the inputs' varying mesh axes
    (vma) — required for pallas_call inside shard_map regions with
    check_vma=True."""
    aval_of = getattr(jax, "typeof", None) or jax.core.get_aval
    vma: frozenset = frozenset()
    for x in like:
        v = getattr(aval_of(x), "vma", None)
        if v:
            vma |= frozenset(v)
    try:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    except TypeError:   # older jax without vma support
        return jax.ShapeDtypeStruct(shape, dtype)


# ===========================================================================
# Pure-JAX reference (also the CPU fallback and the autodiff oracle)
# ===========================================================================
def mha_reference(q: jax.Array, k: jax.Array, v: jax.Array,
                  causal: bool = False,
                  sm_scale: float | None = None) -> jax.Array:
    """Dense softmax attention. q,k,v: [B, T, H, D] (BTHD)."""
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * sm_scale
    if causal:
        tq, tk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((tq, tk), bool), k=tk - tq)
        s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1).astype(v.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


# ===========================================================================
# Pallas forward kernel
# ===========================================================================
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                acc_ref, m_ref, l_ref, *,
                sm_scale: float, causal: bool, causal_offset: int,
                block_q: int, block_k: int, n_kv: int):
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    kj = pl.program_id(2)

    @pl.when(kj == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    # Causal: blocks strictly above the diagonal contribute nothing.
    run = True
    if causal:
        run = kj * block_k <= qi * block_q + (block_q - 1) + causal_offset

    @pl.when(run)
    def _compute():
        # MXU dots take the inputs in their own (bf16) dtype with fp32
        # accumulation: casting inputs to fp32 first would force fp32
        # multiply passes at a fraction of the bf16 MXU rate. Softmax
        # statistics stay fp32 (standard flash numerics).
        q = q_ref[0]                                  # [bq, d]
        k = k_ref[0]                                  # [bk, d]
        v = v_ref[0]                                  # [bk, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale   # [bq, bk]
        if causal:
            rows = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = kj * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows + causal_offset >= cols, s, NEG_INF)

        m_prev = m_ref[:, 0]                          # [bq]
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=1))
        alpha = jnp.exp(m_prev - m_cur)               # [bq]
        p = jnp.exp(s - m_cur[:, None])               # [bq, bk]
        l_ref[:, 0] = l_ref[:, 0] * alpha + jnp.sum(p, axis=1)
        m_ref[:, 0] = m_cur
        acc_ref[...] = acc_ref[...] * alpha[:, None] + jax.lax.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32)

    @pl.when(kj == n_kv - 1)
    def _finalize():
        l = l_ref[:, 0]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[...] / l_safe[:, None]).astype(o_ref.dtype)
        # lse is per-row but stored lane-broadcast at [bq, _LANE]: TPU
        # blocks need their last two dims (8, 128)-tileable, so a bare
        # [1, bq] output is unmappable (same layout as the upstream jax
        # flash kernel's l/m outputs).
        lse = jnp.where(l == 0.0, NEG_INF, m_ref[:, 0] + jnp.log(l_safe))
        lse_ref[0] = jax.lax.broadcast_in_dim(lse, (block_q, _LANE), (0,))


def _flash_fwd_pallas(q, k, v, *, sm_scale, causal, block_q, block_k,
                      interpret):
    """q,k,v: [BH, T, D] → (o [BH, T, D], lse [BH, T, _LANE] lane-bcast)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh, tq, d = q.shape
    tk = k.shape[1]
    block_q = min(block_q, tq)
    block_k = min(block_k, tk)
    assert tq % block_q == 0 and tk % block_k == 0, \
        f"seq lengths ({tq},{tk}) must divide blocks ({block_q},{block_k})"
    n_q, n_kv = tq // block_q, tk // block_k

    kernel = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, causal=causal,
        causal_offset=tk - tq, block_q=block_q, block_k=block_k, n_kv=n_kv)
    o, lse = pl.pallas_call(
        kernel,
        grid=(bh, n_q, n_kv),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, _LANE), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            _out_struct((bh, tq, d), q.dtype, q, k, v),
            _out_struct((bh, tq, _LANE), jnp.float32, q, k, v),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, _LANE), jnp.float32),
            pltpu.VMEM((block_q, _LANE), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return o, lse


# ===========================================================================
# Pallas backward kernels (FlashAttention-2 split: dq, then dk/dv)
# ===========================================================================
def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, dq_acc, *,
                   sm_scale: float, causal: bool, causal_offset: int,
                   block_q: int, block_k: int, n_kv: int):
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    kj = pl.program_id(2)

    @pl.when(kj == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    run = True
    if causal:
        run = kj * block_k <= qi * block_q + (block_q - 1) + causal_offset

    @pl.when(run)
    def _compute():
        # bf16 MXU inputs + fp32 accumulation (see _fwd_kernel note).
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0, :, 0]                        # lane-bcast → [bq]
        delta = delta_ref[0, :, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        if causal:
            rows = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = kj * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows + causal_offset >= cols, s, NEG_INF)
        p = jnp.exp(s - lse[:, None])                 # [bq, bk]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)       # [bq, bk]
        ds = p * (dp - delta[:, None]) * sm_scale
        dq_acc[...] += jax.lax.dot(ds.astype(k.dtype), k,
                                   preferred_element_type=jnp.float32)

    @pl.when(kj == n_kv - 1)
    def _finalize():
        dq_ref[0] = dq_acc[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc, *,
                    sm_scale: float, causal: bool, causal_offset: int,
                    block_q: int, block_k: int, n_q: int):
    from jax.experimental import pallas as pl

    kj = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    run = True
    if causal:
        run = kj * block_k <= qi * block_q + (block_q - 1) + causal_offset

    @pl.when(run)
    def _compute():
        # bf16 MXU inputs + fp32 accumulation (see _fwd_kernel note).
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0, :, 0]                        # lane-bcast → [bq]
        delta = delta_ref[0, :, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale   # [bq, bk]
        if causal:
            rows = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = kj * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows + causal_offset >= cols, s, NEG_INF)
        p = jnp.exp(s - lse[:, None])                 # [bq, bk]
        dv_acc[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)       # [bk, d]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)       # [bq, bk]
        ds = (p * (dp - delta[:, None]) * sm_scale).astype(q.dtype)
        dk_acc[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)       # [bk, d]

    @pl.when(qi == n_q - 1)
    def _finalize():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _flash_bwd_pallas(q, k, v, o, lse, do, *, sm_scale, causal,
                      block_q, block_k, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh, tq, d = q.shape
    tk = k.shape[1]
    block_q = min(block_q, tq)
    block_k = min(block_k, tk)
    n_q, n_kv = tq // block_q, tk // block_k

    # lse and delta ride lane-broadcast at [BH, T, _LANE] so their blocks
    # satisfy the (8, 128) tiling rule (materialized only for the span of
    # the two backward kernels).
    lse = jnp.broadcast_to(lse[:, :, None], (bh, tq, _LANE))
    delta = jnp.broadcast_to(
        jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                axis=-1)[:, :, None], (bh, tq, _LANE))

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, sm_scale=sm_scale, causal=causal,
                          causal_offset=tk - tq,
                          block_q=block_q, block_k=block_k, n_kv=n_kv),
        grid=(bh, n_q, n_kv),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, _LANE), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, _LANE), lambda b, i, j: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=_out_struct((bh, tq, d), q.dtype, q, k, v, do),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, sm_scale=sm_scale, causal=causal,
                          causal_offset=tk - tq,
                          block_q=block_q, block_k=block_k, n_q=n_q),
        grid=(bh, n_kv, n_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, _LANE), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, _LANE), lambda b, j, i: (b, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            _out_struct((bh, tk, d), k.dtype, q, k, v, do),
            _out_struct((bh, tk, d), v.dtype, q, k, v, do),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ===========================================================================
# Blockwise pure-JAX path (CPU fallback; numerically matches the kernel)
# ===========================================================================
def _blockwise_jax(q, k, v, *, sm_scale, causal):
    """[BH, T, D] online-softmax attention with lse, differentiable."""
    s = jnp.einsum("bqd,bkd->bqk", q.astype(jnp.float32),
                   k.astype(jnp.float32),
                   preferred_element_type=jnp.float32) * sm_scale
    if causal:
        tq, tk = s.shape[-2], s.shape[-1]
        rows = jnp.arange(tq)[:, None]
        cols = jnp.arange(tk)[None, :]
        # Bottom-right alignment for tq != tk, matching mha_reference's
        # tril(k=tk-tq) (cross-attention / decode windows).
        s = jnp.where(rows + (tk - tq) >= cols, s, NEG_INF)
    m = jnp.max(s, axis=-1)
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)
    o = jnp.einsum("bqk,bkd->bqd", p, v.astype(jnp.float32)) / l[..., None]
    lse = m + jnp.log(l)
    return o.astype(q.dtype), lse


# ===========================================================================
# Public API with custom VJP
# ===========================================================================
def _merge_heads(x):
    """[B, T, H, D] → [B*H, T, D]."""
    b, t, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, t, d)


def _split_heads(x, b, h):
    bh, t, d = x.shape
    return x.reshape(b, h, t, d).transpose(0, 2, 1, 3)


def _on_tpu() -> bool:
    return jax.default_backend() == "tpu"


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9))
def _flash(q, k, v, sm_scale, causal, block_q, block_k,
           block_q_bwd, block_k_bwd, interpret):
    o, _res = _flash_fwd(q, k, v, sm_scale, causal, block_q, block_k,
                         interpret)
    return o


def _flash_fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    # The residual keeps lse at [BH, T]: holding the kernels' lane-
    # broadcast [BH, T, _LANE] layout across fwd→bwd would pin 128× the
    # HBM for the whole backward span; the backward re-broadcasts it.
    if _on_tpu() or interpret:
        o, lse = _flash_fwd_pallas(q, k, v, sm_scale=sm_scale,
                                   causal=causal, block_q=block_q,
                                   block_k=block_k, interpret=interpret)
        lse = lse[:, :, 0]
    else:
        o, lse = _blockwise_jax(q, k, v, sm_scale=sm_scale, causal=causal)
    return o, (q, k, v, o, lse)


def _flash_fwd_rule(q, k, v, sm_scale, causal, block_q, block_k,
                    block_q_bwd, block_k_bwd, interpret):
    o, res = _flash_fwd(q, k, v, sm_scale, causal, block_q, block_k,
                        interpret)
    return o, res


def _flash_bwd_rule(sm_scale, causal, block_q, block_k,
                    block_q_bwd, block_k_bwd, interpret, res, g):
    # The backward kernel holds more live tiles than the forward (dq, dk,
    # dv accumulators + recomputed p), so its VMEM-optimal blocks are
    # usually SMALLER; they default to the forward's but are sweepable
    # independently (r3 found fwd 1024/1024 optimal while 1024/2048
    # exceeded the 16 MiB scoped-vmem limit).
    q, k, v, o, lse = res
    if _on_tpu() or interpret:
        dq, dk, dv = _flash_bwd_pallas(
            q, k, v, o, lse, g, sm_scale=sm_scale, causal=causal,
            block_q=block_q_bwd or block_q,
            block_k=block_k_bwd or block_k, interpret=interpret)
    else:
        _, vjp = jax.vjp(
            lambda q_, k_, v_: _blockwise_jax(q_, k_, v_,
                                              sm_scale=sm_scale,
                                              causal=causal)[0], q, k, v)
        dq, dk, dv = vjp(g)
    return dq, dk, dv


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def _fit_block(t: int, block: int) -> int:
    """Largest block <= requested that divides the sequence length (the
    kernels assume exact tiling; odd lengths degrade granularity instead of
    failing)."""
    block = min(block, t)
    while t % block:
        block -= 1
    return block


def _check_dtypes(q: jax.Array, k: jax.Array, v: jax.Array) -> None:
    """The kernels feed q/k/v to the MXU dots in their RAW dtypes (fp32
    casts would forfeit the bf16 MXU rate), so mixed-dtype inputs either
    fail Mosaic lowering with an opaque error or silently change
    accumulation.  Make the contract explicit at the entry point."""
    if not (q.dtype == k.dtype == v.dtype):
        raise ValueError(
            f"flash attention requires q, k and v to share one dtype "
            f"(got q={q.dtype}, k={k.dtype}, v={v.dtype}); cast the "
            f"inputs to a common dtype first")


def _check_causal_shapes(causal: bool, tq: int, tk: int) -> None:
    """Bottom-right causal alignment leaves the first tq-tk query rows with
    zero valid keys when tq > tk — attention is undefined there (the dense
    reference degenerates to uniform weights over garbage). Reject loudly
    instead of silently diverging."""
    if causal and tq > tk:
        raise ValueError(
            f"causal attention requires tq <= tk (got tq={tq}, tk={tk}): "
            "with bottom-right alignment the leading query rows would "
            "attend to nothing")


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = False, sm_scale: float | None = None,
                    block_q: int = 128, block_k: int = 128,
                    block_q_bwd: int | None = None,
                    block_k_bwd: int | None = None,
                    interpret: bool = False) -> jax.Array:
    """Fused multi-head attention. q,k,v: [B, T, H, D] (BTHD). Differentiable
    (custom VJP with Pallas backward kernels on TPU).  ``block_*_bwd``
    override the backward kernel's tiling (defaults: same as forward)."""
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    _check_dtypes(q, k, v)
    _check_causal_shapes(causal, q.shape[1], k.shape[1])
    b, _, h, _ = q.shape
    block_q = _fit_block(q.shape[1], block_q)
    block_k = _fit_block(k.shape[1], block_k)
    bq_bwd = _fit_block(q.shape[1], block_q_bwd) if block_q_bwd else 0
    bk_bwd = _fit_block(k.shape[1], block_k_bwd) if block_k_bwd else 0
    out = _flash(_merge_heads(q), _merge_heads(k), _merge_heads(v),
                 float(sm_scale), bool(causal), int(block_q), int(block_k),
                 int(bq_bwd), int(bk_bwd), bool(interpret))
    return _split_heads(out, b, h)


def flash_attention_with_lse(q: jax.Array, k: jax.Array, v: jax.Array,
                             causal: bool = False,
                             sm_scale: float | None = None,
                             block_q: int = 128, block_k: int = 128,
                             interpret: bool = False
                             ) -> tuple[jax.Array, jax.Array]:
    """Like :func:`flash_attention` but also returns the log-sum-exp
    [B, H, T] — the merge statistic ring attention needs. Differentiation
    flows through the non-lse output only."""
    b, _, h, _ = q.shape
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    _check_dtypes(q, k, v)
    _check_causal_shapes(causal, q.shape[1], k.shape[1])
    block_q = _fit_block(q.shape[1], block_q)
    block_k = _fit_block(k.shape[1], block_k)
    qm, km, vm = _merge_heads(q), _merge_heads(k), _merge_heads(v)
    if _on_tpu() or interpret:
        o, lse = _flash_fwd_pallas(qm, km, vm, sm_scale=float(sm_scale),
                                   causal=causal, block_q=block_q,
                                   block_k=block_k, interpret=interpret)
        lse = lse[:, :, 0]   # un-broadcast the lane dim
    else:
        o, lse = _blockwise_jax(qm, km, vm, sm_scale=float(sm_scale),
                                causal=causal)
    t = q.shape[1]
    return _split_heads(o, b, h), lse.reshape(b, h, t)
