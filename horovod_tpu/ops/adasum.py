"""Adasum: scale-insensitive gradient reduction.

Reference: horovod/common/ops/adasum/adasum.h:38-552 — recursive
vector-halving distance-doubling (VHDD): at each level ranks pair up
(partner = rank XOR distance), split their fragment in half, exchange the
half they don't keep, compute the pairwise dot products, sum those dots over
the aligned 2·distance rank group, and combine with the scale-adaptive rule

    a' = a·(1 − ab/(2·aa)) + b·(1 − ab/(2·bb))

which orthogonalises the pair of gradients instead of summing them, making
the effective step robust to learning-rate × world-size blowup.  After the
down-sweep each rank holds the combined result for its fragment; the reverse
sweep reassembles the full vector.

`adasum_combine` is the pure math shared by the TCP (CPU) and XLA (TPU)
paths; `adasum_tcp` runs VHDD over the PeerMesh sockets.
"""
from __future__ import annotations

import numpy as np


def adasum_combine(a: np.ndarray, b: np.ndarray,
                   aa: float, bb: float, ab: float) -> np.ndarray:
    """Combine fragments a,b given *global* dot products aa=‖a‖², bb=‖b‖²,
    ab=a·b (reference: adasum.h ComputeDotAndNormSqrds + ScaledAdd)."""
    if aa == 0.0 and bb == 0.0:
        return a + b
    acoef = 1.0 if aa == 0.0 else 1.0 - ab / (2.0 * aa)
    bcoef = 1.0 if bb == 0.0 else 1.0 - ab / (2.0 * bb)
    return acoef * a + bcoef * b


def _is_pow2(n: int) -> bool:
    return n > 0 and (n & (n - 1)) == 0


def _group_scalar_allreduce(coll, values: np.ndarray, group_bits: int) -> np.ndarray:
    """Sum small fp64 vectors over the aligned 2^group_bits rank group via
    recursive doubling (reference: adasum reduction_comms_)."""
    acc = values.astype(np.float64, copy=True)
    for j in range(group_bits):
        peer = coll.rank ^ (1 << j)
        data = coll._sendrecv(peer, acc.tobytes(), peer)
        acc += np.frombuffer(data, dtype=np.float64)
    return acc


def adasum_tcp(coll, buf: np.ndarray) -> np.ndarray:
    """Full Adasum allreduce over the TCP PeerMesh.

    Requires a power-of-2 world size (the reference's VHDD has the same
    constraint; reference: adasum.h power-of-2 rank pairing).
    """
    size, rank = coll.size, coll.rank
    if size == 1:
        return buf
    if not _is_pow2(size):
        raise ValueError(
            f"Adasum requires a power-of-2 world size, got {size}")

    orig_dtype = buf.dtype
    frag = buf.astype(np.float64, copy=True)
    path: list[tuple[int, bool, int]] = []   # (partner, kept_first, my_len)

    distance = 1
    level = 0
    while distance < size:
        partner = rank ^ distance
        n = frag.size
        mid = n // 2
        kept_first = rank < partner
        keep = frag[:mid] if kept_first else frag[mid:]
        give = frag[mid:] if kept_first else frag[:mid]
        data = coll._sendrecv(partner, give.tobytes(), partner)
        partner_frag = np.frombuffer(data, dtype=np.float64)

        # Consistent vector identity across the pair: `a` is the vector held
        # by the lower half of the group (ranks with bit `level` clear),
        # `b` by the upper half — otherwise the summed dot products mix
        # ‖a‖² and ‖b‖² pieces (reference: adasum.h rank pairing).
        a_frag, b_frag = (keep, partner_frag) if kept_first \
            else (partner_frag, keep)
        dots = np.array([a_frag @ a_frag, b_frag @ b_frag, a_frag @ b_frag],
                        dtype=np.float64)
        # Dots must cover the *whole* vectors being combined, whose fragments
        # are spread over the aligned 2·distance rank group.
        dots = _group_scalar_allreduce(coll, dots, level + 1)
        aa, bb, ab = dots
        frag = adasum_combine(a_frag, b_frag, aa, bb, ab)
        path.append((partner, kept_first, frag.size))
        distance <<= 1
        level += 1

    # Reverse sweep: reassemble the full combined vector.
    for partner, kept_first, _ in reversed(path):
        data = coll._sendrecv(partner, frag.tobytes(), partner)
        other = np.frombuffer(data, dtype=np.float64)
        frag = np.concatenate([frag, other] if kept_first else [other, frag])

    return frag.astype(orig_dtype, copy=False)


def adasum_reference(tensors: list[np.ndarray]) -> np.ndarray:
    """Serial n-way Adasum for test oracles: combine in the same pairwise
    tree order VHDD uses ((0,1),(2,3)) → ((01),(23)) → ..."""
    vals = [np.asarray(t, dtype=np.float64).reshape(-1) for t in tensors]
    while len(vals) > 1:
        nxt = []
        for i in range(0, len(vals), 2):
            a, b = vals[i], vals[i + 1]
            nxt.append(adasum_combine(a, b, float(a @ a), float(b @ b),
                                      float(a @ b)))
        vals = nxt
    return vals[0].reshape(np.asarray(tensors[0]).shape)
