"""horovod_tpu: a TPU-native distributed training framework.

Horovod-compatible public API (reference: horovod/torch/mpi_ops.py,
horovod/common/basics.py) over a TPU-first runtime:

- control plane: rendezvous KV + coordinator protocol with response caching
  over TCP (DCN), mirroring the reference's Gloo controller;
- data plane: XLA collectives (psum/all_gather/all_to_all/ppermute) compiled
  over the ICI device mesh inside jit for SPMD training, plus a CPU TCP ring
  backend for multi-process worlds without TPUs;
- the same semantics: tensor fusion, grouped ops, pre/postscale, Adasum,
  Join-based uneven-data handling, elastic state, timeline, autotune.

Synchronous ops return results in the caller's framework (numpy in → numpy
out, torch in → torch out, jax in → jax out).
"""
from __future__ import annotations

# hvdsan runtime witness (HOROVOD_SAN=1; analysis/hvdsan/san.py) must
# patch the threading factories BEFORE any package module creates a
# lock — core's module-level _init_lock is born a few imports below.
from .analysis.hvdsan import maybe_enable as _hvdsan_maybe_enable

_hvdsan_maybe_enable()

from typing import Any, Sequence

import numpy as np

from . import core
from .common.exceptions import (HorovodInternalError, HorovodTpuError,
                                HostsUpdatedInterrupt, RanksFailedError)
from .common.status import Status
from .core import (Handle, init, is_initialized, shutdown, rank, size,
                   local_rank, local_size, cross_rank, cross_size,
                   is_homogeneous, start_timeline, stop_timeline)


def run(func, args=(), kwargs=None, np=None, hosts=None, env=None,
        use_gloo=True, start_timeout=120.0, min_np=None, max_np=None,
        host_discovery_script=None, reset_limit=None,
        elastic_timeout=None, slots=None):
    """Programmatic N-worker launch of a function
    (reference: horovod/runner/__init__.py:92-210 horovod.run).
    min_np/max_np/host_discovery_script switch to the elastic driver."""
    from .runner.run_api import run as _run
    return _run(func, args=args, kwargs=kwargs, np=np, hosts=hosts,
                env=env, use_gloo=use_gloo, start_timeout=start_timeout,
                min_np=min_np, max_np=max_np,
                host_discovery_script=host_discovery_script,
                reset_limit=reset_limit, elastic_timeout=elastic_timeout,
                slots=slots)

__version__ = "0.1.0"


# --- Reduce-op markers (reference: horovod/common/basics.py Sum/Average/Adasum)
class _ReduceOp:
    def __init__(self, name: str) -> None:
        self.name = name

    def __repr__(self) -> str:
        return f"hvd.{self.name}"


Sum = _ReduceOp("Sum")
Average = _ReduceOp("Average")
Adasum = _ReduceOp("Adasum")


def _op_kind(op, average: bool | None) -> tuple[str, bool]:
    """Map (op, legacy average flag) → (sum|average, adasum?)."""
    if average is not None:
        if op is not None and op is not Average and op is not Sum:
            raise ValueError("Cannot specify both op and average")
        return ("average" if average else "sum"), False
    if op is None or op is Average:
        return "average", False
    if op is Sum:
        return "sum", False
    if op is Adasum:
        return "sum", True
    raise ValueError(f"Unknown reduce op: {op}")


# --- Framework-preserving output wrapping ----------------------------------
def _wrap_like(reference: Any, out: np.ndarray) -> Any:
    mod = type(reference).__module__
    if mod.startswith("torch"):
        import torch
        return torch.from_numpy(np.ascontiguousarray(out)).to(
            reference.dtype)
    if mod.startswith(("jax", "jaxlib")):
        import jax.numpy as jnp
        return jnp.asarray(out)
    return out


def _wrap_int_like(reference: Any, out: np.ndarray) -> Any:
    """Wrap an integer auxiliary result (e.g. received splits) into the
    caller's framework *keeping its integer dtype*."""
    mod = type(reference).__module__
    if mod.startswith("torch"):
        import torch
        return torch.from_numpy(np.ascontiguousarray(out))
    if mod.startswith(("jax", "jaxlib")):
        import jax.numpy as jnp
        return jnp.asarray(out)
    return out


def _result(handle: Handle, reference: Any) -> Any:
    status = handle.wait()
    status.raise_if_error()
    return _wrap_like(reference, handle.entries[0].output)


_name_counters: dict[str, int] = {}


def _auto_name(prefix: str, name: str | None) -> str:
    if name is not None:
        return name
    n = _name_counters.get(prefix, 0)
    _name_counters[prefix] = n + 1
    return f"{prefix}.noname.{n}"


# ---------------------------------------------------------------------------
# Async collectives + handle plumbing (reference: torch/mpi_ops.py:95-900)
# ---------------------------------------------------------------------------
def allreduce_async(tensor, average: bool | None = None, name: str | None = None,
                    op=None, prescale_factor: float = 1.0,
                    postscale_factor: float = 1.0,
                    compression=None, spec=None) -> Handle:
    """``compression`` selects the wire codec: a name ("fp16", "bf16",
    "int8", "uint4"), a compress.CompressionCodec, or a framework
    Compression marker class; None honors HOROVOD_COMPRESSION.
    ``spec`` annotates the tensor's sharding layout (PartitionSpec,
    axis-entry iterable, or canonical token string): it joins the
    collective's cross-rank fingerprint identity and rides the wire as
    sp_spec (hvdshard; docs/analysis.md)."""
    kind, adasum = _op_kind(op, average)
    _, handle = core.enqueue_allreduce(
        _auto_name("allreduce", name), tensor, op=kind,
        prescale_factor=prescale_factor, postscale_factor=postscale_factor,
        adasum=adasum, codec=compression, spec=spec)
    handle.wrap_refs = [tensor]
    return handle


def grouped_allreduce_async(tensors: Sequence[Any],
                            average: bool | None = None,
                            name: str | None = None, op=None,
                            prescale_factor: float = 1.0,
                            postscale_factor: float = 1.0,
                            compression=None) -> Handle:
    kind, adasum = _op_kind(op, average)
    base = _auto_name("grouped_allreduce", name)
    names = [f"{base}.{i}" for i in range(len(tensors))]
    _, handle = core.enqueue_grouped_allreduce(
        names, list(tensors), op=kind, prescale_factor=prescale_factor,
        postscale_factor=postscale_factor, adasum=adasum,
        codec=compression)
    handle.wrap_refs = list(tensors)
    return handle


def allgather_async(tensor, name: str | None = None, spec=None) -> Handle:
    _, handle = core.enqueue_allgather(_auto_name("allgather", name), tensor,
                                       spec=spec)
    handle.wrap_refs = [tensor]
    return handle


def broadcast_async(tensor, root_rank: int, name: str | None = None,
                    spec=None) -> Handle:
    _, handle = core.enqueue_broadcast(_auto_name("broadcast", name), tensor,
                                       root_rank, spec=spec)
    handle.wrap_refs = [tensor]
    return handle


def alltoall_async(tensor, splits=None, name: str | None = None) -> Handle:
    _, handle = core.enqueue_alltoall(_auto_name("alltoall", name), tensor,
                                      splits)
    handle.wrap_refs = [tensor]
    return handle


def synchronize(handle: Handle):
    """Wait for an async op; return its output(s) in the caller's framework
    (reference: torch/mpi_ops.py:862-884)."""
    status = handle.wait()
    status.raise_if_error()
    refs = handle.wrap_refs or [None] * len(handle.entries)
    outs = [e.output if r is None else _wrap_like(r, e.output)
            for r, e in zip(refs, handle.entries)]
    return outs[0] if len(outs) == 1 else outs


def poll(handle: Handle) -> bool:
    """True if the async op has completed
    (reference: torch/mpi_ops.py:846)."""
    return handle.done()


# ---------------------------------------------------------------------------
# Synchronous collectives
# ---------------------------------------------------------------------------
def allreduce(tensor, average: bool | None = None, name: str | None = None,
              op=None, prescale_factor: float = 1.0,
              postscale_factor: float = 1.0, compression=None, spec=None):
    handle = allreduce_async(tensor, average, name, op, prescale_factor,
                             postscale_factor, compression, spec)
    return _result(handle, tensor)


def grouped_allreduce(tensors: Sequence[Any], average: bool | None = None,
                      name: str | None = None, op=None,
                      prescale_factor: float = 1.0,
                      postscale_factor: float = 1.0, compression=None):
    handle = grouped_allreduce_async(tensors, average, name, op,
                                     prescale_factor, postscale_factor,
                                     compression)
    status = handle.wait()
    status.raise_if_error()
    return [_wrap_like(t, e.output)
            for t, e in zip(tensors, handle.entries)]


def reducescatter_async(tensor, name: str | None = None, op=None,
                        prescale_factor: float = 1.0,
                        postscale_factor: float = 1.0) -> Handle:
    # op=None averages, matching upstream Horovod's reducescatter default
    # (and this package's allreduce _op_kind mapping).
    if op in (None, Average):
        op_name = "average"
    elif op is Sum:
        op_name = "sum"
    else:
        raise ValueError(f"Unknown reducescatter op: {op}")
    _, handle = core.enqueue_reducescatter(
        _auto_name("reducescatter", name), tensor, op=op_name,
        prescale_factor=prescale_factor, postscale_factor=postscale_factor)
    handle.wrap_refs = [tensor]
    return handle


def allgather(tensor, name: str | None = None, spec=None):
    return _result(allgather_async(tensor, name, spec=spec), tensor)


def reducescatter(tensor, name: str | None = None, op=None,
                  prescale_factor: float = 1.0,
                  postscale_factor: float = 1.0):
    """Reduce over all ranks and return this rank's dim-0 slice."""
    return _result(reducescatter_async(tensor, name, op, prescale_factor,
                                       postscale_factor), tensor)


def broadcast(tensor, root_rank: int, name: str | None = None):
    return _result(broadcast_async(tensor, root_rank, name), tensor)


def alltoall(tensor, splits=None, name: str | None = None):
    handle = alltoall_async(tensor, splits, name)
    status = handle.wait()
    status.raise_if_error()
    entry = handle.entries[0]
    out = _wrap_like(tensor, entry.output)
    if splits is None:
        return out
    recv_splits = np.asarray(entry.received_splits, dtype=np.int32)
    return out, _wrap_int_like(tensor, recv_splits)


def barrier() -> None:
    _, handle = core.enqueue_barrier()
    handle.wait().raise_if_error()


def join() -> int:
    """Block until every rank has joined; meanwhile this rank participates
    in outstanding collectives with zero stand-ins
    (reference: torch/mpi_ops.py:885-900)."""
    _, handle = core.enqueue_join()
    handle.wait().raise_if_error()
    return int(handle.entries[0].output)


# ---------------------------------------------------------------------------
# Convenience object/parameter sync (reference: torch/functions.py)
# ---------------------------------------------------------------------------
def broadcast_object(obj: Any, root_rank: int = 0,
                     name: str | None = None) -> Any:
    """Broadcast an arbitrary picklable object by serializing to bytes
    (reference: torch/functions.py broadcast_object)."""
    import pickle
    name = _auto_name("broadcast_object", name)
    if rank() == root_rank:
        payload = np.frombuffer(pickle.dumps(obj), dtype=np.uint8).copy()
        sz = np.array([payload.size], dtype=np.int64)
    else:
        payload = None
        sz = np.array([0], dtype=np.int64)
    sz = broadcast(sz, root_rank, name=f"{name}.size")
    if payload is None:
        payload = np.zeros(int(sz[0]), dtype=np.uint8)
    payload = broadcast(payload, root_rank, name=f"{name}.data")
    return pickle.loads(payload.tobytes()) if rank() != root_rank else obj


def start_profiler(logdir: str) -> None:
    """Start a device trace (reference analogue: the Horovod Timeline /
    NVTX ranges, SURVEY §5.1 — on TPU the native tool is the jax profiler;
    view with tensorboard or xprof)."""
    import jax
    jax.profiler.start_trace(logdir)


def stop_profiler() -> None:
    import jax
    jax.profiler.stop_trace()


def profiler_annotation(name: str):
    """Context manager labelling a region in device traces (the NVTX-range
    analogue, reference: common/nvtx_op_range.h)."""
    import jax
    return jax.profiler.TraceAnnotation(name)


def allgather_object(obj: Any, name: str | None = None) -> list:
    """Gather one arbitrary picklable object per rank; every rank receives
    the full list ordered by rank (reference: torch/mpi_ops.py
    allgather_object)."""
    import pickle
    name = _auto_name("allgather_object", name)
    payload = np.frombuffer(pickle.dumps(obj), dtype=np.uint8).copy()
    sizes = allgather(np.array([payload.size], dtype=np.int64),
                      name=f"{name}.size")
    data = allgather(payload, name=f"{name}.data")
    data = np.asarray(data)
    objs, offset = [], 0
    for sz in np.asarray(sizes).reshape(-1):
        objs.append(pickle.loads(data[offset:offset + int(sz)].tobytes()))
        offset += int(sz)
    return objs


# Build-variant introspection (reference: horovod/common/util.py:137-186)
def xla_built() -> bool:
    try:
        import jax  # noqa: F401
        return True
    except ImportError:
        return False


def tcp_built() -> bool:
    return True


def gloo_built() -> bool:   # compat alias: our TCP plane plays gloo's role
    return True


def nccl_built() -> bool:
    return False


def mpi_built() -> bool:
    return False


def mpi_enabled() -> bool:
    return False


def mpi_threads_supported() -> bool:
    return False


# --- Resilience surface (resilience/; docs/resilience.md) -------------------
def run_with_recovery(fn, *, policy=None, max_retries=None,
                      base_backoff=None):
    """Run an idempotent eager collective under HOROVOD_ON_FAILURE
    (raise | retry-with-rebuilt-channels | shrink-via-elastic)."""
    from .resilience import run_with_recovery as _rwr
    return _rwr(fn, policy=policy, max_retries=max_retries,
                base_backoff=base_backoff)
