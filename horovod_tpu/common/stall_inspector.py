"""Stalled-collective detector.

Reference: horovod/common/stall_inspector.{cc,h}:30-96.  When some ranks have
submitted a tensor and others have not for `HOROVOD_STALL_CHECK_TIME_SECONDS`
(default 60s), the coordinator logs which ranks are missing; past
`HOROVOD_STALL_SHUTDOWN_TIME_SECONDS` it aborts the job.  This is the
slow-failure detector that turns silent hangs into actionable errors.
"""
from __future__ import annotations

import time

from . import config
from .logging import logger


class StallInspector:
    def __init__(self) -> None:
        self.warning_time = float(config.STALL_CHECK_TIME_SECONDS.get())
        self.shutdown_time = float(config.STALL_SHUTDOWN_TIME_SECONDS.get())
        self.enabled = not config.STALL_CHECK_DISABLE.get()
        # Coordinator side: tensor name -> (first-seen time, ranks that
        # submitted it so far).
        self._ready: dict[str, tuple[float, set[int]]] = {}
        # Worker side: tensor name -> time submitted locally (for cached
        # tensors that never reach the coordinator).
        self._uncached: dict[str, float] = {}
        self._last_check = time.monotonic()

    # --- coordinator bookkeeping -------------------------------------------
    def record_uncached_tensor(self, name: str, rank: int) -> None:
        now = time.monotonic()
        first, ranks = self._ready.get(name, (now, set()))
        ranks.add(rank)
        self._ready[name] = (first, ranks)

    def remove_uncached_tensor(self, name: str) -> None:
        self._ready.pop(name, None)

    # --- worker-side cached-tensor bookkeeping -----------------------------
    def record_cached_tensor(self, name: str) -> None:
        self._uncached.setdefault(name, time.monotonic())

    def remove_cached_tensor(self, name: str) -> None:
        self._uncached.pop(name, None)

    def invalidate_stalled_cached_tensors(self, cache_coordinator,
                                          response_cache) -> None:
        """Mark cache bits invalid for tensors stalled on this rank so that
        the coordinated OR forces a full (re-)negotiation and the coordinator
        regains visibility (reference: controller.cc:125-135)."""
        if not self.enabled:
            return
        now = time.monotonic()
        for name, t0 in self._uncached.items():
            if now - t0 > self.warning_time:
                try:
                    pos = response_cache.peek_cache_position(name)
                except KeyError:
                    continue
                cache_coordinator.record_invalid(pos)
                cache_coordinator.uncached_in_queue = True

    def should_check(self) -> bool:
        if not self.enabled:
            return False
        return time.monotonic() - self._last_check > self.warning_time

    def check_for_stalled_tensors(self, global_size: int) -> bool:
        """Coordinator check. Returns True if the job should shut down."""
        self._last_check = time.monotonic()
        now = self._last_check
        should_shutdown = False
        for name, (first, ranks) in self._ready.items():
            lag = now - first
            if lag <= self.warning_time:
                continue
            missing = sorted(set(range(global_size)) - ranks)
            logger.warning(
                "One or more tensors were submitted to be reduced, gathered "
                "or broadcasted by subset of ranks and are waiting for "
                "remainder of ranks for more than %ds. Stalled op: %s "
                "[missing ranks: %s]. If the missing ranks are alive, they "
                "are likely submitting different collectives: set "
                "HOROVOD_FINGERPRINT=cycle to get a structured error "
                "naming the first divergent op, and run hvdlint "
                "(python -m horovod_tpu.analysis.lint) over the training "
                "script (docs/analysis.md).", int(self.warning_time), name,
                ", ".join(map(str, missing)))
            if self.shutdown_time > 0 and lag > self.shutdown_time:
                should_shutdown = True
        return should_shutdown
