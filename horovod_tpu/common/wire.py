"""Compact binary codec for control-plane messages.

The reference serializes Request/Response with flatbuffers
(reference: horovod/common/wire/message.fbs:18-119, message.cc).  The rebuild
uses a tiny self-contained varint+struct codec: the control plane exchanges
kilobyte-scale metadata messages over DCN/TCP, so a dependency-free format
that both the Python controller and a future C++ core can read is worth more
than flatbuffers' zero-copy.

Layout primitives: unsigned varints (LEB128), length-prefixed UTF-8 strings,
little-endian fixed-width scalars.
"""
from __future__ import annotations

import struct

# ---------------------------------------------------------------------------
# Versioned wire handshake (HELLO{proto_version, feature_bits})
# ---------------------------------------------------------------------------
# Exchanged at every channel/mesh establishment (PeerMesh bootstrap, the
# elastic RPC connect): both sides advertise the highest schema they
# speak and every encode/decode thereafter is gated on the negotiated
# min proto / AND of feature bits.  Every OPTIONAL control-plane field
# group lives behind a feature bit (the hvdsan HVD505 optional-field
# gate asserts this at lint time), so a world can roll from framework
# version N to N+1 rank-by-rank: mixed-version peers simply negotiate
# the old schema until the last rank upgrades.
PROTO_VERSION = 3

FEATURE_FINGERPRINT = 1 << 0   # RequestList fp_* (collective digests)
FEATURE_TELEMETRY = 1 << 1     # RequestList tm_* (straggler snapshot)
FEATURE_TRACE = 1 << 2         # Response trace_* (distributed tracing)
FEATURE_SHARDING = 1 << 3      # Request/Response sp_* (partition specs)

FEATURES_ALL = (FEATURE_FINGERPRINT | FEATURE_TELEMETRY | FEATURE_TRACE
                | FEATURE_SHARDING)

# Feature bits each protocol version may carry: proto 1 is the base
# schema with every optional group absent; proto 2 froze the fp_/tm_/
# trace_ groups (spelled as the literal three-bit mask — FEATURES_ALL
# keeps growing, a frozen proto's field set must not); proto 3 adds the
# sharding-spec group and is current.
PROTO_FEATURE_SETS = {
    1: 0,
    2: FEATURE_FINGERPRINT | FEATURE_TELEMETRY | FEATURE_TRACE,
    3: FEATURES_ALL,
}

# Optional-field prefix -> gating feature bit.  The single source of
# truth both message.py's conditional encode/decode and the HVD505
# optional-field check key on (tests assert the analyzer's mirror of
# the prefixes matches this table).
OPTIONAL_FIELD_FEATURES = {
    "fp_": FEATURE_FINGERPRINT,
    "tm_": FEATURE_TELEMETRY,
    "trace_": FEATURE_TRACE,
    "sp_": FEATURE_SHARDING,
}

HELLO_MAGIC = b"HVDH"
_HELLO = struct.Struct(">4sHHI")   # magic, proto, reserved, features
HELLO_LEN = _HELLO.size


def proto_features(proto: int) -> int:
    """Feature bits a given protocol version may advertise."""
    return PROTO_FEATURE_SETS.get(proto, FEATURES_ALL)


def pack_hello(proto: int, features: int) -> bytes:
    return _HELLO.pack(HELLO_MAGIC, proto, 0, features)


def unpack_hello(raw) -> tuple[int, int]:
    magic, proto, _reserved, features = _HELLO.unpack(bytes(raw))
    if magic != HELLO_MAGIC:
        raise ValueError(
            "peer opened the channel without a HELLO frame (bad magic); "
            "pre-handshake builds cannot join a versioned world")
    return proto, features


def negotiate(proto_a: int, features_a: int, proto_b: int,
              features_b: int) -> tuple[int, int]:
    """Min common schema of two HELLOs: lowest proto, intersected
    feature bits, masked to what the chosen proto may carry."""
    proto = min(proto_a, proto_b)
    return proto, features_a & features_b & proto_features(proto)


class Encoder:
    __slots__ = ("_parts",)

    def __init__(self) -> None:
        self._parts: list[bytes] = []

    def uvarint(self, value: int) -> "Encoder":
        if value < 0:
            raise ValueError("uvarint requires a non-negative value")
        out = bytearray()
        while True:
            b = value & 0x7F
            value >>= 7
            if value:
                out.append(b | 0x80)
            else:
                out.append(b)
                break
        self._parts.append(bytes(out))
        return self

    def svarint(self, value: int) -> "Encoder":
        # zigzag encoding
        return self.uvarint((value << 1) ^ (value >> 63))

    def f64(self, value: float) -> "Encoder":
        self._parts.append(struct.pack("<d", float(value)))
        return self

    def string(self, value: str) -> "Encoder":
        raw = value.encode("utf-8")
        self.uvarint(len(raw))
        self._parts.append(raw)
        return self

    def blob(self, value: bytes) -> "Encoder":
        self.uvarint(len(value))
        self._parts.append(bytes(value))
        return self

    def bool_(self, value: bool) -> "Encoder":
        self._parts.append(b"\x01" if value else b"\x00")
        return self

    def uvarint_list(self, values) -> "Encoder":
        self.uvarint(len(values))
        for v in values:
            self.uvarint(v)
        return self

    def svarint_list(self, values) -> "Encoder":
        self.uvarint(len(values))
        for v in values:
            self.svarint(v)
        return self

    def string_list(self, values) -> "Encoder":
        self.uvarint(len(values))
        for v in values:
            self.string(v)
        return self

    def getvalue(self) -> bytes:
        return b"".join(self._parts)


class Decoder:
    __slots__ = ("_buf", "_pos")

    def __init__(self, buf: bytes) -> None:
        self._buf = buf
        self._pos = 0

    def uvarint(self) -> int:
        result = 0
        shift = 0
        buf = self._buf
        pos = self._pos
        while True:
            if pos >= len(buf):
                raise ValueError("truncated uvarint")
            b = buf[pos]
            pos += 1
            result |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
        self._pos = pos
        return result

    def svarint(self) -> int:
        z = self.uvarint()
        return (z >> 1) ^ -(z & 1)

    def f64(self) -> float:
        v = struct.unpack_from("<d", self._buf, self._pos)[0]
        self._pos += 8
        return v

    def string(self) -> str:
        n = self.uvarint()
        raw = self._buf[self._pos:self._pos + n]
        self._pos += n
        return raw.decode("utf-8")

    def blob(self) -> bytes:
        n = self.uvarint()
        raw = self._buf[self._pos:self._pos + n]
        self._pos += n
        return raw

    def bool_(self) -> bool:
        v = self._buf[self._pos] != 0
        self._pos += 1
        return v

    def uvarint_list(self) -> list[int]:
        return [self.uvarint() for _ in range(self.uvarint())]

    def svarint_list(self) -> list[int]:
        return [self.svarint() for _ in range(self.uvarint())]

    def string_list(self) -> list[str]:
        return [self.string() for _ in range(self.uvarint())]

    def eof(self) -> bool:
        return self._pos >= len(self._buf)
