"""Chrome-trace timeline of collective negotiation and execution.

Reference: horovod/common/timeline.{cc,h}:37-80 — per-tensor phase machine
NEGOTIATING → <OP> → activities, written as Chrome trace events ("cat ph ts
pid name args") by an async writer thread fed through a queue so the hot
path never blocks on file IO.  Controlled by HOROVOD_TIMELINE
('DYNAMIC' starts stopped; start_timeline/stop_timeline flip it at runtime —
reference: operations.cc:740-769).
"""
from __future__ import annotations

import json
import queue
import threading
import time


def rank_path(path: str, rank: int) -> str:
    """Per-rank timeline path: rank 0 keeps the exact configured path
    (existing tooling reads it); other ranks get the metrics-dump
    convention ('{rank}' substitutes, else '.r<rank>' before the
    extension) so a launcher-wide identical HOROVOD_TIMELINE yields one
    stitchable file per rank instead of N ranks clobbering one file
    (telemetry/trace.py merges them)."""
    if "{rank}" in path:
        return path.format(rank=rank)
    if rank == 0:
        return path
    root, dot, ext = path.rpartition(".")
    if dot:
        return f"{root}.r{rank}.{ext}"
    return f"{path}.r{rank}"


class Timeline:
    def __init__(self, path: str = "", mark_cycles: bool = False,
                 rank: int = 0) -> None:
        self._path = path
        self._mark_cycles = mark_cycles
        self.rank = rank
        # Coordinator-clock sync estimate (tcp_transport round-trip
        # probes at init): recorded as trace METADATA — timestamps stay
        # in this rank's own monotonic base; the merge tool applies the
        # offset, never the recorder (a destructive shift would make the
        # raw file lie about what this rank observed).
        self._clock_offset_us: float | None = None
        self._clock_rtt_us: float = 0.0
        self._queue: queue.Queue = queue.Queue()
        self._active = False
        self._writer: threading.Thread | None = None
        self._file = None
        self._start = time.monotonic()
        # Open enqueue->callback async spans: tensor name -> flow id of
        # the latest 'b' event (ph "b"/"e", cat "op_queue" — async spans
        # live outside the per-lane B/E stacks, so a callback firing on
        # a stream worker cannot unbalance a lane).
        self._queue_ids: dict[str, int] = {}
        self._next_queue_id = 0
        self._tensor_tids: dict[str, int] = {}
        # Per-tensor negotiation state (the reference's per-tensor phase
        # machine, timeline.cc): a request resubmitted across cycles —
        # e.g. a local cache hit whose bit didn't survive the global AND
        # and was pushed back to the queue — must not open a second
        # NEGOTIATE span, and a joined rank's stand-in entry (which never
        # negotiated here) must not emit an unmatched end.
        self._negotiating: set[str] = set()
        # Per-tensor count of OPEN activity spans: an activity_end whose
        # matching start was suppressed (timeline off at the time, e.g. a
        # dynamic start_timeline() mid-collective) must not emit an
        # unmatched 'E' — the guard lives here so every call site (core
        # and all backends) inherits it.
        self._open_acts: dict[str, int] = {}
        self._lock = threading.Lock()
        if path and path != "DYNAMIC":
            self.start(path)
        elif path == "DYNAMIC":
            self._path = ""

    # -- lifecycle ------------------------------------------------------
    def start(self, path: str) -> None:
        with self._lock:
            if self._active:
                return
            # Fresh file: reset per-tensor state so lanes re-emit their
            # thread_name metadata and no phase state leaks from a
            # previous recording window.
            self._negotiating.clear()
            self._open_acts.clear()
            self._tensor_tids.clear()
            self._queue_ids.clear()
            # A DYNAMIC stop/start window begins at ts~0, not minutes
            # into the process: ts is defined relative to THIS recording
            # window's start (the clock-sync metadata below carries the
            # absolute monotonic base for cross-rank alignment).
            self._start = time.monotonic()
            self._path = rank_path(path, self.rank)
            self._file = open(self._path, "w")
            self._file.write("[\n")
            self._active = True
            self._writer = threading.Thread(target=self._write_loop,
                                            daemon=True,
                                            name="hvd-timeline")
            self._writer.start()
            self._emit_clock_metadata()

    def set_clock_sync(self, offset_us: float, rtt_us: float) -> None:
        """Record this rank's estimated clock offset against the
        coordinator (coordinator_monotonic - local_monotonic, µs) plus
        the probe round-trip as trace metadata."""
        self._clock_offset_us = float(offset_us)
        self._clock_rtt_us = float(rtt_us)
        with self._lock:
            if self._active:
                self._emit_clock_metadata()

    def _emit_clock_metadata(self) -> None:
        """Per-file stitching metadata (caller holds the lock): the rank
        (process_name renders it in viewers; the merge tool trusts the
        args), this window's monotonic base, and the clock-offset
        estimate when probed."""
        self._emit({"name": "process_name", "ph": "M", "pid": 0,
                    "args": {"name": f"rank {self.rank}"}})
        args: dict = {"rank": self.rank,
                      "start_us": (self._start * 1e6)}
        if self._clock_offset_us is not None:
            args["clock_offset_us"] = self._clock_offset_us
            args["clock_rtt_us"] = self._clock_rtt_us
        self._emit({"name": "horovod_clock_sync", "ph": "M", "pid": 0,
                    "args": args})

    def stop(self) -> None:
        with self._lock:
            if not self._active:
                return
            # The end marker goes through the queue so the writer thread
            # handles comma placement uniformly.
            self._queue.put({"name": "end", "ph": "i", "ts": self._ts(),
                             "pid": 0, "s": "g"})
            self._active = False
            self._negotiating.clear()
            self._open_acts.clear()
            self._queue_ids.clear()
            self._queue.put(None)
            writer, self._writer = self._writer, None
        if writer is not None:
            # Unbounded join AFTER poisoning the queue: the writer exits
            # as soon as it drains to the sentinel, and the file below is
            # only closed once it has — a bounded join could return with
            # the writer mid-drain and close the file under its write
            # (the pre-fix race; the writer's own closed-file guard in
            # _flush_pending is defense in depth, not the contract).
            writer.join()
        if self._file is not None:
            self._file.write("\n]\n")
            self._file.close()
            self._file = None

    @property
    def enabled(self) -> bool:
        return self._active

    # -- event emission -------------------------------------------------
    def _ts(self) -> int:
        return int((time.monotonic() - self._start) * 1e6)

    def _tid(self, tensor_name: str) -> int:
        tid = self._tensor_tids.get(tensor_name)
        if tid is None:
            tid = len(self._tensor_tids)
            self._tensor_tids[tensor_name] = tid
            self._emit({"name": "thread_name", "ph": "M", "pid": 0,
                        "tid": tid, "args": {"name": tensor_name}})
        return tid

    def _emit(self, event: dict) -> None:
        if self._active:
            self._queue.put(event)

    def negotiate_start(self, tensor_name: str, request_type) -> None:
        if not self._active or tensor_name in self._negotiating:
            return
        self._negotiating.add(tensor_name)
        name = getattr(request_type, "name", str(request_type))
        self._emit({"name": f"NEGOTIATE_{name}", "ph": "B",
                    "ts": self._ts(), "pid": 0,
                    "tid": self._tid(tensor_name)})

    def negotiate_end(self, tensor_name: str,
                      trace: str | None = None) -> None:
        if not self._active or tensor_name not in self._negotiating:
            return
        self._negotiating.discard(tensor_name)
        event = {"name": "", "ph": "E", "ts": self._ts(), "pid": 0,
                 "tid": self._tid(tensor_name)}
        if trace is not None:
            # The id is only known at pop (the coordinator assigned it
            # during THIS negotiation); Chrome merges E args into the
            # span, so the NEGOTIATE span still carries the trace.
            event["args"] = {"trace": trace}
        self._emit(event)

    def activity_start(self, tensor_name: str, activity: str,
                       stream: int = 0, trace: str | None = None) -> None:
        """Open an activity span; a nonzero multi-stream dispatch lane is
        recorded in the event args so traces show which channel set a
        fused response rode, and the collective's cross-rank trace id
        ("cycle.seq", telemetry/trace.py) rides the args so the merge
        tool can flow-link the same collective across ranks (stream-0
        untraced events stay byte-identical to the legacy format)."""
        if not self._active:
            return
        self._open_acts[tensor_name] = \
            self._open_acts.get(tensor_name, 0) + 1
        event = {"name": activity, "ph": "B", "ts": self._ts(),
                 "pid": 0, "tid": self._tid(tensor_name)}
        args = {}
        if stream:
            args["stream"] = stream
        if trace is not None:
            args["trace"] = trace
        if args:
            event["args"] = args
        self._emit(event)

    def activity_end(self, tensor_name: str) -> None:
        if not self._active:
            return
        count = self._open_acts.get(tensor_name, 0)
        if count <= 0:
            return   # matching start was suppressed: drop the end too
        self._open_acts[tensor_name] = count - 1
        self._emit({"name": "", "ph": "E", "ts": self._ts(), "pid": 0,
                    "tid": self._tid(tensor_name)})

    def activity_start_all(self, entries, activity: str,
                           stream: int = 0) -> None:
        """Open one ``activity`` span per entry of a (possibly fused)
        response — the reference's ActivityStartAll (timeline.cc), called
        from inside ops so pack/collective/unpack phases are separable in
        the trace.  Entries dispatched through core carry the response's
        trace id (``entry.trace``), so every backend sub-activity is
        cross-rank linkable without touching the planes."""
        if not self._active:
            return
        for e in entries:
            self.activity_start(e.tensor_name, activity, stream=stream,
                                trace=getattr(e, "trace", None))

    # -- enqueue -> callback async spans --------------------------------
    def queue_start(self, tensor_name: str) -> None:
        """Open the enqueue->callback span for one submitted tensor:
        Chrome async events ("ph":"b"/"e", cat "op_queue") on the
        tensor's lane — queue wait is the phase the per-lane B/E spans
        cannot show (the callback fires on a stream worker, outside any
        lane's stack discipline)."""
        if not self._active:
            return
        with self._lock:
            qid = self._next_queue_id
            self._next_queue_id += 1
            self._queue_ids[tensor_name] = qid
        self._emit({"name": "QUEUE", "cat": "op_queue", "ph": "b",
                    "id": qid, "ts": self._ts(), "pid": 0,
                    "tid": self._tid(tensor_name)})

    def queue_end(self, tensor_name: str,
                  trace: str | None = None) -> None:
        """Close the enqueue->callback span (entry callback).  The trace
        id — unknown at enqueue, assigned during negotiation — rides the
        end event's args."""
        if not self._active:
            return
        with self._lock:
            qid = self._queue_ids.pop(tensor_name, None)
        if qid is None:
            return   # opened while the timeline was off: drop the end
        event = {"name": "QUEUE", "cat": "op_queue", "ph": "e",
                 "id": qid, "ts": self._ts(), "pid": 0,
                 "tid": self._tid(tensor_name)}
        if trace is not None:
            event["args"] = {"trace": trace}
        self._emit(event)

    def activity_end_all(self, entries) -> None:
        if not self._active:
            return
        for e in entries:
            self.activity_end(e.tensor_name)

    def mark_cycle(self) -> None:
        if self._active and self._mark_cycles:
            self._emit({"name": "CYCLE", "ph": "i", "ts": self._ts(),
                        "pid": 0, "s": "g"})

    def counter(self, name: str, values: dict) -> None:
        """Chrome-trace counter track ("ph":"C"): queue depth, wire
        bytes, ... render as stacked area series alongside the spans
        (telemetry layer; docs/observability.md)."""
        if not self._active:
            return
        self._emit({"name": name, "ph": "C", "ts": self._ts(), "pid": 0,
                    "args": dict(values)})

    # -- writer thread --------------------------------------------------
    # Flush policy: the pre-batching writer flushed after EVERY event, so
    # heavy tracing perturbed the data plane it was measuring.  Events now
    # accumulate and hit the file when a batch fills, on CYCLE marks
    # (a consistent cut point for live tailing), or when the queue goes
    # momentarily idle — so a reader after stop() still sees everything
    # (stop() joins the drained writer before closing the file).
    _WRITE_BATCH = 64

    def _write_loop(self) -> None:
        first = True
        pending: list[str] = []
        while True:
            event = self._queue.get()
            if event is None:
                break
            line = json.dumps(event)
            pending.append(line if first else ",\n" + line)
            first = False
            if (len(pending) >= self._WRITE_BATCH
                    or event.get("name") == "CYCLE"
                    or self._queue.empty()):
                self._flush_pending(pending)
        self._flush_pending(pending)

    def _flush_pending(self, pending: list[str]) -> None:
        if not pending:
            return
        f = self._file
        if f is None:
            return
        try:
            f.write("".join(pending))
            f.flush()
        except ValueError:
            # File closed under us: only reachable if stop()'s join
            # contract is violated; drop rather than crash the writer.
            pass
        pending.clear()
