"""Leveled logger controlled by HOROVOD_LOG_LEVEL / HOROVOD_LOG_HIDE_TIME.

Reference: horovod/common/logging.{cc,h} — a minimal glog-style logger.  We
delegate to the stdlib logging module but honour the same env knobs and tag
records with the global rank once known.
"""
from __future__ import annotations

import logging as _logging
import sys

from . import config

TRACE = 5
_LEVELS = {
    "trace": TRACE,
    "debug": _logging.DEBUG,
    "info": _logging.INFO,
    "warning": _logging.WARNING,
    "error": _logging.ERROR,
    "fatal": _logging.CRITICAL,
}

_logging.addLevelName(TRACE, "TRACE")

logger = _logging.getLogger("horovod_tpu")
_configured = False


def configure(rank: int | None = None) -> None:
    global _configured
    level = _LEVELS.get(str(config.LOG_LEVEL.get()).lower(), _logging.WARNING)
    logger.setLevel(level)
    if not _configured:
        handler = _logging.StreamHandler(sys.stderr)
        fmt = "[%(levelname)s] %(message)s" if config.LOG_HIDE_TIME.get() \
            else "%(asctime)s [%(levelname)s] %(message)s"
        handler.setFormatter(_logging.Formatter(fmt))
        logger.addHandler(handler)
        logger.propagate = False
        _configured = True
    if rank is not None:
        for h in logger.handlers:
            fmt = f"[rank {rank}] %(levelname)s: %(message)s" \
                if config.LOG_HIDE_TIME.get() \
                else f"%(asctime)s [rank {rank}] %(levelname)s: %(message)s"
            h.setFormatter(_logging.Formatter(fmt))


configure()
