"""Canonical dtype table shared by the wire format, controller and backends.

TPU-native analogue of the reference DataType enum
(reference: horovod/common/wire/message.fbs:18-33, message.cc).  bfloat16 is
first-class here (it is the TPU matmul dtype); the reference's fp16 paths map
onto both float16 and bfloat16.
"""
from __future__ import annotations

import enum

import numpy as np


class DataType(enum.IntEnum):
    UINT8 = 0
    INT8 = 1
    UINT16 = 2
    INT16 = 3
    INT32 = 4
    INT64 = 5
    FLOAT16 = 6
    FLOAT32 = 7
    FLOAT64 = 8
    BOOL = 9
    BFLOAT16 = 10


_NP_BY_DTYPE: dict[DataType, np.dtype] = {}
_DTYPE_BY_NAME: dict[str, DataType] = {}


def _register(dt: DataType, np_dtype) -> None:
    np_dtype = np.dtype(np_dtype)
    _NP_BY_DTYPE[dt] = np_dtype
    _DTYPE_BY_NAME[np_dtype.name] = dt


_register(DataType.UINT8, np.uint8)
_register(DataType.INT8, np.int8)
_register(DataType.UINT16, np.uint16)
_register(DataType.INT16, np.int16)
_register(DataType.INT32, np.int32)
_register(DataType.INT64, np.int64)
_register(DataType.FLOAT16, np.float16)
_register(DataType.FLOAT32, np.float32)
_register(DataType.FLOAT64, np.float64)
_register(DataType.BOOL, np.bool_)

try:  # ml_dtypes ships with jax; bfloat16 is the TPU-native reduced dtype
    import ml_dtypes

    _register(DataType.BFLOAT16, ml_dtypes.bfloat16)
except ImportError:  # pragma: no cover - ml_dtypes is bundled with jax
    pass


_ELEMENT_SIZE = {
    DataType.UINT8: 1,
    DataType.INT8: 1,
    DataType.UINT16: 2,
    DataType.INT16: 2,
    DataType.INT32: 4,
    DataType.INT64: 8,
    DataType.FLOAT16: 2,
    DataType.FLOAT32: 4,
    DataType.FLOAT64: 8,
    DataType.BOOL: 1,
    DataType.BFLOAT16: 2,
}

_FLOATING = {
    DataType.FLOAT16,
    DataType.FLOAT32,
    DataType.FLOAT64,
    DataType.BFLOAT16,
}


def element_size(dt: DataType) -> int:
    return _ELEMENT_SIZE[dt]


def is_floating(dt: DataType) -> bool:
    return dt in _FLOATING


def to_numpy(dt: DataType) -> np.dtype:
    return _NP_BY_DTYPE[dt]


def from_any(dtype_like) -> DataType:
    """Map a numpy/jax/torch dtype (or its name) to the canonical DataType."""
    name = getattr(dtype_like, "name", None)
    if name is None:
        name = str(dtype_like)
        # torch dtypes stringify as "torch.float32"
        if name.startswith("torch."):
            name = name[len("torch."):]
        if name == "bool":
            name = "bool_"
    if name == "bool_":
        name = "bool"
    dt = _DTYPE_BY_NAME.get(name)
    if dt is None:
        raise ValueError(f"Unsupported dtype: {dtype_like!r}")
    return dt
