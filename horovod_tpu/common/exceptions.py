"""Exception hierarchy (reference: horovod/common/exceptions.py:19-46).

`HorovodInternalError` signals a failed collective — in elastic mode the
training loop catches it, restores the last committed state and
re-rendezvouses.  `HostsUpdatedInterrupt` is raised proactively when host
membership changed so workers can re-form the mesh without losing state.
"""


class HorovodTpuError(Exception):
    """Base class for all framework errors."""


class HorovodInternalError(HorovodTpuError):
    """Internal error raised when a collective routine fails.

    In elastic mode this triggers state restore + re-rendezvous
    (reference: horovod/common/elastic.py:151-175).
    """


class HorovodVersionMismatchError(HorovodInternalError):
    pass


class HostsUpdatedInterrupt(HorovodTpuError):
    """Host membership changed; re-rendezvous without restoring state.

    ``skip_sync`` mirrors the reference's distinction between an immediate
    update (state already consistent) and one discovered after a failure.
    """

    def __init__(self, skip_sync: bool = False):
        super().__init__()
        self.skip_sync = skip_sync


class RanksFailedError(HorovodInternalError, ConnectionError):
    """One or more ranks died, became unreachable, or missed a collective
    deadline; the hang was converted into this structured, attributed
    error (resilience/; docs/resilience.md).

    Subclasses :class:`HorovodInternalError` so the elastic retry loop's
    restore/re-rendezvous path fires unchanged, and :class:`ConnectionError`
    so pre-resilience transport-failure handlers keep working.

    ``failed_ranks`` is the set of ranks believed dead/unreachable, ``op``
    names the collective that observed the failure, ``phase`` the blocking
    wait that expired (``recv``/``send``/``gather``/``shm_barrier``/...).
    """

    _WIRE_RE = None   # compiled lazily; see from_wire

    def __init__(self, failed_ranks, op: str = "", phase: str = "",
                 message: str = ""):
        self.failed_ranks = frozenset(int(r) for r in failed_ranks)
        self.op = op
        self.phase = phase
        self.detail = message
        super().__init__(self.to_wire())

    def to_wire(self) -> str:
        """Stable one-line form that survives Status.reason and the
        Response.error_message wire field; parse back with from_wire."""
        ranks = ",".join(str(r) for r in sorted(self.failed_ranks))
        head = f"[ranks-failed ranks={ranks} op={self.op} " \
               f"phase={self.phase}]"
        tail = self.detail or (
            f"rank(s) {{{ranks}}} failed or became unreachable during "
            f"'{self.op or 'collective'}' ({self.phase or 'wait'}); the "
            f"hang was converted into this error by the resilience "
            f"layer (HOROVOD_FAULT_TIMEOUT).")
        return f"{head} {tail}"

    @staticmethod
    def matches(message: str) -> bool:
        return bool(message) and message.startswith("[ranks-failed ")

    @classmethod
    def from_wire(cls, message: str) -> "RanksFailedError":
        import re
        if cls._WIRE_RE is None:
            cls._WIRE_RE = re.compile(
                r"^\[ranks-failed ranks=([\d,]*) op=([^ \]]*) "
                r"phase=([^ \]]*)\] ?(.*)$", re.S)
        m = cls._WIRE_RE.match(message or "")
        if not m:
            return cls(frozenset(), message=message)
        ranks = [int(r) for r in m.group(1).split(",") if r]
        return cls(ranks, op=m.group(2), phase=m.group(3),
                   message=m.group(4))


class NotSupportedError(HorovodTpuError):
    """Requested operation is not supported on this backend/topology."""


class TensorShapeMismatchError(HorovodTpuError):
    """Cross-rank tensor shape mismatch detected by the controller."""


class TensorDtypeMismatchError(HorovodTpuError):
    """Cross-rank tensor dtype mismatch detected by the controller."""
