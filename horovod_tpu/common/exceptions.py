"""Exception hierarchy (reference: horovod/common/exceptions.py:19-46).

`HorovodInternalError` signals a failed collective — in elastic mode the
training loop catches it, restores the last committed state and
re-rendezvouses.  `HostsUpdatedInterrupt` is raised proactively when host
membership changed so workers can re-form the mesh without losing state.
"""


class HorovodTpuError(Exception):
    """Base class for all framework errors."""


class HorovodInternalError(HorovodTpuError):
    """Internal error raised when a collective routine fails.

    In elastic mode this triggers state restore + re-rendezvous
    (reference: horovod/common/elastic.py:151-175).
    """


class HorovodVersionMismatchError(HorovodInternalError):
    pass


class HostsUpdatedInterrupt(HorovodTpuError):
    """Host membership changed; re-rendezvous without restoring state.

    ``skip_sync`` mirrors the reference's distinction between an immediate
    update (state already consistent) and one discovered after a failure.
    """

    def __init__(self, skip_sync: bool = False):
        super().__init__()
        self.skip_sync = skip_sync


class NotSupportedError(HorovodTpuError):
    """Requested operation is not supported on this backend/topology."""


class TensorShapeMismatchError(HorovodTpuError):
    """Cross-rank tensor shape mismatch detected by the controller."""


class TensorDtypeMismatchError(HorovodTpuError):
    """Cross-rank tensor dtype mismatch detected by the controller."""
