"""Control-plane message types: Request / RequestList / Response / ResponseList.

TPU-native rebuild of the reference message layer
(reference: horovod/common/message.h:50-251, message.cc, wire/message.fbs).
Semantics preserved:

- a `Request` announces "rank R's tensor named N with dtype/shape S is ready
  for collective op T";
- workers batch them into a `RequestList` gathered by the coordinator;
- the coordinator validates cross-rank consistency and answers with fused
  `Response`s (one response may carry many tensor names = one fused buffer);
- every rank executes the identical `ResponseList` in identical order — the
  deadlock-freedom invariant (reference: SURVEY §5.8).
"""
from __future__ import annotations

import enum
from dataclasses import dataclass, field

from .dtypes import DataType
from .wire import (FEATURE_FINGERPRINT, FEATURE_SHARDING, FEATURE_TELEMETRY,
                   FEATURE_TRACE, FEATURES_ALL, Decoder, Encoder)


class RequestType(enum.IntEnum):
    ALLREDUCE = 0
    ALLGATHER = 1
    BROADCAST = 2
    JOIN = 3
    ADASUM = 4
    ALLTOALL = 5
    BARRIER = 6
    REDUCESCATTER = 7


class ResponseType(enum.IntEnum):
    ALLREDUCE = 0
    ALLGATHER = 1
    BROADCAST = 2
    JOIN = 3
    ADASUM = 4
    ALLTOALL = 5
    BARRIER = 6
    REDUCESCATTER = 7
    ERROR = 8


@dataclass
class Request:
    request_rank: int = 0
    request_type: RequestType = RequestType.ALLREDUCE
    tensor_type: DataType = DataType.FLOAT32
    tensor_name: str = ""
    root_rank: int = -1
    device: int = -1
    tensor_shape: tuple[int, ...] = ()
    prescale_factor: float = 1.0
    postscale_factor: float = 1.0
    # Wire-compression codec (compress.CompressionCodec value) + block
    # size for the quantized codecs.  Negotiated like every other request
    # parameter: the coordinator rejects cross-rank mismatches with a
    # structured ERROR (a rank reducing int8 blocks against a peer's raw
    # fp32 would corrupt silently).
    codec: int = 0
    codec_block_size: int = 0
    # Canonical sharding-spec token (analysis/hvdshard/specs.py
    # spec_token): the mesh-axis tuple string this rank believes the
    # tensor is partitioned over, "" = unannotated/replicated.  Part of
    # collective identity (op×name×dtype×dims×spec) folded into the
    # runtime fingerprint, so two ranks disagreeing on *how* a tensor is
    # sharded diverge loudly instead of silently re-replicating.
    sp_spec: str = ""

    def tensor_size_elements(self) -> int:
        n = 1
        for d in self.tensor_shape:
            n *= d
        return n

    def encode(self, enc: Encoder,
               features: int = FEATURES_ALL) -> None:
        (enc.uvarint(self.request_rank)
            .uvarint(int(self.request_type))
            .uvarint(int(self.tensor_type))
            .string(self.tensor_name)
            .svarint(self.root_rank)
            .svarint(self.device)
            .svarint_list(list(self.tensor_shape))
            .f64(self.prescale_factor)
            .f64(self.postscale_factor)
            .uvarint(self.codec)
            .uvarint(self.codec_block_size))
        if features & FEATURE_SHARDING:
            enc.string(self.sp_spec)

    @classmethod
    def decode(cls, dec: Decoder,
               features: int = FEATURES_ALL) -> "Request":
        req = cls(
            request_rank=dec.uvarint(),
            request_type=RequestType(dec.uvarint()),
            tensor_type=DataType(dec.uvarint()),
            tensor_name=dec.string(),
            root_rank=dec.svarint(),
            device=dec.svarint(),
            tensor_shape=tuple(dec.svarint_list()),
            prescale_factor=dec.f64(),
            postscale_factor=dec.f64(),
            codec=dec.uvarint(),
            codec_block_size=dec.uvarint(),
        )
        if features & FEATURE_SHARDING:
            req.sp_spec = dec.string()
        return req


@dataclass
class RequestList:
    requests: list[Request] = field(default_factory=list)
    shutdown: bool = False
    # Collective-fingerprint stream state (analysis/fingerprint.py;
    # HOROVOD_FINGERPRINT).  fp_seq counts ops this rank has folded into
    # its rolling 64-bit digest; the tail lists carry the last
    # HOROVOD_FINGERPRINT_WINDOW (seq, digest-after, descriptor) records
    # so the coordinator can locate the FIRST divergent op, not just the
    # fact of divergence.  Kept as parallel primitive lists so the wire
    # layer stays free of analysis-layer imports.
    fp_seq: int = 0
    fp_digest: int = 0
    fp_tail_seqs: list[int] = field(default_factory=list)
    fp_tail_digests: list[int] = field(default_factory=list)
    fp_tail_descs: list[str] = field(default_factory=list)
    # Bounded telemetry snapshot (telemetry/straggler.py; HOROVOD_METRICS).
    # Four scalars — cycles in the window, summed cycle wall time, summed
    # control-plane sync wait, queue depth at negotiation — ride every
    # gathered RequestList so the coordinator can export per-rank gauges
    # without any extra collective.  All zero when metrics are off.
    tm_cycles: int = 0
    tm_cycle_ms: float = 0.0
    tm_sync_wait_ms: float = 0.0
    tm_queue_depth: int = 0

    def to_bytes(self, features: int = FEATURES_ALL) -> bytes:
        """`features` is the mesh-negotiated wire schema (HELLO
        handshake): every optional field group is gated on its feature
        bit, symmetrically with :meth:`from_bytes`, so mixed-version
        worlds exchange only the min common schema."""
        enc = Encoder()
        enc.bool_(self.shutdown)
        if features & FEATURE_FINGERPRINT:
            enc.uvarint(self.fp_seq)
            enc.uvarint(self.fp_digest)
            enc.uvarint_list(self.fp_tail_seqs)
            enc.uvarint_list(self.fp_tail_digests)
            enc.string_list(self.fp_tail_descs)
        if features & FEATURE_TELEMETRY:
            enc.uvarint(self.tm_cycles)
            enc.f64(self.tm_cycle_ms)
            enc.f64(self.tm_sync_wait_ms)
            enc.uvarint(self.tm_queue_depth)
        enc.uvarint(len(self.requests))
        for r in self.requests:
            r.encode(enc, features)
        return enc.getvalue()

    @classmethod
    def from_bytes(cls, raw: bytes,
                   features: int = FEATURES_ALL) -> "RequestList":
        dec = Decoder(raw)
        shutdown = dec.bool_()
        fp_seq = fp_digest = 0
        fp_tail_seqs: list[int] = []
        fp_tail_digests: list[int] = []
        fp_tail_descs: list[str] = []
        tm_cycles = tm_queue_depth = 0
        tm_cycle_ms = tm_sync_wait_ms = 0.0
        if features & FEATURE_FINGERPRINT:
            fp_seq = dec.uvarint()
            fp_digest = dec.uvarint()
            fp_tail_seqs = dec.uvarint_list()
            fp_tail_digests = dec.uvarint_list()
            fp_tail_descs = dec.string_list()
        if features & FEATURE_TELEMETRY:
            tm_cycles = dec.uvarint()
            tm_cycle_ms = dec.f64()
            tm_sync_wait_ms = dec.f64()
            tm_queue_depth = dec.uvarint()
        n = dec.uvarint()
        return cls(requests=[Request.decode(dec, features)
                             for _ in range(n)],
                   shutdown=shutdown, fp_seq=fp_seq, fp_digest=fp_digest,
                   fp_tail_seqs=fp_tail_seqs,
                   fp_tail_digests=fp_tail_digests,
                   fp_tail_descs=fp_tail_descs,
                   tm_cycles=tm_cycles, tm_cycle_ms=tm_cycle_ms,
                   tm_sync_wait_ms=tm_sync_wait_ms,
                   tm_queue_depth=tm_queue_depth)


@dataclass
class Response:
    response_type: ResponseType = ResponseType.ALLREDUCE
    tensor_names: list[str] = field(default_factory=list)
    error_message: str = ""
    devices: list[int] = field(default_factory=list)
    # Allgather/alltoall: per-rank first-dim sizes so every rank can size the
    # output buffer (reference: message.h tensor_sizes()).
    tensor_sizes: list[int] = field(default_factory=list)
    tensor_type: DataType = DataType.FLOAT32
    prescale_factor: float = 1.0
    postscale_factor: float = 1.0
    # Ranks that have joined (zero-filled stand-ins participate on their
    # behalf; reference: controller.cc:254-308).
    last_joined_rank: int = -1
    root_rank: int = -1          # broadcast root
    grouped: bool = False        # built from an explicit tensor group
    # Negotiated wire-compression codec the data planes must apply
    # (identical on every rank by construction — see Request.codec).
    codec: int = 0
    codec_block_size: int = 0
    # Distributed-trace id (telemetry/trace.py; mirrors the PR 2 fp_*
    # wire-field pattern): the coordinator assigns a monotone
    # (cycle, seq) pair to every negotiated collective so each rank's
    # Timeline spans — and the flight-recorder events — for the SAME
    # collective carry the SAME id and can be stitched into one
    # cross-rank flow.  -1 = unassigned (legacy frames, unit fixtures).
    # Cache-steady-state responses never ride the wire; they are stamped
    # locally from counters that advance in lockstep on every rank (the
    # deadlock-freedom invariant makes the local stamp rank-identical).
    trace_cycle: int = -1
    trace_seq: int = -1
    # Negotiated sharding-spec token the data planes must honour
    # (identical on every rank by construction — see Request.sp_spec;
    # the coordinator rejects cross-rank spec mismatches with a
    # structured ERROR before any response is built).
    sp_spec: str = ""

    def encode(self, enc: Encoder,
               features: int = FEATURES_ALL) -> None:
        (enc.uvarint(int(self.response_type))
            .string_list(self.tensor_names)
            .string(self.error_message)
            .svarint_list(self.devices)
            .svarint_list(self.tensor_sizes)
            .uvarint(int(self.tensor_type))
            .f64(self.prescale_factor)
            .f64(self.postscale_factor)
            .svarint(self.last_joined_rank)
            .svarint(self.root_rank)
            .bool_(self.grouped)
            .uvarint(self.codec)
            .uvarint(self.codec_block_size))
        if features & FEATURE_TRACE:
            enc.svarint(self.trace_cycle)
            enc.svarint(self.trace_seq)
        if features & FEATURE_SHARDING:
            enc.string(self.sp_spec)

    @classmethod
    def decode(cls, dec: Decoder,
               features: int = FEATURES_ALL) -> "Response":
        resp = cls(
            response_type=ResponseType(dec.uvarint()),
            tensor_names=dec.string_list(),
            error_message=dec.string(),
            devices=dec.svarint_list(),
            tensor_sizes=dec.svarint_list(),
            tensor_type=DataType(dec.uvarint()),
            prescale_factor=dec.f64(),
            postscale_factor=dec.f64(),
            last_joined_rank=dec.svarint(),
            root_rank=dec.svarint(),
            grouped=dec.bool_(),
            codec=dec.uvarint(),
            codec_block_size=dec.uvarint(),
        )
        if features & FEATURE_TRACE:
            resp.trace_cycle = dec.svarint()
            resp.trace_seq = dec.svarint()
        if features & FEATURE_SHARDING:
            resp.sp_spec = dec.string()
        return resp

    def trace_id(self) -> str | None:
        """Compact "cycle.seq" form used in Timeline span args and flow
        events, or None while unassigned."""
        if self.trace_cycle < 0 or self.trace_seq < 0:
            return None
        return f"{self.trace_cycle}.{self.trace_seq}"


@dataclass
class ResponseList:
    responses: list[Response] = field(default_factory=list)
    shutdown: bool = False
    # Autotuned parameters broadcast from the coordinator
    # (reference: Controller::SynchronizeParameters, controller.cc:39-53).
    tuned_fusion_threshold: int = -1
    tuned_cycle_time_ms: float = -1.0
    # Autotuned default wire codec (-1 = unchanged): lets the parameter
    # manager flip HOROVOD_COMPRESSION at runtime on every rank in the
    # same cycle.
    tuned_codec: int = -1
    # Autotuned TCP-pipeline knobs (-1 = unchanged): segment granularity
    # for the ring's segmented receive+accumulate, and the number of
    # active dispatch streams (capped by HOROVOD_NUM_STREAMS, whose
    # channel sets were formed at init).  Applied by every rank BEFORE
    # executing this list's responses so stream assignment stays
    # rank-symmetric.
    tuned_segment_bytes: int = -1
    tuned_num_streams: int = -1
    # Autotuned fused-codec-kernel dispatch (-1 = unchanged, else 0/1):
    # flips HOROVOD_FUSED_KERNELS at runtime on every rank in the same
    # cycle (compress/fused.py single-pass legs vs the reference chain).
    tuned_fused: int = -1
    # Autotuned allreduce algorithm (-1 = unchanged, else an index into
    # common/topology.ALGO_NAMES) and tree/ring crossover threshold in
    # bytes (-1 = unchanged).  Broadcast like every other tuned field and
    # applied by all ranks BEFORE dispatch, so algorithm choice can never
    # diverge across ranks (the deadlock-freedom invariant).
    tuned_algo: int = -1
    tuned_tree_threshold: int = -1

    def to_bytes(self, features: int = FEATURES_ALL) -> bytes:
        enc = Encoder()
        enc.bool_(self.shutdown)
        enc.svarint(self.tuned_fusion_threshold)
        enc.f64(self.tuned_cycle_time_ms)
        enc.svarint(self.tuned_codec)
        enc.svarint(self.tuned_segment_bytes)
        enc.svarint(self.tuned_num_streams)
        enc.svarint(self.tuned_fused)
        enc.svarint(self.tuned_algo)
        enc.svarint(self.tuned_tree_threshold)
        enc.uvarint(len(self.responses))
        for r in self.responses:
            r.encode(enc, features)
        return enc.getvalue()

    @classmethod
    def from_bytes(cls, raw: bytes,
                   features: int = FEATURES_ALL) -> "ResponseList":
        dec = Decoder(raw)
        shutdown = dec.bool_()
        threshold = dec.svarint()
        cycle = dec.f64()
        codec = dec.svarint()
        segment = dec.svarint()
        streams = dec.svarint()
        fused = dec.svarint()
        algo = dec.svarint()
        tree_threshold = dec.svarint()
        n = dec.uvarint()
        return cls(responses=[Response.decode(dec, features)
                              for _ in range(n)],
                   shutdown=shutdown,
                   tuned_fusion_threshold=threshold,
                   tuned_cycle_time_ms=cycle,
                   tuned_codec=codec,
                   tuned_segment_bytes=segment,
                   tuned_num_streams=streams,
                   tuned_fused=fused,
                   tuned_algo=algo,
                   tuned_tree_threshold=tree_threshold)
