"""Coordination protocol: which tensors are globally ready, fused how.

TPU-native rebuild of the reference Controller
(reference: horovod/common/controller.{cc,h} — ComputeResponseList at
controller.cc:69-450, ConstructResponse at 472-749, FuseResponses at
778-915, IncrementTensorCount at 943-966).

Protocol per background cycle (all ranks run it in lockstep):
1. Pop locally-submitted Requests.
2. Cache path: look up each request in the ResponseCache; sync two bitvector
   words across ranks (AND of hits, OR of invalid+flags); execute common hits
   straight from the cache — steady state never ships RequestLists.
3. Uncached path (when any rank has uncached work, globally OR-decided):
   workers send their RequestList to the coordinator (rank 0); the
   coordinator counts readiness per tensor, validates cross-rank consistency
   (dtype/shape/op/root mismatches become structured ERROR responses, never
   hangs), fuses ready responses up to the fusion threshold with look-ahead,
   and broadcasts the final ResponseList.
4. Every rank executes the identical ResponseList in identical order — the
   deadlock-freedom invariant.

Transport (gather/broadcast/bitwise-allreduce) is abstract: LocalTransport
for single-process worlds, TcpTransport (runner/network.py) for
multi-process worlds over the DCN control plane.
"""
from __future__ import annotations

import time
from abc import ABC, abstractmethod
from dataclasses import dataclass, field

from . import config
from ..analysis.fingerprint import FingerprintTracker, OpRecord
from .dtypes import element_size
from .exceptions import RanksFailedError
from .group_table import GroupTable
from .message import (Request, RequestList, RequestType, Response,
                      ResponseList, ResponseType)
from .response_cache import CacheCoordinator, CacheState, ResponseCache
from .stall_inspector import StallInspector
from .tensor_queue import TensorQueue

# Fusion buffers are sized in multiples of this unit so fused buffers always
# divide evenly for hierarchical ops (reference: common.h:103
# FUSION_BUFFER_ATOMIC_UNIT=64, controller.cc:452-470).
FUSION_BUFFER_ATOMIC_UNIT = 64


def _round_to_atomic(threshold: int, divisor: int) -> int:
    unit = FUSION_BUFFER_ATOMIC_UNIT * max(divisor, 1)
    if threshold <= 0:
        return 0
    return max(unit, (threshold // unit) * unit)


@dataclass
class _TensorCount:
    """Coordinator-side readiness record for one tensor name."""
    requests: dict[int, Request] = field(default_factory=dict)  # rank -> req
    arrival: int = 0   # order in which the tensor was first requested
    # rank -> monotonic time its request arrived (telemetry straggler
    # signal; only populated when HOROVOD_METRICS is on).
    times: dict[int, float] = field(default_factory=dict)


class Transport(ABC):
    """Control-plane primitives between ranks (DCN/TCP or in-process)."""

    @abstractmethod
    def bitwise_sync(self, and_word: int, or_word: int) -> tuple[int, int]:
        """Allreduce: bitwise AND over first word, OR over second."""

    @abstractmethod
    def gather_requests(self, request_list: RequestList) -> list[RequestList] | None:
        """Workers send; coordinator returns all lists indexed by rank."""

    @abstractmethod
    def broadcast_responses(self, response_list: ResponseList | None) -> ResponseList:
        """Coordinator sends its list; workers receive it."""

    @abstractmethod
    def barrier(self) -> None:
        """Block until every rank arrives."""


class LocalTransport(Transport):
    """Single-process world: all ops are identities."""

    def bitwise_sync(self, and_word: int, or_word: int) -> tuple[int, int]:
        return and_word, or_word

    def gather_requests(self, request_list: RequestList):
        return [request_list]

    def broadcast_responses(self, response_list):
        return response_list

    def barrier(self) -> None:
        return None


class Controller:
    def __init__(self,
                 rank: int,
                 size: int,
                 transport: Transport,
                 tensor_queue: TensorQueue,
                 group_table: GroupTable | None = None,
                 response_cache: ResponseCache | None = None,
                 stall_inspector: StallInspector | None = None,
                 local_rank: int = 0,
                 local_size: int = 1,
                 cross_rank: int = 0,
                 cross_size: int = 1,
                 timeline=None,
                 fingerprint: FingerprintTracker | None = None) -> None:
        self.rank = rank
        self.size = size
        self.local_rank = local_rank
        self.local_size = local_size
        self.cross_rank = cross_rank
        self.cross_size = cross_size
        self.transport = transport
        self.tensor_queue = tensor_queue
        self.group_table = group_table or GroupTable()
        self.response_cache = response_cache if response_cache is not None \
            else ResponseCache(config.CACHE_CAPACITY.get())
        self.stall_inspector = stall_inspector or StallInspector()
        self.fingerprint = fingerprint if fingerprint is not None \
            else FingerprintTracker.from_config()
        # Spec column of the collective identity (hvdshard): folded only
        # when the mesh negotiated FEATURE_SHARDING — the negotiated
        # feature word is identical on every rank (min proto / AND of
        # HELLO bits), so either every rank folds op×name×dtype×dims×spec
        # or every rank folds the legacy 5-column identity.  A
        # mixed-version world that negotiated sp_* away stays
        # fingerprint-green.  HOROVOD_SHARD_SPEC_IDENTITY=0 is the
        # launcher-set (hence world-symmetric) kill switch.
        from .wire import FEATURE_SHARDING, FEATURES_ALL
        self.fingerprint.fold_spec = bool(
            getattr(transport, "features", FEATURES_ALL)
            & FEATURE_SHARDING) and config.SHARD_SPEC_IDENTITY.get()
        self.timeline = timeline
        self.tensor_fusion_threshold = config.FUSION_THRESHOLD.get()
        self.disable_group_fusion = config.DISABLE_GROUP_FUSION.get()

        # Coordinator-side readiness table.
        self._message_table: dict[str, _TensorCount] = {}
        self._arrival_counter = 0
        # Join bookkeeping (reference: controller.cc:254-308).
        self.joined_ranks: set[int] = set()
        self.last_joined_rank = -1
        # Requests that hit the local cache this cycle, by name — if the
        # global AND kills their bit they must be renegotiated.
        self._local_hits: dict[str, Request] = {}
        # This rank has called join() and is riding along with zero
        # stand-ins until everyone joins.
        self.local_joined = False
        # Autotuner proposals awaiting broadcast (coordinator only).
        self.pending_tuned_params: tuple[int, float] | None = None
        self.pending_tuned_codec: int | None = None
        # (segment_bytes, num_streams) TCP-pipeline proposal.
        self.pending_tuned_pipeline: tuple[int, int] | None = None
        # Fused-codec-kernel proposal (0/1; compress/fused.py dispatch).
        self.pending_tuned_fused: int | None = None
        # (algo index, tree threshold bytes) allreduce-algorithm proposal
        # (common/topology.ALGO_NAMES; backend/tcp.py selection).
        self.pending_tuned_algo: tuple[int, int] | None = None
        # Last request params per tensor, for cache insertion on every rank.
        self._last_request_params: dict[str, Request] = {}

        # Telemetry (HOROVOD_METRICS; telemetry/): controller-plane
        # counters + the coordinator's cross-rank straggler aggregation.
        # The Null registry makes every call below a no-op when off.
        from ..telemetry import metrics as _tm_metrics
        self.metrics = _tm_metrics()
        self._m_cache_hit = self.metrics.counter(
            "horovod_controller_cache_hit_total",
            "Requests answered from the response cache at controller pop")
        self._m_cache_miss = self.metrics.counter(
            "horovod_controller_cache_miss_total",
            "Requests that needed (re-)negotiation")
        self._m_negotiations = self.metrics.counter(
            "horovod_controller_negotiations_total",
            "Full RequestList gather/broadcast cycles")
        self._m_negotiation_ms = self.metrics.histogram(
            "horovod_controller_negotiation_ms",
            "Wall time of one gather+broadcast negotiation round")
        self._m_sync_wait_ms = self.metrics.histogram(
            "horovod_controller_sync_wait_ms",
            "Wall time blocked in the per-cycle bitvector sync (a fast "
            "rank's wait here is a slow peer's lag)")
        self.straggler = None
        if self.metrics.enabled and self.is_coordinator and size > 1:
            from ..telemetry.straggler import StragglerAggregator
            self.straggler = StragglerAggregator(size, self.metrics)
        # Worker-side window accumulators for the RequestList tm_*
        # snapshot (core's background loop feeds record_cycle).
        self._tm_cycles = 0
        self._tm_cycle_ms = 0.0
        self._tm_sync_wait_ms = 0.0
        # Within-round per-rank arrival times of the current gather.
        self._gather_arrivals: dict[int, float] = {}

        # Distributed-trace cycle counter (telemetry/trace.py): advances
        # once per compute_response_list call.  Cycles are lockstep
        # across ranks — every cycle either runs the bitvector sync or a
        # full negotiation round — so a locally-incremented counter is
        # identical on every rank, which is what lets cache-steady
        # responses (which never ride the wire) be stamped locally while
        # negotiated responses carry the coordinator's id on the wire.
        self._trace_cycle = 0
        # Flight recorder (telemetry/flight.py): Null when HOROVOD_FLIGHT
        # is off, so every hook below is one attribute test.
        from ..telemetry import flight as _flight
        self.flight = _flight.recorder()

    # ------------------------------------------------------------------
    @property
    def is_coordinator(self) -> bool:
        return self.rank == 0

    def fusion_threshold_bytes(self) -> int:
        return _round_to_atomic(self.tensor_fusion_threshold, self.local_size)

    # ------------------------------------------------------------------
    def compute_response_list(self, shutdown_requested: bool = False) -> ResponseList:
        self._trace_cycle += 1
        message_queue = self.tensor_queue.pop_messages_from_queue()
        if self.fingerprint.enabled:
            # Fold every locally-submitted op into this rank's rolling
            # fingerprint in submission order (fold() itself skips JOIN —
            # rank-asymmetric by design — and requests re-popped after a
            # cache-bit miss, which were already folded on first pop).
            for req in message_queue:
                self.fingerprint.fold(req)
        if self.timeline is not None:
            for req in message_queue:
                self.timeline.negotiate_start(req.tensor_name,
                                              req.request_type)

        # Stall check rides the every-cycle heartbeat, not just
        # negotiation: a one-sided tensor leaves every queue empty after
        # its single submission, so negotiation never runs again — exactly
        # the stalled state the inspector exists to catch. The decision
        # propagates through the cache bit-sync OR (cache on) or the
        # gathered RequestList (cache off).
        if self.is_coordinator and self.stall_inspector.should_check():
            if self.stall_inspector.check_for_stalled_tensors(self.size):
                shutdown_requested = True

        cached_responses: list[Response] = []

        for req in message_queue:
            if req.request_type == RequestType.JOIN:
                self.local_joined = True

        if self.response_cache.enabled():
            coordinator = CacheCoordinator(self.response_cache.capacity)
            uncached: list[Request] = []
            if self.local_joined:
                # A joined rank asserts the cache bits of ops a zero
                # stand-in can legally satisfy (allreduce/adasum) so the
                # global AND still passes for the remaining ranks
                # (reference: controller.cc joined-rank cache handling).
                # Ops where absence has MEANING — allgather/alltoall/
                # reducescatter contribute shaped blocks, broadcast a
                # root — cannot be fabricated: mark those positions
                # INVALID instead, so the OR-propagated invalidation
                # evicts them everywhere, the peers renegotiate, and
                # ConstructResponse surfaces the structured
                # join-unsupported error rather than this rank executing
                # a cached response it never submitted (or hanging its
                # peers by silently dropping the bit).
                fabricatable = {ResponseType.ALLREDUCE, ResponseType.ADASUM}
                for pos in self.response_cache.positions():
                    rtype = self.response_cache.response_type_by_position(
                        pos)
                    if rtype in fabricatable:
                        coordinator.record_hit(pos)
                    else:
                        coordinator.record_invalid(pos)
            if self.is_coordinator and (
                    self.pending_tuned_params is not None
                    or self.pending_tuned_codec is not None
                    or self.pending_tuned_pipeline is not None
                    or self.pending_tuned_fused is not None
                    or self.pending_tuned_algo is not None):
                # Force one negotiation cycle so autotuned parameters reach
                # every rank even in cache steady state.
                coordinator.uncached_in_queue = True
            if self.fingerprint.strict:
                # Strict mode: a negotiation heartbeat EVERY cycle, so
                # fingerprints are compared even in cache steady state
                # (which otherwise never ships RequestLists) — divergence
                # surfaces within one cycle instead of at the next
                # natural negotiation.
                coordinator.uncached_in_queue = True
            for req in message_queue:
                state = self.response_cache.cached(req)
                if state == CacheState.HIT:
                    pos = self.response_cache.peek_cache_position(
                        req.tensor_name)
                    coordinator.record_hit(pos)
                    self._local_hits[req.tensor_name] = req
                    self.stall_inspector.record_cached_tensor(req.tensor_name)
                    self._m_cache_hit.inc()
                else:
                    if state == CacheState.INVALID:
                        pos = self.response_cache.peek_cache_position(
                            req.tensor_name)
                        coordinator.record_invalid(pos)
                    coordinator.uncached_in_queue = True
                    uncached.append(req)
                    self._m_cache_miss.inc()
            coordinator.shutdown = shutdown_requested
            self.stall_inspector.invalidate_stalled_cached_tensors(
                coordinator, self.response_cache)

            # Both words sync every cycle — this is the lockstep heartbeat
            # that keeps all ranks advancing together (reference:
            # controller.cc:751-776 CoordinateCacheAndState).
            and_word, or_word = coordinator.pack()
            t0 = time.monotonic() if self.metrics.enabled else 0.0
            try:
                and_word, or_word = self.transport.bitwise_sync(and_word,
                                                                or_word)
            except RanksFailedError as exc:
                return self._poison_response_list(exc)
            if self.metrics.enabled:
                wait_ms = (time.monotonic() - t0) * 1e3
                self._m_sync_wait_ms.observe(wait_ms)
                self._tm_sync_wait_ms += wait_ms
            coordinator.unpack(and_word, or_word)

            if coordinator.shutdown:
                return ResponseList(shutdown=True)

            for pos in sorted(coordinator.invalid_bits):
                self.response_cache.erase_by_position(pos)

            # Execute globally-common cache hits in bit order — positions are
            # identical across ranks because cache insertions happen in
            # identical response order on every rank.
            for pos in sorted(coordinator.hit_bits):
                resp = self.response_cache.get_response_by_position(pos)
                for name in resp.tensor_names:
                    self.stall_inspector.remove_cached_tensor(name)
                    self._local_hits.pop(name, None)
                cached_responses.append(resp)

            # Local hits whose bit didn't survive the AND: some rank hasn't
            # submitted this tensor yet.  Resubmit next cycle and wait for
            # the global AND to pass — negotiation is only entered when the
            # globally-ORed uncached flag says so, keeping every rank's
            # decision identical (the deadlock-freedom invariant).
            for req in self._local_hits.values():
                self.tensor_queue.push_back_to_queue(req)
            self._local_hits.clear()
            message_queue = uncached

            need_negotiation = coordinator.uncached_in_queue
        else:
            # Without a cache the reference gathers every cycle; an idle rank
            # still participates so the coordinator can make progress.
            need_negotiation = True

        fused_cached = self.fuse_responses(cached_responses)
        if not need_negotiation:
            return self._stamp_trace_ids(
                ResponseList(responses=fused_cached))

        response_list = self._negotiate(message_queue, shutdown_requested,
                                        trace_offset=len(fused_cached))
        if self._is_poison(response_list):
            # World poisoned mid-negotiation (resilience/): drop this
            # cycle's cached hits — their data-plane execution would
            # block on the dead rank; the poison ERROR already names
            # every pending tensor, so no waiter is left hanging.
            return response_list
        response_list.responses = fused_cached + response_list.responses
        self._stamp_trace_ids(response_list)

        if self.response_cache.enabled():
            for resp in response_list.responses:
                self._maybe_cache(resp)
        if response_list.tuned_fusion_threshold >= 0:
            self.tensor_fusion_threshold = response_list.tuned_fusion_threshold
        return response_list

    # ------------------------------------------------------------------
    def _stamp_trace_ids(self, response_list: ResponseList) -> ResponseList:
        """Assign the monotone (cycle, seq) trace id to every response
        that does not already carry one from the wire.  Negotiated
        responses arrive stamped by the coordinator (seq offset past
        this cycle's cached hits); cache-steady responses are stamped
        here — the final list is identical on every rank, so the local
        stamp is rank-identical too."""
        for seq, resp in enumerate(response_list.responses):
            if resp.trace_seq < 0:
                resp.trace_cycle = self._trace_cycle
                resp.trace_seq = seq
        return response_list

    def _poison_response_list(self, exc: RanksFailedError) -> ResponseList:
        """Convert a detected rank failure into the structured-ERROR
        shutdown every rank performs locally (resilience/ tentpole): one
        ERROR response naming EVERY tensor still pending in the local
        table (so each blocked Handle raises RanksFailedError rather
        than hanging or getting a generic abort), plus the shutdown
        flag.  Rank-local tensor naming is safe here precisely because
        ERROR responses never touch a data plane — nothing about this
        list has to match across ranks.  The coordinator's transport has
        already poison-broadcast the same failure to all survivors, so
        the whole world converges within one detection window."""
        names = sorted(set(self.tensor_queue.pending_names()))
        for name in names:
            self._message_table.pop(name, None)
            self.stall_inspector.remove_uncached_tensor(name)
        if self.flight.enabled:
            # Every structured failure ships the last N trace events:
            # the dump's tail names the op the world died inside
            # (telemetry/flight.py; docs/observability.md).
            self.flight.record("ranks-failed", exc.op,
                               detail=exc.to_wire()[:200])
            self.flight.dump(reason=exc.to_wire())
        return ResponseList(
            responses=[Response(response_type=ResponseType.ERROR,
                                tensor_names=names,
                                error_message=exc.to_wire())],
            shutdown=True)

    @staticmethod
    def _is_poison(response_list: ResponseList) -> bool:
        return (response_list.shutdown and bool(response_list.responses)
                and response_list.responses[0].response_type
                == ResponseType.ERROR
                and RanksFailedError.matches(
                    response_list.responses[0].error_message))

    def _maybe_cache(self, resp: Response) -> None:
        """Cache single-tensor non-error responses keyed by their request.

        Fused responses are not cached as a unit: each member caches
        individually (via earlier single-tensor cycles) and steady-state
        hits are re-fused by fuse_responses — matching the reference, where
        cache entries are per-tensor and fusion happens after lookup.
        """
        if resp.response_type in (ResponseType.ERROR, ResponseType.JOIN,
                                  ResponseType.BARRIER):
            return
        if len(resp.tensor_names) != 1:
            return
        req = self._last_request_params.get(resp.tensor_names[0])
        if req is None:
            # This rank never submitted the request (it has joined): cache
            # with parameters synthesized from the response so bit positions
            # stay identical on every rank.  The synthesized flat shape can
            # only cause a harmless INVALID→renegotiation if this rank ever
            # submits the tensor again.
            rtype = {ResponseType.ALLREDUCE: RequestType.ALLREDUCE,
                     ResponseType.ADASUM: RequestType.ADASUM,
                     ResponseType.REDUCESCATTER: RequestType.REDUCESCATTER,
                     ResponseType.ALLGATHER: RequestType.ALLGATHER,
                     ResponseType.BROADCAST: RequestType.BROADCAST,
                     ResponseType.ALLTOALL: RequestType.ALLTOALL}.get(
                         resp.response_type)
            if rtype is None:
                return
            req = Request(request_rank=self.rank, request_type=rtype,
                          tensor_type=resp.tensor_type,
                          tensor_name=resp.tensor_names[0],
                          root_rank=resp.root_rank,
                          tensor_shape=(sum(resp.tensor_sizes),),
                          prescale_factor=resp.prescale_factor,
                          postscale_factor=resp.postscale_factor,
                          codec=resp.codec,
                          codec_block_size=resp.codec_block_size)
        self.response_cache.put(resp, req)

    # ------------------------------------------------------------------
    def record_cycle(self, cycle_ms: float) -> None:
        """Fold one background-loop cycle's wall time into the window
        snapshot the next negotiation ships (core._background_loop calls
        this only when metrics are on)."""
        self._tm_cycles += 1
        self._tm_cycle_ms += cycle_ms

    def _attach_telemetry_snapshot(self, my_list: RequestList,
                                   queue_depth: int) -> None:
        my_list.tm_cycles = self._tm_cycles
        my_list.tm_cycle_ms = self._tm_cycle_ms
        my_list.tm_sync_wait_ms = self._tm_sync_wait_ms
        my_list.tm_queue_depth = queue_depth
        self._tm_cycles = 0
        self._tm_cycle_ms = 0.0
        self._tm_sync_wait_ms = 0.0

    def _negotiate(self, message_queue: list[Request],
                   shutdown_requested: bool,
                   trace_offset: int = 0) -> ResponseList:
        for req in message_queue:
            self._last_request_params[req.tensor_name] = req
        my_list = RequestList(requests=list(message_queue),
                              shutdown=shutdown_requested)
        if self.fingerprint.enabled:
            seq, digest, tail = self.fingerprint.snapshot()
            my_list.fp_seq, my_list.fp_digest = seq, digest
            my_list.fp_tail_seqs = [rec.seq for rec in tail]
            my_list.fp_tail_digests = [rec.digest for rec in tail]
            my_list.fp_tail_descs = [rec.descriptor for rec in tail]
        tm_on = self.metrics.enabled
        if tm_on:
            self._attach_telemetry_snapshot(my_list, len(message_queue))
            t_neg = time.monotonic()
        if self.is_coordinator:
            try:
                gathered = self.transport.gather_requests(my_list)
            except RanksFailedError as exc:
                # The transport has already poison-broadcast to the
                # survivors; this is the coordinator's local half.
                return self._poison_response_list(exc)
            assert gathered is not None
            if self.straggler is not None:
                self.straggler.observe_snapshots(gathered)
                # Within-round arrival times from the transport (absent on
                # LocalTransport; _handle_request then stamps on handle,
                # which still carries the cross-round signal — requests
                # completing a tensor in a LATER round arrive later).
                self._gather_arrivals = dict(getattr(
                    self.transport, "last_gather_arrivals", {}) or {})
            shutdown = False
            for rank_list in gathered:
                shutdown = shutdown or rank_list.shutdown
                for req in rank_list.requests:
                    self._handle_request(req)
            responses = [self._construct_response(names)
                         for names in self._pop_ready_tensors()]
            fp_error = self._check_fingerprints(gathered)
            if fp_error is not None:
                # The divergence error leads the list so every rank fails
                # the divergent entries before executing anything else.
                responses.insert(0, fp_error)
            join_resp = self._maybe_join_response()
            if join_resp is not None:
                responses.append(join_resp)
            # (Stall check already ran on the compute_response_list
            # heartbeat; shutdown_requested carries its verdict here.)
            response_list = ResponseList(responses=self.fuse_responses(responses),
                                         shutdown=shutdown)
            if self.pending_tuned_params is not None:
                threshold, cycle = self.pending_tuned_params
                response_list.tuned_fusion_threshold = threshold
                response_list.tuned_cycle_time_ms = cycle
                self.pending_tuned_params = None
            if self.pending_tuned_codec is not None:
                response_list.tuned_codec = self.pending_tuned_codec
                self.pending_tuned_codec = None
            if self.pending_tuned_pipeline is not None:
                segment, streams = self.pending_tuned_pipeline
                response_list.tuned_segment_bytes = segment
                response_list.tuned_num_streams = streams
                self.pending_tuned_pipeline = None
            if self.pending_tuned_fused is not None:
                response_list.tuned_fused = self.pending_tuned_fused
                self.pending_tuned_fused = None
            if self.pending_tuned_algo is not None:
                algo, tree_threshold = self.pending_tuned_algo
                response_list.tuned_algo = algo
                response_list.tuned_tree_threshold = tree_threshold
                self.pending_tuned_algo = None
            # Coordinator-assigned trace ids ride the broadcast wire
            # (the fp_* pattern): seq is offset past this cycle's cached
            # hits, which every rank prepends in the same order.
            for i, resp in enumerate(response_list.responses):
                resp.trace_cycle = self._trace_cycle
                resp.trace_seq = trace_offset + i
            try:
                self.transport.broadcast_responses(response_list)
            except RanksFailedError as exc:
                return self._poison_response_list(exc)
        else:
            try:
                self.transport.gather_requests(my_list)
                response_list = self.transport.broadcast_responses(None)
            except RanksFailedError as exc:
                # Local detection (coordinator dead/unreachable) or a
                # received poison frame: same structured local shutdown.
                return self._poison_response_list(exc)
            for resp in response_list.responses:
                if resp.response_type == ResponseType.JOIN:
                    self.joined_ranks.clear()
                    self.last_joined_rank = -1
                    self.local_joined = False
        if tm_on:
            self._m_negotiation_ms.observe(
                (time.monotonic() - t_neg) * 1e3)
            self._m_negotiations.inc()
        return response_list

    # ------------------------------------------------------------------
    # Coordinator internals
    # ------------------------------------------------------------------
    def _check_fingerprints(self, gathered: list[RequestList]) -> Response | None:
        """Compare the ranks' rolling collective fingerprints; divergence
        becomes a structured ERROR naming the first divergent op — the
        failure mode the per-tensor validation in _construct_single can
        never see (it requires every rank to have submitted the SAME
        tensor name; fingerprinting catches ranks submitting different
        ops entirely, which otherwise stalls until the stall inspector's
        60s warning or the job timeout)."""
        if not self.fingerprint.enabled:
            return None
        divergence = self.fingerprint.check_gathered([
            (rl.fp_seq, rl.fp_digest,
             [OpRecord(s, d, t) for s, d, t in
              zip(rl.fp_tail_seqs, rl.fp_tail_digests, rl.fp_tail_descs)])
            for rl in gathered])
        if divergence is None:
            return None
        if self.flight.enabled:
            self.flight.record("fingerprint-divergence", "",
                               detail=divergence.message()[:200])
            self.flight.dump(reason=divergence.message())
        names = divergence.tensor_names()
        for name in names:
            # Divergent tensors will never become globally ready: drop
            # their readiness records so the stall inspector does not
            # keep warning about an already-reported failure.
            self._message_table.pop(name, None)
            self.stall_inspector.remove_uncached_tensor(name)
        return Response(response_type=ResponseType.ERROR,
                        tensor_names=names,
                        error_message=divergence.message())

    def _handle_request(self, req: Request) -> None:
        if req.request_type == RequestType.JOIN:
            self.joined_ranks.add(req.request_rank)
            self.last_joined_rank = max(self.last_joined_rank,
                                        req.request_rank)
            return
        rec = self._message_table.get(req.tensor_name)
        if rec is None:
            rec = _TensorCount(arrival=self._arrival_counter)
            self._arrival_counter += 1
            self._message_table[req.tensor_name] = rec
        rec.requests[req.request_rank] = req
        if self.straggler is not None:
            rec.times[req.request_rank] = self._gather_arrivals.get(
                req.request_rank, time.monotonic())
        self.stall_inspector.record_uncached_tensor(req.tensor_name,
                                                    req.request_rank)

    def _required_count(self) -> int:
        return self.size - len(self.joined_ranks)

    def _pop_ready_tensors(self) -> list[list[str]]:
        """Return groups of tensor names ready for response construction.

        Grouped tensors (GroupTable) are only released when every member is
        ready (reference: controller.cc:199-223); ungrouped tensors release
        individually, ordered by first arrival for determinism.
        """
        required = self._required_count()
        ready = [name for name, rec in self._message_table.items()
                 if len(rec.requests) >= required]
        ready.sort(key=lambda n: self._message_table[n].arrival)

        out: list[list[str]] = []
        ready_set = set(ready)
        seen_groups: set[int] = set()
        for name in ready:
            gid = self.group_table.get_group_id(name)
            if gid < 0:
                out.append([name])
            elif gid not in seen_groups:
                members = self.group_table.get_group_tensor_names(gid)
                if all(m in ready_set for m in members):
                    seen_groups.add(gid)
                    out.append(members)
        return out

    def _maybe_join_response(self) -> Response | None:
        if self.size > 0 and len(self.joined_ranks) == self.size:
            resp = Response(response_type=ResponseType.JOIN,
                            last_joined_rank=self.last_joined_rank)
            self.joined_ranks.clear()
            self.last_joined_rank = -1
            self.local_joined = False
            return resp
        return None

    # -- ConstructResponse (reference: controller.cc:472-749) ----------
    def _construct_response(self, names: list[str]) -> Response:
        if len(names) == 1:
            resp = self._construct_single(names[0])
        else:
            parts = [self._construct_single(n) for n in names]
            err = next((p for p in parts
                        if p.response_type == ResponseType.ERROR), None)
            if err is not None:
                # One bad member poisons the group: report the error for all
                # member tensors so no entry is left hanging.
                all_names = [n for p in parts for n in p.tensor_names]
                resp = Response(response_type=ResponseType.ERROR,
                                tensor_names=all_names,
                                error_message=err.error_message)
            else:
                resp = parts[0]
                resp.grouped = True
                for p in parts[1:]:
                    resp.tensor_names.extend(p.tensor_names)
                    resp.tensor_sizes.extend(p.tensor_sizes)
        self.group_table.deregister_groups(names)
        return resp

    def _construct_single(self, name: str) -> Response:
        rec = self._message_table.pop(name)
        self.stall_inspector.remove_uncached_tensor(name)
        if self.straggler is not None and rec.times:
            # The tensor just became globally ready: the spread of its
            # request arrivals IS the negotiation skew, and the last
            # arrival names the straggler (telemetry/straggler.py).
            self.straggler.observe_tensor(rec.times)
        reqs = [rec.requests[r] for r in sorted(rec.requests)]
        first = reqs[0]

        def error(msg: str) -> Response:
            return Response(response_type=ResponseType.ERROR,
                            tensor_names=[name], error_message=msg)

        if any(r.request_type != first.request_type for r in reqs):
            ops = {r.request_rank: r.request_type.name for r in reqs}
            return error(f"Mismatched collective operations for tensor "
                         f"{name}: {ops}. All ranks must submit the same "
                         f"operation.")
        if any(r.tensor_type != first.tensor_type for r in reqs):
            dts = {r.request_rank: r.tensor_type.name for r in reqs}
            return error(f"Mismatched data types for tensor {name}: {dts}.")
        if any(r.prescale_factor != first.prescale_factor or
               r.postscale_factor != first.postscale_factor for r in reqs):
            return error(f"Mismatched prescale/postscale factors for tensor "
                         f"{name}.")
        if any(r.codec != first.codec or
               r.codec_block_size != first.codec_block_size for r in reqs):
            # A rank decoding int8 blocks against a peer's raw payload
            # would corrupt silently — same failure class as a dtype
            # mismatch, same structured-ERROR answer (SURVEY §5.2).
            codecs = {r.request_rank: (r.codec, r.codec_block_size)
                      for r in reqs}
            return error(f"Mismatched compression codecs for tensor "
                         f"{name}: {codecs}. All ranks must use the same "
                         f"codec and block size.")

        rtype = first.request_type
        joined = len(self.joined_ranks) > 0
        devices = [0] * self.size
        for r in reqs:
            if 0 <= r.request_rank < self.size:
                devices[r.request_rank] = r.device

        if rtype in (RequestType.ALLREDUCE, RequestType.ADASUM,
                     RequestType.REDUCESCATTER):
            if rtype == RequestType.REDUCESCATTER and joined:
                # A joined rank's zero stand-in has no shape, and the
                # dim-0 output split needs every rank's shape — same
                # category as allgather/broadcast under Join.
                return error("Reducescatter is not supported after a rank "
                             "has joined: all ranks must participate.")
            for r in reqs[1:]:
                if tuple(r.tensor_shape) != tuple(first.tensor_shape):
                    return error(
                        f"Mismatched {rtype.name.lower()} tensor shapes for "
                        f"tensor {name}: rank {r.request_rank} has shape "
                        f"{tuple(r.tensor_shape)}, rank "
                        f"{first.request_rank} has shape "
                        f"{tuple(first.tensor_shape)}.")
            from ..compress import QUANTIZED_CODECS
            if rtype == RequestType.ADASUM and \
                    first.codec in QUANTIZED_CODECS:
                # Adasum's per-layer dot products are computed on the
                # wire payload; quantized blocks would make the norms
                # meaningless.  Cast codecs (fp16/bf16) compose fine.
                return error("Adasum does not support quantized "
                             "compression codecs (int8/uint4); use none, "
                             "fp16 or bf16.")
            resp_type = {
                RequestType.ALLREDUCE: ResponseType.ALLREDUCE,
                RequestType.ADASUM: ResponseType.ADASUM,
                RequestType.REDUCESCATTER: ResponseType.REDUCESCATTER,
            }[rtype]
            return Response(
                response_type=resp_type, tensor_names=[name],
                devices=devices, tensor_type=first.tensor_type,
                tensor_sizes=[first.tensor_size_elements()],
                prescale_factor=first.prescale_factor,
                postscale_factor=first.postscale_factor,
                last_joined_rank=self.last_joined_rank,
                codec=first.codec,
                codec_block_size=first.codec_block_size,
                sp_spec=first.sp_spec)

        if rtype == RequestType.ALLGATHER:
            if joined:
                return error("Allgather is not supported after a rank has "
                             "joined: all ranks must participate.")
            for r in reqs[1:]:
                if len(r.tensor_shape) != len(first.tensor_shape) or \
                        tuple(r.tensor_shape[1:]) != tuple(first.tensor_shape[1:]):
                    return error(
                        f"Mismatched allgather tensor shapes for tensor "
                        f"{name}: all dimensions except the first must "
                        f"match (rank {r.request_rank}: "
                        f"{tuple(r.tensor_shape)} vs "
                        f"{tuple(first.tensor_shape)}).")
            sizes = [(r.tensor_shape[0] if r.tensor_shape else 1)
                     for r in reqs]
            return Response(response_type=ResponseType.ALLGATHER,
                            tensor_names=[name], devices=devices,
                            tensor_type=first.tensor_type,
                            tensor_sizes=sizes,
                            sp_spec=first.sp_spec)

        if rtype == RequestType.BROADCAST:
            if joined:
                return error("Broadcast is not supported after a rank has "
                             "joined: all ranks must participate.")
            if any(r.root_rank != first.root_rank for r in reqs):
                roots = {r.request_rank: r.root_rank for r in reqs}
                return error(f"Mismatched broadcast root ranks for tensor "
                             f"{name}: {roots}.")
            root = next((r for r in reqs
                         if r.request_rank == first.root_rank), first)
            for r in reqs:
                if tuple(r.tensor_shape) != tuple(root.tensor_shape):
                    return error(
                        f"Mismatched broadcast tensor shapes for tensor "
                        f"{name}: rank {r.request_rank} has "
                        f"{tuple(r.tensor_shape)}, root has "
                        f"{tuple(root.tensor_shape)}.")
            return Response(response_type=ResponseType.BROADCAST,
                            tensor_names=[name], devices=devices,
                            tensor_type=first.tensor_type,
                            tensor_sizes=[root.tensor_size_elements()],
                            root_rank=first.root_rank,
                            sp_spec=first.sp_spec)

        if rtype == RequestType.ALLTOALL:
            if joined:
                return error("Alltoall is not supported after a rank has "
                             "joined: all ranks must participate.")
            for r in reqs[1:]:
                if tuple(r.tensor_shape[1:]) != tuple(first.tensor_shape[1:]):
                    return error(
                        f"Mismatched alltoall tensor shapes for tensor "
                        f"{name}: trailing dimensions must match.")
            return Response(response_type=ResponseType.ALLTOALL,
                            tensor_names=[name], devices=devices,
                            tensor_type=first.tensor_type)

        if rtype == RequestType.BARRIER:
            return Response(response_type=ResponseType.BARRIER,
                            tensor_names=[name])

        return error(f"Unsupported request type {rtype} for tensor {name}.")

    # -- FuseResponses (reference: controller.cc:778-915) --------------
    def _response_payload_bytes(self, resp: Response) -> int:
        """Bytes a response contributes to a fusion buffer.  Allreduce:
        element count × element size.  Allgather: OUTPUT bytes —
        sum of per-rank first dims × the entry's trailing-dim element
        count (reference: controller.cc:917-937
        TotalByteSizeOfAllgatherOutput, looked up via the tensor queue
        exactly as the reference does).  Fusion-determinism invariant:
        every rank that reaches here with an allgather response HAS the
        entry — it submitted the request (a joined rank invalidates
        cached allgather bits instead of asserting them, so these
        responses never execute there), and trailing dims are cross-rank
        validated equal — so the computed size is identical on all ranks.
        The KeyError arm is defensive only."""
        esz = element_size(resp.tensor_type)
        total = sum(resp.tensor_sizes)
        if resp.response_type == ResponseType.ALLGATHER:
            try:
                entry = self.tensor_queue.get_tensor_entry(
                    resp.tensor_names[0])
                shape = getattr(entry.tensor, "shape", ())
                rest = 1
                for d in shape[1:]:
                    rest *= int(d)
            except KeyError:   # defensive: see docstring
                rest = 1
            return total * rest * esz
        return total * esz

    def fuse_responses(self, responses: list[Response]) -> list[Response]:
        """Greedy fusion with look-ahead: merge compatible
        allreduce/adasum/allgather responses until the fusion-buffer
        threshold is reached.  Later compatible responses may be pulled
        forward past incompatible ones — legal because the merged order
        is identical on all ranks.  A fused allgather response keeps one
        world_size block of per-rank first dims per entry in
        tensor_sizes (reference: message.cc:380-388
        Response::add_allgather_response)."""
        threshold = self.fusion_threshold_bytes()
        if threshold <= 0:
            return list(responses)
        fusable = {ResponseType.ALLREDUCE, ResponseType.ADASUM,
                   ResponseType.ALLGATHER}
        out: list[Response] = []
        pending = list(responses)
        i = 0
        while i < len(pending):
            resp = pending[i]
            i += 1
            if resp.response_type not in fusable or not resp.tensor_sizes:
                out.append(resp)
                continue
            if self.disable_group_fusion and getattr(resp, "grouped", False):
                out.append(resp)
                continue
            acc_bytes = self._response_payload_bytes(resp)
            if acc_bytes >= threshold:
                out.append(resp)
                continue
            j = i
            while j < len(pending) and acc_bytes < threshold:
                cand = pending[j]
                if (cand.response_type == resp.response_type and
                        cand.tensor_type == resp.tensor_type and
                        cand.devices == resp.devices and
                        cand.prescale_factor == resp.prescale_factor and
                        cand.postscale_factor == resp.postscale_factor and
                        cand.codec == resp.codec and
                        cand.codec_block_size == resp.codec_block_size and
                        cand.tensor_sizes and
                        not (self.disable_group_fusion and
                             getattr(cand, "grouped", False))):
                    cand_bytes = self._response_payload_bytes(cand)
                    if acc_bytes + cand_bytes <= threshold:
                        resp.tensor_names.extend(cand.tensor_names)
                        resp.tensor_sizes.extend(cand.tensor_sizes)
                        acc_bytes += cand_bytes
                        pending.pop(j)
                        continue
                j += 1
            out.append(resp)
        return out

    # ------------------------------------------------------------------
    def reset(self) -> None:
        self._message_table.clear()
        self._arrival_counter = 0
        self.joined_ranks.clear()
        self.last_joined_rank = -1
        self._local_hits.clear()
        self._last_request_params.clear()
        self.response_cache.clear()
        self.fingerprint.reset()
        self._tm_cycles = 0
        self._tm_cycle_ms = 0.0
        self._tm_sync_wait_ms = 0.0
        self._gather_arrivals.clear()
        self._trace_cycle = 0
