"""Pending-tensor table + request queue shared with the background thread.

Reference: horovod/common/tensor_queue.{cc,h}:28-65.  Semantics preserved:
duplicate tensor names are rejected while an op is in flight
(DUPLICATE_NAME_ERROR, common.h:169-172), and `finalize` fails every pending
entry with ABORTED at shutdown so callers never hang
(reference: operations.cc:571 FinalizeTensorQueue).
"""
from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Callable

from .message import Request
from .status import Status

DUPLICATE_NAME_ERROR = (
    "Requested to collect a tensor with the same name as another tensor that "
    "is currently being processed. If you want to request another tensor, use "
    "a different tensor name.")


@dataclass
class TensorTableEntry:
    """One queued collective operand (reference: common.h:252-281)."""
    tensor_name: str
    tensor: Any = None                     # numpy/jax array payload
    output: Any = None                     # filled by the backend
    root_rank: int = -1
    device: int = -1
    callback: Callable[[Status], None] | None = None
    # Alltoall split sizes along dim 0 (reference: common.h splits field).
    splits: list[int] = field(default_factory=list)
    received_splits: list[int] = field(default_factory=list)
    context: Any = None                    # framework op context (allocator)
    # Cross-rank trace id ("cycle.seq") of the response this entry rode,
    # stamped at pop by core so Timeline sub-activity spans and the
    # flight recorder can correlate one collective across ranks
    # (telemetry/trace.py); None until dispatched.
    trace: str | None = None
    # Absolute monotonic deadline propagated from the enqueuing thread
    # (resilience.deadline_scope — serving per-request SLOs); the
    # dispatch thread re-raises it through op_scope so transport waits
    # of this op are bounded by the SLO, not the full fault window.
    deadline: float | None = None

    def finish(self, status: Status) -> None:
        cb, self.callback = self.callback, None
        if cb is not None:
            cb(status)


class TensorQueue:
    def __init__(self) -> None:
        self._mutex = threading.Lock()
        self._table: dict[str, TensorTableEntry] = {}
        self._queue: list[Request] = []
        self._finalized = False
        # Pulses on enqueue so the background loop can wake immediately
        # instead of finishing its cycle sleep (single-op latency); the
        # loop still applies a short batching grace so gradient bursts
        # keep fusing (the reason the reference holds a fixed cadence).
        self._work = threading.Event()

    def add_to_tensor_queue(self, entry: TensorTableEntry, request: Request) -> Status:
        return self.add_to_tensor_queue_multi([entry], [request])

    def add_to_tensor_queue_multi(
            self, entries: list[TensorTableEntry],
            requests: list[Request]) -> Status:
        with self._mutex:
            if self._finalized:
                return Status.aborted("Horovod has been shut down.")
            for e in entries:
                if e.tensor_name in self._table:
                    return Status.invalid_argument(DUPLICATE_NAME_ERROR)
            for e, r in zip(entries, requests):
                self._table[e.tensor_name] = e
                self._queue.append(r)
            self._work.set()
        return Status.ok()

    def pending_names(self) -> list[str]:
        """Names of every entry still in the tensor table — the set a
        poison ERROR response must cover so no local waiter is left
        hanging when the world aborts (resilience/)."""
        with self._mutex:
            return list(self._table)

    def wait_for_work(self, timeout: float) -> bool:
        """Block up to ``timeout`` seconds for an enqueue pulse; returns
        True if work arrived.  Resubmissions via push_back_to_queue do
        NOT pulse — they are next-cycle work by design."""
        if timeout <= 0:
            return self._work.is_set()
        fired = self._work.wait(timeout)
        self._work.clear()
        return fired

    def pop_messages_from_queue(self) -> list[Request]:
        with self._mutex:
            msgs, self._queue = self._queue, []
            return msgs

    def get_tensor_entry(self, name: str) -> TensorTableEntry:
        with self._mutex:
            return self._table[name]

    def has_tensor_entry(self, name: str) -> bool:
        with self._mutex:
            return name in self._table

    def get_tensor_entries(self, names: list[str]) -> list[TensorTableEntry]:
        """Remove and return entries for a finalized response."""
        with self._mutex:
            return [self._table.pop(n) for n in names]

    def pop_tensor_entry(self, name: str) -> TensorTableEntry:
        with self._mutex:
            return self._table.pop(name)

    def push_back_to_queue(self, request: Request) -> None:
        with self._mutex:
            self._queue.append(request)

    def remove_joined_tensor(self, name: str) -> None:
        with self._mutex:
            self._table.pop(name, None)

    def size(self) -> int:
        with self._mutex:
            return len(self._table)

    def finalize(self) -> None:
        """Abort everything still pending (reference: tensor_queue.cc
        FinalizeTensorQueue)."""
        with self._mutex:
            self._finalized = True
            entries = list(self._table.values())
            self._table.clear()
            self._queue.clear()
        aborted = Status.aborted("Horovod has been shut down.")
        for e in entries:
            e.finish(aborted)

    def reset(self) -> None:
        with self._mutex:
            self._finalized = False
            self._table.clear()
            self._queue.clear()
