"""Explicit tensor-group registry for grouped collectives.

Reference: horovod/common/group_table.{cc,h}.  A grouped allreduce registers
its member tensor names under one group id; the controller only marks the
group ready when *all* members are ready on *all* ranks, and fuses the group
as a unit (or not at all when group fusion is disabled,
reference: controller.cc:199-223,311-357).
"""
from __future__ import annotations

import threading


class GroupTable:
    def __init__(self) -> None:
        self._mutex = threading.Lock()
        self._next_id = 0
        self._group_ids: dict[str, int] = {}        # tensor name -> group id
        self._groups: dict[int, list[str]] = {}     # group id -> member names

    def register_group(self, tensor_names: list[str]) -> int:
        with self._mutex:
            gid = self._next_id
            self._next_id += 1
            self._groups[gid] = list(tensor_names)
            for name in tensor_names:
                self._group_ids[name] = gid
            return gid

    def get_group_id(self, tensor_name: str) -> int:
        with self._mutex:
            return self._group_ids.get(tensor_name, -1)

    def get_group_tensor_names(self, group_id: int) -> list[str]:
        with self._mutex:
            return list(self._groups.get(group_id, []))

    def deregister_groups(self, finished_names: list[str]) -> None:
        with self._mutex:
            gids = {self._group_ids.get(n, -1) for n in finished_names}
            gids.discard(-1)
            for gid in gids:
                for name in self._groups.pop(gid, []):
                    self._group_ids.pop(name, None)

    def empty(self) -> bool:
        with self._mutex:
            return not self._groups
