"""Centralised knob registry, env-var driven like the reference.

The reference scatters ~30 `HOROVOD_*` env knobs across
horovod/common/common.h:66-96 and parses them ad hoc inside
BackgroundThreadLoop (operations.cc:395-540) + utils/env_parser.cc.  Here
every knob is declared once with its type, default and documentation, and the
same `HOROVOD_*` names are honoured so existing launch scripts keep working.
The runtime autotuner (parameter_manager) may override a subset at runtime.
"""
from __future__ import annotations

import dataclasses
import os
from typing import Any, Callable


def _parse_bool(v: str) -> bool:
    return v.strip().lower() in ("1", "true", "yes", "on")


@dataclasses.dataclass
class Knob:
    name: str            # env var name (HOROVOD_* for compatibility)
    default: Any
    parser: Callable[[str], Any]
    doc: str = ""

    def get(self) -> Any:
        raw = os.environ.get(self.name)
        if raw is None or raw == "":
            return self.default
        try:
            return self.parser(raw)
        except (ValueError, TypeError):
            return self.default


_REGISTRY: dict[str, Knob] = {}


def register(name: str, default: Any, parser: Callable[[str], Any], doc: str = "") -> Knob:
    knob = Knob(name, default, parser, doc)
    _REGISTRY[name] = knob
    return knob


def get(name: str) -> Any:
    return _REGISTRY[name].get()


def all_knobs() -> dict[str, Knob]:
    return dict(_REGISTRY)


def _knob_type_name(parser: Callable[[str], Any]) -> str:
    return {"_parse_bool": "bool", "parse_tristate": "tristate"}.get(
        getattr(parser, "__name__", ""),
        getattr(parser, "__name__", "str"))


def _knob_default_repr(default: Any) -> str:
    if isinstance(default, bool):
        return "1" if default else "0"
    if default == "" or default is None:
        return "*(unset)*"
    return f"`{default}`"


def configuration_markdown() -> str:
    """The generated knob table: one row per registered ``HOROVOD_*``
    knob (name, type, default, doc).  ``python -m
    horovod_tpu.analysis.lint --knobs`` prints it, docs/configuration.md
    embeds it, and CI asserts the two are byte-identical — an
    undocumented knob cannot exist, and hvdflow's HVD604 flags any raw
    environment read of a name missing from this registry."""
    lines = [
        "# Configuration — the typed `HOROVOD_*` knob registry",
        "",
        "<!-- GENERATED FILE — do not edit by hand.  Regenerate with",
        "     `python -m horovod_tpu.analysis.lint --knobs >"
        " docs/configuration.md`;",
        "     tests/test_lint_clean.py asserts this file matches the",
        "     registry in horovod_tpu/common/config.py. -->",
        "",
        f"Every knob is declared once in `horovod_tpu/common/config.py`"
        f" with its type,",
        "default and doc line; raw `os.environ` reads of `HOROVOD_*`"
        " names outside the",
        "registry are flagged by hvdflow rule HVD604"
        " (docs/analysis.md).",
        "",
        f"{len(_REGISTRY)} knobs:",
        "",
        "| knob | type | default | description |",
        "|---|---|---|---|",
    ]
    for name in sorted(_REGISTRY):
        k = _REGISTRY[name]
        doc = " ".join(k.doc.split())
        lines.append(f"| `{name}` | {_knob_type_name(k.parser)} | "
                     f"{_knob_default_repr(k.default)} | {doc} |")
    lines.append("")
    return "\n".join(lines)


# --- Core cycle / fusion knobs (reference: common/common.h:66-96) -----------
FUSION_THRESHOLD = register(
    "HOROVOD_FUSION_THRESHOLD", 64 * 1024 * 1024, int,
    "Tensor-fusion buffer threshold in bytes (0 disables fusion).")
CYCLE_TIME = register(
    "HOROVOD_CYCLE_TIME", 1.0, float,
    "Background-loop cycle time in milliseconds.")
CACHE_CAPACITY = register(
    "HOROVOD_CACHE_CAPACITY", 1024, int,
    "Response-cache capacity (0 disables caching).")
HIERARCHICAL_ALLREDUCE = register(
    "HOROVOD_HIERARCHICAL_ALLREDUCE", False, _parse_bool,
    "Two-level reduce: reduce-scatter over ICI, cross-reduce over DCN, "
    "all-gather over ICI.")
HIERARCHICAL_ALLGATHER = register(
    "HOROVOD_HIERARCHICAL_ALLGATHER", False, _parse_bool,
    "Two-level allgather over (ICI, DCN) axes.")
SHM_OPERATIONS = register(
    "HOROVOD_SHM_OPERATIONS", "auto", str,
    "Same-host shared-memory data plane for eager allreduce: 1=require, "
    "0=disable, auto=use when every rank shares one memory domain.")
SHM_CAPACITY = register(
    "HOROVOD_SHM_CAPACITY", 0, int,
    "Per-rank shm region bytes (0 = max(fusion threshold, 64MB)); "
    "payloads above it fall through to the TCP plane.")
SEGMENT_BYTES = register(
    "HOROVOD_SEGMENT_BYTES", 256 * 1024, int,
    "TCP ring pipeline segment: the receiver consumes each ring chunk in "
    "segments of this many bytes, accumulating segment k while the NIC "
    "streams segment k+1 (comm/compute overlap; bit-identical numerics). "
    "0 disables segmentation (one monolithic receive+add per chunk).")
TOPOLOGY = register(
    "HOROVOD_TOPOLOGY", "", str,
    "Physical layout declaration for topology-aware collectives: flat "
    "(layout-oblivious) | host (two-level host x slot; rings keep "
    "intra-host peers adjacent) | torus:RxC (R x C grid, rank = "
    "row*C + col; rings walk grid neighbors and the two-phase torus "
    "allreduce becomes eligible).  Empty = auto: host when the env "
    "describes a homogeneous two-level layout, else flat.  Must be "
    "launcher-uniform across ranks.")
HOST_IDS = register(
    "HOROVOD_HOST_IDS", "", str,
    "World-wide rank-to-host-index map as comma-separated ints "
    "(\"0,0,1,1\"), set by the launcher from the slot layout so topology "
    "resolution can group ring orders by host even when the layout is "
    "not homogeneous host-major (elastic re-assignments, uneven slots "
    "per host).  Empty = derive from local/cross sizes.  Ignored unless "
    "its length equals the world size.  Launcher-uniform across ranks.")
ALGO = register(
    "HOROVOD_ALGO", "auto", str,
    "Eager-plane allreduce algorithm: auto (tree under "
    "HOROVOD_TREE_THRESHOLD_BYTES, torus two-phase on a declared torus, "
    "segmented ring otherwise) | ring | tree (binomial gather-to-root + "
    "broadcast, O(log N) latency) | rhd (recursive halving-doubling; "
    "power-of-two worlds, else tree) | torus.  Launcher-uniform; the "
    "autotuner can retune it at runtime (ResponseList.tuned_algo).")
TREE_THRESHOLD_BYTES = register(
    "HOROVOD_TREE_THRESHOLD_BYTES", 64 * 1024, int,
    "Payloads at or below this many wire bytes take the O(log N) tree "
    "allreduce instead of the O(N)-step ring under HOROVOD_ALGO=auto "
    "(latency-bound small tensors; the ring stays bandwidth-optimal "
    "above it).  0 disables the small-tensor path; the autotuner sweeps "
    "it (ResponseList.tuned_tree_threshold).")
BATCH_D2D_MEMCOPIES = register(
    "HOROVOD_BATCH_D2D_MEMCOPIES", True, _parse_bool,
    "Fuse gather/scatter staging copies into batched device ops.")
DISABLE_GROUP_FUSION = register(
    "HOROVOD_DISABLE_GROUP_FUSION", False, _parse_bool,
    "Disable fusion across explicitly grouped collectives.")
ELASTIC = register(
    "HOROVOD_ELASTIC", False, _parse_bool,
    "Enable elastic (fault tolerant / autoscaling) mode.")

# --- Wire compression (compress/ subsystem; EQuARX-style, PAPERS.md) --------
COMPRESSION = register(
    "HOROVOD_COMPRESSION", "none", str,
    "Default wire codec for eager allreduces: none | fp16 | bf16 | int8 "
    "| uint4.  Quantized codecs apply blockwise scale+zero-point "
    "compression to floating tensors; integer tensors always ride "
    "uncompressed.  Per-call `codec=`/`compression=` arguments override.")
COMPRESSION_BLOCK_SIZE = register(
    "HOROVOD_COMPRESSION_BLOCK_SIZE", 256, int,
    "Elements per quantization block for the int8/uint4 codecs (must be "
    "even for uint4).  Smaller blocks: tighter error bound, more scale "
    "metadata on the wire (8 bytes/block).")
AUTOTUNE_COMPRESSION = register(
    "HOROVOD_AUTOTUNE_COMPRESSION", False, _parse_bool,
    "Let the autotuner sweep wire codecs (none/fp16/int8) by measured "
    "allreduce throughput and broadcast the winner to every rank.")
FUSED_KERNELS = register(
    "HOROVOD_FUSED_KERNELS", True, _parse_bool,
    "Single-pass fused codec kernels on the quantized/cast collective "
    "legs (compress/fused.py): dequantize+accumulate straight off the "
    "wire, requantize straight into a persistent wire image.  Bitwise "
    "identical to the reference chain; 0 restores the per-chunk "
    "dequant/requant path (the fused-vs-reference A/B baseline).  Must "
    "be set identically on every rank; the autotuner can retune it at "
    "runtime (ResponseList.tuned_fused).")

# --- Autotune (reference: common/parameter_manager.cc) ----------------------
AUTOTUNE = register(
    "HOROVOD_AUTOTUNE", False, _parse_bool,
    "Enable Bayesian autotuning of fusion threshold and cycle time.")
AUTOTUNE_LOG = register(
    "HOROVOD_AUTOTUNE_LOG", "", str,
    "CSV file to log autotune samples to.")
AUTOTUNE_WARMUP_SAMPLES = register(
    "HOROVOD_AUTOTUNE_WARMUP_SAMPLES", 3, int,
    "Discarded warmup samples per autotune step.")
AUTOTUNE_STEPS_PER_SAMPLE = register(
    "HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE", 10, int,
    "Training steps scored per autotune sample.")
AUTOTUNE_BAYES_OPT_MAX_SAMPLES = register(
    "HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES", 20, int,
    "Max Bayesian-optimization samples before fixing parameters.")
AUTOTUNE_GAUSSIAN_PROCESS_NOISE = register(
    "HOROVOD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE", 0.8, float,
    "GP observation-noise hyperparameter (alpha).")

# --- Timeline (reference: common/timeline.cc) -------------------------------
TIMELINE = register(
    "HOROVOD_TIMELINE", "", str,
    "Path for the Chrome-trace timeline JSON ('DYNAMIC' = start stopped).")
TIMELINE_MARK_CYCLES = register(
    "HOROVOD_TIMELINE_MARK_CYCLES", False, _parse_bool,
    "Mark background-loop cycles in the timeline.")

# --- Telemetry (telemetry/ subsystem; docs/observability.md) ----------------
METRICS = register(
    "HOROVOD_METRICS", False, _parse_bool,
    "Per-rank metrics registry + cross-rank straggler aggregation "
    "(on|off).  Off (the default) keeps every hot path free of new "
    "locks and syscalls: all instrumentation resolves to shared no-op "
    "metrics.")
METRICS_PORT = register(
    "HOROVOD_METRICS_PORT", 0, int,
    "Base port for the Prometheus text exposition endpoint; rank r "
    "serves on port+r (ephemeral fallback if taken).  0 disables the "
    "HTTP server (the registry still records).")
METRICS_FILE = register(
    "HOROVOD_METRICS_FILE", "", str,
    "Path for the shutdown JSON metrics dump; '{rank}' substitutes the "
    "rank, otherwise '.r<rank>' is inserted before the extension.  "
    "Empty disables the dump.  Summarize with "
    "python -m horovod_tpu.telemetry.report.")
METRICS_BIND = register(
    "HOROVOD_METRICS_BIND", "127.0.0.1", str,
    "Bind address for the Prometheus exposition endpoint.  Defaults to "
    "localhost: metrics name tensors, hosts and failure details, so "
    "off-host exposure must be an explicit decision ('' or 0.0.0.0 "
    "binds all interfaces for real scrape deployments).")
METRICS_WINDOW = register(
    "HOROVOD_METRICS_WINDOW", 32, int,
    "Negotiated tensors per straggler-aggregation window: the "
    "coordinator publishes min/mean/max/p99 cross-rank arrival lag and "
    "names the slowest rank once per window.")
STRAGGLER_THRESHOLD_MS = register(
    "HOROVOD_STRAGGLER_THRESHOLD_MS", 5.0, float,
    "Mean arrival lag (ms behind the fastest rank, per window) above "
    "which the coordinator logs a structured straggler warning and sets "
    "the straggler-rank gauge.")

# --- perfscope roofline accounting (telemetry/perfmodel.py; ISSUE 19) -------
PERF_PEAK_MBPS = register(
    "HOROVOD_PERF_PEAK_MBPS", 0.0, float,
    "Peak per-link bus bandwidth (MB/s) the perfscope roofline divides "
    "measured busbw by (docs/observability.md).  0 = self-calibrate: "
    "the best measured (plane, algo, codec, size-bucket) cell in the "
    "ledger window IS the roofline, so efficiencies answer 'how far "
    "below the best this fabric demonstrated' without a link spec.")
PERF_PEAK_FLOPS = register(
    "HOROVOD_PERF_PEAK_FLOPS", 0.0, float,
    "Peak per-chip dense FLOP/s the MFU ledger divides by.  0 = the "
    "published per-device_kind table (telemetry/perfmodel.py), with a "
    "nominal 1e12 for unknown kinds (CPU dev boxes) so the MFU "
    "trajectory stays populated and self-comparable.")
PERF_TOLERANCE_PCT = register(
    "HOROVOD_PERF_TOLERANCE_PCT", 10.0, float,
    "Regression-gate tolerance: telemetry.perfcheck fails (exit 1, "
    "structured finding) when a (plane, op, size-bucket) busbw cell or "
    "the MFU drops more than this percentage below the baseline "
    "ledger.")
PERF_MIN_SAMPLES = register(
    "HOROVOD_PERF_MIN_SAMPLES", 1, int,
    "Observations a (plane, op, codec, algo, size-bucket) cell needs "
    "before the perf ledger includes it (noise floor for the busbw "
    "table and the perfcheck gate).")

# --- Flight recorder (telemetry/flight.py; docs/observability.md) -----------
FLIGHT = register(
    "HOROVOD_FLIGHT", True, _parse_bool,
    "Always-on flight recorder: a lock-light bounded ring of recent "
    "trace events per rank (enqueue, dispatch, completion, failure "
    "conversions), dumped as rank-stamped JSON when a structured "
    "failure fires (RanksFailedError, fingerprint divergence, deadline "
    "poison, SIGTERM) — trace evidence without HOROVOD_TIMELINE.  "
    "0 restores the exact zero-overhead posture: a shared no-op "
    "recorder, no ring, no signal handler, no threads either way.")
FLIGHT_EVENTS = register(
    "HOROVOD_FLIGHT_EVENTS", 256, int,
    "Ring capacity of the flight recorder: the last N trace events per "
    "rank survive into a failure dump.")
FLIGHT_FILE = register(
    "HOROVOD_FLIGHT_FILE", "horovod_flight.json", str,
    "Path of the flight-recorder failure dump; '{rank}' substitutes, "
    "otherwise '.r<rank>' is inserted before the extension (the "
    "HOROVOD_METRICS_FILE convention).  Written only when a structured "
    "failure fires.")

# --- hvdsan runtime witness (analysis/hvdsan/; docs/analysis.md) ------------
# NOTE: san.py reads the raw environment directly (it must run at
# package import, before this registry is touched); the knobs are
# registered here so `all_knobs()` documents them.
SAN = register(
    "HOROVOD_SAN", False, _parse_bool,
    "hvdsan runtime lock-order witness: wrap every package "
    "threading.Lock/RLock/Condition in a recording proxy, record "
    "per-thread acquisition-order edges (first observations also land "
    "in the flight-recorder ring), and dump the observed lock-order "
    "graph as rank-stamped JSON at interpreter exit.  CI diffs it "
    "against the static graph (python -m horovod_tpu.analysis.hvdsan): "
    "observed edges missing statically fail the build.  Off (the "
    "default) patches nothing — zero overhead.")
SAN_FILE = register(
    "HOROVOD_SAN_FILE", "hvdsan_witness.json", str,
    "Path of the hvdsan witness dump; '{rank}' substitutes, otherwise "
    "'.r<rank>' is inserted before the extension (the "
    "HOROVOD_METRICS_FILE convention).")

# --- hvdlife runtime census witness (analysis/hvdlife/; docs/analysis.md) ---
LIFE_CENSUS = register(
    "HOROVOD_LIFE_CENSUS", False, _parse_bool,
    "hvdlife runtime resource census: snapshot the process's live "
    "threads (normalized names), fds (sockets / shm / pipes / files) "
    "and /dev/shm mmap regions around every world transition "
    "(core.init, reinit_world) and dump the labeled snapshots as "
    "rank-stamped JSON at exit.  CI diffs an elastic cycle's "
    "return-to-baseline snapshot against its baseline — the dynamic "
    "twin of the HVD704 epoch-scoped-leak rule.  Off (the default) "
    "takes no snapshots and reads no /proc files — zero overhead.")
LIFE_CENSUS_FILE = register(
    "HOROVOD_LIFE_CENSUS_FILE", "hvdlife_census.json", str,
    "Path of the hvdlife census dump; '{rank}' substitutes, otherwise "
    "'.r<rank>' is inserted before the extension (the "
    "HOROVOD_METRICS_FILE convention).")

# --- Resilience (resilience/ subsystem; docs/resilience.md) -----------------
FAULT_TOLERANCE = register(
    "HOROVOD_FAULT_TOLERANCE", False, _parse_bool,
    "Failure detection + deadline-bounded collectives: heartbeats over "
    "the rendezvous liveness table, socket-level deadlines on every "
    "blocking collective wait, and structured RanksFailedError instead "
    "of a hang when a peer dies or wedges.  Off (the default) keeps "
    "every hot path byte-identical to the pre-resilience behavior: no "
    "monitor thread, no socket timeouts, no per-recv branches beyond "
    "one None test.")
FAULT_TIMEOUT = register(
    "HOROVOD_FAULT_TIMEOUT", 30.0, float,
    "Failure-detection window in seconds: a peer whose heartbeat stops "
    "advancing for this long is declared failed, and a blocking "
    "collective wait that exceeds it raises RanksFailedError naming the "
    "unresponsive peer.  Also the default per-op deadline of the "
    "ResilienceContext.")
ON_FAILURE = register(
    "HOROVOD_ON_FAILURE", "raise", str,
    "Recovery policy applied by resilience.run_with_recovery when a "
    "collective raises RanksFailedError: raise (safe default) | retry "
    "(re-run an idempotent eager collective with exponential backoff "
    "over rebuilt channels, only while every rank is still live) | "
    "shrink (hand the surviving-rank set to the elastic driver for a "
    "world-resize and blacklist the dead host).")
FAULT_RETRIES = register(
    "HOROVOD_FAULT_RETRIES", 3, int,
    "Maximum retry attempts under HOROVOD_ON_FAILURE=retry.")
FAULT_BACKOFF_SECONDS = register(
    "HOROVOD_FAULT_BACKOFF_SECONDS", 0.5, float,
    "Base of the exponential retry backoff (attempt k sleeps "
    "base * 2**k seconds).")
CHAOS = register(
    "HOROVOD_CHAOS", "", str,
    "Deterministic fault-injection spec (resilience/chaos.py): "
    "';'-separated actions 'kind:key=val,...' — kill/freeze/fail at a "
    "global collective index, delay/drop/dup a specific peer-channel "
    "send.  Empty (the default) installs nothing.  See "
    "docs/resilience.md for the grammar.")

# --- Elastic state streaming (statesync/ subsystem; docs/statesync.md) ------
STATESYNC = register(
    "HOROVOD_STATESYNC", False, _parse_bool,
    "Peer-to-peer live state streaming + the grow side of elasticity: "
    "a per-step membership check (one tiny symmetric collective) lets "
    "incumbents admit a joining rank at a step boundary, donate a "
    "copy-on-write state snapshot from live peers (no checkpoint file, "
    "no training pause), and rebuild the world one rank larger once the "
    "joiner's streamed state digest-verifies.  Off (the default) adds "
    "no collectives and no threads.")
STATESYNC_CHUNK_BYTES = register(
    "HOROVOD_STATESYNC_CHUNK_BYTES", 1 << 20, int,
    "Chunk size of one streamed state frame (donor->joiner).  Chunks "
    "are independently addressed (offset, length, crc), so a transfer "
    "resumes at chunk granularity when a donor dies mid-stream.")
STATESYNC_POLL_SECONDS = register(
    "HOROVOD_STATESYNC_POLL_SECONDS", 0.1, float,
    "Interval of the statesync watcher thread's rendezvous-KV polls "
    "for join announcements / joiner-ready marks.")
STATESYNC_TIMEOUT_SECONDS = register(
    "HOROVOD_STATESYNC_TIMEOUT_SECONDS", 60.0, float,
    "Deadline for one streaming round (mesh formation + transfer + "
    "verify) on both the donor and joiner side; a round that exceeds "
    "it is abandoned (the joiner re-announces, donors stand down).")
STATESYNC_WORLD = register(
    "HOROVOD_STATESYNC_WORLD", "world", str,
    "Name of this process's world-membership record in the coordinator "
    "KV (scope 'statesync').  A fleet deployment runs TWO live worlds "
    "— training and serving — against one coordinator "
    "(fleet/controller.py), so each names its record distinctly "
    "('train' / 'serve') and a joiner targets the right one; single-"
    "world deployments keep the default.")
PREEMPT_GRACE_SECONDS = register(
    "HOROVOD_PREEMPT_GRACE_S", 0.0, float,
    "Preemption-notice grace window: > 0 installs a SIGTERM handler "
    "that lets the rank finish its in-flight step, announce an orderly "
    "departure through the statesync membership check (survivors "
    "shrink proactively — no RanksFailedError, no heartbeat deadline), "
    "write its bye| liveness stamp and exit 0.  If no step boundary "
    "arrives within the window, a backstop stamps bye|, dumps the "
    "flight recorder and re-delivers the default SIGTERM disposition.  "
    "0 (the default) keeps the stock SIGTERM behavior.")
PREEMPT_DONATE = register(
    "HOROVOD_PREEMPT_DONATE", True, _parse_bool,
    "On an orderly preemption departure, fast-donate this rank's "
    "ring-sharded (ZeRO) optimizer-state shard to the rendezvous KV so "
    "survivors can re-shard without the departed rank (only when the "
    "training loop registered a shard provider; see docs/statesync.md).")

# --- Autoscale policy loop (statesync/autoscale.py) -------------------------
AUTOSCALE = register(
    "HOROVOD_AUTOSCALE", False, _parse_bool,
    "Rank-0 autoscale controller thread: watches the straggler-lag / "
    "queue-depth gauges (telemetry/) and the serving shed rate, and "
    "drives the elastic driver's target world size up/down with "
    "hysteresis.  Decisions are metrics + flight-recorder events.")
AUTOSCALE_INTERVAL_SECONDS = register(
    "HOROVOD_AUTOSCALE_INTERVAL_S", 5.0, float,
    "Observation interval of the autoscale controller loop.")
AUTOSCALE_UP_SHED_RATE = register(
    "HOROVOD_AUTOSCALE_UP_SHED_RATE", 0.05, float,
    "Scale up when the serving shed rate over one interval exceeds "
    "this fraction (capacity, not deadline, is the binding constraint).")
AUTOSCALE_UP_QUEUE_FRACTION = register(
    "HOROVOD_AUTOSCALE_UP_QUEUE_FRACTION", 0.5, float,
    "Scale up when queue depth exceeds this fraction of "
    "HOROVOD_SERVE_QUEUE_DEPTH (or the configured depth limit).")
AUTOSCALE_DOWN_LAG_MS = register(
    "HOROVOD_AUTOSCALE_DOWN_LAG_MS", 50.0, float,
    "Scale down when the coordinator straggler lag exceeds this many "
    "ms while the queue is idle and nothing is shed: one dragging rank "
    "costs more step time than its share of the work is worth.")
AUTOSCALE_HYSTERESIS_ROUNDS = register(
    "HOROVOD_AUTOSCALE_HYSTERESIS_ROUNDS", 3, int,
    "Consecutive intervals a scale condition must hold before a "
    "decision fires (and the cooldown after each decision), so one "
    "burst never flaps the world size.")

# --- Fleet controller (fleet/ subsystem; docs/fleet.md) ---------------------
FLEET = register(
    "HOROVOD_FLEET", False, _parse_bool,
    "Unified train+serve fleet controller: a rank-0-hosted, "
    "coordinator-KV-backed loop that arbitrates one shared host pool "
    "between a training world and a serving world — traffic-driven "
    "rank rebalancing plus continuous weight deployment.")
FLEET_INTERVAL_S = register(
    "HOROVOD_FLEET_INTERVAL_S", 2.0, float,
    "Observation interval of the fleet controller loop (gauge poll + "
    "policy tick + migration-journal advance).")
FLEET_PUBLISH_STEPS = register(
    "HOROVOD_FLEET_PUBLISH_STEPS", 50, int,
    "The trainer publishes a version-stamped param snapshot to the "
    "fleet KV scope every this many optimizer steps (0 disables "
    "continuous weight deployment).")
FLEET_PUBLISH_KEEP = register(
    "HOROVOD_FLEET_PUBLISH_KEEP", 2, int,
    "Published snapshot versions retained in the KV before the "
    "publisher garbage-collects the oldest (>= 2, so a puller mid-"
    "fetch never races the GC of the version it is verifying).")
FLEET_CHUNK_BYTES = register(
    "HOROVOD_FLEET_CHUNK_BYTES", 1 << 20, int,
    "Shard size of one published-snapshot KV record; serving pullers "
    "fetch shards independently and digest-verify the reassembly.")
FLEET_HYSTERESIS_ROUNDS = register(
    "HOROVOD_FLEET_HYSTERESIS_ROUNDS", 3, int,
    "Consecutive controller intervals a rebalance condition must hold "
    "before a migration fires, so one traffic burst never flaps ranks "
    "between the worlds.")
FLEET_COOLDOWN_ROUNDS = register(
    "HOROVOD_FLEET_COOLDOWN_ROUNDS", 5, int,
    "Controller intervals the policy stays silent after each "
    "migration decision (on top of hysteresis): a move must settle — "
    "join complete, gauges refreshed — before the next is considered.")
FLEET_UP_SHED_RATE = register(
    "HOROVOD_FLEET_UP_SHED_RATE", 0.05, float,
    "Move a rank train->serve when the serving shed rate over one "
    "interval exceeds this fraction (serving capacity, not deadline, "
    "is the binding constraint).")
FLEET_UP_QUEUE_FRACTION = register(
    "HOROVOD_FLEET_UP_QUEUE_FRACTION", 0.5, float,
    "Move a rank train->serve when serving queue depth exceeds this "
    "fraction of the configured depth limit.")
FLEET_IDLE_QUEUE_FRACTION = register(
    "HOROVOD_FLEET_IDLE_QUEUE_FRACTION", 0.05, float,
    "Move a rank serve->train when serving queue depth stays under "
    "this fraction (and nothing is shed) while the trainer drags: the "
    "serving world is over-provisioned.")
FLEET_TRAIN_LAG_MS = register(
    "HOROVOD_FLEET_TRAIN_LAG_MS", 50.0, float,
    "Trainer straggler-lag threshold (ms) that, combined with an idle "
    "serving queue, marks the trainer as the starved world.")
FLEET_MIN_TRAIN = register(
    "HOROVOD_FLEET_MIN_TRAIN", 2, int,
    "Floor on the training world size: the policy never proposes a "
    "migration that would shrink training below this many ranks.")
FLEET_MIN_SERVE = register(
    "HOROVOD_FLEET_MIN_SERVE", 1, int,
    "Floor on the serving world size: the policy never proposes a "
    "migration that would shrink serving below this many ranks.")
FLEET_MIGRATE_TIMEOUT_S = register(
    "HOROVOD_FLEET_MIGRATE_TIMEOUT_S", 120.0, float,
    "Deadline for one journaled migration (depart directive written -> "
    "joined mark observed); a migration that exceeds it is marked "
    "aborted so a wedged mover never blocks the controller forever.")

# --- Fleet-scale harness (fleetsim/ subsystem; docs/fleetsim.md) ------------
FLEETSIM_RANKS = register(
    "HOROVOD_FLEETSIM_RANKS", 32, int,
    "Virtual ranks the fleetsim harness runs inside one process: each "
    "executes the real control-plane client, heartbeat monitor, and "
    "membership boundary exchange (compute is stubbed).")
FLEETSIM_STEPS = register(
    "HOROVOD_FLEETSIM_STEPS", 12, int,
    "Boundary exchanges (virtual training steps) one fleetsim episode "
    "runs before the orderly fleet-wide stop.")
FLEETSIM_STEP_MS = register(
    "HOROVOD_FLEETSIM_STEP_MS", 5.0, float,
    "Stubbed per-step compute delay of every virtual rank, ms (the "
    "model-compute stand-in between membership boundaries).")
FLEETSIM_HOST_GROUP = register(
    "HOROVOD_FLEETSIM_HOST_GROUP", 16, int,
    "Virtual ranks per simulated host: one host group shares a "
    "rendezvous client, batches its heartbeat stamps into a single "
    "PUT /.batch/ per window, and refreshes liveness from one scope "
    "dump instead of size-many gets.")
FLEETSIM_HEARTBEAT_S = register(
    "HOROVOD_FLEETSIM_HEARTBEAT_S", 1.0, float,
    "Heartbeat publish/poll interval of every virtual rank's monitor.")
FLEETSIM_FAULT_TIMEOUT_S = register(
    "HOROVOD_FLEETSIM_FAULT_TIMEOUT_S", 20.0, float,
    "Heartbeat staleness window before a virtual rank declares a peer "
    "failed (must exceed the control-plane failover window under "
    "coordkill chaos, or the whole fleet condemns itself).")
FLEETSIM_STRAGGLER_RANK = register(
    "HOROVOD_FLEETSIM_STRAGGLER_RANK", -1, int,
    "Launch id of one virtual rank made to drag every step "
    "(HOROVOD_FLEETSIM_STRAGGLER_MS extra delay); -1 disables.  "
    "Exercises the coordinator straggler-attribution path at fleet "
    "scale.")
FLEETSIM_STRAGGLER_MS = register(
    "HOROVOD_FLEETSIM_STRAGGLER_MS", 40.0, float,
    "Extra per-step delay of the designated straggler virtual rank.")
FLEETSIM_STEP_TIMEOUT_S = register(
    "HOROVOD_FLEETSIM_STEP_TIMEOUT_S", 60.0, float,
    "Bound on one boundary exchange: a virtual rank that cannot "
    "complete the membership allgather inside it counts a failed step "
    "and leaves the fleet (desync backstop, never silent hang).")
FLEETSIM_DUMP_DIR = register(
    "HOROVOD_FLEETSIM_DUMP_DIR", "", str,
    "Directory the episode's rank-stamped evidence lands in (flight "
    "ring, metrics snapshot, control-plane role probes, episode "
    "summary) — the operator console replays an episode from it.  "
    "Empty disables dumping.")
FLEETSIM_AUTOSCALE = register(
    "HOROVOD_FLEETSIM_AUTOSCALE", False, _parse_bool,
    "Drive the real autoscale policy from the harness's synthetic "
    "serving load: up-decisions admit joiner virtual ranks, "
    "down-decisions preempt the highest launch id (exercises "
    "autoscale oscillation against the live control plane).")

# --- Operator console (console/ subsystem; docs/observability.md) -----------
CONSOLE_REFRESH_S = register(
    "HOROVOD_CONSOLE_REFRESH_S", 2.0, float,
    "Delay between live-mode console frames (scrape mode).")
CONSOLE_TOPK = register(
    "HOROVOD_CONSOLE_TOPK", 8, int,
    "Rows per console section (top-K ranks, last-K membership events).")

# --- Inference serving (serving/ subsystem; docs/serving.md) ----------------
SERVE_MAX_BATCH = register(
    "HOROVOD_SERVE_MAX_BATCH", 8, int,
    "Decode slots per replica: the continuous batcher admits new "
    "requests into in-flight decode batches up to this many concurrent "
    "sequences per replica (the KV cache is allocated for exactly this "
    "batch).")
SERVE_TOKEN_BUDGET = register(
    "HOROVOD_SERVE_TOKEN_BUDGET", 256, int,
    "Per-replica token budget of one serve step: prefill tokens of "
    "newly admitted requests plus one decode token per active slot "
    "must fit; the batcher defers admissions that would exceed it "
    "(keeps step time — and therefore SLO math — predictable).")
SERVE_QUEUE_DEPTH = register(
    "HOROVOD_SERVE_QUEUE_DEPTH", 1024, int,
    "Front-end ingress queue bound; submissions beyond it are shed at "
    "the door (never silently buffered — an unbounded queue turns "
    "overload into unbounded latency, hvdlint HVD1006).")
SERVE_SLO_MS = register(
    "HOROVOD_SERVE_SLO_MS", 30000.0, float,
    "Default per-request SLO in ms, stamped as an absolute deadline at "
    "ingress; per-request slo_ms overrides.  Flows into "
    "resilience.context per-op deadlines (deadline_scope) and into "
    "admission control: a request that cannot finish inside it is shed "
    "at admission, never executed.")
SERVE_SHED_QUEUE_FRACTION = register(
    "HOROVOD_SERVE_SHED_QUEUE_FRACTION", 0.9, float,
    "Admission sheds new requests while the live queue-depth gauge "
    "exceeds this fraction of HOROVOD_SERVE_QUEUE_DEPTH (load-based "
    "shedding keyed off telemetry, not just deadline feasibility).")
SERVE_MAX_SEQ = register(
    "HOROVOD_SERVE_MAX_SEQ", 256, int,
    "KV-cache length per decode slot (prompt + generated tokens).")
SERVE_GROUP_SIZE = register(
    "HOROVOD_SERVE_GROUP_SIZE", 1, int,
    "Ranks per serving replica group: 1 = pure data-parallel (every "
    "rank an independent replica); N > 1 runs each group's members in "
    "lockstep on identical batch plans (the sharded-replica posture — "
    "model-parallel groups reuse parallel/ meshes inside the model).  "
    "Must divide the world size; falls back to 1 after an elastic "
    "shrink breaks divisibility.")
SERVE_PAGED = register(
    "HOROVOD_SERVE_PAGED", False, _parse_bool,
    "Paged KV cache (serving/kvpool.py): decode-slot KV state lives in "
    "fixed-size blocks drawn from a per-replica free-list pool instead "
    "of dense per-slot arrays, so concurrent-sequence count is bounded "
    "by live token residency (the pool), not the batch shape.  Enables "
    "prefix/prompt caching and copy-on-write block sharing.")
SERVE_BLOCK_TOKENS = register(
    "HOROVOD_SERVE_BLOCK_TOKENS", 16, int,
    "Tokens per KV block under HOROVOD_SERVE_PAGED: the paged "
    "allocator's unit of allocation, prefix-hash granularity (one FNV "
    "chain link per full block) and copy-on-write granularity.")
SERVE_POOL_BLOCKS = register(
    "HOROVOD_SERVE_POOL_BLOCKS", 0, int,
    "KV blocks in the per-replica paged pool (0 = auto: "
    "HOROVOD_SERVE_MAX_BATCH x ceil(max_seq / block_tokens), i.e. the "
    "same token memory the dense layout reserves).  The pool — not the "
    "slot count — bounds max concurrent sequences.")
SERVE_PAGED_SLOTS = register(
    "HOROVOD_SERVE_PAGED_SLOTS", 0, int,
    "Decode slots per replica under HOROVOD_SERVE_PAGED (0 = auto: "
    "2 x HOROVOD_SERVE_MAX_BATCH).  Slots beyond the dense batch are "
    "backed by the shared block pool, so short sequences pack more "
    "concurrency into the same KV memory; admission defers when the "
    "pool cannot cover a prompt's worst-case blocks.")
SERVE_MAX_DEFERRALS = register(
    "HOROVOD_SERVE_MAX_DEFERRALS", 8, int,
    "Steps a queued prompt may be deferred for budget/slot pressure "
    "before the batcher turns it urgent: an urgent prompt reserves the "
    "step's admission budget (nothing behind it is admitted) and "
    "bypasses the token budget for its own admission, so a stream of "
    "small prompts can never starve a large one indefinitely.")
SERVE_PREFILL_RANKS = register(
    "HOROVOD_SERVE_PREFILL_RANKS", 0, int,
    "Disaggregated prefill/decode: the highest N ranks of the serving "
    "world run prompt prefill only and stream finished KV blocks to "
    "the decode ranks over a dedicated PeerMesh (serving/kvstream.py, "
    "CRC'd addressed chunks), so long prompts never occupy a decode "
    "step.  0 = every rank prefills its own admissions (clamped so at "
    "least one decode rank remains).")
SERVE_KVSTREAM_CHUNK_BYTES = register(
    "HOROVOD_SERVE_KVSTREAM_CHUNK_BYTES", 1 << 18, int,
    "Chunk size of one prefill-to-decode KV-block stream frame "
    "(serving/kvstream.py); each chunk is independently addressed and "
    "CRC-verified on arrival.")

# --- Collective fingerprinting (analysis/fingerprint.py) --------------------
FINGERPRINT = register(
    "HOROVOD_FINGERPRINT", "off", str,
    "Runtime collective-symmetry fingerprinting: off | cycle (compare "
    "rolling per-rank op fingerprints on every natural negotiation "
    "cycle) | strict (force a negotiation heartbeat every cycle so "
    "divergence is caught even in response-cache steady state).  "
    "Cross-rank divergence becomes a structured ERROR naming the first "
    "divergent op instead of a stall (docs/analysis.md).")
FINGERPRINT_WINDOW = register(
    "HOROVOD_FINGERPRINT_WINDOW", 64, int,
    "Ops of fingerprint history each rank ships with its RequestList; "
    "divergences older than the window are reported as 'at or before' "
    "the oldest commonly-visible op.")
SHARD_SPEC_IDENTITY = register(
    "HOROVOD_SHARD_SPEC_IDENTITY", True, _parse_bool,
    "Fold each collective's canonical sharding-spec token (the sp_spec "
    "wire field) into the runtime fingerprint, making collective "
    "identity op×name×dtype×dims×spec (hvdshard; docs/analysis.md).  "
    "Only effective when the mesh negotiated FEATURE_SHARDING; "
    "launcher-set and identical on every rank.  0 restores the "
    "5-column identity.")

# --- Stall inspector (reference: common/stall_inspector.cc) -----------------
STALL_CHECK_DISABLE = register(
    "HOROVOD_STALL_CHECK_DISABLE", False, _parse_bool,
    "Disable the stalled-tensor warning check.")
STALL_CHECK_TIME_SECONDS = register(
    "HOROVOD_STALL_CHECK_TIME_SECONDS", 60.0, float,
    "Seconds before warning about ranks with missing submissions.")
STALL_SHUTDOWN_TIME_SECONDS = register(
    "HOROVOD_STALL_SHUTDOWN_TIME_SECONDS", 0.0, float,
    "Seconds before a stall aborts the job (0 = never).")

# --- Logging ----------------------------------------------------------------
LOG_LEVEL = register(
    "HOROVOD_LOG_LEVEL", "warning", str,
    "trace|debug|info|warning|error|fatal")
LOG_HIDE_TIME = register(
    "HOROVOD_LOG_HIDE_TIME", False, _parse_bool,
    "Hide timestamps in log output.")

# --- Rendezvous / cluster layout (set by the launcher) ----------------------
# (reference: gloo_context.cc:136-152 reads the same family of variables)
RANK = register("HOROVOD_RANK", -1, int, "Global rank of this process.")
SIZE = register("HOROVOD_SIZE", -1, int, "Global number of ranks.")
LOCAL_RANK = register("HOROVOD_LOCAL_RANK", -1, int, "Rank within this host.")
LOCAL_SIZE = register("HOROVOD_LOCAL_SIZE", -1, int, "Ranks on this host.")
CROSS_RANK = register("HOROVOD_CROSS_RANK", -1, int, "Host index.")
CROSS_SIZE = register("HOROVOD_CROSS_SIZE", -1, int, "Number of hosts.")
HOSTNAME = register("HOROVOD_HOSTNAME", "", str, "Assigned hostname.")
RENDEZVOUS_ADDR = register(
    "HOROVOD_GLOO_RENDEZVOUS_ADDR", "", str,
    "Rendezvous KV-store host (control plane over DCN).")
RENDEZVOUS_PORT = register(
    "HOROVOD_GLOO_RENDEZVOUS_PORT", -1, int, "Rendezvous KV-store port.")
RENDEZVOUS_REPLICAS = register(
    "HOROVOD_RENDEZVOUS_REPLICAS", 0, int,
    "Standby rendezvous replicas launched next to the primary (0 = the "
    "single-server control plane); requires HOROVOD_RENDEZVOUS_WAL_DIR. "
    "Standbys tail the primary's WAL and promote on lease lapse "
    "(docs/controlplane.md).")
RENDEZVOUS_LEASE_MS = register(
    "HOROVOD_RENDEZVOUS_LEASE_MS", 3000.0, float,
    "Rendezvous leader lease in milliseconds: the primary renews every "
    "third of it, a standby promotes after ~2x of silence, and a "
    "primary whose lease lapsed must re-verify the log (epoch fence) "
    "before accepting another write.")
RENDEZVOUS_WAL_DIR = register(
    "HOROVOD_RENDEZVOUS_WAL_DIR", "", str,
    "Directory of the rendezvous write-ahead log (shared by the "
    "replica set).  Empty = no WAL: the KV is in-memory only and does "
    "not survive coordinator death.")
PROTO_COMPAT = register(
    "HOROVOD_PROTO_COMPAT", 0, int,
    "Advertise this wire protocol version (masking newer feature bits) "
    "at every channel HELLO instead of the build's native version; 0 = "
    "native.  The rolling-upgrade lever: peers negotiate the min "
    "common schema per mesh.")
CONTROLLER = register(
    "HOROVOD_CONTROLLER", "local", str,
    "Controller plane: local (in-process) | tcp (multi-process rendezvous).")
GLOO_TIMEOUT_SECONDS = register(
    "HOROVOD_GLOO_TIMEOUT_SECONDS", 30.0, float,
    "Control-plane connect/recv timeout.")

# --- TPU-specific knobs (no reference analogue) -----------------------------
MESH_SHAPE = register(
    "HOROVOD_TPU_MESH_SHAPE", "", str,
    "Override device mesh shape, e.g. '4,2' → axes (replica, local).")
XLA_DONATE = register(
    "HOROVOD_TPU_DONATE_BUFFERS", True, _parse_bool,
    "Donate input buffers to fused XLA collectives (in-place on HBM).")
NUM_STREAMS = register(
    "HOROVOD_NUM_STREAMS", 1, int,
    "Parallel response-dispatch streams (analogue of "
    "HOROVOD_NUM_NCCL_STREAMS): N worker threads execute independent "
    "responses of one cycle concurrently, each over its own dedicated "
    "TCP channel set so streams never interleave bytes on a shared "
    "socket.  Stream assignment is round-robin over the coordinator-"
    "ordered ResponseList (identical on every rank).  1 = the serial "
    "background-loop dispatch, unchanged.")
AUTOTUNE_PIPELINE = register(
    "HOROVOD_AUTOTUNE_PIPELINE", False, _parse_bool,
    "Let the autotuner sweep the TCP pipeline knobs (segment bytes x "
    "active streams, bounded by HOROVOD_NUM_STREAMS) by measured "
    "allreduce throughput before the Bayesian phase, broadcasting the "
    "winner to every rank.")
BENCH_PROBE_BUDGET_S = register(
    "HOROVOD_BENCH_PROBE_BUDGET_S", 25.0, float,
    "Per-probe timeout for bench.py's accelerator probe (seconds).  A "
    "probe that runs to this timeout means jax.devices() itself wedged "
    "— after 2 consecutive timed-out probes the absence is definitive "
    "and the CPU fallback starts immediately (2 x default 25 s keeps "
    "it under a minute).  Probe CRASHES stay retryable on the watcher "
    "schedule; only timeouts are terminal.")
TRACK_ACCURACY = register(
    "HOROVOD_TRACK_ACCURACY", True, _parse_bool,
    "Compute the per-step training-accuracy metric in Trainer.step. "
    "For LM-head-sized logits the argmax is a full extra read of a "
    "multi-GB tensor per step; disable for throughput runs.")
def parse_tristate(value: str) -> bool | None:
    """'1'/'true'/... -> True, '0'/'false'/... -> False, else None (auto).
    Shared by the tri-state knobs (JAX_DISTRIBUTED, XLA_OPERATIONS)."""
    v = value.strip().lower()
    if v in ("1", "true", "yes", "on"):
        return True
    if v in ("0", "false", "no", "off"):
        return False
    return None


JAX_DISTRIBUTED = register(
    "HOROVOD_JAX_DISTRIBUTED", "auto", str,
    "Form the multi-process JAX world at init (jax.distributed.initialize "
    "via the rendezvous KV): 1 | 0 | auto (yes on accelerator backends).")
JAX_HEARTBEAT_TIMEOUT_SECONDS = register(
    "HOROVOD_JAX_HEARTBEAT_TIMEOUT_SECONDS", 100.0, float,
    "jax.distributed coordinator heartbeat timeout passed through to "
    "jax.distributed.initialize when the installed jaxlib accepts it "
    "(parallel/multihost.py filters kwargs by signature).")
JAX_TEARDOWN_GRACE_SECONDS = register(
    "HOROVOD_JAX_TEARDOWN_GRACE_SECONDS", 30.0, float,
    "Grace window for jax.distributed.shutdown at world teardown "
    "before the process gives up waiting on the coordination service.")
JAX_TEARDOWN_SETTLE_SECONDS = register(
    "HOROVOD_JAX_TEARDOWN_SETTLE_SECONDS", 10.0, float,
    "Settle pause after a jax.distributed teardown so late peer RPCs "
    "drain before the next epoch's world forms (elastic rebuilds).")
SHM_BARRIER_TIMEOUT_SECONDS = register(
    "HOROVOD_SHM_BARRIER_TIMEOUT_SECONDS", 600.0, float,
    "Timeout of the shared-memory plane's 3-phase lockstep barrier; a "
    "rank missing past it aborts the op with a structured error naming "
    "the lagging rank instead of spinning forever.")
STREAMING_CE_MIN_ELEMENTS = register(
    "HOROVOD_STREAMING_CE_MIN_ELEMENTS", 0, int,
    "Logit-tensor element count above which the trainer switches to "
    "the streaming (chunked) cross-entropy loss; unset derives the "
    "threshold from discoverable device memory (HBM/16), 0 forces "
    "streaming everywhere (training.py).")
TPU_DISABLE_NATIVE = register(
    "HOROVOD_TPU_DISABLE_NATIVE", False, _parse_bool,
    "Force the pure-numpy fallbacks for the native C codec/fused "
    "kernels (native/): a perf switch, never a correctness one — both "
    "implementations are bitwise identical.")

# --- Launcher / cluster integration (read at their launch-time sites) -------
# These are set by launchers for the worker processes they spawn and
# read before (or outside) any registry import; they are declared here
# so the typed registry — and docs/configuration.md, generated from it —
# is the one complete knob inventory (hvdflow HVD604 flags any raw
# HOROVOD_* read whose name is missing from this file).
DRIVER_ADDR = register(
    "HOROVOD_DRIVER_ADDR", "", str,
    "Elastic driver RPC address the worker dials back to "
    "(elastic/worker.py; set by the elastic launcher).")
DRIVER_PORT = register(
    "HOROVOD_DRIVER_PORT", -1, int,
    "Elastic driver RPC port (elastic/worker.py; set by the launcher).")
GLOO_IFACE = register(
    "HOROVOD_GLOO_IFACE", "", str,
    "Network interface name that pins the address peers dial for the "
    "TCP data/control planes (runner/network.py); empty = the default "
    "route's interface.")
RENDEZVOUS_EPOCH = register(
    "HOROVOD_RENDEZVOUS_EPOCH", "0", str,
    "Rendezvous-KV key namespace of the current world incarnation; "
    "elastic rebuilds, retry recovery and statesync grow bump it "
    "(e.g. '3~r1', '3+j2') so a rebuilt world never collides with "
    "stale keys from the previous epoch.  Set by launchers and "
    "recovery paths, not by hand.")
SECRET_KEY = register(
    "HOROVOD_SECRET_KEY", "", str,
    "Shared HMAC secret authenticating elastic driver<->worker RPCs "
    "(elastic/rpc.py); generated by the launcher per run.")
JSRUN_CPU_PER_SLOT = register(
    "HOROVOD_JSRUN_CPU_PER_SLOT", -1, int,
    "CPUs per resource-set slot for the LSF/jsrun launcher "
    "(runner/js_run.py); unset derives it from the allocation.")
JSRUN_HOSTS = register(
    "HOROVOD_JSRUN_HOSTS", "", str,
    "Explicit host list override for the LSF/jsrun launcher.")
LSF_COMPUTE_HOSTS = register(
    "HOROVOD_LSF_COMPUTE_HOSTS", "", str,
    "LSF compute-host list override consulted before LSB_MCPU_HOSTS "
    "(runner/js_run.py).")
XLA_OPERATIONS = register(
    "HOROVOD_XLA_OPERATIONS", "auto", str,
    "Eager-core device data plane: 1 (require XLA backend) | 0 (TCP only) "
    "| auto (use XLA collectives when a device mesh is available).")
