"""LRU response cache + bitvector coordination state.

Reference: horovod/common/response_cache.{cc,h}:45-169 and its use in
controller.cc:81-237.  Purpose: in steady state every step submits the same
tensors, so instead of re-gathering full RequestLists each cycle, ranks sync
two fixed-size bitvectors (hits AND, invalid/flags OR) and execute the cached
fused Responses directly — collapsing the control plane to two small
allreduces per cycle.

Cache entries occupy stable bit positions so the bitvectors mean the same
thing on every rank; eviction invalidates the position everywhere via the
"invalid" bitvector on the next sync.
"""
from __future__ import annotations

import enum
from collections import OrderedDict
from dataclasses import dataclass, replace

from .dtypes import DataType
from .message import Request, Response, ResponseType


class CacheState(enum.IntEnum):
    MISS = 0
    HIT = 1
    INVALID = 2


@dataclass(frozen=True)
class _Params:
    """Per-tensor parameters that must match for a cache hit."""
    response_type: ResponseType
    tensor_type: DataType
    shape: tuple[int, ...]
    root_rank: int
    device: int
    prescale_factor: float
    postscale_factor: float
    last_joined_rank: int
    codec: int
    codec_block_size: int


def _params_of(request: Request, joined_size: int) -> _Params:
    from .message import RequestType
    rt = {
        RequestType.ALLREDUCE: ResponseType.ALLREDUCE,
        RequestType.ALLGATHER: ResponseType.ALLGATHER,
        RequestType.BROADCAST: ResponseType.BROADCAST,
        RequestType.ALLTOALL: ResponseType.ALLTOALL,
        RequestType.ADASUM: ResponseType.ADASUM,
        RequestType.REDUCESCATTER: ResponseType.REDUCESCATTER,
        RequestType.BARRIER: ResponseType.BARRIER,
    }[request.request_type]
    return _Params(rt, request.tensor_type, tuple(request.tensor_shape),
                   request.root_rank, request.device,
                   request.prescale_factor, request.postscale_factor,
                   joined_size, request.codec, request.codec_block_size)


class ResponseCache:
    def __init__(self, capacity: int = 0) -> None:
        self._capacity = capacity
        # name -> (bit position, Response, params); ordered LRU (front = LRU)
        self._entries: OrderedDict[str, tuple[int, Response, _Params]] = OrderedDict()
        self._free_positions: list[int] = list(range(capacity - 1, -1, -1))
        self._by_position: dict[int, str] = {}
        self.printed_caching_warning = False

    @property
    def capacity(self) -> int:
        return self._capacity

    def enabled(self) -> bool:
        return self._capacity > 0

    def cached(self, request: Request, joined_size: int = 0) -> CacheState:
        ent = self._entries.get(request.tensor_name)
        if ent is None:
            return CacheState.MISS
        _, _, params = ent
        if params == _params_of(request, joined_size):
            return CacheState.HIT
        return CacheState.INVALID

    def put(self, response: Response, request: Request, joined_size: int = 0) -> None:
        """Cache a single-tensor response (fusion happens after lookup)."""
        if not self.enabled():
            return
        name = request.tensor_name
        if name in self._entries:
            pos, _, _ = self._entries.pop(name)
        else:
            if not self._free_positions:
                # Evict LRU entry; its position is recycled and will be
                # broadcast as invalid on the next coordination cycle.
                old_name, (pos, _, _) = self._entries.popitem(last=False)
                self._by_position.pop(pos, None)
            else:
                pos = self._free_positions.pop()
        # Store a private copy — the caller's object flows on into fusion
        # and execution and may be mutated there.  The trace id is reset:
        # it names ONE negotiated instance, and every later cache hit is
        # a new collective that gets a fresh id at assembly
        # (controller._stamp_trace_ids) — a stale id would alias two
        # different steps in the merged cross-rank trace.
        stored = replace(response, tensor_names=list(response.tensor_names),
                         tensor_sizes=list(response.tensor_sizes),
                         devices=list(response.devices),
                         trace_cycle=-1, trace_seq=-1)
        self._entries[name] = (pos, stored, _params_of(request, joined_size))
        self._by_position[pos] = name

    def peek_cache_position(self, name: str) -> int:
        return self._entries[name][0]

    def get_response_by_position(self, position: int) -> Response:
        name = self._by_position[position]
        pos, resp, params = self._entries.pop(name)
        self._entries[name] = (pos, resp, params)   # refresh LRU
        # Return a copy: downstream fusion mutates tensor_names/sizes in
        # place and must never corrupt the cached entry.
        return replace(resp, tensor_names=list(resp.tensor_names),
                       tensor_sizes=list(resp.tensor_sizes),
                       devices=list(resp.devices))

    def response_type_by_position(self, position: int):
        """Type of the cached response, without the defensive copy (and
        LRU refresh) get_response_by_position pays — for per-cycle scans
        like the joined-rank bit loop that only need the type."""
        return self._entries[self._by_position[position]][1].response_type

    def erase_by_position(self, position: int) -> None:
        name = self._by_position.pop(position, None)
        if name is not None:
            self._entries.pop(name, None)
            self._free_positions.append(position)

    def erase(self, name: str) -> None:
        ent = self._entries.pop(name, None)
        if ent is not None:
            pos = ent[0]
            self._by_position.pop(pos, None)
            self._free_positions.append(pos)

    def positions(self) -> list[int]:
        return [pos for pos, _, _ in self._entries.values()]

    def num_active_bits(self) -> int:
        return len(self._entries)

    def clear(self) -> None:
        self._entries.clear()
        self._by_position.clear()
        self._free_positions = list(range(self._capacity - 1, -1, -1))


class CacheCoordinator:
    """Per-cycle bitvector state synced across ranks.

    Reference: response_cache.h CacheCoordinator + controller.cc
    CoordinateCacheAndState (751-776): one bitwise-AND allreduce over
    [hit bits] and one bitwise-OR allreduce over [invalid bits | flags].
    """

    FLAG_SHUTDOWN = 0
    FLAG_UNCACHED_IN_QUEUE = 1
    FLAG_SHOULD_SYNC = 2
    NUM_FLAGS = 3

    def __init__(self, num_bits: int) -> None:
        self.num_bits = num_bits
        self.hit_bits: set[int] = set()
        self.invalid_bits: set[int] = set()
        self.shutdown = False
        self.uncached_in_queue = False
        self.should_sync = False   # another sync round needed after this one

    def record_hit(self, position: int) -> None:
        self.hit_bits.add(position)

    def record_invalid(self, position: int) -> None:
        self.invalid_bits.add(position)

    def pack(self) -> tuple[int, int]:
        """Return (and_word, or_word) integer bitsets.

        and_word: bit i set ⇔ tensor at cache position i is hit locally.
        or_word: low flag bits then invalid bits (offset by NUM_FLAGS).
        """
        and_word = 0
        for b in self.hit_bits:
            and_word |= 1 << b
        or_word = 0
        if self.shutdown:
            or_word |= 1 << self.FLAG_SHUTDOWN
        if self.uncached_in_queue:
            or_word |= 1 << self.FLAG_UNCACHED_IN_QUEUE
        if self.should_sync:
            or_word |= 1 << self.FLAG_SHOULD_SYNC
        for b in self.invalid_bits:
            or_word |= 1 << (b + self.NUM_FLAGS)
        return and_word, or_word

    def unpack(self, and_word: int, or_word: int) -> None:
        """Apply globally reduced words back onto this coordinator."""
        self.shutdown = bool(or_word & (1 << self.FLAG_SHUTDOWN))
        self.uncached_in_queue = bool(or_word & (1 << self.FLAG_UNCACHED_IN_QUEUE))
        self.should_sync = bool(or_word & (1 << self.FLAG_SHOULD_SYNC))
        invalid = set()
        hits = set()
        word = or_word >> self.NUM_FLAGS
        pos = 0
        while word:
            if word & 1:
                invalid.add(pos)
            word >>= 1
            pos += 1
        word = and_word
        pos = 0
        while word:
            if word & 1 and pos not in invalid:
                hits.add(pos)
            word >>= 1
            pos += 1
        self.invalid_bits = invalid
        self.hit_bits = hits
