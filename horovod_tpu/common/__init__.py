"""Shared infrastructure: messages, controller, caches, config, logging."""
