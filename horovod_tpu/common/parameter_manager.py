"""Runtime autotuner for fusion threshold and cycle time.

Reference: horovod/common/parameter_manager.{cc,h}:42-120 — scores each
parameter setting by aggregate allreduce bytes/sec over a sampling window,
drives new settings from Bayesian optimization, and broadcasts winning
parameters from the coordinator so every rank stays consistent
(reference: Controller::SynchronizeParameters, controller.cc:39-53; here the
sync rides the ResponseList `tuned_*` fields).
"""
from __future__ import annotations

import time

from . import config
from .logging import logger
from .optim.bayesian_optimization import BayesianOptimization

# Search space: log2(fusion threshold bytes) × cycle time ms.
_THRESHOLD_LOG2_BOUNDS = (20.0, 28.0)      # 1 MiB .. 256 MiB
_CYCLE_MS_BOUNDS = (1.0, 25.0)


class ParameterManager:
    def __init__(self, controller, active: bool) -> None:
        self._controller = controller
        self._active = active           # only the coordinator tunes
        self._warmup_left = config.AUTOTUNE_WARMUP_SAMPLES.get()
        self._steps_per_sample = config.AUTOTUNE_STEPS_PER_SAMPLE.get()
        self._max_samples = config.AUTOTUNE_BAYES_OPT_MAX_SAMPLES.get()
        self._bo = BayesianOptimization(
            [_THRESHOLD_LOG2_BOUNDS, _CYCLE_MS_BOUNDS],
            alpha=config.AUTOTUNE_GAUSSIAN_PROCESS_NOISE.get())
        self._log_path = config.AUTOTUNE_LOG.get()
        if self._log_path and active:
            with open(self._log_path, "w") as f:
                f.write("timestamp,fusion_threshold,cycle_time_ms,score,"
                        "event\n")

        self._steps = 0
        self._bytes = 0
        self._t0 = time.monotonic()
        self._done = False
        self._current = (float(controller.tensor_fusion_threshold),
                         float(config.CYCLE_TIME.get()))

        # Codec sweep (HOROVOD_AUTOTUNE_COMPRESSION): before the BO
        # phase, score each candidate wire codec for one sample window by
        # the same logical-bytes/sec metric — a faster wire moves more
        # gradient bytes per second — and broadcast the winner through
        # ResponseList.tuned_codec.  Candidates stay conservative (the
        # codecs whose accuracy story needs no per-model judgement rides
        # on error feedback for int8; uint4 is opt-in only).
        self._codec_candidates: list[str] = \
            ["none", "fp16", "int8"] if active and \
            config.AUTOTUNE_COMPRESSION.get() else []
        self._codec_scores: dict[str, float] = {}
        self._codec_index = 0

        # TCP-pipeline sweep (HOROVOD_AUTOTUNE_PIPELINE): after the codec
        # sweep, score (segment bytes x active streams) combinations one
        # sample window each — the same logical-bytes/sec metric — and
        # broadcast the winner through ResponseList.tuned_segment_bytes /
        # tuned_num_streams.  Stream width can only be swept up to
        # HOROVOD_NUM_STREAMS (the per-stream channel sets were formed at
        # init; activation is the runtime knob).
        self._pipeline_candidates: list[tuple[int, int]] = []
        if active and config.AUTOTUNE_PIPELINE.get():
            max_streams = max(config.NUM_STREAMS.get(), 1)
            segments = [0, 1 << 16, 1 << 18, 1 << 20]
            self._pipeline_candidates = [
                (seg, s) for s in range(1, max_streams + 1)
                for seg in segments]
        self._pipeline_scores: dict[tuple[int, int], float] = {}
        self._pipeline_index = 0

        # Fused-kernel sweep (rides HOROVOD_AUTOTUNE_PIPELINE): after the
        # pipeline sweep, score the single-pass fused codec legs against
        # the reference dequant/requant chain for one window each and pin
        # the winner through ResponseList.tuned_fused.  Both settings are
        # bitwise identical, so the sweep is purely a speed question —
        # fused wins on codec-heavy wires, and on pure-fp32 rings the two
        # are the same code path (sweeping stays cheap either way).
        self._fused_candidates: list[int] = \
            [1, 0] if active and config.AUTOTUNE_PIPELINE.get() else []
        self._fused_scores: dict[int, float] = {}
        self._fused_index = 0

        # Allreduce-algorithm sweep (rides HOROVOD_AUTOTUNE_PIPELINE):
        # after the fused sweep, score (algo, tree threshold) candidates
        # one window each and pin the winner through
        # ResponseList.tuned_algo / tuned_tree_threshold.  Candidates are
        # (ALGO_NAMES index, threshold bytes): the pure flat ring as the
        # baseline, then "auto" selection at increasing tree/ring
        # crossover thresholds — each one a different small-tensor
        # latency/bandwidth trade on the live workload.
        self._algo_candidates: list[tuple[int, int]] = []
        if active and config.AUTOTUNE_PIPELINE.get():
            from .topology import algo_index
            ring, auto = algo_index("ring"), algo_index("auto")
            self._algo_candidates = [
                (ring, 0), (auto, 1 << 14), (auto, 1 << 16),
                (auto, 1 << 18)]
        self._algo_scores: dict[tuple[int, int], float] = {}
        self._algo_index = 0

    def observe(self, tensor_names: list[str], nbytes: int) -> None:
        """Called once per background cycle with the allreduced bytes."""
        if not self._active or self._done:
            return
        self._bytes += nbytes
        if nbytes > 0:
            self._steps += 1
        if self._steps < self._steps_per_sample:
            return

        elapsed = max(time.monotonic() - self._t0, 1e-9)
        score = self._bytes / elapsed
        self._steps = 0
        self._bytes = 0
        self._t0 = time.monotonic()

        if self._warmup_left > 0:
            self._warmup_left -= 1
            return

        if self._codec_candidates:
            from ..compress import codec_from_name
            if self._codec_index > 0:
                # This window measured the previously proposed codec.
                measured = self._codec_candidates[self._codec_index - 1]
                self._codec_scores[measured] = score
                self._log(*self._current, score,
                          event=f"codec-{measured}")
            if self._codec_index < len(self._codec_candidates):
                nxt = self._codec_candidates[self._codec_index]
                self._codec_index += 1
                self._controller.pending_tuned_codec = int(
                    codec_from_name(nxt))
                return
            # Sweep complete: pin the winner, then continue into BO.
            best = max(self._codec_scores, key=self._codec_scores.get)
            self._controller.pending_tuned_codec = int(
                codec_from_name(best))
            self._log(*self._current, self._codec_scores[best],
                      event=f"codec-winner-{best}")
            logger.info("autotune codec sweep: %s -> %s",
                        self._codec_scores, best)
            self._codec_candidates = []
            return

        if self._pipeline_candidates:
            if self._pipeline_index > 0:
                measured = self._pipeline_candidates[
                    self._pipeline_index - 1]
                self._pipeline_scores[measured] = score
                self._log(*self._current, score,
                          event=f"pipeline-{measured[0]}x{measured[1]}")
            if self._pipeline_index < len(self._pipeline_candidates):
                seg, streams = self._pipeline_candidates[
                    self._pipeline_index]
                self._pipeline_index += 1
                self._controller.pending_tuned_pipeline = (seg, streams)
                return
            best = max(self._pipeline_scores, key=self._pipeline_scores.get)
            self._controller.pending_tuned_pipeline = best
            self._log(*self._current, self._pipeline_scores[best],
                      event=f"pipeline-winner-{best[0]}x{best[1]}")
            logger.info("autotune pipeline sweep: %s -> segment=%d "
                        "streams=%d", self._pipeline_scores, *best)
            self._pipeline_candidates = []
            return

        if self._fused_candidates:
            if self._fused_index > 0:
                measured = self._fused_candidates[self._fused_index - 1]
                self._fused_scores[measured] = score
                self._log(*self._current, score,
                          event=f"fused-{measured}")
            if self._fused_index < len(self._fused_candidates):
                nxt = self._fused_candidates[self._fused_index]
                self._fused_index += 1
                self._controller.pending_tuned_fused = nxt
                return
            best = max(self._fused_scores, key=self._fused_scores.get)
            self._controller.pending_tuned_fused = best
            self._log(*self._current, self._fused_scores[best],
                      event=f"fused-winner-{best}")
            logger.info("autotune fused-kernel sweep: %s -> fused=%d",
                        self._fused_scores, best)
            self._fused_candidates = []
            return

        if self._algo_candidates:
            from .topology import ALGO_NAMES, algo_name
            if self._algo_index > 0:
                measured = self._algo_candidates[self._algo_index - 1]
                self._algo_scores[measured] = score
                self._log(*self._current, score,
                          event=f"algo-{algo_name(measured[0])}"
                                f"@{measured[1]}")
            if self._algo_index < len(self._algo_candidates):
                cand = self._algo_candidates[self._algo_index]
                self._algo_index += 1
                self._controller.pending_tuned_algo = cand
                return
            best = max(self._algo_scores, key=self._algo_scores.get)
            self._controller.pending_tuned_algo = best
            self._log(*self._current, self._algo_scores[best],
                      event=f"algo-winner-{algo_name(best[0])}"
                            f"@{best[1]}")
            logger.info("autotune algo sweep: %s -> algo=%s threshold=%d",
                        self._algo_scores, ALGO_NAMES[best[0]], best[1])
            self._algo_candidates = []
            return

        import math
        threshold, cycle = self._current
        self._bo.add_sample(
            [math.log2(max(threshold, 1.0)), cycle], score)
        self._log(threshold, cycle, score)

        if self._bo.num_samples >= self._max_samples:
            best = self._bo.best()
            assert best is not None
            (log_thr, cycle), best_score = best
            self._propose(2.0 ** log_thr, cycle)
            self._done = True
            self._log(2.0 ** log_thr, cycle, best_score,
                      event="converged")
            logger.info(
                "autotune converged: fusion_threshold=%d cycle_time=%.1fms "
                "(%.1f MB/s)", int(2.0 ** log_thr), cycle,
                best_score / 1e6)
            return

        log_thr, cycle = self._bo.suggest_next()
        self._propose(2.0 ** log_thr, cycle)

    def _propose(self, threshold: float, cycle_ms: float) -> None:
        self._current = (threshold, cycle_ms)
        # Stamped onto the next broadcast ResponseList so all ranks apply
        # identical parameters on the same cycle.
        self._controller.pending_tuned_params = (int(threshold),
                                                 float(cycle_ms))

    def _log(self, threshold: float, cycle: float, score: float,
             event: str = "sample") -> None:
        if self._log_path:
            with open(self._log_path, "a") as f:
                f.write(f"{time.time()},{int(threshold)},{cycle},{score},"
                        f"{event}\n")
