"""Controller transport over TCP sockets — the Gloo-controller equivalent.

Reference: horovod/common/gloo/gloo_controller.cc:35-199 — the same
coordination protocol as MPI (request gather to rank 0, response broadcast,
bitvector sync) but over point-to-point TCP bootstrapped from the rendezvous
KV store.  Here all three primitives run over a dedicated PeerMesh (separate
from the bulk data-plane mesh so control never queues behind tensor bytes).
"""
from __future__ import annotations

import struct
import time

from .controller import Transport
from .message import RequestList, ResponseList
from ..runner.network import PeerMesh

_WORDLEN = struct.Struct(">I")


def _pack_words(and_word: int, or_word: int) -> bytes:
    a = and_word.to_bytes((max(and_word.bit_length(), 1) + 7) // 8, "big")
    o = or_word.to_bytes((max(or_word.bit_length(), 1) + 7) // 8, "big")
    return _WORDLEN.pack(len(a)) + a + _WORDLEN.pack(len(o)) + o

def _unpack_words(raw: bytes) -> tuple[int, int]:
    (la,) = _WORDLEN.unpack_from(raw, 0)
    a = int.from_bytes(raw[4:4 + la], "big")
    (lo,) = _WORDLEN.unpack_from(raw, 4 + la)
    o = int.from_bytes(raw[8 + la:8 + la + lo], "big")
    return a, o


class TcpTransport(Transport):
    def __init__(self, mesh: PeerMesh) -> None:
        self.mesh = mesh
        self.rank = mesh.rank
        self.size = mesh.size
        # Coordinator-side: monotonic arrival time of each rank's last
        # gathered RequestList (telemetry straggler signal; the controller
        # reads it via getattr so LocalTransport needs no counterpart).
        self.last_gather_arrivals: dict[int, float] = {}

    # -- bitvector sync (reference: gloo_controller.cc bitwise ops) ------
    def bitwise_sync(self, and_word: int, or_word: int) -> tuple[int, int]:
        if self.size == 1:
            return and_word, or_word
        if self.rank == 0:
            # Drain peers in ARRIVAL order (selectors), not rank order:
            # AND/OR are commutative, and one slow rank no longer stalls
            # the reads of every faster rank queued behind it.
            for _, raw in self.mesh.recv_in_arrival_order(
                    range(1, self.size)):
                a, o = _unpack_words(raw)
                and_word &= a
                or_word |= o
            payload = _pack_words(and_word, or_word)
            for peer in range(1, self.size):
                self.mesh.send(peer, payload)
            return and_word, or_word
        self.mesh.send(0, _pack_words(and_word, or_word))
        return _unpack_words(self.mesh.recv(0))

    # -- RequestList gather (reference: gloo_controller.cc allgatherv) ---
    def gather_requests(self, request_list: RequestList):
        if self.size == 1:
            return [request_list]
        if self.rank == 0:
            # Arrival-order drain (selectors): decode each rank's list
            # while slower peers are still sending, cutting the
            # negotiation tail when one rank lags.  The result stays
            # rank-indexed — arrival order never leaks downstream.
            lists: list[RequestList | None] = [None] * self.size
            lists[0] = request_list
            arrivals = {0: time.monotonic()}
            for peer, raw in self.mesh.recv_in_arrival_order(
                    range(1, self.size)):
                arrivals[peer] = time.monotonic()
                lists[peer] = RequestList.from_bytes(raw)
            self.last_gather_arrivals = arrivals
            return lists
        self.mesh.send(0, request_list.to_bytes())
        return None

    # -- ResponseList broadcast ------------------------------------------
    def broadcast_responses(self, response_list):
        if self.size == 1:
            return response_list
        if self.rank == 0:
            payload = response_list.to_bytes()
            for peer in range(1, self.size):
                self.mesh.send(peer, payload)
            return response_list
        return ResponseList.from_bytes(self.mesh.recv(0))

    def barrier(self) -> None:
        if self.size == 1:
            return
        if self.rank == 0:
            for _ in self.mesh.recv_in_arrival_order(range(1, self.size)):
                pass
            for peer in range(1, self.size):
                self.mesh.send(peer, b"\x01")
        else:
            self.mesh.send(0, b"\x01")
            self.mesh.recv(0)
