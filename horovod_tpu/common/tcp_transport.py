"""Controller transport over TCP sockets — the Gloo-controller equivalent.

Reference: horovod/common/gloo/gloo_controller.cc:35-199 — the same
coordination protocol as MPI (request gather to rank 0, response broadcast,
bitvector sync) but over point-to-point TCP bootstrapped from the rendezvous
KV store.  Here all three primitives run over a dedicated PeerMesh (separate
from the bulk data-plane mesh so control never queues behind tensor bytes).
"""
from __future__ import annotations

import struct
import time

from .controller import Transport
from .exceptions import RanksFailedError
from .logging import logger
from .message import RequestList, ResponseList
from ..runner.network import PeerMesh

_WORDLEN = struct.Struct(">I")

# Poison/abort frame (resilience/): when the coordinator's bounded drain
# detects a dead or deadline-missing rank it broadcasts this frame to
# every surviving peer, whatever recv state that peer is blocked in
# (bitwise reply, ResponseList broadcast, barrier release) — the leading
# 0xff byte cannot open any legitimate control frame (bitwise payloads
# start with a 4-byte big-endian length <= 2^24, Request/ResponseList
# bytes with a bool), so one prefix test per control recv suffices.
# The payload is the RanksFailedError wire form, riding the same
# structured-ERROR path the fingerprint divergence errors use.
POISON_MAGIC = b"\xffHVDPOISON\xff"


def check_poison(raw) -> None:
    """Raise the carried RanksFailedError when `raw` is a poison frame."""
    if raw[:len(POISON_MAGIC)] == POISON_MAGIC:
        raise RanksFailedError.from_wire(
            bytes(raw[len(POISON_MAGIC):]).decode(errors="replace"))


# State-frame verb (statesync/): the frames peer-to-peer live-state
# streaming puts on its dedicated sync mesh (never on the ctrl/data
# meshes, so they can never interleave with protocol frames).  Layout:
#   STATE_MAGIC | u8 kind | u32 meta_len | meta json | payload
# The magic shares the poison frame's property — the leading 0xff byte
# cannot open any legitimate control frame — so a stray state frame on
# a control mesh is rejected at one prefix test, and vice versa.
STATE_MAGIC = b"\xffHVDSTATE\xff"
_STATE_HDR = struct.Struct(">BI")

# Frame kinds of the streaming protocol (stream.py documents the flow).
STATE_HELLO = 1     # joiner -> donor: open round (meta: join id, round)
STATE_META = 2      # donor -> joiner: snapshot stamp + byte total
STATE_REQ = 3       # joiner -> donor: request a byte range
STATE_DATA = 4      # donor -> joiner: one chunk (meta: offset/len/crc)
STATE_END = 5       # donor -> joiner: requested range fully streamed
STATE_BYE = 6       # joiner -> donor: transfer complete, stand down


def pack_state_frame(kind: int, meta: dict, payload=b"") -> bytes:
    """Encode one state frame (statesync wire verb)."""
    import json
    meta_raw = json.dumps(meta, sort_keys=True).encode()
    head = STATE_MAGIC + _STATE_HDR.pack(kind, len(meta_raw)) + meta_raw
    if not payload:
        return head
    return head + bytes(payload)


def unpack_state_frame(raw) -> tuple[int, dict, memoryview]:
    """Decode one state frame; raises ValueError on a non-state frame
    (every read of a statesync channel must go through here — the
    digest/epoch checks downstream only see frames this verb accepted)."""
    import json
    view = memoryview(raw) if not isinstance(raw, memoryview) \
        else raw
    n_magic = len(STATE_MAGIC)
    if bytes(view[:n_magic]) != STATE_MAGIC:
        raise ValueError(
            "not a state frame (bad magic); statesync channels carry "
            "only STATE_MAGIC frames")
    kind, meta_len = _STATE_HDR.unpack_from(view, n_magic)
    meta_start = n_magic + _STATE_HDR.size
    meta = json.loads(bytes(view[meta_start:meta_start + meta_len]))
    return kind, meta, view[meta_start + meta_len:]


def _pack_words(and_word: int, or_word: int) -> bytes:
    a = and_word.to_bytes((max(and_word.bit_length(), 1) + 7) // 8, "big")
    o = or_word.to_bytes((max(or_word.bit_length(), 1) + 7) // 8, "big")
    return _WORDLEN.pack(len(a)) + a + _WORDLEN.pack(len(o)) + o

def _unpack_words(raw: bytes) -> tuple[int, int]:
    (la,) = _WORDLEN.unpack_from(raw, 0)
    a = int.from_bytes(raw[4:4 + la], "big")
    (lo,) = _WORDLEN.unpack_from(raw, 4 + la)
    o = int.from_bytes(raw[8 + la:8 + la + lo], "big")
    return a, o


class TcpTransport(Transport):
    def __init__(self, mesh: PeerMesh) -> None:
        self.mesh = mesh
        self.rank = mesh.rank
        self.size = mesh.size
        # Mesh-negotiated wire schema (HELLO handshake at formation):
        # identical on every rank (min proto / AND of feature bits over
        # the full mesh), so the coordinator's single encoded payload
        # decodes on every peer and optional field groups stay
        # symmetric in a mixed-version world.
        from .wire import FEATURES_ALL
        self.features = getattr(mesh, "negotiated_features",
                                FEATURES_ALL)

    def _mask_unnegotiated(self, request_list: RequestList):
        """The coordinator's own RequestList never crosses the wire, so
        its optional field groups survive even when the world
        negotiated them away — while every peer's decode as zeros.  A
        strict-mode fingerprint compare would then see rank 0 diverge
        from everyone.  Mask the un-negotiated groups on the local
        list too, so all ranks present the identical (absent)
        schema."""
        import dataclasses

        from .wire import (FEATURE_FINGERPRINT, FEATURE_SHARDING,
                           FEATURE_TELEMETRY)
        kw = {}
        if not self.features & FEATURE_FINGERPRINT:
            kw.update(fp_seq=0, fp_digest=0, fp_tail_seqs=[],
                      fp_tail_digests=[], fp_tail_descs=[])
        if not self.features & FEATURE_TELEMETRY:
            kw.update(tm_cycles=0, tm_cycle_ms=0.0,
                      tm_sync_wait_ms=0.0, tm_queue_depth=0)
        if not self.features & FEATURE_SHARDING and \
                any(r.sp_spec for r in request_list.requests):
            # sp_spec is per-Request, not list-level: blank each one.
            kw.update(requests=[dataclasses.replace(r, sp_spec="")
                                for r in request_list.requests])
        return dataclasses.replace(request_list, **kw) if kw \
            else request_list
        # Coordinator-side: monotonic arrival time of each rank's last
        # gathered RequestList (telemetry straggler signal; the controller
        # reads it via getattr so LocalTransport needs no counterpart).
        self.last_gather_arrivals: dict[int, float] = {}

    # -- poison broadcast (resilience/) ----------------------------------
    def broadcast_poison(self, exc: RanksFailedError) -> None:
        """Best-effort abort frame to every surviving peer: whatever
        control recv each is blocked in, its next frame is this one, so
        ALL ranks raise RanksFailedError within one detection window
        instead of deadlocking behind the dead rank (ISSUE 5 tentpole)."""
        payload = POISON_MAGIC + exc.to_wire().encode()
        for peer in range(self.size):
            if peer == self.rank or peer in exc.failed_ranks:
                continue
            try:
                self.mesh.send(peer, payload)
            except Exception:  # noqa: BLE001 - peer may be gone too
                logger.debug("poison frame to rank %d undeliverable",
                             peer, exc_info=True)

    def _drain_or_poison(self, gen):
        """Run a coordinator-side arrival-order drain; on a detected
        rank failure, poison the survivors BEFORE re-raising so the
        whole world converts the hang into the same structured error."""
        try:
            yield from gen
        except RanksFailedError as exc:
            self.broadcast_poison(exc)
            raise

    # -- clock-offset probes (telemetry/trace.py cross-rank stitching) ---
    def estimate_clock_offset(self, rounds: int = 5) -> tuple[float, float]:
        """Estimate this rank's monotonic-clock offset against the
        coordinator via NTP-style round-trip probes: the worker stamps
        t0, the coordinator answers with its own monotonic time tc, the
        worker stamps t1; the minimum-RTT round gives
        ``offset = tc - (t0 + t1) / 2`` with error bounded by rtt/2.

        Runs ONCE at init, before the background loop touches the ctrl
        mesh — the probe frames are the first bytes on every ctrl
        channel, so they can never interleave with protocol frames.
        The estimate is recorded as trace METADATA (Timeline
        ``horovod_clock_sync``) and never applied destructively: raw
        per-rank files keep their own clock, the merge tool aligns.
        Returns ``(offset_us, rtt_us)``; the coordinator is the
        reference clock and returns ``(0.0, 0.0)``."""
        if self.size == 1:
            return 0.0, 0.0
        if self.rank == 0:
            for _ in range(rounds):
                for peer, _raw in self.mesh.recv_in_arrival_order(
                        range(1, self.size)):
                    self.mesh.send(peer,
                                   struct.pack("<d", time.monotonic()))
            return 0.0, 0.0
        best_rtt = float("inf")
        best_offset = 0.0
        for _ in range(rounds):
            t0 = time.monotonic()
            self.mesh.send(0, b"\x01")
            raw = self.mesh.recv(0)  # hvdlint: disable=unbounded-blocking-wait -- init-time probe; bounded inside the peer channel under fault tolerance like every ctrl recv
            t1 = time.monotonic()
            check_poison(raw)
            (tc,) = struct.unpack("<d", bytes(raw))
            rtt = t1 - t0
            if rtt < best_rtt:
                best_rtt = rtt
                best_offset = tc - (t0 + t1) / 2.0
        return best_offset * 1e6, best_rtt * 1e6

    # -- bitvector sync (reference: gloo_controller.cc bitwise ops) ------
    def bitwise_sync(self, and_word: int, or_word: int) -> tuple[int, int]:
        if self.size == 1:
            return and_word, or_word
        if self.rank == 0:
            # Drain peers in ARRIVAL order (selectors), not rank order:
            # AND/OR are commutative, and one slow rank no longer stalls
            # the reads of every faster rank queued behind it.
            for _, raw in self._drain_or_poison(
                    self.mesh.recv_in_arrival_order(range(1, self.size))):
                a, o = _unpack_words(raw)
                and_word &= a
                or_word |= o
            payload = _pack_words(and_word, or_word)
            for peer in range(1, self.size):
                self.mesh.send(peer, payload)
            return and_word, or_word
        self.mesh.send(0, _pack_words(and_word, or_word))
        raw = self.mesh.recv(0)  # hvdlint: disable=unbounded-blocking-wait -- bounded inside the peer channel under fault tolerance; poison frames convert coordinator-detected failures
        check_poison(raw)
        return _unpack_words(raw)

    # -- RequestList gather (reference: gloo_controller.cc allgatherv) ---
    def gather_requests(self, request_list: RequestList):
        if self.size == 1:
            return [request_list]
        if self.rank == 0:
            # Arrival-order drain (selectors): decode each rank's list
            # while slower peers are still sending, cutting the
            # negotiation tail when one rank lags.  The result stays
            # rank-indexed — arrival order never leaks downstream.
            lists: list[RequestList | None] = [None] * self.size
            lists[0] = self._mask_unnegotiated(request_list)
            arrivals = {0: time.monotonic()}
            for peer, raw in self._drain_or_poison(
                    self.mesh.recv_in_arrival_order(range(1, self.size))):
                arrivals[peer] = time.monotonic()
                lists[peer] = RequestList.from_bytes(raw, self.features)
            self.last_gather_arrivals = arrivals
            return lists
        self.mesh.send(0, request_list.to_bytes(self.features))
        return None

    # -- ResponseList broadcast ------------------------------------------
    def broadcast_responses(self, response_list):
        if self.size == 1:
            return response_list
        if self.rank == 0:
            payload = response_list.to_bytes(self.features)
            failure: RanksFailedError | None = None
            for peer in range(1, self.size):
                try:
                    self.mesh.send(peer, payload)
                except RanksFailedError as exc:
                    # Keep delivering to the SURVIVORS — a peer they can
                    # still hear from must not strand them — then poison.
                    failure = exc
            if failure is not None:
                self.broadcast_poison(failure)
                raise failure
            return response_list
        raw = self.mesh.recv(0)  # hvdlint: disable=unbounded-blocking-wait -- bounded inside the peer channel under fault tolerance; poison frames convert coordinator-detected failures
        check_poison(raw)
        return ResponseList.from_bytes(raw, self.features)

    def barrier(self) -> None:
        if self.size == 1:
            return
        if self.rank == 0:
            for _ in self._drain_or_poison(
                    self.mesh.recv_in_arrival_order(range(1, self.size))):
                pass
            for peer in range(1, self.size):
                self.mesh.send(peer, b"\x01")
        else:
            self.mesh.send(0, b"\x01")
            raw = self.mesh.recv(0)  # hvdlint: disable=unbounded-blocking-wait -- bounded inside the peer channel under fault tolerance; poison frames convert coordinator-detected failures
            check_poison(raw)
