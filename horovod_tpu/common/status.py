"""Status type threaded through every collective operation.

TPU-native analogue of the reference Status class
(reference: horovod/common/common.h:138-196): a collective either completes
OK, is still IN_PROGRESS (async), was ABORTED at shutdown, hit an
INVALID_ARGUMENT (cross-rank mismatch) or a generic ERROR.  The reference
delivers these to user callbacks instead of hanging — "mismatch → structured
error, not hang" is a first-class behavior we preserve.
"""
from __future__ import annotations

import enum
from dataclasses import dataclass, field


class StatusType(enum.IntEnum):
    OK = 0
    UNKNOWN_ERROR = 1
    PRECONDITION_ERROR = 2
    ABORTED = 3
    INVALID_ARGUMENT = 4
    IN_PROGRESS = 5


@dataclass(frozen=True)
class Status:
    type: StatusType = StatusType.OK
    reason: str = field(default="")

    @staticmethod
    def ok() -> "Status":
        return _OK

    @staticmethod
    def unknown_error(msg: str) -> "Status":
        return Status(StatusType.UNKNOWN_ERROR, msg)

    @staticmethod
    def precondition_error(msg: str) -> "Status":
        return Status(StatusType.PRECONDITION_ERROR, msg)

    @staticmethod
    def aborted(msg: str) -> "Status":
        return Status(StatusType.ABORTED, msg)

    @staticmethod
    def invalid_argument(msg: str) -> "Status":
        return Status(StatusType.INVALID_ARGUMENT, msg)

    @staticmethod
    def ranks_failed(exc) -> "Status":
        """A collective observed dead/unreachable ranks (resilience/).
        The structured attribution rides the reason string in
        RanksFailedError wire form so it survives both the in-process
        Status path and the Response.error_message wire field;
        raise_if_error re-raises the typed exception."""
        return Status(StatusType.UNKNOWN_ERROR, exc.to_wire())

    @staticmethod
    def in_progress() -> "Status":
        return _IN_PROGRESS

    def ok_p(self) -> bool:
        return self.type == StatusType.OK

    def in_progress_p(self) -> bool:
        return self.type == StatusType.IN_PROGRESS

    def raise_if_error(self) -> None:
        if self.type in (StatusType.OK, StatusType.IN_PROGRESS):
            return
        from .exceptions import HorovodInternalError, RanksFailedError

        if RanksFailedError.matches(self.reason):
            raise RanksFailedError.from_wire(self.reason)
        raise HorovodInternalError(self.reason or self.type.name)


_OK = Status(StatusType.OK, "")
_IN_PROGRESS = Status(StatusType.IN_PROGRESS, "")
