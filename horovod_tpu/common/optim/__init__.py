"""Numerical optimization helpers for the autotuner (GP + Bayesian opt)."""
