"""Bayesian optimization: GP surrogate + expected-improvement acquisition.

Reference: horovod/common/optim/bayesian_optimization.cc — same structure:
normalise parameters to the unit box, fit the GP on observed (params, score)
pairs, and pick the next sample by maximising expected improvement over a
candidate set (dense grid here instead of L-BFGS restarts; the search space
is 2-D and tiny).
"""
from __future__ import annotations

import math

import numpy as np

from .gaussian_process import GaussianProcess


def _norm_cdf(z: np.ndarray) -> np.ndarray:
    return 0.5 * (1.0 + np.vectorize(math.erf)(z / math.sqrt(2.0)))


def _norm_pdf(z: np.ndarray) -> np.ndarray:
    return np.exp(-0.5 * z * z) / math.sqrt(2.0 * math.pi)


class BayesianOptimization:
    def __init__(self, bounds: list[tuple[float, float]],
                 alpha: float = 0.8, xi: float = 0.01,
                 seed: int = 0) -> None:
        self.bounds = np.asarray(bounds, dtype=np.float64)
        self.gp = GaussianProcess(length_scale=0.2, alpha=alpha)
        self.xi = xi
        self._x: list[np.ndarray] = []
        self._y: list[float] = []
        self._rng = np.random.RandomState(seed)

    @property
    def dim(self) -> int:
        return len(self.bounds)

    def _to_unit(self, x: np.ndarray) -> np.ndarray:
        lo, hi = self.bounds[:, 0], self.bounds[:, 1]
        return (x - lo) / np.maximum(hi - lo, 1e-12)

    def _from_unit(self, u: np.ndarray) -> np.ndarray:
        lo, hi = self.bounds[:, 0], self.bounds[:, 1]
        return lo + u * (hi - lo)

    def add_sample(self, x, y: float) -> None:
        self._x.append(self._to_unit(np.asarray(x, dtype=np.float64)))
        self._y.append(float(y))
        self.gp.fit(np.stack(self._x), np.asarray(self._y))

    def suggest_next(self) -> np.ndarray:
        if not self._x:
            return self._from_unit(self._rng.uniform(size=self.dim))
        candidates = self._rng.uniform(size=(256, self.dim))
        mu, std = self.gp.predict(candidates)
        best = max(self._y)
        imp = mu - best - self.xi
        z = imp / std
        ei = imp * _norm_cdf(z) + std * _norm_pdf(z)
        ei[std < 1e-9] = 0.0
        return self._from_unit(candidates[int(np.argmax(ei))])

    def best(self) -> tuple[np.ndarray, float] | None:
        if not self._y:
            return None
        i = int(np.argmax(self._y))
        return self._from_unit(self._x[i]), self._y[i]

    @property
    def num_samples(self) -> int:
        return len(self._y)
