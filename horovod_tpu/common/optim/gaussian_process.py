"""Minimal Gaussian-process regressor (RBF kernel) for the autotuner.

Reference: horovod/common/optim/gaussian_process.cc (Eigen + L-BFGS there;
numpy closed-form here — the autotuner's 2-D, ≤20-sample problem doesn't
need hyperparameter optimization, a fixed length-scale works).
"""
from __future__ import annotations

import numpy as np


class GaussianProcess:
    def __init__(self, length_scale: float = 1.0, sigma_f: float = 1.0,
                 alpha: float = 1e-6) -> None:
        self.length_scale = length_scale
        self.sigma_f = sigma_f
        self.alpha = alpha   # observation noise on the diagonal
        self._x: np.ndarray | None = None
        self._y: np.ndarray | None = None
        self._k_inv: np.ndarray | None = None

    def _kernel(self, a: np.ndarray, b: np.ndarray) -> np.ndarray:
        # RBF: sigma_f^2 * exp(-|a-b|^2 / (2 l^2))
        sq = ((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
        return self.sigma_f ** 2 * np.exp(-0.5 * sq / self.length_scale ** 2)

    def fit(self, x: np.ndarray, y: np.ndarray) -> None:
        x = np.atleast_2d(np.asarray(x, dtype=np.float64))
        y = np.asarray(y, dtype=np.float64).reshape(-1)
        k = self._kernel(x, x) + self.alpha * np.eye(len(x))
        self._x, self._y = x, y
        self._k_inv = np.linalg.inv(k)

    def predict(self, x: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """Return (mean, std) at query points."""
        x = np.atleast_2d(np.asarray(x, dtype=np.float64))
        if self._x is None:
            return np.zeros(len(x)), np.ones(len(x))
        k_s = self._kernel(x, self._x)
        k_ss = self._kernel(x, x)
        mu = k_s @ self._k_inv @ self._y
        cov = k_ss - k_s @ self._k_inv @ k_s.T
        std = np.sqrt(np.maximum(np.diag(cov), 1e-12))
        return mu, std
