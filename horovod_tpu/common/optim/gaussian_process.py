"""Gaussian-process regressor (RBF kernel) for the autotuner.

Reference: horovod/common/optim/gaussian_process.cc — the reference fits
kernel hyperparameters with Eigen + L-BFGS on the log marginal
likelihood.  Here the search space is the unit box and samples number
<= ~20, so a dense log-spaced length-scale sweep maximizing the same log
marginal likelihood (closed form via Cholesky per candidate) reaches the
same optimum without a line-search dependency; targets are normalized to
zero-mean/unit-variance before fitting so the noise term `alpha` is
scale-free against real step-time jitter.
"""
from __future__ import annotations

import numpy as np

_LOG_2PI = float(np.log(2.0 * np.pi))


class GaussianProcess:
    def __init__(self, length_scale: float = 1.0, sigma_f: float = 1.0,
                 alpha: float = 1e-6, optimize: bool = True) -> None:
        self.length_scale = length_scale
        self.sigma_f = sigma_f
        self.alpha = alpha   # observation noise on the diagonal
        self.optimize = optimize
        self._x: np.ndarray | None = None
        self._y: np.ndarray | None = None
        self._k_inv: np.ndarray | None = None
        self._y_mean = 0.0
        self._y_std = 1.0
        self.last_lml: float | None = None   # observability/tests

    def _kernel(self, a: np.ndarray, b: np.ndarray,
                length_scale: float | None = None) -> np.ndarray:
        # RBF: sigma_f^2 * exp(-|a-b|^2 / (2 l^2))
        ls = self.length_scale if length_scale is None else length_scale
        sq = ((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
        return self.sigma_f ** 2 * np.exp(-0.5 * sq / ls ** 2)

    def _lml(self, x: np.ndarray, y: np.ndarray,
             length_scale: float) -> float:
        """Log marginal likelihood of the normalized targets under the
        RBF kernel with the given length scale (gaussian_process.cc
        computes the same objective for its L-BFGS fit)."""
        k = self._kernel(x, x, length_scale) + self.alpha * np.eye(len(x))
        try:
            chol = np.linalg.cholesky(k)
        except np.linalg.LinAlgError:
            return -np.inf
        alpha_v = np.linalg.solve(chol.T, np.linalg.solve(chol, y))
        return float(-0.5 * y @ alpha_v
                     - np.log(np.diag(chol)).sum()
                     - 0.5 * len(x) * _LOG_2PI)

    def fit(self, x: np.ndarray, y: np.ndarray) -> None:
        x = np.atleast_2d(np.asarray(x, dtype=np.float64))
        y_raw = np.asarray(y, dtype=np.float64).reshape(-1)
        # Normalize targets: bytes/sec scores span orders of magnitude
        # across hardware; the kernel amplitude and noise stay O(1).
        self._y_mean = float(y_raw.mean())
        self._y_std = float(y_raw.std()) or 1.0
        yn = (y_raw - self._y_mean) / self._y_std

        if self.optimize and len(x) >= 3:
            # Dense sweep over length scales spanning "one candidate
            # apart" to "the whole unit box" — the 1-D analogue of the
            # reference's gradient fit, robust to LML multimodality.
            candidates = np.logspace(-1.3, 0.3, 17)
            scored = [(self._lml(x, yn, ls), ls) for ls in candidates]
            self.last_lml, self.length_scale = max(scored)
        else:
            self.last_lml = self._lml(x, yn, self.length_scale) \
                if len(x) else None

        k = self._kernel(x, x) + self.alpha * np.eye(len(x))
        self._x, self._y = x, yn
        self._k_inv = np.linalg.inv(k)

    def predict(self, x: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """Return (mean, std) at query points, in the RAW target scale."""
        x = np.atleast_2d(np.asarray(x, dtype=np.float64))
        if self._x is None:
            return np.zeros(len(x)), np.ones(len(x))
        k_s = self._kernel(x, self._x)
        k_ss = self._kernel(x, x)
        mu = k_s @ self._k_inv @ self._y
        cov = k_ss - k_s @ self._k_inv @ k_s.T
        std = np.sqrt(np.maximum(np.diag(cov), 1e-12))
        return (mu * self._y_std + self._y_mean), std * self._y_std
