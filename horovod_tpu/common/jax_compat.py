"""Version-tolerant jax API shims.

The codebase targets the modern ``jax.shard_map`` surface
(``check_vma=``, ``axis_names=``); older jaxlib builds (<= 0.4.x, the
pin in some CI containers) only ship
``jax.experimental.shard_map.shard_map`` with the ``check_rep=`` /
``auto=`` spelling.  This module maps one onto the other so every
caller — training.Trainer, parallel/collectives, grad_sync, the model
zoo and the tests — works on both without scattering try/except imports.
"""
from __future__ import annotations

from typing import Any

import jax


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True,
              axis_names: Any = None):
    """``jax.shard_map`` when available, else the experimental API with
    ``check_vma``→``check_rep`` and ``axis_names``→``auto`` translated."""
    modern = getattr(jax, "shard_map", None)
    if modern is not None:
        kwargs: dict[str, Any] = {"check_vma": check_vma}
        if axis_names is not None:
            kwargs["axis_names"] = axis_names
        return modern(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **kwargs)
    from jax.experimental.shard_map import shard_map as legacy
    # axis_names (manual axes) would map to the legacy ``auto=``
    # complement, but legacy partial-auto lowering is broken on the
    # versions that lack jax.shard_map (axis_index emits a PartitionId
    # the SPMD partitioner rejects).  Run fully manual instead: axes the
    # specs don't mention are replicated, which preserves results for
    # spec-closed functions at the cost of duplicated compute on the
    # would-be-auto axes.
    return legacy(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_rep=check_vma, auto=frozenset())
