"""Topology discovery and neighbor-preferring ring construction.

Reference: arXiv:1909.09756 (MLPerf on TPU-v3 pods) — the interconnect is
a 2-D torus of hosts×chips, and collective schedules that walk physical
neighbors (torus-ordered rings, hierarchical host×chip reduction) beat
layout-oblivious rings by keeping every hop on an adjacent link.

This module is the single source of truth for *what the layout is*; the
data planes (backend/tcp.py, backend/hierarchical.py) consume it as a
ring-order permutation, a torus shape, and a list of hierarchy levels.

Declaration: `HOROVOD_TOPOLOGY` =
  - ``flat``       — layout-oblivious; identity ring order (the pre-18
                     behavior, and the safe default for unknown fabrics);
  - ``host``       — two-level host×slot layout; ring orders keep
                     intra-host peers adjacent so cross-host links carry
                     only 1/local_size of the ring bytes;
  - ``torus:RxC``  — R×C grid, rank = row*C + col (row-major); ring
                     orders walk the grid boustrophedon (snake) so every
                     ring hop is a grid-neighbor link, and the two-phase
                     torus allreduce becomes eligible;
  - ``""`` (auto)  — ``host`` when the launcher env describes a
                     homogeneous two-level host-major layout (the same
                     eligibility test the hierarchical backend applies),
                     else ``flat``.

The knob is launcher-set and identical on every rank, so every consumer
below derives rank-symmetric decisions from it (the deadlock-freedom
invariant: algorithm choice additionally rides the negotiated
ResponseList, never a local heuristic).
"""
from __future__ import annotations

from dataclasses import dataclass

from . import config

# Allreduce algorithm vocabulary shared by the selection logic
# (backend/tcp.py), the autotuner sweep (parameter_manager.py) and the
# ResponseList.tuned_algo wire field: the svarint carries the index.
ALGO_NAMES = ("auto", "ring", "tree", "rhd", "torus")


def algo_index(name: str) -> int:
    """Wire index of an algorithm name (HVD_ALGO / tuned_algo)."""
    return ALGO_NAMES.index(name)


def algo_name(index: int) -> str:
    """Algorithm name for a tuned_algo wire index (bounds-checked: an
    out-of-range index from a newer peer degrades to 'auto')."""
    return ALGO_NAMES[index] if 0 <= index < len(ALGO_NAMES) else "auto"


@dataclass(frozen=True)
class Topology:
    """Immutable layout descriptor; all deriveds are pure functions."""

    size: int
    kind: str = "flat"            # flat | host | torus
    rows: int = 0                 # torus only
    cols: int = 0                 # torus only
    local_size: int = 1           # host only (slots per host)
    # Optional explicit rank->host map (elastic driver slots); when
    # present it overrides the homogeneous host-major assumption for the
    # host ring order.  A tuple so the dataclass stays hashable.
    hosts: tuple[int, ...] | None = None

    # -- validity ------------------------------------------------------
    def valid(self) -> bool:
        if self.kind == "torus":
            return self.rows >= 1 and self.cols >= 1 and \
                self.rows * self.cols == self.size
        if self.kind == "host":
            return self.local_size >= 1 and \
                self.size % max(self.local_size, 1) == 0
        return True

    # -- ring construction ---------------------------------------------
    def ring_order(self) -> list[int]:
        """Permutation of ranks in ring-walk order.

        torus: boustrophedon (snake) grid walk — row 0 left-to-right,
        row 1 right-to-left, ... — so consecutive ring positions are
        grid neighbors on every hop except (best-effort) the wrap link.
        host: ranks grouped by host (host-major), so each host's slots
        are adjacent on the ring and exactly ONE inbound + ONE outbound
        ring edge per host crosses the slow axis.  flat: identity."""
        if self.kind == "torus" and self.valid():
            order: list[int] = []
            for r in range(self.rows):
                cols = range(self.cols) if r % 2 == 0 \
                    else range(self.cols - 1, -1, -1)
                order.extend(r * self.cols + c for c in cols)
            return order
        if self.kind == "host":
            if self.hosts is not None and len(self.hosts) == self.size:
                # Explicit slot map (elastic driver): stable sort keeps
                # ranks ordered within each host.
                return sorted(range(self.size),
                              key=lambda r: (self.hosts[r], r))
            # Launcher's homogeneous host-major assignment
            # (rank == host * local_size + slot) is already host-grouped.
            return list(range(self.size))
        return list(range(self.size))

    # -- hierarchy -----------------------------------------------------
    def levels(self) -> list[int]:
        """Per-level group sizes, innermost (fastest links) first."""
        if self.kind == "host" and self.valid() and self.local_size > 1:
            return [self.local_size, self.size // self.local_size]
        if self.kind == "torus" and self.valid():
            return [self.cols, self.rows]
        return [self.size]

    def describe(self) -> str:
        """Stable human/payload label, e.g. 'torus:2x4', 'host:4x2'."""
        if self.kind == "torus":
            return f"torus:{self.rows}x{self.cols}"
        if self.kind == "host":
            return f"host:{self.size // max(self.local_size, 1)}" \
                   f"x{self.local_size}"
        return "flat"


def parse(spec: str, *, size: int, local_size: int = 1,
          cross_size: int = 1,
          hosts: tuple[int, ...] | None = None) -> Topology:
    """Build a Topology from a HOROVOD_TOPOLOGY spec string.

    Invalid specs degrade to flat with a warning rather than raising:
    the knob is launcher-uniform, so every rank degrades identically."""
    from .logging import logger
    spec = (spec or "").strip().lower()
    if spec.startswith("torus:"):
        shape = spec[len("torus:"):]
        try:
            r_s, c_s = shape.split("x", 1)
            rows, cols = int(r_s), int(c_s)
        except ValueError:
            rows = cols = 0
        topo = Topology(size=size, kind="torus", rows=rows, cols=cols)
        if topo.valid():
            return topo
        logger.warning("HOROVOD_TOPOLOGY=%s does not tile %d ranks; "
                       "using flat", spec, size)
        return Topology(size=size)
    if spec == "host":
        topo = Topology(size=size, kind="host", local_size=local_size,
                        hosts=hosts)
        if topo.valid() and local_size > 1:
            return topo
        logger.warning("HOROVOD_TOPOLOGY=host but the env describes no "
                       "multi-slot hosts (local_size=%d); using flat",
                       local_size)
        return Topology(size=size)
    if spec in ("", "auto"):
        # Auto-detect: the same homogeneous two-level eligibility test
        # the hierarchical backend applies (core.py layout verdict).
        if local_size > 1 and cross_size > 1 and \
                local_size * cross_size == size:
            return Topology(size=size, kind="host",
                            local_size=local_size, hosts=hosts)
        # Uneven multi-host layout with an explicit rank→host map
        # (HOROVOD_HOST_IDS): group the ring by host anyway.  local_size
        # is pinned to 1 — NOT the per-rank env value, which varies
        # across hosts here and would give ranks diverging Topologies —
        # so the level ladder stays [size] (hierarchy needs homogeneity)
        # while ring_order still clusters each host's slots.
        if hosts is not None and len(hosts) == size and \
                1 < len(set(hosts)) < size:
            return Topology(size=size, kind="host", hosts=hosts)
        return Topology(size=size)
    if spec != "flat":
        logger.warning("unknown HOROVOD_TOPOLOGY=%r; using flat", spec)
    return Topology(size=size)


def resolve(size: int, local_size: int = 1, cross_size: int = 1,
            hosts: tuple[int, ...] | None = None) -> Topology:
    """Topology for this world from the HOROVOD_TOPOLOGY knob."""
    return parse(config.TOPOLOGY.get(), size=size, local_size=local_size,
                 cross_size=cross_size, hosts=hosts)
