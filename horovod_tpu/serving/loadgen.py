"""Open-loop SLO load harness: ``python -m horovod_tpu.serving.loadgen``.

Drives synthetic traffic at the serving world and reports the numbers a
capacity planner actually needs, next to the training benches:

- **Open-loop Poisson arrivals** (``--rate``, ``--profile
  steady|burst|ramp``): arrival times are drawn independently of
  completion times, so an overloaded server sees the queue grow instead
  of the load generator politely slowing down — the only honest way to
  measure shed behavior (closed-loop generators hide collapse).
- **SLO accounting**: every request carries a deadline stamped at
  ingress; the report separates served / served-within-SLO / shed /
  expired / lost, with p50/p99/p999 latency and goodput vs offered
  load.
- **Chaos**: run under ``HOROVOD_CHAOS`` (e.g. a rank kill mid-serve)
  and the world shrinks and keeps serving; the report records every
  shrink.

The JSON report lands in ``--output`` (default ``SERVE_r{rank}.json``,
the BENCH_r*.json convention — ``{rank}`` substitutes), one file per
rank; the front end's file carries the latency/goodput stats.
"""
from __future__ import annotations

import argparse
import json
import random
import threading
import time

from ..common import config
from .replica import ReplicaExecutor, ServeConfig

SCHEMA = "horovod_tpu.serving.loadgen/2"


def arrival_times(rng: random.Random, n: int, duration: float,
                  rate: float, profile: str) -> list[float]:
    """Relative arrival offsets: Poisson process at ``rate`` req/s,
    shaped by profile (burst = 4x rate through the middle fifth; ramp =
    0.25x -> 2x linearly), truncated at ``n`` requests or ``duration``
    seconds, whichever first."""
    times: list[float] = []
    t = 0.0
    while len(times) < n:
        frac = min(t / duration, 1.0) if duration > 0 else 0.0
        r = rate
        if profile == "burst" and 0.4 <= frac < 0.6:
            r = rate * 4.0
        elif profile == "ramp":
            r = rate * (0.25 + 1.75 * frac)
        t += rng.expovariate(r)
        if duration > 0 and t >= duration:
            break
        times.append(t)
    return times


def drive_ingress(executor: ReplicaExecutor, times: list[float],
                  rng: random.Random, *, prompt_tokens: int,
                  max_new_tokens: int, slo_ms: float | None,
                  done: threading.Event, prompt_pool: int = 0) -> None:
    """Submit one request per arrival time (front-end thread); closes
    the queue and sets ``done`` when the schedule is exhausted.
    ``prompt_pool > 0`` draws prompts from that many fixed token lists
    instead of fresh randomness — the repeated-prompt profile that
    exercises the paged prefix cache (ISSUE 14)."""
    vocab = executor.model.cfg.vocab_size
    pool = None
    if prompt_pool > 0:
        pool = [[rng.randrange(2, vocab)
                 for _ in range(rng.randint(2, max(2, prompt_tokens)))]
                for _ in range(prompt_pool)]
    start = time.monotonic()
    try:
        for i, t in enumerate(times):
            delay = start + t - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            if pool is not None:
                toks = pool[i % len(pool)]
            else:
                n = rng.randint(2, max(2, prompt_tokens))
                toks = [rng.randrange(2, vocab) for _ in range(n)]
            executor.stats["offered"] += 1
            executor.queue.submit(toks, max_new_tokens, slo_ms)
    finally:
        executor.queue.close()
        done.set()


def build_report(executor: ReplicaExecutor, *, offered: int,
                 wall_s: float, args_echo: dict) -> dict:
    """The SERVE_r*.json payload (front end carries the full stats;
    other ranks report their local completion view)."""
    stats = executor.stats
    lat = sorted(stats["latencies_ms"])

    def pct(q: float) -> float:
        if not lat:
            return 0.0
        return lat[min(len(lat) - 1, int(q * len(lat)))]

    reg_snapshot = {m["name"]: m for m
                    in _registry_snapshot(executor)["metrics"]
                    if m["name"] == "horovod_serve_step_ms"}
    step_hist = executor.admission._m_step
    served = stats["served"]
    report = {
        "schema": SCHEMA,
        "rank": executor.rank,
        "world": {"size": executor.size,
                  "replica_groups": executor.num_groups,
                  "group_size": executor.group_size,
                  "shrinks": stats["shrinks"],
                  "grows": stats["grows"]},
        "goodput_phases": _goodput_phases(executor, wall_s),
        "config": args_echo,
        "offered": offered,
        "served": served,
        "served_within_slo": stats["served_slo"],
        "expired": stats["expired"],
        "lost_on_failure": stats["lost"],
        "shed": max(0, offered - served - stats["expired"]
                    - stats["lost"]),
        "shed_rate": (max(0, offered - served) / offered
                      if offered else 0.0),
        "latency_ms": {"p50": pct(0.50), "p99": pct(0.99),
                       "p999": pct(0.999),
                       "mean": (sum(lat) / len(lat)) if lat else 0.0,
                       "max": lat[-1] if lat else 0.0},
        "step_ms": {"p50": step_hist.quantile(0.5),
                    "p99": step_hist.quantile(0.99),
                    "count": step_hist.count},
        "goodput_rps": served / wall_s if wall_s > 0 else 0.0,
        "offered_rps": offered / wall_s if wall_s > 0 else 0.0,
        "tokens_generated": sum(rec["tokens"]
                                for rec in executor.completed.values()),
        "local_completed": len(executor.completed),
        "wall_s": wall_s,
        "steps": executor._step,
        "step_metrics_present": bool(reg_snapshot),
        # Paged-KV residency/reuse (None in dense mode): the A/B
        # numbers bench.py --model serve reports next to the dense leg.
        "kv": executor.kv_stats(),
        "max_concurrent_seqs": executor.batcher.max_concurrent,
        # Fleet continuous-deployment staleness accounting: which weight
        # versions served this rank's completions and how many trainer
        # steps behind the newest staged snapshot any of them ran
        # (docs/fleet.md).
        "weights": _weights_report(executor),
    }
    return report


def _weights_report(executor: ReplicaExecutor) -> dict:
    versions: dict[str, int] = {}
    stale_max = 0
    for rec in executor.completed.values():
        v = str(rec.get("weights", 0))
        versions[v] = versions.get(v, 0) + 1
        stale_max = max(stale_max, rec.get("weights_stale_steps", 0))
    return {"final_version": executor.weight_version,
            "versions": versions,
            "max_staleness_steps": stale_max,
            "swaps": [{"version": s["version"], "step": s["step"]}
                      for s in executor.stats["weight_swaps"]]}


def _goodput_phases(executor: ReplicaExecutor,
                    wall_s: float) -> dict | None:
    """Goodput (served/s) before, during and after the FIRST elastic
    grow — the number that shows incumbents kept serving through the
    catch-up (docs/statesync.md).  None when no grow happened."""
    grows = executor.stats["grows"]
    done = executor.stats["completed_at"]
    if not grows or wall_s <= 0:
        return None
    g = grows[0]
    t1 = g["at"]                       # grow transition completed
    t0 = t1 - max(g.get("window_s", 0.0), 1e-9)   # donation started
    start = min(done + [t0])
    end = max(done + [t1])

    def rate(lo: float, hi: float) -> float:
        span = hi - lo
        if span <= 0:
            return 0.0
        return sum(1 for t in done if lo <= t < hi) / span

    return {"before_rps": rate(start, t0),
            "during_rps": rate(t0, t1),
            "after_rps": rate(t1, end + 1e-9),
            "window_s": t1 - t0}


def _registry_snapshot(executor: ReplicaExecutor) -> dict:
    from .. import telemetry
    return telemetry.metrics().snapshot()


def write_report(report: dict, output: str, rank: int) -> str:
    path = output.replace("{rank}", str(rank))
    with open(path, "w") as f:
        json.dump(report, f, indent=1, sort_keys=True)
        f.write("\n")
    return path


def run(args: argparse.Namespace) -> dict:
    import horovod_tpu as hvd
    hvd.init()
    overrides = {}
    if args.max_batch:
        overrides["max_batch"] = args.max_batch
    if args.token_budget:
        overrides["token_budget"] = args.token_budget
    if args.slo_ms:
        overrides["slo_ms"] = args.slo_ms
    executor = ReplicaExecutor(ServeConfig.from_env(**overrides))
    statesync_service = None
    if config.STATESYNC.get():
        # Elastic grow mid-serve (docs/statesync.md): every serve step
        # ends with the membership check, so a joining replica
        # (serving/replica.py join_serving_world) can enter while this
        # harness drives traffic — the report's world.grows and
        # goodput_phases record the transition.
        from .. import statesync
        statesync_service = statesync.StateSyncService(
            state_provider=executor.state_tree, static_state=True)
        executor.attach_statesync(statesync_service)
    done = threading.Event()
    t0 = time.monotonic()
    ingress = None
    if executor.rank == executor.front:
        rng = random.Random(args.seed)
        times = arrival_times(rng, args.requests, args.duration,
                              args.rate, args.profile)
        ingress = threading.Thread(
            target=drive_ingress, daemon=True, name="serve-ingress",
            args=(executor, times, rng),
            kwargs=dict(prompt_tokens=args.prompt_tokens,
                        max_new_tokens=args.max_new_tokens,
                        slo_ms=args.slo_ms, done=done,
                        prompt_pool=args.prompt_pool))
        ingress.start()
    executor.serve_loop(stop_when=done.is_set)
    wall = time.monotonic() - t0
    if ingress is not None:
        # Reap the ingress driver (hvdlife HVD701): it sets `done` as
        # its last act, so by the time serve_loop returned it is at
        # most one submit away from exit.
        ingress.join(timeout=10.0)
    report = build_report(
        executor, offered=executor.stats["offered"], wall_s=wall,
        args_echo={"requests": args.requests, "duration": args.duration,
                   "rate": args.rate, "profile": args.profile,
                   "prompt_tokens": args.prompt_tokens,
                   "max_new_tokens": args.max_new_tokens,
                   "slo_ms": args.slo_ms
                   or config.SERVE_SLO_MS.get(),
                   "prompt_pool": args.prompt_pool,
                   "paged": executor.cfg.paged,
                   "seed": args.seed})
    path = write_report(report, args.output, executor.rank)
    if executor.rank == executor.front:
        print(json.dumps({k: report[k] for k in
                          ("served", "shed", "expired", "goodput_rps",
                           "latency_ms", "world")}, sort_keys=True))
        print(f"loadgen: report written to {path}")
    if statesync_service is not None:
        statesync_service.close()
    executor.close()
    hvd.shutdown()
    return report


def make_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="python -m horovod_tpu.serving.loadgen",
        description="Open-loop Poisson load harness for the serving "
                    "subsystem (docs/serving.md).")
    parser.add_argument("--requests", type=int, default=64,
                        help="max requests to offer")
    parser.add_argument("--duration", type=float, default=5.0,
                        help="ingress window seconds (0 = until "
                             "--requests exhausts)")
    parser.add_argument("--rate", type=float, default=20.0,
                        help="mean offered load, requests/second")
    parser.add_argument("--profile", default="steady",
                        choices=["steady", "burst", "ramp"])
    parser.add_argument("--prompt-tokens", type=int, default=12,
                        help="max prompt length (uniform 2..N)")
    parser.add_argument("--max-new-tokens", type=int, default=8)
    parser.add_argument("--slo-ms", type=float, default=0.0,
                        help="per-request SLO (0 = HOROVOD_SERVE_SLO_MS)")
    parser.add_argument("--max-batch", type=int, default=0)
    parser.add_argument("--token-budget", type=int, default=0)
    parser.add_argument("--prompt-pool", type=int, default=0,
                        help="draw prompts from N fixed token lists "
                             "(0 = fresh random per request); the "
                             "repeated-prompt profile that exercises "
                             "the paged prefix cache")
    parser.add_argument("--seed", type=int, default=1234)
    parser.add_argument("--output", default="SERVE_r{rank}.json",
                        help="report path; {rank} substitutes")
    return parser


def main(argv: list[str] | None = None) -> int:
    args = make_parser().parse_args(argv)
    if args.slo_ms == 0.0:
        args.slo_ms = None
    run(args)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
