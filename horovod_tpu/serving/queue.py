"""Front-end request queue: bounded ingress with deadlines stamped at
the door.

Every request that enters the serving system gets its absolute SLO
deadline computed HERE, at ingress — not when it is scheduled — so time
spent queued counts against the SLO exactly like time spent decoding
(the property the MLPerf serving rules and every production queue share).
The queue itself is bounded: a full queue sheds at submit instead of
buffering, because an unbounded ingress queue converts overload into
unbounded latency for every later request (hvdlint HVD1006 enforces the
same discipline tree-wide in serving/).

Deadlines are ``time.monotonic()``-absolute.  The batch plan ships them
to replicas as *remaining milliseconds* (re-stamped on arrival), so a
cross-host clock offset shifts a deadline by one plan hop, not by the
absolute clock difference.
"""
from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from dataclasses import field

from ..common import config


@dataclasses.dataclass
class ServeRequest:
    """One inference request as the front end sees it."""
    rid: int
    tokens: list[int]                  # prompt token ids
    max_new_tokens: int
    arrival: float                     # monotonic ingress stamp
    deadline: float                    # absolute monotonic SLO deadline
    slo_ms: float
    replica: int = -1                  # assigned replica group (batcher)
    generated: list[int] = field(default_factory=list)
    # Steps the batcher has deferred this request for budget/slot/block
    # pressure; past HOROVOD_SERVE_MAX_DEFERRALS it turns urgent and
    # reserves the step's admission budget (starvation fix, ISSUE 14).
    deferrals: int = 0

    def remaining_ms(self, now: float | None = None) -> float:
        now = time.monotonic() if now is None else now
        return (self.deadline - now) * 1e3


class RequestQueue:
    """Bounded FIFO ingress queue (front-end rank only holds traffic;
    other ranks keep an empty one so a promoted front end after an
    elastic shrink is ready immediately)."""

    def __init__(self, maxsize: int | None = None,
                 default_slo_ms: float | None = None,
                 registry=None) -> None:
        self.maxsize = config.SERVE_QUEUE_DEPTH.get() \
            if maxsize is None else int(maxsize)
        self.default_slo_ms = config.SERVE_SLO_MS.get() \
            if default_slo_ms is None else float(default_slo_ms)
        self._lock = threading.Lock()
        self._items: deque[ServeRequest] = deque()
        self._next_rid = 0
        self._closed = False
        if registry is None:
            from .. import telemetry
            registry = telemetry.metrics()
            if not registry.enabled:
                # Real depth/shed accounting even with training-path
                # telemetry off (see AdmissionController).
                from ..telemetry.registry import MetricsRegistry
                registry = MetricsRegistry(0)
        self._m_depth = registry.gauge(
            "horovod_serve_queue_depth",
            "Requests waiting in the front-end ingress queue")
        self._m_rejected = registry.counter(
            "horovod_serve_requests_total",
            "Serving requests by outcome",
            labels={"outcome": "rejected_full"})

    # -- ingress ---------------------------------------------------------
    def submit(self, tokens, max_new_tokens: int,
               slo_ms: float | None = None) -> int | None:
        """Enqueue one request; returns its rid, or None when the queue
        is full (the caller counts the shed — nothing blocks)."""
        now = time.monotonic()
        slo = self.default_slo_ms if slo_ms is None else float(slo_ms)
        with self._lock:
            if self._closed or len(self._items) >= self.maxsize:
                self._m_rejected.inc()
                return None
            rid = self._next_rid
            self._next_rid += 1
            self._items.append(ServeRequest(
                rid=rid, tokens=[int(t) for t in tokens],
                max_new_tokens=int(max_new_tokens), arrival=now,
                deadline=now + slo / 1e3, slo_ms=slo))
            self._m_depth.set(len(self._items))
            return rid

    def close(self) -> None:
        """No further submissions; queued requests still drain."""
        with self._lock:
            self._closed = True

    @property
    def closed(self) -> bool:
        return self._closed

    # -- scheduling side -------------------------------------------------
    def pop_ready(self, limit: int,
                  now: float | None = None
                  ) -> tuple[list[ServeRequest], list[ServeRequest]]:
        """Dequeue up to ``limit`` requests in arrival order, splitting
        out the ones whose deadline already expired while queued (they
        are shed — 'expired' — and must never be executed)."""
        now = time.monotonic() if now is None else now
        ready: list[ServeRequest] = []
        expired: list[ServeRequest] = []
        with self._lock:
            while self._items and len(ready) < limit:
                req = self._items.popleft()
                (expired if req.deadline <= now else ready).append(req)
            self._m_depth.set(len(self._items))
        return ready, expired

    def requeue_front(self, reqs: list[ServeRequest]) -> None:
        """Return not-yet-admitted requests to the head of the queue in
        their original order (budget/slot pressure, not a shed)."""
        with self._lock:
            for req in reversed(reqs):
                self._items.appendleft(req)
            self._m_depth.set(len(self._items))

    def depth(self) -> int:
        with self._lock:
            return len(self._items)
