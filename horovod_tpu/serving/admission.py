"""Admission control: deadline feasibility + load shedding, keyed off
live telemetry.

A request is admitted only when BOTH hold:

- **Deadline feasibility.**  Its estimated completion time — prefill
  plus ``max_new_tokens`` decode steps at the live per-step latency
  estimate, padded by the coordinator straggler-lag gauge — fits inside
  the remaining SLO budget.  An infeasible request is shed at admission
  and never executed: executing it would burn a decode slot to produce
  an answer nobody can use, which is how overload collapses goodput.
- **Load.**  The ingress queue-depth gauge stays under
  ``HOROVOD_SERVE_SHED_QUEUE_FRACTION`` of the queue bound.  Depth is a
  leading indicator: by the time latency SLOs blow, the queue has been
  growing for many steps.

The step-latency estimate is the telemetry path shared with training
(``Histogram.quantile`` over ``horovod_serve_step_ms``), with an EWMA
warm-start so the first requests of a cold process are not admitted
against a zero estimate.  All outcomes are counted:
``horovod_serve_requests_total{outcome=admitted|shed|expired|served|
lost|rejected_full}``.
"""
from __future__ import annotations

import time

from ..common import config


class AdmissionController:
    """Per-process admission policy (consulted on the front-end rank)."""

    def __init__(self, registry=None, *, queue_depth_limit: int | None = None,
                 shed_fraction: float | None = None,
                 step_ms_seed: float = 5.0) -> None:
        if registry is None:
            from .. import telemetry
            registry = telemetry.metrics()
            if not registry.enabled:
                # Admission is CONTROL, not just observability: the
                # step-time histogram and outcome counters must be real
                # even when the training-path registry is the no-op
                # (serving hot paths are steps, not per-byte sends, so
                # the zero-overhead-off contract does not apply).
                from ..telemetry.registry import MetricsRegistry
                registry = MetricsRegistry(0)
        self._reg = registry
        self.queue_depth_limit = config.SERVE_QUEUE_DEPTH.get() \
            if queue_depth_limit is None else int(queue_depth_limit)
        self.shed_fraction = config.SERVE_SHED_QUEUE_FRACTION.get() \
            if shed_fraction is None else float(shed_fraction)
        # EWMA warm-start for the cold process; the histogram takes over
        # as soon as real steps land.
        self._ewma_step_ms = float(step_ms_seed)
        self._m_step = registry.histogram(
            "horovod_serve_step_ms",
            "Wall time of one serve step (plan exchange + prefill + "
            "decode + completion exchange)")
        self._m_latency = registry.histogram(
            "horovod_serve_request_latency_ms",
            "End-to-end request latency, ingress to final token")
        self._m_outcome = {
            outcome: registry.counter(
                "horovod_serve_requests_total",
                "Serving requests by outcome",
                labels={"outcome": outcome})
            for outcome in ("admitted", "shed", "expired", "served",
                            "lost")}

    # -- live estimates --------------------------------------------------
    def step_ms(self, q: float = 0.5) -> float:
        """Live per-step latency estimate: the shared histogram quantile
        path once data exists, the EWMA warm-start before that."""
        if self._m_step.count >= 8:
            return self._m_step.quantile(q)
        return self._ewma_step_ms

    def straggler_lag_ms(self) -> float:
        """Coordinator straggler-lag gauge (telemetry/straggler.py);
        0.0 when metrics are off or no window has completed."""
        return self._reg.gauge(
            "horovod_controller_straggler_lag_ms",
            labels={"stat": "mean"}).value

    def observe_step_ms(self, ms: float) -> None:
        self._m_step.observe(ms)
        self._ewma_step_ms += 0.2 * (ms - self._ewma_step_ms)

    # -- the decision ----------------------------------------------------
    def estimate_completion_ms(self, req, steps_per_token: float = 1.0
                               ) -> float:
        """Estimated ms until req's final token if admitted now: one
        prefill step plus one decode step per generated token at the
        live p50 step time, padded by the straggler lag (a slow replica
        stretches every broadcast-consistent step)."""
        per_step = self.step_ms() + self.straggler_lag_ms()
        return (1.0 + req.max_new_tokens * steps_per_token) * per_step

    def admit(self, req, queue_depth: int,
              now: float | None = None) -> tuple[bool, str]:
        """(admit?, outcome) — outcome is the counted disposition when
        refused ('expired' | 'shed'); the caller records 'admitted'."""
        now = time.monotonic() if now is None else now
        if req.deadline <= now:
            self.count("expired")
            return False, "expired"
        if queue_depth > self.shed_fraction * self.queue_depth_limit:
            self.count("shed")
            return False, "shed"
        if now + self.estimate_completion_ms(req) / 1e3 > req.deadline:
            self.count("shed")
            return False, "shed"
        self.count("admitted")
        return True, "admitted"

    # -- accounting ------------------------------------------------------
    def count(self, outcome: str, n: int = 1) -> None:
        self._m_outcome[outcome].inc(n)

    def outcome_totals(self) -> dict:
        """Cumulative request counts by outcome — the fleet gauge
        publisher (fleet/wiring.py) computes per-interval shed rate
        from the deltas."""
        return {k: c.value for k, c in self._m_outcome.items()}

    def observe_latency_ms(self, ms: float) -> None:
        self._m_latency.observe(ms)
