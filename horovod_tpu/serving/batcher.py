"""Continuous batcher: token-budgeted batch assembly over in-flight
decode batches.

Classic batching waits for a batch to fill, runs it to completion, and
only then admits more — tail latency inherits the longest generation in
every batch.  Continuous batching (Orca-style) instead treats the batch
as a set of SLOTS: every serve step, finished slots free up and the
batcher admits queued requests straight into the half-decoded batch.
The unit of work per step is bounded by a token budget (prefill tokens
of new admissions + one decode token per active slot), which keeps step
time — and therefore the admission controller's SLO math — predictable.

The batcher runs on the front-end rank and produces one :class:`BatchPlan`
per step; the plan is broadcast to every rank (replica.py), which is the
broadcast-consistent scheduling discipline: replicas never diverge on a
collective because every rank executes the same plan sequence.
"""
from __future__ import annotations

import dataclasses
import time
from dataclasses import field

from ..common import config
from .queue import RequestQueue, ServeRequest


@dataclasses.dataclass
class Assignment:
    """One request newly admitted into a replica group's decode batch."""
    rid: int
    replica: int                       # replica-group index
    tokens: list[int]
    max_new_tokens: int
    age_ms: float                      # ingress age when the plan formed
    deadline_rel_ms: float             # SLO budget left when it formed
    slo_ms: float = 0.0
    # Disaggregated prefill/decode (HOROVOD_SERVE_PREFILL_RANKS): the
    # rank that runs this prompt's prefill and streams the finished KV
    # blocks to the decode replica; -1 = the replica prefills locally.
    prefill: int = -1


@dataclasses.dataclass
class BatchPlan:
    """The per-step schedule every rank executes identically (pickled
    over hvd.broadcast_object)."""
    step: int
    assign: list[Assignment] = field(default_factory=list)
    stop: bool = False
    # Fleet continuous deployment (fleet/deploy.py): when non-zero,
    # every rank swaps its staged weight snapshot to this version at
    # THIS step — the broadcast IS the swap schedule, so replicas never
    # decode one step with mixed weights.
    swap_version: int = 0


class ContinuousBatcher:
    """Front-end accounting of replica-group slots + plan assembly.

    With paged KV (``block_capacity > 0``) the batcher also mirrors each
    replica's block-pool residency: an admission reserves the prompt's
    worst-case block count (prompt + max_new tokens, plus one block of
    copy-on-write headroom) and a candidate replica must have capacity.
    The mirror is conservative — prefix-cache hits on the replica use
    fewer physical blocks than reserved — which is exactly what makes
    reserve-at-admission safe: a replica can never run out of blocks
    mid-decode."""

    def __init__(self, num_replicas: int,
                 slots_per_replica: int | None = None,
                 token_budget: int | None = None,
                 max_prompt_tokens: int | None = None,
                 block_capacity: int = 0,
                 block_tokens: int | None = None,
                 max_deferrals: int | None = None) -> None:
        self.slots_per_replica = config.SERVE_MAX_BATCH.get() \
            if slots_per_replica is None else int(slots_per_replica)
        self.token_budget = config.SERVE_TOKEN_BUDGET.get() \
            if token_budget is None else int(token_budget)
        max_seq = config.SERVE_MAX_SEQ.get()
        self.max_prompt_tokens = max_seq if max_prompt_tokens is None \
            else int(max_prompt_tokens)
        self.block_capacity = int(block_capacity)
        self.block_tokens = config.SERVE_BLOCK_TOKENS.get() \
            if block_tokens is None else int(block_tokens)
        self.max_deferrals = config.SERVE_MAX_DEFERRALS.get() \
            if max_deferrals is None else int(max_deferrals)
        # rid -> replica group, the front end's in-flight view (rebuilt
        # from ground truth after an elastic shrink — see rebuild()).
        self.inflight: dict[int, int] = {}
        self._active: list[int] = [0] * num_replicas   # slots in use
        self._blocks: list[int] = [0] * num_replicas   # blocks reserved
        self._req_blocks: dict[int, int] = {}          # rid -> reserve
        # Peak concurrent in-flight sequences — the number the paged A/B
        # reports next to SERVE_MAX_BATCH (bench.py --model serve).
        self.max_concurrent = 0

    @property
    def num_replicas(self) -> int:
        return len(self._active)

    def inflight_count(self) -> int:
        return len(self.inflight)

    def blocks_needed(self, req: ServeRequest) -> int:
        """Worst-case pool reservation: every prompt + generated token
        paged, plus one block of COW headroom (a sequence extending its
        own published tail copies it first)."""
        tokens = min(len(req.tokens), self.max_prompt_tokens) \
            + req.max_new_tokens
        return -(-tokens // self.block_tokens) + 1

    # -- assembly --------------------------------------------------------
    def assemble(self, step: int, queue: RequestQueue, admission,
                 stop: bool = False, prefill_ranks=()
                 ) -> tuple[BatchPlan, list[ServeRequest]]:
        """Build the step's plan: admit queued requests into free slots
        replica-by-replica (least-loaded first) under the token budget
        (and, when paged, the block-capacity mirror).  Returns (plan,
        expired-in-queue requests).  Requests that fit no slot or
        budget THIS step are returned to the queue head — that is
        back-pressure, not a shed; the admission controller decides
        actual sheds.  A request deferred more than ``max_deferrals``
        steps turns URGENT: it bypasses the token budget (one over-sized
        step beats unbounded starvation) and raises a barrier — nothing
        behind it is admitted until it lands — so a stream of small
        prompts can never starve a large one indefinitely."""
        now = time.monotonic()
        plan = BatchPlan(step=step, stop=stop)
        free_slots = sum(self.slots_per_replica - a for a in self._active)
        if free_slots <= 0:
            return plan, []
        ready, expired = queue.pop_ready(free_slots, now=now)
        # Decode tokens already claimed this step by in-flight slots.
        budget = [self.token_budget - a for a in self._active]
        deferred: list[ServeRequest] = []
        barrier = False
        n_prefill = len(prefill_ranks)
        for req in ready:
            if barrier:
                # Reserved for the urgent prompt ahead: requeued without
                # aging (these were never individually refused).
                deferred.append(req)
                continue
            urgent = req.deferrals >= self.max_deferrals
            need = self.blocks_needed(req) if self.block_capacity else 0
            # Least-loaded replica group with a free slot, budget for
            # the prompt's prefill tokens (waived when urgent) and block
            # capacity (never waived — blocks are real memory); no
            # candidate is back-pressure (requeued, no admission verdict
            # yet), not a shed.
            candidates = [r for r in range(self.num_replicas)
                          if self._active[r] < self.slots_per_replica
                          and (urgent or budget[r] >= len(req.tokens))
                          and (not self.block_capacity
                               or self._blocks[r] + need
                               <= self.block_capacity)]
            if not candidates:
                req.deferrals += 1
                deferred.append(req)
                if urgent:
                    barrier = True
                continue
            ok, _ = admission.admit(req, queue.depth(), now=now)
            if not ok:
                continue
            r = min(candidates, key=lambda i: self._active[i])
            self._active[r] += 1
            budget[r] -= len(req.tokens)
            if self.block_capacity:
                self._blocks[r] += need
                self._req_blocks[req.rid] = need
            self.inflight[req.rid] = r
            self.max_concurrent = max(self.max_concurrent,
                                      len(self.inflight))
            req.replica = r
            plan.assign.append(Assignment(
                rid=req.rid, replica=r, tokens=req.tokens,
                max_new_tokens=req.max_new_tokens,
                age_ms=(now - req.arrival) * 1e3,
                deadline_rel_ms=req.remaining_ms(now),
                slo_ms=req.slo_ms,
                prefill=prefill_ranks[req.rid % n_prefill]
                if n_prefill else -1))
        if deferred:
            queue.requeue_front(deferred)
        return plan, expired

    # -- completion / failure accounting ---------------------------------
    def note_done(self, rid: int) -> None:
        r = self.inflight.pop(rid, None)
        if r is not None and 0 <= r < self.num_replicas:
            self._active[r] = max(0, self._active[r] - 1)
            freed = self._req_blocks.pop(rid, 0)
            self._blocks[r] = max(0, self._blocks[r] - freed)

    def rebuild(self, per_replica_rids: list[list[int]]) -> list[int]:
        """Resynchronize from ground truth after an elastic shrink: slot
        occupancy, block reservations and the in-flight map are rebuilt
        from each surviving replica group's actual resident rids;
        returns the rids that vanished with dead replicas (lost
        in-flight work)."""
        before = set(self.inflight)
        self.inflight = {}
        self._active = [0] * len(per_replica_rids)
        self._blocks = [0] * len(per_replica_rids)
        for r, rids in enumerate(per_replica_rids):
            for rid in rids:
                self.inflight[rid] = r
                self._active[r] += 1
                self._blocks[r] += self._req_blocks.get(rid, 0)
        for rid in before - set(self.inflight):
            self._req_blocks.pop(rid, None)
        return sorted(before - set(self.inflight))
