"""Continuous batcher: token-budgeted batch assembly over in-flight
decode batches.

Classic batching waits for a batch to fill, runs it to completion, and
only then admits more — tail latency inherits the longest generation in
every batch.  Continuous batching (Orca-style) instead treats the batch
as a set of SLOTS: every serve step, finished slots free up and the
batcher admits queued requests straight into the half-decoded batch.
The unit of work per step is bounded by a token budget (prefill tokens
of new admissions + one decode token per active slot), which keeps step
time — and therefore the admission controller's SLO math — predictable.

The batcher runs on the front-end rank and produces one :class:`BatchPlan`
per step; the plan is broadcast to every rank (replica.py), which is the
broadcast-consistent scheduling discipline: replicas never diverge on a
collective because every rank executes the same plan sequence.
"""
from __future__ import annotations

import dataclasses
import time
from dataclasses import field

from ..common import config
from .queue import RequestQueue, ServeRequest


@dataclasses.dataclass
class Assignment:
    """One request newly admitted into a replica group's decode batch."""
    rid: int
    replica: int                       # replica-group index
    tokens: list[int]
    max_new_tokens: int
    age_ms: float                      # ingress age when the plan formed
    deadline_rel_ms: float             # SLO budget left when it formed
    slo_ms: float = 0.0


@dataclasses.dataclass
class BatchPlan:
    """The per-step schedule every rank executes identically (pickled
    over hvd.broadcast_object)."""
    step: int
    assign: list[Assignment] = field(default_factory=list)
    stop: bool = False


class ContinuousBatcher:
    """Front-end accounting of replica-group slots + plan assembly."""

    def __init__(self, num_replicas: int,
                 slots_per_replica: int | None = None,
                 token_budget: int | None = None,
                 max_prompt_tokens: int | None = None) -> None:
        self.slots_per_replica = config.SERVE_MAX_BATCH.get() \
            if slots_per_replica is None else int(slots_per_replica)
        self.token_budget = config.SERVE_TOKEN_BUDGET.get() \
            if token_budget is None else int(token_budget)
        max_seq = config.SERVE_MAX_SEQ.get()
        self.max_prompt_tokens = max_seq if max_prompt_tokens is None \
            else int(max_prompt_tokens)
        # rid -> replica group, the front end's in-flight view (rebuilt
        # from ground truth after an elastic shrink — see rebuild()).
        self.inflight: dict[int, int] = {}
        self._active: list[int] = [0] * num_replicas   # slots in use

    @property
    def num_replicas(self) -> int:
        return len(self._active)

    def inflight_count(self) -> int:
        return len(self.inflight)

    # -- assembly --------------------------------------------------------
    def assemble(self, step: int, queue: RequestQueue, admission,
                 stop: bool = False) -> tuple[BatchPlan,
                                              list[ServeRequest]]:
        """Build the step's plan: admit queued requests into free slots
        replica-by-replica (least-loaded first) under the token budget.
        Returns (plan, expired-in-queue requests).  Requests that fit no
        slot or budget THIS step are returned to the queue head — that
        is back-pressure, not a shed; the admission controller decides
        actual sheds."""
        now = time.monotonic()
        plan = BatchPlan(step=step, stop=stop)
        free_slots = sum(self.slots_per_replica - a for a in self._active)
        if free_slots <= 0:
            return plan, []
        ready, expired = queue.pop_ready(free_slots, now=now)
        # Decode tokens already claimed this step by in-flight slots.
        budget = [self.token_budget - a for a in self._active]
        deferred: list[ServeRequest] = []
        for req in ready:
            # Least-loaded replica group with a free slot AND budget for
            # the prompt's prefill tokens; no candidate is back-pressure
            # (requeued, no admission verdict yet), not a shed.
            candidates = [r for r in range(self.num_replicas)
                          if self._active[r] < self.slots_per_replica
                          and budget[r] >= len(req.tokens)]
            if not candidates:
                deferred.append(req)
                continue
            ok, _ = admission.admit(req, queue.depth(), now=now)
            if not ok:
                continue
            r = min(candidates, key=lambda i: self._active[i])
            self._active[r] += 1
            budget[r] -= len(req.tokens)
            self.inflight[req.rid] = r
            req.replica = r
            plan.assign.append(Assignment(
                rid=req.rid, replica=r, tokens=req.tokens,
                max_new_tokens=req.max_new_tokens,
                age_ms=(now - req.arrival) * 1e3,
                deadline_rel_ms=req.remaining_ms(now),
                slo_ms=req.slo_ms))
        if deferred:
            queue.requeue_front(deferred)
        return plan, expired

    # -- completion / failure accounting ---------------------------------
    def note_done(self, rid: int) -> None:
        r = self.inflight.pop(rid, None)
        if r is not None and 0 <= r < self.num_replicas:
            self._active[r] = max(0, self._active[r] - 1)

    def rebuild(self, per_replica_rids: list[list[int]]) -> list[int]:
        """Resynchronize from ground truth after an elastic shrink: slot
        occupancy and the in-flight map are rebuilt from each surviving
        replica group's actual resident rids; returns the rids that
        vanished with dead replicas (lost in-flight work)."""
        before = set(self.inflight)
        self.inflight = {}
        self._active = [0] * len(per_replica_rids)
        for r, rids in enumerate(per_replica_rids):
            for rid in rids:
                self.inflight[rid] = r
                self._active[r] += 1
        return sorted(before - set(self.inflight))
