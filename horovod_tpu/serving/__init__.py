"""serving/ — continuous-batching data-parallel inference serving on the
collective runtime (ISSUE 9; docs/serving.md).

The training world's machinery reused for a traffic profile training
never produces:

- :class:`~.queue.RequestQueue` — bounded ingress, SLO deadline stamped
  at the door (hvdlint HVD1006 keeps serving/ queues bounded).
- :class:`~.batcher.ContinuousBatcher` — token-budgeted batch assembly
  that admits new requests into in-flight decode batches (Orca-style
  slot scheduling, no run-to-completion batches).
- :class:`~.admission.AdmissionController` — deadline-feasibility +
  load shedding keyed off live telemetry (queue depth, the shared
  ``Histogram.quantile`` step-time path, straggler lag); a request that
  cannot meet its SLO is shed at admission, never executed.
- :class:`~.replica.ReplicaExecutor` — the per-rank serve loop on the
  core/controller dispatch path: broadcast-consistent batch plans (so
  replicas never diverge on a collective), per-request deadlines
  propagated into resilience per-op deadlines, and elastic shrink
  mid-serve on RanksFailedError (survivors keep serving).
- :class:`~.kvpool.KVBlockPool` — paged KV blocks (ISSUE 14): free-list
  allocation with refcounts, FNV-chain prefix caching, copy-on-write
  and LRU eviction, so concurrency scales with live token residency
  instead of the batch shape.
- ``serving/kvstream.py`` — disaggregated prefill/decode: prefill-only
  ranks stream finished KV blocks to decode replicas over a dedicated
  PeerMesh (addressed CRC'd chunks, the STATE_MAGIC mold), keeping
  long prompts out of decode steps.
- ``python -m horovod_tpu.serving.loadgen`` — open-loop Poisson SLO
  load harness; reports p50/p99/p999 latency, goodput vs offered load
  and shed rate to ``SERVE_r{rank}.json``.
"""
from __future__ import annotations

from .admission import AdmissionController
from .batcher import Assignment, BatchPlan, ContinuousBatcher
from .kvpool import KVBlockPool
from .queue import RequestQueue, ServeRequest
from .replica import ReplicaExecutor, ServeConfig

__all__ = [
    "AdmissionController", "Assignment", "BatchPlan",
    "ContinuousBatcher", "KVBlockPool", "ReplicaExecutor",
    "RequestQueue", "ServeConfig", "ServeRequest",
]
