"""Replica executor: the serve loop every rank runs, on the same
core/controller dispatch path training uses.

Execution model (ISSUE 9 tentpole):

- The **front end** (lowest live rank) owns the ingress queue, the
  continuous batcher and admission control.  Every serve step it
  assembles one :class:`~.batcher.BatchPlan` and **broadcasts** it
  (``hvd.broadcast_object`` — a real negotiated collective on the data
  plane).  Because every rank executes the identical plan sequence,
  replicas can never diverge on a collective: the broadcast IS the
  schedule.
- Each **replica group** (``HOROVOD_SERVE_GROUP_SIZE`` ranks; 1 = pure
  data-parallel) prefills newly assigned requests into free KV-cache
  slots and advances every in-flight slot by one greedy decode token per
  step (models/transformer.py ``prefill``/``decode_step`` — continuous
  batching, not run-to-completion).
- Completions ride back on an **allgather** each step, so the front end
  frees slots and records latencies without any side channel.
- **Deadline propagation**: the earliest in-flight request deadline
  bounds the step's collective waits via
  ``resilience.deadline_scope`` → per-op deadlines
  (resilience/context.py), so a dead peer surfaces within the SLO
  budget instead of the full fault window.
- **Elastic shrink mid-serve**: when a collective raises
  :class:`RanksFailedError`, every survivor converges on the
  heartbeat-confirmed dead set, deterministically renumbers itself,
  rebuilds the world one rank smaller (fresh rendezvous epoch), resyncs
  the in-flight map from ground truth, and keeps serving.  In-flight
  requests on surviving replicas are untouched — their KV caches are
  process-local JAX arrays that do not care about the mesh.
"""
from __future__ import annotations

import dataclasses
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

from ..common import config
from ..common.exceptions import RanksFailedError
from ..common.logging import logger
from ..models import transformer as tfm
from .admission import AdmissionController
from .batcher import Assignment, BatchPlan, ContinuousBatcher
from .queue import RequestQueue


@dataclasses.dataclass
class ServeConfig:
    """Serving knobs (env defaults: the HOROVOD_SERVE_* family)."""
    max_batch: int = 8
    token_budget: int = 256
    max_seq: int = 256
    group_size: int = 1
    slo_ms: float = 30000.0
    queue_depth: int = 1024
    eos_id: int = -1                   # -1 disables EOS stopping
    seed: int = 0
    model_cfg: object | None = None    # TransformerConfig; None = tiny LM
    # Prefill shape buckets compiled at startup so the first real
    # requests never stall a broadcast-consistent step on an XLA
    # compile (a multi-second stall looks exactly like a wedged rank
    # to a peer's SLO-bounded wait).
    warmup_buckets: tuple = (8, 16)

    @classmethod
    def from_env(cls, **overrides) -> "ServeConfig":
        base = dict(
            max_batch=config.SERVE_MAX_BATCH.get(),
            token_budget=config.SERVE_TOKEN_BUDGET.get(),
            max_seq=config.SERVE_MAX_SEQ.get(),
            group_size=config.SERVE_GROUP_SIZE.get(),
            slo_ms=config.SERVE_SLO_MS.get(),
            queue_depth=config.SERVE_QUEUE_DEPTH.get())
        base.update(overrides)
        return cls(**base)


@dataclasses.dataclass
class _Slot:
    """One in-flight sequence in this replica's decode batch."""
    rid: int
    remaining: int                     # decode tokens still to produce
    deadline: float                    # absolute local monotonic
    assigned_at: float
    age_ms: float                      # ingress age when assigned
    slo_ms: float
    generated: list[int]


class ReplicaExecutor:
    """One rank's half of the data-parallel serving world."""

    def __init__(self, serve_cfg: ServeConfig | None = None,
                 params=None) -> None:
        import horovod_tpu as hvd
        self.hvd = hvd
        self.cfg = serve_cfg or ServeConfig.from_env()
        self.rank = hvd.rank()
        self.size = hvd.size()
        self.front = 0
        self._gen = 0                  # shrink generation (name/epoch tag)
        self._step = 0
        self._stop_requested = False
        self._configure_groups()

        model_cfg = self.cfg.model_cfg
        if model_cfg is None:
            model_cfg = tfm.gpt_tiny(dtype=jnp.float32)
        model_cfg = dataclasses.replace(model_cfg, decode=True,
                                        max_seq_len=self.cfg.max_seq)
        self.model = tfm.TransformerLM(model_cfg)
        if params is None:
            # Seeded, deterministic: every replica materializes identical
            # weights without a broadcast (replace with a checkpoint
            # restore or hvd.broadcast_object for real weights).
            params = self.model.init(
                jax.random.PRNGKey(self.cfg.seed),
                jnp.zeros((1, 8), jnp.int32))["params"]
        self.params = params

        self.slots: list[_Slot | None] = [None] * self.cfg.max_batch
        self._last_tokens = np.zeros(self.cfg.max_batch, np.int32)
        self.completed: dict[int, dict] = {}
        self.prefilled: set[int] = set()
        # Completions not yet acknowledged by a successful exchange: a
        # step that fails mid-allgather re-sends them after the shrink,
        # so a request finished during the failure window is never
        # misclassified as lost (front dedups via batcher membership).
        self._unreported: list[dict] = []
        self.stats = {"offered": 0, "expired": 0, "served": 0,
                      "served_slo": 0, "lost": 0,
                      "latencies_ms": [], "completed_at": [],
                      "shrinks": [], "grows": []}
        # Elastic grow mid-serve (statesync/): attach_statesync wires a
        # membership service in; None = the pre-ISSUE-10 behavior with
        # zero extra collectives.
        self.statesync = None

        self.queue = RequestQueue(maxsize=self.cfg.queue_depth,
                                  default_slo_ms=self.cfg.slo_ms)
        self.admission = AdmissionController(
            queue_depth_limit=self.cfg.queue_depth)
        self.batcher = ContinuousBatcher(
            self.num_groups, slots_per_replica=self.cfg.max_batch,
            token_budget=self.cfg.token_budget)

        self._decode_jit = jax.jit(self._decode_impl)
        self._prefill_jit = jax.jit(self._prefill_impl)
        self._init_cache()
        self._warmup()

    # -- topology --------------------------------------------------------
    def _configure_groups(self) -> None:
        gs = self.cfg.group_size
        if gs <= 0 or self.size % gs:
            if gs > 1:
                logger.warning(
                    "serving: group size %d does not divide world size "
                    "%d; falling back to per-rank replicas", gs, self.size)
            gs = 1
        self.group_size = gs
        self.group = self.rank // gs
        self.num_groups = self.size // gs
        self.group_leader = self.rank % gs == 0

    # -- model plumbing --------------------------------------------------
    def _decode_impl(self, params, cache, tokens):
        logits, cache = tfm.decode_step(self.model, {"params": params},
                                        cache, tokens)
        return (jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32),
                cache)

    def _prefill_impl(self, params, tokens, n):
        logits, cache = tfm.prefill(self.model, {"params": params},
                                    tokens, lengths=n)
        return (jnp.argmax(logits[0, n - 1, :]).astype(jnp.int32), cache)

    def _init_cache(self) -> None:
        zeros = jnp.zeros((self.cfg.max_batch, 1), jnp.int32)
        _, mut = self.model.apply({"params": self.params}, zeros,
                                  mutable=["cache"])
        self._cache = tfm._with_cache_index(mut["cache"], 0)

    def _warmup(self) -> None:
        for bucket in self.cfg.warmup_buckets:
            if bucket > self.cfg.max_seq:
                continue
            tok, cache1 = self._prefill_jit(
                self.params, jnp.zeros((1, bucket), jnp.int32),
                jnp.int32(1))
            jax.block_until_ready(tok)
        nxt, _ = self._decode_jit(
            self.params, self._cache,
            jnp.asarray(self._last_tokens[:, None]))
        jax.block_until_ready(nxt)
        self._init_cache()             # discard warmup cache writes

    @staticmethod
    def _bucket(n: int) -> int:
        return max(8, 1 << max(0, (n - 1)).bit_length())

    # -- per-step halves -------------------------------------------------
    def _assemble(self) -> BatchPlan:
        stop = (self._stop_requested and self.queue.depth() == 0
                and self.batcher.inflight_count() == 0)
        plan, expired = self.batcher.assemble(
            self._step, self.queue, self.admission, stop=stop)
        for req in expired:
            # Expired while queued: shed at admission, never executed.
            self.admission.count("expired")
            self.stats["expired"] += 1
        return plan

    def _exchange_plan(self, plan: BatchPlan | None) -> BatchPlan:
        from ..resilience import deadline_scope
        deadlines = [s.deadline for s in self.slots if s is not None]
        with deadline_scope(min(deadlines) if deadlines else None):
            return self.hvd.broadcast_object(
                plan, root_rank=self.front,
                name=f"serve.plan.g{self._gen}.{self._step}")

    def _apply_plan(self, plan: BatchPlan) -> None:
        now = time.monotonic()
        for a in plan.assign:
            if a.replica != self.group:
                continue
            slot = next(i for i, s in enumerate(self.slots) if s is None)
            self._prefill_slot(slot, a, now)

    def _prefill_slot(self, slot: int, a: Assignment, now: float) -> None:
        # Clamp so prompt + generation always fits the KV cache.
        limit = self.cfg.max_seq - a.max_new_tokens
        toks = a.tokens[:max(1, limit)]
        bucket = min(self._bucket(len(toks)), self.cfg.max_seq)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :len(toks)] = toks
        first, cache1 = self._prefill_jit(
            self.params, jnp.asarray(padded), jnp.int32(len(toks)))
        self._cache = jax.tree_util.tree_map(
            lambda big, small: big.at[slot].set(small[0]),
            self._cache, cache1)
        first = int(first)
        self._last_tokens[slot] = first
        self.slots[slot] = _Slot(
            rid=a.rid, remaining=a.max_new_tokens - 1,
            deadline=now + a.deadline_rel_ms / 1e3, assigned_at=now,
            age_ms=a.age_ms, slo_ms=a.slo_ms, generated=[first])
        self.prefilled.add(a.rid)

    def _decode_once(self) -> None:
        active = [i for i, s in enumerate(self.slots)
                  if s is not None and s.remaining > 0]
        if not active:
            return
        nxt, self._cache = self._decode_jit(
            self.params, self._cache,
            jnp.asarray(self._last_tokens[:, None]))
        nxt = np.asarray(nxt)
        for i in active:
            s = self.slots[i]
            tok = int(nxt[i])
            s.generated.append(tok)
            s.remaining -= 1
            self._last_tokens[i] = tok
            if self.cfg.eos_id >= 0 and tok == self.cfg.eos_id:
                s.remaining = 0

    def _collect_completions(self) -> None:
        now = time.monotonic()
        for i, s in enumerate(self.slots):
            if s is None or s.remaining > 0:
                continue
            rec = {"rid": s.rid, "replica": self.group,
                   "latency_ms": s.age_ms + (now - s.assigned_at) * 1e3,
                   "tokens": len(s.generated),
                   "slo_met": now <= s.deadline}
            self.completed[s.rid] = rec
            if self.group_leader:
                # Every group member frees slots identically; only the
                # leader reports, so completions appear exactly once.
                self._unreported.append(rec)
            self.slots[i] = None

    def _exchange_completions(self) -> list[dict]:
        from ..resilience import deadline_scope
        done = list(self._unreported)
        deadlines = [s.deadline for s in self.slots if s is not None]
        with deadline_scope(min(deadlines) if deadlines else None):
            per_rank = self.hvd.allgather_object(
                done, name=f"serve.done.g{self._gen}.{self._step}")
        self._unreported.clear()       # acknowledged by the exchange
        return [rec for ranklist in per_rank for rec in ranklist]

    def _account(self, completions: list[dict]) -> None:
        if self.rank != self.front:
            return
        now = time.monotonic()
        for rec in completions:
            if rec["rid"] not in self.batcher.inflight:
                continue   # duplicate re-send after a failed exchange
            self.batcher.note_done(rec["rid"])
            self.admission.count("served")
            self.admission.observe_latency_ms(rec["latency_ms"])
            self.stats["served"] += 1
            self.stats["served_slo"] += bool(rec["slo_met"])
            self.stats["latencies_ms"].append(rec["latency_ms"])
            # Completion wall times let the load harness report goodput
            # before/during/after an elastic grow (docs/serving.md).
            self.stats["completed_at"].append(now)

    # -- elastic grow mid-serve (statesync/) -----------------------------
    def attach_statesync(self, service) -> None:
        """Wire a statesync membership service in: every serve step ends
        with its boundary check, so a joining replica is admitted at a
        step boundary and enters after its streamed params verify."""
        self.statesync = service

    def state_tree(self) -> dict:
        """The streamed-state template/provider for serving: params are
        the only cross-replica state (KV caches are per-request), and
        they never change between steps — the statesync service runs in
        static mode, so the bulk image IS the joiner's entry state."""
        import jax

        return {"params": jax.tree_util.tree_map(np.asarray,
                                                 self.params)}

    def _statesync_boundary(self) -> None:
        change = self.statesync.step_boundary()
        if change is not None and change.kind == "grow":
            self._grow_resync(change.join_id, change.rank, change.size)

    def _grow_resync(self, join_id: int, new_rank: int,
                     new_size: int) -> None:
        """Realign the serving world after a grow: every rank (the
        joiner included — this is its first collective) exchanges
        (step, gen, resident rids), adopts the maxima, and rebuilds the
        batcher with the new replica group present but empty.  Nothing
        in flight is touched: incumbents' KV caches are process-local."""
        old_size = self.size
        self.rank, self.size = new_rank, new_size
        self.front = 0
        self._configure_groups()
        mine = {"step": self._step, "gen": self._gen,
                "rids": (sorted(s.rid for s in self.slots
                                if s is not None)
                         if self.group_leader else [])}
        per_rank = self.hvd.allgather_object(
            mine, name=f"serve.growsync.{join_id}")
        self._step = max(p["step"] for p in per_rank)
        # Fresh gen: post-grow collective names never collide with any
        # pre-grow step another rank might still have cached.
        self._gen = max(p["gen"] for p in per_rank) + 1
        per_group = [per_rank[g * self.group_size]["rids"]
                     for g in range(self.num_groups)]
        self.batcher.rebuild(per_group)
        windows = getattr(self.statesync, "grow_windows", [])
        self.stats["grows"].append(
            {"join": join_id, "from": old_size, "to": new_size,
             "step": self._step, "at": time.monotonic(),
             "window_s": windows[-1][1] - windows[-1][0]
             if windows else 0.0})
        logger.warning("serving: grow %d->%d (join %d) at step %d",
                       old_size, new_size, join_id, self._step)

    # -- the loop --------------------------------------------------------
    def _serve_step(self) -> bool:
        t0 = time.monotonic()
        plan = self._assemble() if self.rank == self.front else None
        plan = self._exchange_plan(plan)
        self._step += 1
        if plan.stop:
            return False
        self._apply_plan(plan)
        self._decode_once()
        self._collect_completions()
        completions = self._exchange_completions()
        self._account(completions)
        if self.statesync is not None:
            self._statesync_boundary()
        self.admission.observe_step_ms((time.monotonic() - t0) * 1e3)
        return True

    def serve_loop(self, *, stop_when=None, max_steps: int | None = None,
                   idle_sleep: float = 0.002) -> None:
        """Run serve steps until the front end declares the system
        drained (``stop_when()`` true on the front end AND queue and
        in-flight empty), riding elastic shrinks across rank failures.
        ``max_steps`` is a safety bound for tests."""
        while max_steps is None or self._step < max_steps:
            if self.rank == self.front:
                if stop_when is not None and stop_when():
                    self._stop_requested = True
                if (not self._stop_requested
                        and self.queue.depth() == 0
                        and self.batcher.inflight_count() == 0):
                    time.sleep(idle_sleep)   # don't hot-spin empty plans
            try:
                if not self._serve_step():
                    return
            except RanksFailedError as exc:
                self._shrink_and_resume(exc)

    # -- elastic shrink --------------------------------------------------
    def _shrink_and_resume(self, exc: RanksFailedError) -> None:
        from .. import core
        from ..resilience import converge_confirmed_dead

        # Converge on the heartbeat-CONFIRMED dead set (shared with the
        # statesync failure-shrink path, resilience/policy.py): every
        # survivor computes the same membership, and suspicion alone (a
        # slow peer) re-raises instead of shrinking.
        dead = converge_confirmed_dead(exc)
        survivors = [r for r in range(self.size) if r not in dead]
        new_rank = survivors.index(self.rank)
        new_size = len(survivors)
        from ..telemetry import flight

        rec = flight.recorder()
        if rec.enabled:
            rec.record("shrink", f"dead {sorted(dead)}",
                       detail=f"serving {self.size}->{new_size} at "
                              f"step {self._step}")
        logger.warning(
            "serving: shrink %d->%d (dead=%s); this rank %d -> %d",
            self.size, new_size, sorted(dead), self.rank, new_rank)
        base = os.environ.get("HOROVOD_RENDEZVOUS_EPOCH", "0")
        self._gen += 1
        tag = "_".join(str(r) for r in sorted(dead))
        core.reinit_world(
            rank=new_rank, size=new_size,
            epoch=f"{base.split('~', 1)[0]}~sv{self._gen}x{tag}")
        old = (self.rank, self.size)
        self.rank, self.size = new_rank, new_size
        self.front = 0
        self._configure_groups()
        if self.statesync is not None:
            self.statesync.notify_world_changed()
        self._resync()
        self.stats["shrinks"].append(
            {"dead": sorted(dead), "from": old[1], "to": new_size,
             "step": self._step})

    def _resync(self) -> None:
        """Rebuild shared state from ground truth after a world rebuild.

        - Survivors may have caught the failure at DIFFERENT steps (a
          per-rank data-plane error can abort rank A's plan broadcast
          while rank B fails one exchange later), so the step counter
          realigns to the maximum — collective names must match again.
        - Each group leader reports its resident rids (plus completions
          awaiting re-send); requests that vanished with dead replicas
          are counted lost.  Nothing on a surviving replica is ever
          dropped, so the zero-failed-on-survivors invariant holds.
        """
        rids = sorted(s.rid for s in self.slots if s is not None)
        rids += [rec["rid"] for rec in self._unreported]
        mine = {"step": self._step,
                "rids": rids if self.group_leader else []}
        per_rank = self.hvd.allgather_object(
            mine, name=f"serve.resync.g{self._gen}")
        self._step = max(p["step"] for p in per_rank)
        per_group = [per_rank[g * self.group_size]["rids"]
                     for g in range(self.num_groups)]
        lost = self.batcher.rebuild(per_group)
        if self.rank == self.front:
            for _ in lost:
                self.admission.count("lost")
            self.stats["lost"] += len(lost)

    # -- introspection ---------------------------------------------------
    def inflight_rids(self) -> list[int]:
        return sorted(s.rid for s in self.slots if s is not None)

    def request_stop(self) -> None:
        self._stop_requested = True


def serving_params_template(cfg: ServeConfig) -> dict:
    """The state tree a serving joiner offers to ``join_world``: the
    model's parameter pytree (shapes/dtypes only matter — values are
    replaced by the streamed image)."""
    import horovod_tpu  # noqa: F401 - jax config side effects

    model_cfg = cfg.model_cfg
    if model_cfg is None:
        model_cfg = tfm.gpt_tiny(dtype=jnp.float32)
    model_cfg = dataclasses.replace(model_cfg, decode=True,
                                    max_seq_len=cfg.max_seq)
    model = tfm.TransformerLM(model_cfg)
    params = model.init(jax.random.PRNGKey(cfg.seed),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return {"params": jax.tree_util.tree_map(np.asarray, params)}


def join_serving_world(serve_cfg: ServeConfig | None = None
                       ) -> "ReplicaExecutor":
    """Join a live serving world as a fresh replica (statesync grow):
    stream the incumbents' params peer-to-peer, enter as rank N, and
    return a ReplicaExecutor already realigned (step/gen/batcher) and
    ready for ``serve_loop``.  The incumbents' only stall is this
    rank's executor construction (model compile) between world rebuild
    and the first realign exchange — the bulk params transfer happened
    before they rebuilt anything."""
    from .. import statesync

    cfg = serve_cfg or ServeConfig.from_env()
    template = serving_params_template(cfg)
    tree, info = statesync.join_world(template)
    params = jax.tree_util.tree_map(jnp.asarray, tree["params"])
    ex = ReplicaExecutor(cfg, params=params)
    service = statesync.StateSyncService(state_provider=ex.state_tree,
                                         static_state=True)
    ex.attach_statesync(service)
    # First collective on the new world: adopt the incumbents'
    # step/gen and announce this (empty) replica group.
    ex._grow_resync(info.join_id, info.rank, info.size)
    return ex
