"""Replica executor: the serve loop every rank runs, on the same
core/controller dispatch path training uses.

Execution model (ISSUE 9 tentpole, extended by ISSUE 14):

- The **front end** (lowest live rank) owns the ingress queue, the
  continuous batcher and admission control.  Every serve step it
  assembles one :class:`~.batcher.BatchPlan` and **broadcasts** it
  (``hvd.broadcast_object`` — a real negotiated collective on the data
  plane).  Because every rank executes the identical plan sequence,
  replicas can never diverge on a collective: the broadcast IS the
  schedule.
- Each **replica group** (``HOROVOD_SERVE_GROUP_SIZE`` ranks; 1 = pure
  data-parallel) prefills newly assigned requests into free KV-cache
  slots and advances every in-flight slot by one greedy token per step
  (models/transformer.py — continuous batching, not run-to-completion).
- **Paged KV** (``HOROVOD_SERVE_PAGED``, ISSUE 14): slot KV state lives
  in fixed-size blocks from a per-replica :class:`~.kvpool.KVBlockPool`
  instead of dense per-slot arrays, so slot count is bounded by live
  token residency (the pool), not the batch shape.  Prompt blocks are
  content-addressed (FNV chain hash): a request whose prefix blocks are
  already resident bumps refcounts instead of re-prefilling, with
  copy-on-write on the first divergent write and LRU eviction of
  refcount-0 cached blocks.
- **Disaggregated prefill/decode** (``HOROVOD_SERVE_PREFILL_RANKS``):
  the highest N ranks run prompt prefill only and stream finished KV
  blocks to decode replicas over the dedicated kvstream mesh, so a long
  prompt overlaps decode steps instead of stalling them.  Streaming is
  point-to-point — the plan broadcast stays the only schedule source
  and the collective fingerprint stream is identical on every rank.
- Completions ride back on an **allgather** each step, so the front end
  frees slots and records latencies without any side channel.
- **Deadline propagation**: the earliest in-flight request deadline
  bounds the step's collective waits via
  ``resilience.deadline_scope`` → per-op deadlines
  (resilience/context.py), so a dead peer surfaces within the SLO
  budget instead of the full fault window.
- **Elastic shrink mid-serve**: when a collective raises
  :class:`RanksFailedError`, every survivor converges on the
  heartbeat-confirmed dead set, deterministically renumbers itself,
  rebuilds the world one rank smaller (fresh rendezvous epoch), resyncs
  the in-flight map from ground truth, and keeps serving.  In-flight
  requests on surviving replicas are untouched — their KV state
  (dense caches or paged block pools) is process-local and does not
  care about the mesh.
"""
from __future__ import annotations

import dataclasses
import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np

from ..common import config
from ..common.exceptions import RanksFailedError
from ..common.logging import logger
from ..models import transformer as tfm
from .admission import AdmissionController
from .batcher import Assignment, BatchPlan, ContinuousBatcher
from .kvpool import FNV_SEED, KVBlockPool, chain_hash
from .queue import RequestQueue


@dataclasses.dataclass
class ServeConfig:
    """Serving knobs (env defaults: the HOROVOD_SERVE_* family)."""
    max_batch: int = 8
    token_budget: int = 256
    max_seq: int = 256
    group_size: int = 1
    slo_ms: float = 30000.0
    queue_depth: int = 1024
    eos_id: int = -1                   # -1 disables EOS stopping
    seed: int = 0
    model_cfg: object | None = None    # TransformerConfig; None = tiny LM
    # Paged KV cache (ISSUE 14): blocks of block_tokens from a
    # pool_blocks pool; 0 = auto (max_batch x ceil(max_seq/bt), the
    # dense layout's token memory).  paged_slots (0 = auto: 2 x
    # max_batch) is the decode batch width — the pool, not the batch
    # shape, bounds concurrency.
    paged: bool = False
    block_tokens: int = 16
    pool_blocks: int = 0
    paged_slots: int = 0
    # Disaggregated prefill/decode: highest N ranks prefill-only
    # (requires paged; clamped so at least one decode rank remains).
    prefill_ranks: int = 0
    # Prefill shape buckets compiled at startup so the first real
    # requests never stall a broadcast-consistent step on an XLA
    # compile (a multi-second stall looks exactly like a wedged rank
    # to a peer's SLO-bounded wait).
    warmup_buckets: tuple = (8, 16)

    @classmethod
    def from_env(cls, **overrides) -> "ServeConfig":
        base = dict(
            max_batch=config.SERVE_MAX_BATCH.get(),
            token_budget=config.SERVE_TOKEN_BUDGET.get(),
            max_seq=config.SERVE_MAX_SEQ.get(),
            group_size=config.SERVE_GROUP_SIZE.get(),
            slo_ms=config.SERVE_SLO_MS.get(),
            queue_depth=config.SERVE_QUEUE_DEPTH.get(),
            paged=config.SERVE_PAGED.get(),
            block_tokens=config.SERVE_BLOCK_TOKENS.get(),
            pool_blocks=config.SERVE_POOL_BLOCKS.get(),
            paged_slots=config.SERVE_PAGED_SLOTS.get(),
            prefill_ranks=config.SERVE_PREFILL_RANKS.get())
        base.update(overrides)
        return cls(**base)

    @property
    def slots(self) -> int:
        """Decode slots per replica: the dense batch, or the (wider)
        paged slot count backed by the shared pool."""
        if not self.paged:
            return self.max_batch
        return self.paged_slots if self.paged_slots > 0 \
            else 2 * self.max_batch

    @property
    def table_width(self) -> int:
        return -(-self.max_seq // self.block_tokens)

    @property
    def resolved_pool_blocks(self) -> int:
        """Pool size; the auto default reserves exactly the dense
        layout's token memory (max_batch x max_seq tokens)."""
        if self.pool_blocks > 0:
            return self.pool_blocks
        return self.max_batch * self.table_width


@dataclasses.dataclass
class _Slot:
    """One in-flight sequence in this replica's decode batch."""
    rid: int
    remaining: int                     # decode tokens still to produce
    deadline: float                    # absolute local monotonic
    assigned_at: float
    age_ms: float                      # ingress age when assigned
    slo_ms: float
    generated: list[int]
    # Paged mode: physical block ids in logical order (each held once
    # by this slot) and the sequence write cursor.
    blocks: list = dataclasses.field(default_factory=list)
    seq_len: int = 0
    # Disaggregated mode: the original assignment while the streamed
    # prefill is still in flight (slot skips decode until it lands or
    # the fallback re-prefills locally), and when it went pending.
    pending: Assignment | None = None
    pending_since: float = 0.0


class ReplicaExecutor:
    """One rank's half of the data-parallel serving world."""

    def __init__(self, serve_cfg: ServeConfig | None = None,
                 params=None) -> None:
        import horovod_tpu as hvd
        self.hvd = hvd
        self.cfg = serve_cfg or ServeConfig.from_env()
        self.rank = hvd.rank()
        self.size = hvd.size()
        self.front = 0
        self._gen = 0                  # shrink generation (name/epoch tag)
        self._step = 0
        self._stop_requested = False
        self._configure_groups()

        model_cfg = self.cfg.model_cfg
        if model_cfg is None:
            model_cfg = tfm.gpt_tiny(dtype=jnp.float32)
        model_cfg = dataclasses.replace(model_cfg, decode=True,
                                        max_seq_len=self.cfg.max_seq)
        if self.cfg.paged:
            model_cfg = dataclasses.replace(
                model_cfg, paged=True,
                kv_pool_blocks=self.cfg.resolved_pool_blocks,
                kv_block_tokens=self.cfg.block_tokens)
        self.model = tfm.TransformerLM(model_cfg)
        if params is None:
            # Seeded, deterministic: every replica materializes identical
            # weights without a broadcast (replace with a checkpoint
            # restore or hvd.broadcast_object for real weights).
            params = self.model.init(
                jax.random.PRNGKey(self.cfg.seed),
                jnp.zeros((1, 8), jnp.int32))["params"]
        self.params = params

        self.slots: list[_Slot | None] = [None] * self.cfg.slots
        self._last_tokens = np.zeros(self.cfg.slots, np.int32)
        self.completed: dict[int, dict] = {}
        self.prefilled: set[int] = set()
        # Completions not yet acknowledged by a successful exchange: a
        # step that fails mid-allgather re-sends them after the shrink,
        # so a request finished during the failure window is never
        # misclassified as lost (front dedups via batcher membership).
        self._unreported: list[dict] = []
        # perfscope serve ledger (telemetry/perfmodel.py): smoothed
        # accepted-tokens/s and the cached per-chip peak for serve MFU.
        self._perf_tps = 0.0
        self._peak_flops: float | None = None
        self.stats = {"offered": 0, "expired": 0, "served": 0,
                      "served_slo": 0, "lost": 0,
                      "latencies_ms": [], "completed_at": [],
                      "shrinks": [], "grows": [],
                      "prefill_streams": 0, "prefill_fallbacks": 0,
                      "prefill_skipped": 0, "weight_swaps": []}
        # Elastic grow mid-serve (statesync/): attach_statesync wires a
        # membership service in; None = the pre-ISSUE-10 behavior with
        # zero extra collectives.
        self.statesync = None
        # Fleet continuous weight deployment (fleet/deploy.py): the
        # puller thread stages verified snapshots here; the front
        # schedules the swap into a BatchPlan once EVERY rank's staged
        # set (piggybacked on the completions allgather) holds it.
        self.weight_version = 0
        self._weight_step = 0          # trainer step of the live weights
        self._fleet_lock = threading.Lock()
        # version -> (params tree, trainer step, digest).  Keyed by
        # version, NOT a single newest-wins slot: the puller can stage
        # a newer version between the completions exchange (which
        # reported this rank's staged set) and the plan's scheduled
        # swap, and every rank of a sharded replica group must still
        # swap exactly plan.swap_version at that boundary.
        self._fleet_staged: dict[int, tuple] = {}
        self._fleet_reported: set[int] = set()
        self._fleet_puller = None
        self._fleet_gauge = None       # --fleet front gauge hook (wiring)
        self._fleet_runtime = None
        self._fleet_common = 0         # newest version staged on EVERY rank
        self._fleet_scheduled = 0      # newest version the front swapped

        self.queue = RequestQueue(maxsize=self.cfg.queue_depth,
                                  default_slo_ms=self.cfg.slo_ms)
        self.admission = AdmissionController(
            queue_depth_limit=self.cfg.queue_depth)
        self.batcher = self._make_batcher()

        # Paged state: the block pool (id bookkeeping), the per-slot
        # block tables/cursors (the model's addressing arguments) and
        # the paged cache (the pools themselves).
        self.pool: KVBlockPool | None = None
        if self.cfg.paged:
            self.pool = KVBlockPool(self.cfg.resolved_pool_blocks,
                                    self.cfg.block_tokens)
            self._sink = self.cfg.resolved_pool_blocks
            self._tables = np.full((self.cfg.slots,
                                    self.cfg.table_width),
                                   self._sink, np.int32)
            self._cursors = np.zeros(self.cfg.slots, np.int32)
            self._paged_jit = jax.jit(self._paged_impl)
            self._paged_prefill_jit = jax.jit(self._paged_prefill_impl)
            self._copy_block_jit = jax.jit(tfm.paged_copy_block)
        else:
            self._decode_jit = jax.jit(self._decode_impl)
            self._prefill_jit = jax.jit(self._prefill_impl)
        self._kvstream = None
        self._init_cache()
        self._warmup()
        if self.prefill_rank_list:
            self._rebuild_kvstream()

    # -- topology --------------------------------------------------------
    def _configure_groups(self) -> None:
        n_pref = 0
        if self.cfg.prefill_ranks > 0:
            if not self.cfg.paged:
                logger.warning(
                    "serving: HOROVOD_SERVE_PREFILL_RANKS needs "
                    "HOROVOD_SERVE_PAGED (block streaming); ignoring")
            else:
                n_pref = min(self.cfg.prefill_ranks, self.size - 1)
        self.decode_size = self.size - n_pref
        self.prefill_rank_list = list(range(self.decode_size, self.size))
        self.is_prefill = self.rank >= self.decode_size
        gs = self.cfg.group_size
        if gs <= 0 or self.decode_size % gs:
            if gs > 1:
                logger.warning(
                    "serving: group size %d does not divide decode size "
                    "%d; falling back to per-rank replicas", gs,
                    self.decode_size)
            gs = 1
        self.group_size = gs
        self.group = self.rank // gs if not self.is_prefill else -1
        self.num_groups = self.decode_size // gs
        self.group_leader = (not self.is_prefill
                             and self.rank % gs == 0)

    def _make_batcher(self) -> ContinuousBatcher:
        return ContinuousBatcher(
            self.num_groups, slots_per_replica=self.cfg.slots,
            token_budget=self.cfg.token_budget,
            block_capacity=self.cfg.resolved_pool_blocks
            if self.cfg.paged else 0,
            block_tokens=self.cfg.block_tokens)

    def _rebuild_kvstream(self) -> None:
        """(Re)form the dedicated prefill-stream mesh — collectively,
        every serving rank, epoch+generation-scoped so a post-shrink
        mesh never collides with the dying one's sockets."""
        from ..statesync.service import _kv_client
        from .kvstream import KVStreamMesh, kvstream_scope

        if self._kvstream is not None:
            self._kvstream.close()
            self._kvstream = None
        base = os.environ.get("HOROVOD_RENDEZVOUS_EPOCH", "0")
        self._kvstream = KVStreamMesh(
            _kv_client(), kvstream_scope(base, self._gen), self.rank,
            self.size, self.prefill_rank_list)

    # -- model plumbing --------------------------------------------------
    def _decode_impl(self, params, cache, tokens):
        logits, cache = tfm.decode_step(self.model, {"params": params},
                                        cache, tokens)
        return (jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32),
                cache)

    def _prefill_impl(self, params, tokens, n):
        logits, cache = tfm.prefill(self.model, {"params": params},
                                    tokens, lengths=n)
        return (jnp.argmax(logits[0, n - 1, :]).astype(jnp.int32), cache)

    def _paged_impl(self, params, cache, tokens, tables, cursors):
        """One paged decode step for the whole slot array: inactive
        slots' tables point at the pool sink row, so their writes land
        in garbage space and their outputs are ignored."""
        logits, cache = tfm.paged_apply(
            self.model, {"params": params}, cache, tokens, tables,
            cursors)
        return (jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32),
                cache)

    def _paged_prefill_impl(self, params, cache, tokens, table, cursor,
                            n):
        """Paged prefill of ONE request (B=1) straight into the shared
        pool through the slot's block table; ``cursor`` > 0 resumes
        past prefix-cache hits and ``n`` masks the padded tail."""
        logits, cache = tfm.paged_apply(
            self.model, {"params": params}, cache, tokens, table,
            cursor, lengths=n)
        return (jnp.argmax(logits[0, n[0] - 1, :]).astype(jnp.int32),
                cache)

    def _init_cache(self) -> None:
        if self.cfg.paged:
            zeros = jnp.zeros((1, 1), jnp.int32)
            _, mut = self.model.apply(
                {"params": self.params}, zeros,
                block_tables=jnp.full((1, self.cfg.table_width),
                                      self._sink, jnp.int32),
                cursors=jnp.zeros((1,), jnp.int32),
                mutable=["cache"])
            from flax.core import unfreeze
            self._cache = unfreeze(mut["cache"])
            return
        zeros = jnp.zeros((self.cfg.slots, 1), jnp.int32)
        _, mut = self.model.apply({"params": self.params}, zeros,
                                  mutable=["cache"])
        self._cache = tfm._with_cache_index(mut["cache"], 0)

    def _warmup(self) -> None:
        if self.cfg.paged:
            table1 = jnp.full((1, self.cfg.table_width), self._sink,
                              jnp.int32)
            for bucket in self.cfg.warmup_buckets:
                if bucket > self.cfg.max_seq:
                    continue
                tok, _ = self._paged_prefill_jit(
                    self.params, self._cache,
                    jnp.zeros((1, bucket), jnp.int32), table1,
                    jnp.zeros((1,), jnp.int32),
                    jnp.ones((1,), jnp.int32))
                jax.block_until_ready(tok)
            nxt, _ = self._paged_jit(
                self.params, self._cache,
                jnp.asarray(self._last_tokens[:, None]),
                jnp.asarray(self._tables), jnp.asarray(self._cursors))
            jax.block_until_ready(nxt)
            self._init_cache()         # discard warmup sink writes
            return
        for bucket in self.cfg.warmup_buckets:
            if bucket > self.cfg.max_seq:
                continue
            tok, cache1 = self._prefill_jit(
                self.params, jnp.zeros((1, bucket), jnp.int32),
                jnp.int32(1))
            jax.block_until_ready(tok)
        nxt, _ = self._decode_jit(
            self.params, self._cache,
            jnp.asarray(self._last_tokens[:, None]))
        jax.block_until_ready(nxt)
        self._init_cache()             # discard warmup cache writes

    @staticmethod
    def _bucket(n: int) -> int:
        return max(8, 1 << max(0, (n - 1)).bit_length())

    # -- per-step halves -------------------------------------------------
    def _assemble(self) -> BatchPlan:
        stop = (self._stop_requested and self.queue.depth() == 0
                and self.batcher.inflight_count() == 0)
        plan, expired = self.batcher.assemble(
            self._step, self.queue, self.admission, stop=stop,
            prefill_ranks=self.prefill_rank_list)
        for req in expired:
            # Expired while queued: shed at admission, never executed.
            self.admission.count("expired")
            self.stats["expired"] += 1
        # Fleet weight rollout: schedule the newest version that EVERY
        # rank reported staged in the last completions exchange — an
        # intersection, not min(newest staged), so a rank that skipped
        # a version (its head poll raced the publisher GC) is never
        # scheduled for an image it does not hold.
        if self._fleet_common > max(self.weight_version,
                                    self._fleet_scheduled):
            plan.swap_version = self._fleet_common
            self._fleet_scheduled = plan.swap_version
        return plan

    def _exchange_plan(self, plan: BatchPlan | None) -> BatchPlan:
        from ..resilience import deadline_scope
        deadlines = [s.deadline for s in self.slots if s is not None]
        with deadline_scope(min(deadlines) if deadlines else None):
            return self.hvd.broadcast_object(
                plan, root_rank=self.front,
                name=f"serve.plan.g{self._gen}.{self._step}")

    def _apply_plan(self, plan: BatchPlan) -> None:
        now = time.monotonic()
        if plan.swap_version:
            self._fleet_swap(plan.swap_version)
        for a in plan.assign:
            if self.is_prefill:
                if a.prefill == self.rank:
                    self._prefill_and_stream(a)
                continue
            if a.replica != self.group:
                continue
            slot = next(i for i, s in enumerate(self.slots) if s is None)
            if a.prefill >= 0:
                self._admit_disaggregated(slot, a, now)
            elif self.cfg.paged:
                self._prefill_slot_paged(slot, a, now)
            else:
                self._prefill_slot(slot, a, now)

    # -- dense prefill (the PR 9 path, unchanged) ------------------------
    def _prefill_slot(self, slot: int, a: Assignment, now: float) -> None:
        # Clamp so prompt + generation always fits the KV cache.
        limit = self.cfg.max_seq - a.max_new_tokens
        toks = a.tokens[:max(1, limit)]
        bucket = min(self._bucket(len(toks)), self.cfg.max_seq)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :len(toks)] = toks
        first, cache1 = self._prefill_jit(
            self.params, jnp.asarray(padded), jnp.int32(len(toks)))
        self._cache = jax.tree_util.tree_map(
            lambda big, small: big.at[slot].set(small[0]),
            self._cache, cache1)
        self._activate_slot(slot, a, now, int(first))

    def _activate_slot(self, slot: int, a: Assignment, now: float,
                       first: int, blocks: list | None = None,
                       seq_len: int = 0) -> None:
        self._last_tokens[slot] = first
        self.slots[slot] = _Slot(
            rid=a.rid, remaining=a.max_new_tokens - 1,
            deadline=now + a.deadline_rel_ms / 1e3, assigned_at=now,
            age_ms=a.age_ms, slo_ms=a.slo_ms, generated=[first],
            blocks=blocks or [], seq_len=seq_len)
        self.prefilled.add(a.rid)

    # -- paged prefill + prefix cache ------------------------------------
    def _clamped_tokens(self, a: Assignment) -> list[int]:
        limit = self.cfg.max_seq - a.max_new_tokens
        return a.tokens[:max(1, limit)]

    def _lookup_prefix(self, toks: list[int]) -> tuple[list, int]:
        """Walk the prompt's block chain through the prefix cache:
        returns (hit block ids — refcounts already bumped, tokens
        covered)."""
        bt = self.cfg.block_tokens
        parent = FNV_SEED
        hits: list[int] = []
        pos = 0
        while pos < len(toks):
            seg = toks[pos:pos + bt]
            blk = self.pool.lookup(parent, seg)
            if blk is None:
                break
            hits.append(blk)
            parent = chain_hash(parent, seg)
            pos += len(seg)
        return hits, pos

    def _publish_prompt(self, toks: list[int], blocks: list) -> None:
        """Content-address every prompt block (full blocks and the
        partial tail) so later identical prefixes hit instead of
        re-prefilling.  Publishing makes a block immutable — the next
        write into the tail COWs it (the first divergent write)."""
        bt = self.cfg.block_tokens
        parent = FNV_SEED
        for i in range(0, len(toks), bt):
            parent = self.pool.publish(blocks[i // bt], parent,
                                       toks[i:i + bt])

    def _ensure_writable(self, slot_blocks: list, j: int) -> bool:
        """COW guard before writing into logical block ``j``: a shared
        or published block gets a private copy (pool ids + tensor rows)
        and the slot's table repoints.  Returns True when a copy
        happened."""
        old = slot_blocks[j]
        new, copied = self.pool.cow(old)
        if copied:
            self._cache = self._copy_block_jit(
                self._cache, jnp.int32(old), jnp.int32(new))
            slot_blocks[j] = new
        return copied

    def _prefill_slot_paged(self, slot: int, a: Assignment,
                            now: float) -> None:
        bt = self.cfg.block_tokens
        toks = self._clamped_tokens(a)
        hits, pos = self._lookup_prefix(toks)
        full_hit = pos >= len(toks)
        if full_hit:
            # Whole prompt resident: no prefill at all — re-run just the
            # last prompt token (its K/V rewrite is value-identical;
            # COW below keeps shared blocks untouched) to get the
            # next-token logits.
            pos = len(toks) - 1
            self.stats["prefill_skipped"] += 1
        total = -(-(len(toks) + a.max_new_tokens) // bt)
        fresh = self.pool.alloc(total - len(hits))
        if fresh is None:
            # The front end reserves worst-case blocks per admission, so
            # this is unreachable unless accounting drifted; fail loud.
            for b in hits:
                self.pool.deref(b)
            raise RuntimeError(
                f"KV pool exhausted admitting rid {a.rid}: "
                f"{self.pool.free_count()} free of {self.pool.num_blocks}")
        blocks = hits + fresh
        j0 = pos // bt
        self._ensure_writable(blocks, j0)
        rem = toks[pos:]
        bucket = min(self._bucket(len(rem)), self.cfg.max_seq)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :len(rem)] = rem
        row = np.full(self.cfg.table_width, self._sink, np.int32)
        row[:total] = blocks
        first, self._cache = self._paged_prefill_jit(
            self.params, self._cache, jnp.asarray(padded),
            jnp.asarray(row[None]), jnp.asarray([pos], np.int32),
            jnp.asarray([len(rem)], np.int32))
        self._publish_prompt(toks, blocks)
        self._tables[slot] = row
        self._activate_slot(slot, a, now, int(first), blocks=blocks,
                            seq_len=len(toks))

    # -- disaggregated prefill/decode ------------------------------------
    def _admit_disaggregated(self, slot: int, a: Assignment,
                             now: float) -> None:
        """Decode-rank admission of a prefill-rank-assigned request: a
        full local prefix hit admits immediately (the stream, when it
        lands, is discarded); otherwise the slot parks PENDING — it
        skips decode until the streamed blocks arrive (or the fallback
        re-prefills locally), so the long prompt never stalls a step."""
        toks = self._clamped_tokens(a)
        hits, pos = self._lookup_prefix(toks)
        if pos >= len(toks):
            for b in hits:          # _prefill_slot_paged re-looks-up
                self.pool.deref(b)
            self._prefill_slot_paged(slot, a, now)
            if self._kvstream is not None:
                self._kvstream.discard(a.rid)
            return
        for b in hits:
            self.pool.deref(b)
        self.slots[slot] = _Slot(
            rid=a.rid, remaining=a.max_new_tokens,
            deadline=now + a.deadline_rel_ms / 1e3, assigned_at=now,
            age_ms=a.age_ms, slo_ms=a.slo_ms, generated=[],
            pending=a, pending_since=now)
        self.prefilled.add(a.rid)

    def _prefill_and_stream(self, a: Assignment) -> None:
        """Prefill-rank half: compute the prompt's KV blocks in the
        local scratch pool (identity table) and stream them to every
        rank of the decode replica group."""
        bt = self.cfg.block_tokens
        toks = self._clamped_tokens(a)
        nblk = -(-len(toks) // bt)
        row = np.full(self.cfg.table_width, self._sink, np.int32)
        row[:nblk] = np.arange(nblk)
        bucket = min(self._bucket(len(toks)), self.cfg.max_seq)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :len(toks)] = toks
        first, self._cache = self._paged_prefill_jit(
            self.params, self._cache, jnp.asarray(padded),
            jnp.asarray(row[None]), jnp.zeros((1,), np.int32),
            jnp.asarray([len(toks)], np.int32))
        image = self._extract_blocks(nblk)
        dests = list(range(a.replica * self.group_size,
                           (a.replica + 1) * self.group_size))
        from ..resilience import deadline_scope

        # The stream is bounded twice over: the request's SLO deadline
        # scopes the step, and the KVStreamGuard silence timeout aborts
        # a send wedged on a dead decode peer (the decode side then
        # re-prefills locally — degradation, never a stall).
        try:
            with deadline_scope(time.monotonic()
                                + a.deadline_rel_ms / 1e3):
                self._kvstream.send_image(
                    a.rid, dests, image.tobytes(), first=int(first),
                    plen=len(toks), cursor=len(toks), shape=image.shape,
                    dtype=str(image.dtype))
        except (ConnectionError, OSError) as exc:
            # The decode side's pending-patience fallback re-prefills
            # locally; a broken stream is degradation, never a stall.
            logger.warning("serving: prefill stream for rid %d failed: "
                           "%s", a.rid, exc)
            return
        self.stats["prefill_streams"] += 1

    def _cache_pool_leaves(self) -> list:
        """The per-layer key/value pool arrays in a deterministic
        traversal order (identical on sender and receiver: same model,
        same cache tree)."""
        leaves = []

        def walk(node):
            if not isinstance(node, dict):
                return
            for key in sorted(node):
                if key in ("key_pool", "value_pool"):
                    leaves.append((key, node))
                else:
                    walk(node[key])
        walk(self._cache)
        return leaves

    def _extract_blocks(self, nblk: int) -> np.ndarray:
        """[n_leaves, nblk, bt, H, D]: the prompt's pool rows across
        every layer, ready to serialize."""
        return np.stack([np.asarray(node[key][:nblk])
                         for key, node in self._cache_pool_leaves()])

    def _insert_blocks(self, ids: list, image: np.ndarray) -> None:
        idx = jnp.asarray(np.asarray(ids, np.int32))
        for i, (key, node) in enumerate(self._cache_pool_leaves()):
            node[key] = node[key].at[idx].set(jnp.asarray(image[i]))

    def _integrate_prefills(self) -> None:
        """Decode-rank step hook: land fully streamed transfers into
        pending slots (non-blocking — a transfer still in flight just
        keeps its slot pending), re-prefill locally when a transfer
        outlived its patience (prefill rank died / stream lost), and
        drop orphaned images."""
        now = time.monotonic()
        pending_rids = set()
        for i, s in enumerate(self.slots):
            if s is None or s.pending is None:
                continue
            pending_rids.add(s.rid)
            img = self._kvstream.pop_ready(s.rid) \
                if self._kvstream is not None else None
            if img is not None:
                self._land_streamed(i, img)
                continue
            patience = max(1.0, s.slo_ms / 4e3)
            if now - s.pending_since > patience:
                a = s.pending
                self.slots[i] = None
                self._prefill_slot_paged(i, a, now)
                self.stats["prefill_fallbacks"] += 1
                if self._kvstream is not None:
                    self._kvstream.discard(a.rid)
        if self._kvstream is not None:
            for rid in self._kvstream.ready_rids():
                if rid not in pending_rids:
                    self._kvstream.discard(rid)

    def _land_streamed(self, slot: int, img) -> None:
        """Insert a streamed prefill into the pool and activate the
        slot: allocate the sequence's full block run, write the prompt
        rows, publish them for prefix reuse."""
        a = self.slots[slot].pending
        now = time.monotonic()
        bt = self.cfg.block_tokens
        toks = self._clamped_tokens(a)
        total = -(-(len(toks) + a.max_new_tokens) // bt)
        blocks = self.pool.alloc(total)
        if blocks is None:
            raise RuntimeError(
                f"KV pool exhausted landing streamed rid {a.rid}")
        image = np.frombuffer(bytes(img.data),
                              np.dtype(img.dtype)).reshape(img.shape)
        nblk = image.shape[1]
        self._insert_blocks(blocks[:nblk], image)
        self._publish_prompt(toks, blocks)
        row = np.full(self.cfg.table_width, self._sink, np.int32)
        row[:total] = blocks
        self._tables[slot] = row
        remaining = self.slots[slot].remaining
        self._last_tokens[slot] = img.first
        self.slots[slot] = dataclasses.replace(
            self.slots[slot], remaining=remaining - 1,
            generated=[img.first], blocks=blocks, seq_len=img.cursor,
            pending=None, pending_since=0.0)

    # -- decode ----------------------------------------------------------
    def _decode_once(self) -> None:
        active = [i for i, s in enumerate(self.slots)
                  if s is not None and s.pending is None
                  and s.remaining > 0]
        if not active:
            return
        if self.cfg.paged:
            bt = self.cfg.block_tokens
            for i in active:
                s = self.slots[i]
                # COW guard: the write position may sit in a published
                # tail (the first divergent write of a shared prefix).
                if self._ensure_writable(s.blocks, s.seq_len // bt):
                    self._tables[i][s.seq_len // bt] = \
                        s.blocks[s.seq_len // bt]
                self._cursors[i] = s.seq_len
            nxt, self._cache = self._paged_jit(
                self.params, self._cache,
                jnp.asarray(self._last_tokens[:, None]),
                jnp.asarray(self._tables), jnp.asarray(self._cursors))
        else:
            nxt, self._cache = self._decode_jit(
                self.params, self._cache,
                jnp.asarray(self._last_tokens[:, None]))
        nxt = np.asarray(nxt)
        for i in active:
            s = self.slots[i]
            tok = int(nxt[i])
            s.generated.append(tok)
            s.remaining -= 1
            s.seq_len += 1
            self._last_tokens[i] = tok
            if self.cfg.eos_id >= 0 and tok == self.cfg.eos_id:
                s.remaining = 0

    def _collect_completions(self) -> None:
        now = time.monotonic()
        stale = self._fleet_staleness_steps()
        for i, s in enumerate(self.slots):
            if s is None or s.pending is not None or s.remaining > 0:
                continue
            rec = {"rid": s.rid, "replica": self.group,
                   "latency_ms": s.age_ms + (now - s.assigned_at) * 1e3,
                   "tokens": len(s.generated),
                   "slo_met": now <= s.deadline,
                   # Which published weights served this request, and
                   # how many trainer steps behind the newest staged
                   # snapshot — the loadgen staleness accounting
                   # (docs/fleet.md).
                   "weights": self.weight_version,
                   "weights_stale_steps": stale}
            self.completed[s.rid] = rec
            if self.group_leader:
                # Every group member frees slots identically; only the
                # leader reports, so completions appear exactly once.
                self._unreported.append(rec)
            self._release_slot(i)

    def _release_slot(self, i: int) -> None:
        s = self.slots[i]
        if self.cfg.paged and s is not None:
            for b in s.blocks:
                self.pool.deref(b)
            self._tables[i] = self._sink
            self._cursors[i] = 0
            if self._kvstream is not None:
                self._kvstream.discard(s.rid)
        self.slots[i] = None

    def _exchange_completions(self) -> list[dict]:
        from ..resilience import deadline_scope
        # Completions plus this rank's staged weight versions ride one
        # allgather: the front learns the version set every rank holds
        # with zero extra collectives, exactly like completions ride
        # the step.
        mine = {"done": list(self._unreported),
                "staged": self._fleet_staged_versions()}
        deadlines = [s.deadline for s in self.slots if s is not None]
        with deadline_scope(min(deadlines) if deadlines else None):
            per_rank = self.hvd.allgather_object(
                mine, name=f"serve.done.g{self._gen}.{self._step}")
        self._unreported.clear()       # acknowledged by the exchange
        common = set.intersection(
            *(set(p.get("staged") or ()) for p in per_rank))
        self._fleet_common = max(common) if common else 0
        return [rec for p in per_rank for rec in p["done"]]

    def _account(self, completions: list[dict]) -> None:
        if self.rank != self.front:
            return
        now = time.monotonic()
        for rec in completions:
            if rec["rid"] not in self.batcher.inflight:
                continue   # duplicate re-send after a failed exchange
            self.batcher.note_done(rec["rid"])
            self.admission.count("served")
            self.admission.observe_latency_ms(rec["latency_ms"])
            self.stats["served"] += 1
            self.stats["served_slo"] += bool(rec["slo_met"])
            self.stats["latencies_ms"].append(rec["latency_ms"])
            # Completion wall times let the load harness report goodput
            # before/during/after an elastic grow (docs/serving.md).
            self.stats["completed_at"].append(now)

    # -- elastic grow mid-serve (statesync/) -----------------------------
    def attach_statesync(self, service) -> None:
        """Wire a statesync membership service in: every serve step ends
        with its boundary check, so a joining replica is admitted at a
        step boundary and enters after its streamed params verify."""
        self.statesync = service

    def state_tree(self) -> dict:
        """The streamed-state template/provider for serving: params are
        the only cross-replica state (KV caches are per-request), and
        they never change between steps — the statesync service runs in
        static mode, so the bulk image IS the joiner's entry state."""
        import jax

        return {"params": jax.tree_util.tree_map(np.asarray,
                                                 self.params)}

    # -- fleet continuous weight deployment (fleet/) ---------------------
    def attach_fleet(self, kv, *, interval_s: float | None = None):
        """Start a fleet weight puller against the coordinator KV: it
        polls the published ``head``, digest-verifies new snapshots and
        stages them here; the front end schedules the swap into a
        broadcast BatchPlan once every rank has staged (docs/fleet.md).
        Returns the puller (owned by this executor — ``close`` joins
        it)."""
        from ..fleet.deploy import WeightPuller

        kwargs = {} if interval_s is None else {"interval_s": interval_s}
        self._fleet_puller = WeightPuller(kv, self._fleet_stage,
                                          **kwargs)
        self._fleet_puller.start()
        return self._fleet_puller

    # Staged-but-unswapped versions a rank holds at most, so a group
    # whose swaps cannot land never accumulates unbounded full param
    # images.  At the cap, a staged version never REPORTED in a
    # completions exchange is evicted for a newer one (the front cannot
    # have scheduled what it never saw); once every staged version has
    # been reported the puller is refused and retries.
    _FLEET_STAGE_CAP = 4

    def _fleet_stage(self, version: int, image, meta) -> bool:
        """WeightPuller stage callback (puller thread): decode the
        already-verified image into a params-shaped tree and park it,
        keyed by version, for the front-scheduled boundary swap.  Never
        touches live params — the swap happens on the serve thread
        inside ``_apply_plan``.

        At the window cap, the oldest version NOT yet reported in a
        completions exchange is evicted to admit the newer one —
        unreported versions cannot be in any plan, and while the serve
        loop is paused (a grow resync: the joiner compiles for many
        publish intervals) refusing instead would wedge the whole
        group: this rank's window fills with versions the publisher
        GCs before the joiner can ever pull them, the staged sets then
        never intersect, and no swap ever frees the window.  A version
        that HAS been reported may already be scheduled, so once every
        staged version is reported the puller is refused (False) and
        retries — a reported image is only ever dropped by the swap
        path."""
        from ..statesync.snapshot import unflatten_state

        if version <= self.weight_version:
            return True                # already serving newer weights
        with self._fleet_lock:
            if version in self._fleet_staged:
                return True            # duplicate push
            if not self._fleet_can_admit():
                return False
        template = {"params": jax.tree_util.tree_map(np.asarray,
                                                     self.params)}
        tree = unflatten_state(image, template)
        with self._fleet_lock:
            if not self._fleet_can_admit():
                return False
            self._fleet_staged[version] = (tree["params"],
                                           int(meta.get("step", 0)),
                                           int(meta.get("digest", 0)))
        return True

    def _fleet_can_admit(self) -> bool:
        """Make room under the stage cap (lock held): evict the oldest
        never-reported version if the window is full; False when every
        staged version has been reported (and so may be scheduled)."""
        if len(self._fleet_staged) < self._FLEET_STAGE_CAP:
            return True
        evictable = sorted(set(self._fleet_staged)
                           - self._fleet_reported)
        if not evictable:
            return False
        del self._fleet_staged[evictable[0]]
        return True

    def _fleet_staged_versions(self) -> tuple:
        """The versions this rank holds staged, for the completions
        exchange: the front schedules the newest version present in
        EVERY rank's report.  Reported versions become eviction-exempt
        — from here on only the swap path may drop them."""
        with self._fleet_lock:
            versions = tuple(sorted(self._fleet_staged))
            self._fleet_reported.update(versions)
            return versions

    def _fleet_staleness_steps(self) -> int:
        """Trainer steps between the newest snapshot this rank has
        staged and the weights currently serving (0 when current) — the
        loadgen staleness accounting (docs/fleet.md)."""
        with self._fleet_lock:
            steps = [s[1] for s in self._fleet_staged.values()]
        newest = max(steps) if steps else self._weight_step
        return max(0, newest - self._weight_step)

    def _fleet_swap(self, version: int) -> None:
        """Swap exactly the scheduled version in at the plan boundary
        the front broadcast.  Every rank executes this at the same step
        with the same version — never "whatever is staged locally",
        which can differ across ranks when a puller staged a newer
        image after the completions exchange, and would let ranks of
        one sharded replica group decode a step under mixed weights.
        In-flight slots keep decoding under the new weights, no
        admitted request is dropped."""
        with self._fleet_lock:
            staged = self._fleet_staged.pop(version, None)
            if staged is not None:
                # Older staged versions are superseded the moment a
                # newer one swaps in; they are dropped only now, after
                # the scheduled swap — never at stage time.
                for old in [v for v in self._fleet_staged
                            if v < version]:
                    del self._fleet_staged[old]
                self._fleet_reported &= set(self._fleet_staged)
        if staged is None:
            # The front schedules from the intersection of every
            # rank's reported staged set, so the version can only be
            # missing after a local restart; keep serving the old
            # weights until the puller re-stages.
            return
        params, meta_step, digest = staged
        self.params = jax.tree_util.tree_map(jnp.asarray, params)
        self.weight_version = version
        self._weight_step = meta_step
        self.stats["weight_swaps"].append(
            {"version": version, "step": self._step, "digest": digest,
             "at": time.monotonic()})
        from ..telemetry import flight
        from ..telemetry import metrics as telemetry_metrics

        rec = flight.recorder()
        if rec.enabled:
            rec.record("fleet-swap", name=f"v{version}",
                       detail=f"swapped at plan step {self._step}")
        tm = telemetry_metrics()
        if tm.enabled:
            tm.gauge("horovod_fleet_weight_version").set(version)
        logger.info("serving: weights v%d swapped at step %d", version,
                    self._step)

    def _statesync_boundary(self) -> None:
        change = self.statesync.step_boundary()
        if change is not None and change.kind == "grow":
            self._grow_resync(change.join_id, change.rank, change.size)

    def _grow_resync(self, join_id: int, new_rank: int,
                     new_size: int) -> None:
        """Realign the serving world after a grow: every rank (the
        joiner included — this is its first collective) exchanges
        (step, gen, resident rids), adopts the maxima, and rebuilds the
        batcher with the new replica group present but empty.  Nothing
        in flight is touched: incumbents' KV caches are process-local."""
        old_size = self.size
        self.rank, self.size = new_rank, new_size
        self.front = 0
        self._configure_groups()
        mine = {"step": self._step, "gen": self._gen,
                "rids": (sorted(s.rid for s in self.slots
                                if s is not None)
                         if self.group_leader else [])}
        per_rank = self.hvd.allgather_object(
            mine, name=f"serve.growsync.{join_id}")
        self._step = max(p["step"] for p in per_rank)
        # Fresh gen: post-grow collective names never collide with any
        # pre-grow step another rank might still have cached.
        self._gen = max(p["gen"] for p in per_rank) + 1
        per_group = [per_rank[g * self.group_size]["rids"]
                     for g in range(self.num_groups)]
        self.batcher.rebuild(per_group)
        if self.prefill_rank_list:
            self._rebuild_kvstream()
        windows = getattr(self.statesync, "grow_windows", [])
        self.stats["grows"].append(
            {"join": join_id, "from": old_size, "to": new_size,
             "step": self._step, "at": time.monotonic(),
             "window_s": windows[-1][1] - windows[-1][0]
             if windows else 0.0})
        logger.warning("serving: grow %d->%d (join %d) at step %d",
                       old_size, new_size, join_id, self._step)

    def _note_perf(self, tokens: int, ctx_sum: int, dt_s: float) -> None:
        """Fold one decode step into the perfscope serve ledger gauges:
        accepted tokens/s, analytic FLOPs per token at the step's mean
        KV context, and their product over the chip peak (serve MFU) —
        the step ledger telemetry/perfmodel.build_ledger merges."""
        from ..telemetry import metrics as telemetry_metrics
        tm = telemetry_metrics()
        if not tm.enabled or tokens <= 0 or dt_s <= 0.0:
            return
        from ..telemetry import perfmodel
        if self._peak_flops is None:
            kind = ""
            try:
                kind = jax.local_devices()[0].device_kind
            except Exception:  # noqa: BLE001 - backend probing only
                pass
            self._peak_flops = perfmodel.peak_flops(kind)
        tps = tokens / dt_s
        # EMA over steps: a serve step is milliseconds, and the raw
        # per-step rate whipsaws with batch occupancy.
        self._perf_tps = tps if self._perf_tps <= 0.0 \
            else 0.8 * self._perf_tps + 0.2 * tps
        flops_per_token = perfmodel.transformer_decode_flops(
            self.model.cfg, ctx_sum / tokens)
        tm.gauge("horovod_serve_tokens_per_sec").set(self._perf_tps)
        tm.gauge("horovod_serve_flops_per_token").set(flops_per_token)
        tm.gauge("horovod_serve_mfu").set(
            self._perf_tps * flops_per_token / self._peak_flops)

    # -- the loop --------------------------------------------------------
    def _serve_step(self) -> bool:
        t0 = time.monotonic()
        plan = self._assemble() if self.rank == self.front else None
        plan = self._exchange_plan(plan)
        self._step += 1
        if plan.stop:
            return False
        self._apply_plan(plan)
        decoded = ctx_sum = 0
        if not self.is_prefill:
            if self.cfg.paged and self.prefill_rank_list:
                self._integrate_prefills()
            self._decode_once()
            for s in self.slots:
                if s is not None and s.pending is None:
                    decoded += 1
                    ctx_sum += s.seq_len
            self._collect_completions()
        completions = self._exchange_completions()
        self._account(completions)
        if self.statesync is not None:
            self._statesync_boundary()
        dt = time.monotonic() - t0
        self.admission.observe_step_ms(dt * 1e3)
        self._note_perf(decoded, ctx_sum, dt)
        if self._fleet_gauge is not None and self.rank == self.front:
            self._fleet_gauge(self)
        return True

    def serve_loop(self, *, stop_when=None, max_steps: int | None = None,
                   idle_sleep: float = 0.002) -> None:
        """Run serve steps until the front end declares the system
        drained (``stop_when()`` true on the front end AND queue and
        in-flight empty), riding elastic shrinks across rank failures.
        ``max_steps`` is a safety bound for tests."""
        if self._fleet_puller is None and config.FLEET.get():
            # HOROVOD_FLEET=1 (horovodrun --fleet): pull published
            # weights and, on the front, feed the controller's serve
            # gauges (fleet/wiring.py).
            from ..fleet.wiring import attach_replica
            self._fleet_runtime = attach_replica(self)
        while max_steps is None or self._step < max_steps:
            if self.rank == self.front:
                if stop_when is not None and stop_when():
                    self._stop_requested = True
                if (not self._stop_requested
                        and self.queue.depth() == 0
                        and self.batcher.inflight_count() == 0):
                    time.sleep(idle_sleep)   # don't hot-spin empty plans
            try:
                if not self._serve_step():
                    return
            except RanksFailedError as exc:
                self._shrink_and_resume(exc)

    # -- elastic shrink --------------------------------------------------
    def _shrink_and_resume(self, exc: RanksFailedError) -> None:
        from .. import core
        from ..resilience import converge_confirmed_dead

        # Converge on the heartbeat-CONFIRMED dead set (shared with the
        # statesync failure-shrink path, resilience/policy.py): every
        # survivor computes the same membership, and suspicion alone (a
        # slow peer) re-raises instead of shrinking.
        dead = converge_confirmed_dead(exc)
        survivors = [r for r in range(self.size) if r not in dead]
        new_rank = survivors.index(self.rank)
        new_size = len(survivors)
        from ..telemetry import flight

        rec = flight.recorder()
        if rec.enabled:
            rec.record("shrink", f"dead {sorted(dead)}",
                       detail=f"serving {self.size}->{new_size} at "
                              f"step {self._step}")
        logger.warning(
            "serving: shrink %d->%d (dead=%s); this rank %d -> %d",
            self.size, new_size, sorted(dead), self.rank, new_rank)
        base = os.environ.get("HOROVOD_RENDEZVOUS_EPOCH", "0")
        self._gen += 1
        tag = "_".join(str(r) for r in sorted(dead))
        core.reinit_world(
            rank=new_rank, size=new_size,
            epoch=f"{base.split('~', 1)[0]}~sv{self._gen}x{tag}")
        old = (self.rank, self.size)
        self.rank, self.size = new_rank, new_size
        self.front = 0
        self._configure_groups()
        if self.statesync is not None:
            self.statesync.notify_world_changed()
        self._resync()
        if self.prefill_rank_list:
            self._rebuild_kvstream()
        if not self.is_prefill:
            self._repair_pending()
        self.stats["shrinks"].append(
            {"dead": sorted(dead), "from": old[1], "to": new_size,
             "step": self._step})

    def _repair_pending(self) -> None:
        """After a world rebuild, any still-pending streamed prefill may
        have died with its prefill rank: re-prefill locally right away
        (the plan already committed these admissions — they are never
        dropped)."""
        now = time.monotonic()
        for i, s in enumerate(self.slots):
            if s is None or s.pending is None:
                continue
            a = s.pending
            self.slots[i] = None
            self._prefill_slot_paged(i, a, now)
            self.stats["prefill_fallbacks"] += 1

    def _resync(self) -> None:
        """Rebuild shared state from ground truth after a world rebuild.

        - Survivors may have caught the failure at DIFFERENT steps (a
          per-rank data-plane error can abort rank A's plan broadcast
          while rank B fails one exchange later), so the step counter
          realigns to the maximum — collective names must match again.
        - Each group leader reports its resident rids (plus completions
          awaiting re-send); requests that vanished with dead replicas
          are counted lost.  Nothing on a surviving replica is ever
          dropped, so the zero-failed-on-survivors invariant holds.
        """
        rids = sorted(s.rid for s in self.slots if s is not None)
        rids += [rec["rid"] for rec in self._unreported]
        mine = {"step": self._step,
                "rids": rids if self.group_leader else []}
        per_rank = self.hvd.allgather_object(
            mine, name=f"serve.resync.g{self._gen}")
        self._step = max(p["step"] for p in per_rank)
        per_group = [per_rank[g * self.group_size]["rids"]
                     for g in range(self.num_groups)]
        lost = self.batcher.rebuild(per_group)
        if self.rank == self.front:
            for _ in lost:
                self.admission.count("lost")
            self.stats["lost"] += len(lost)

    # -- introspection / teardown ----------------------------------------
    def inflight_rids(self) -> list[int]:
        return sorted(s.rid for s in self.slots if s is not None)

    def request_stop(self) -> None:
        self._stop_requested = True

    def kv_stats(self) -> dict | None:
        """The paged pool's residency/reuse numbers for reports and the
        leak census (None in dense mode)."""
        if self.pool is None:
            return None
        return {"pool_blocks": self.pool.num_blocks,
                "block_tokens": self.pool.block_tokens,
                "free": self.pool.free_count(),
                "active": self.pool.active_count(),
                "cached": self.pool.cached_count(),
                "prefix_hits": self.pool._m_hits.value,
                "prefix_misses": self.pool._m_misses.value,
                "evictions": self.pool._m_evicted.value,
                "cow_copies": self.pool._m_cow.value,
                "max_concurrent_seqs": self.batcher.max_concurrent,
                "prefill_streams": self.stats["prefill_streams"],
                "prefill_fallbacks": self.stats["prefill_fallbacks"],
                "prefill_skipped": self.stats["prefill_skipped"]}

    def close(self) -> None:
        """Release the serving resources this executor owns: the
        kvstream mesh (drain threads + sockets) and the KV block pool
        (hvdlife HVD702/704 — the pool must not outlive the executor
        across elastic reinit cycles)."""
        if self._fleet_puller is not None:
            self._fleet_puller.close()
            self._fleet_puller = None
        if self._kvstream is not None:
            self._kvstream.close()
            self._kvstream = None
        if self.pool is not None:
            self.pool.close()


def serving_params_template(cfg: ServeConfig) -> dict:
    """The state tree a serving joiner offers to ``join_world``: the
    model's parameter pytree (shapes/dtypes only matter — values are
    replaced by the streamed image)."""
    import horovod_tpu  # noqa: F401 - jax config side effects

    model_cfg = cfg.model_cfg
    if model_cfg is None:
        model_cfg = tfm.gpt_tiny(dtype=jnp.float32)
    model_cfg = dataclasses.replace(model_cfg, decode=True,
                                    max_seq_len=cfg.max_seq)
    model = tfm.TransformerLM(model_cfg)
    params = model.init(jax.random.PRNGKey(cfg.seed),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return {"params": jax.tree_util.tree_map(np.asarray, params)}


def join_serving_world(serve_cfg: ServeConfig | None = None
                       ) -> "ReplicaExecutor":
    """Join a live serving world as a fresh replica (statesync grow):
    stream the incumbents' params peer-to-peer, enter as rank N, and
    return a ReplicaExecutor already realigned (step/gen/batcher) and
    ready for ``serve_loop``.  The incumbents' only stall is this
    rank's executor construction (model compile) between world rebuild
    and the first realign exchange — the bulk params transfer happened
    before they rebuilt anything."""
    from .. import statesync

    cfg = serve_cfg or ServeConfig.from_env()
    template = serving_params_template(cfg)
    tree, info = statesync.join_world(template)
    params = jax.tree_util.tree_map(jnp.asarray, tree["params"])
    ex = ReplicaExecutor(cfg, params=params)
    service = statesync.StateSyncService(state_provider=ex.state_tree,
                                         static_state=True)
    ex.attach_statesync(service)
    # First collective on the new world: adopt the incumbents'
    # step/gen and announce this (empty) replica group.
    ex._grow_resync(info.join_id, info.rank, info.size)
    return ex
