"""Paged KV-block pool: free-list allocation, refcounted prefix
sharing, copy-on-write and LRU eviction (the vLLM discipline on the
serving replica, ISSUE 14 tentpole).

The dense layout (PR 9) reserves ``max_batch x max_seq`` KV tokens per
replica whether or not any sequence is that long, so max concurrent
sequences is pinned to the batch shape.  Here the same memory is cut
into fixed-size **blocks** (``HOROVOD_SERVE_BLOCK_TOKENS`` tokens each)
and every live sequence holds exactly the blocks its resident tokens
need, so the pool — token residency — is the concurrency bound, not the
batch shape.

This module is pure bookkeeping: block *ids*, refcounts, hashes and the
LRU.  The actual KV tensors live in the model's paged cache
(models/transformer.py) indexed by these ids; the replica
(serving/replica.py) is the only writer and performs the array copy
half of a COW.

Sharing model:

- **Prefix cache.**  Prompt blocks are content-addressed by an FNV-1a
  *chain* hash (the statesync digest family): each block's key folds
  its parent block's key with its own token ids, so a hit at block *k*
  certifies the whole prefix, not just one block.  ``lookup`` verifies
  the stored token ids before trusting a hash (a collision is a miss,
  never silent corruption).
- **Refcounts.**  A resident block is held by every sequence whose
  table points at it.  ``deref`` to zero parks a *published* (hashed)
  block on the LRU instead of freeing it — that is the prompt cache —
  and frees an unpublished one immediately.
- **Copy-on-write.**  Published blocks are immutable (their hash
  certifies their contents) and shared blocks are not exclusively
  owned, so a sequence about to write into either gets a private copy
  first (``cow``); the first divergent write is the COW point.
- **Eviction.**  ``alloc`` under pressure evicts LRU refcount-0 cached
  blocks (oldest hit first) before reporting exhaustion; exhaustion is
  back-pressure to the batcher, never an error mid-decode (admission
  reserves worst-case blocks up front).
"""
from __future__ import annotations

from collections import OrderedDict, deque

from ..common import config

__all__ = ["FNV_SEED", "KVBlockPool", "chain_hash"]

# FNV-1a, the same family the statesync digests and the collective
# fingerprints fold with.
_FNV_OFFSET = 0xcbf29ce484222325
_FNV_PRIME = 0x100000001b3
_FNV_MASK = (1 << 64) - 1

FNV_SEED = _FNV_OFFSET


def chain_hash(parent: int, tokens) -> int:
    """Fold one block's token ids into its parent's chain key: the
    block's identity is (everything before it, its own tokens)."""
    h = parent & _FNV_MASK
    for t in tokens:
        v = int(t) & 0xffffffff
        for _ in range(4):
            h = ((h ^ (v & 0xff)) * _FNV_PRIME) & _FNV_MASK
            v >>= 8
    return h


class KVBlockPool:
    """Per-replica paged KV block bookkeeping (ids only — see module
    docstring for the tensor half)."""

    def __init__(self, num_blocks: int | None = None,
                 block_tokens: int | None = None, registry=None) -> None:
        self.block_tokens = config.SERVE_BLOCK_TOKENS.get() \
            if block_tokens is None else int(block_tokens)
        self.num_blocks = config.SERVE_POOL_BLOCKS.get() \
            if num_blocks is None else int(num_blocks)
        if self.num_blocks <= 0 or self.block_tokens <= 0:
            raise ValueError(
                f"KVBlockPool needs positive sizes, got "
                f"{self.num_blocks} blocks x {self.block_tokens} tokens")
        self._free: deque[int] = deque(range(self.num_blocks))
        self._ref = [0] * self.num_blocks
        # Published (content-addressed) blocks: hash -> block id, plus
        # the reverse map and the token ids backing collision checks.
        self._by_hash: dict[int, int] = {}
        self._hash_of: dict[int, int] = {}
        self._tokens_of: dict[int, tuple] = {}
        # Refcount-0 published blocks, LRU order (oldest first).
        self._lru: "OrderedDict[int, None]" = OrderedDict()
        self._closed = False
        if registry is None:
            from .. import telemetry
            registry = telemetry.metrics()
            if not registry.enabled:
                # The pool is control state, not just observability:
                # gauges back the batcher's residency view and the
                # serve battery's leak census even with HOROVOD_METRICS
                # off (the AdmissionController convention).
                from ..telemetry.registry import MetricsRegistry
                registry = MetricsRegistry(0)
        self._m_blocks = {
            state: registry.gauge(
                "horovod_serve_kv_blocks",
                "Paged KV blocks by state (free = allocatable, active "
                "= referenced by a live sequence, cached = refcount-0 "
                "prefix blocks parked on the LRU)",
                labels={"state": state})
            for state in ("free", "active", "cached")}
        self._m_hits = registry.counter(
            "horovod_serve_prefix_hits_total",
            "Prompt blocks served from the prefix cache (refcount bump "
            "instead of a re-prefill)")
        self._m_misses = registry.counter(
            "horovod_serve_prefix_misses_total",
            "Prompt blocks that had to be prefilled (no resident "
            "content-addressed match)")
        self._m_evicted = registry.counter(
            "horovod_serve_kv_evictions_total",
            "Cached prefix blocks evicted (LRU) to satisfy allocation")
        self._m_cow = registry.counter(
            "horovod_serve_kv_cow_total",
            "Copy-on-write block copies (first divergent write into a "
            "shared or published block)")
        self._update_gauges()

    # -- occupancy views --------------------------------------------------
    def free_count(self) -> int:
        return len(self._free)

    def cached_count(self) -> int:
        return len(self._lru)

    def active_count(self) -> int:
        """Blocks referenced by at least one live sequence — the leak
        census number: zero once every admitted request completed."""
        return self.num_blocks - len(self._free) - len(self._lru)

    def available(self) -> int:
        """Blocks allocatable right now (free + evictable cached)."""
        return len(self._free) + len(self._lru)

    def refcount(self, block: int) -> int:
        return self._ref[block]

    def is_shared(self, block: int) -> bool:
        """True when a write into ``block`` needs a COW first: another
        sequence holds it too, or its published hash certifies its
        current contents."""
        return self._ref[block] > 1 or block in self._hash_of

    def _update_gauges(self) -> None:
        self._m_blocks["free"].set(len(self._free))
        self._m_blocks["cached"].set(len(self._lru))
        self._m_blocks["active"].set(self.active_count())

    # -- allocation -------------------------------------------------------
    def alloc(self, n: int) -> list[int] | None:
        """Take ``n`` blocks (refcount 1 each), evicting LRU cached
        blocks as needed; None when even eviction cannot cover it (the
        caller defers the admission — back-pressure, not an error)."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > self.available():
            return None
        out = []
        for _ in range(n):
            if not self._free:
                self._evict_one()
            b = self._free.popleft()
            self._ref[b] = 1
            out.append(b)
        self._update_gauges()
        return out

    def _evict_one(self) -> None:
        b, _ = self._lru.popitem(last=False)       # oldest hit first
        self._unpublish(b)
        self._free.append(b)
        self._m_evicted.inc()

    def _unpublish(self, block: int) -> None:
        h = self._hash_of.pop(block, None)
        if h is not None and self._by_hash.get(h) == block:
            del self._by_hash[h]
        self._tokens_of.pop(block, None)

    # -- refcounting ------------------------------------------------------
    def ref(self, block: int) -> None:
        if self._ref[block] <= 0:
            raise ValueError(f"ref of unowned block {block}")
        self._ref[block] += 1

    def deref(self, block: int) -> None:
        """Drop one hold.  At zero, a published block parks on the LRU
        (the prompt cache); an unpublished one frees immediately."""
        if self._ref[block] <= 0:
            raise ValueError(f"deref of unowned block {block}")
        self._ref[block] -= 1
        if self._ref[block] == 0:
            if block in self._hash_of:
                self._lru[block] = None
                self._lru.move_to_end(block)
            else:
                self._free.append(block)
        self._update_gauges()

    # -- the prefix cache -------------------------------------------------
    def publish(self, block: int, parent: int, tokens) -> int:
        """Content-address a prompt block (full blocks and the partial
        tail both; the token count is part of the key via the tuple).
        Returns the block's chain key for the next link.  A block whose
        key is already resident keeps the incumbent (dedup favors the
        older, warmer copy); publishing makes the block immutable —
        any later write COWs."""
        key = chain_hash(parent, tokens)
        if key not in self._by_hash:
            self._by_hash[key] = block
            self._hash_of[block] = key
            self._tokens_of[block] = tuple(int(t) for t in tokens)
        return key

    def lookup(self, parent: int, tokens) -> int | None:
        """Prefix-cache probe for one block: a resident block whose
        chain key AND stored token ids match (hash collision = miss).
        A hit bumps the refcount (and lifts the block off the LRU if it
        was parked); the caller points its table at it instead of
        prefilling."""
        key = chain_hash(parent, tokens)
        b = self._by_hash.get(key)
        if b is None or \
                self._tokens_of.get(b) != tuple(int(t) for t in tokens):
            self._m_misses.inc()
            return None
        if self._ref[b] == 0:
            self._lru.pop(b, None)
        self._ref[b] += 1
        self._m_hits.inc()
        self._update_gauges()
        return b

    # -- copy-on-write ----------------------------------------------------
    def cow(self, block: int) -> tuple[int, bool]:
        """Make ``block`` privately writable for the calling sequence.
        Not shared: returned as-is.  Shared or published: allocate a
        fresh block (the caller copies the KV rows old -> new and
        repoints its table), drop this sequence's hold on the old one.
        Returns (writable block id, copied?)."""
        if not self.is_shared(block):
            return block, False
        fresh = self.alloc(1)
        if fresh is None:
            raise RuntimeError(
                "KV pool exhausted during copy-on-write — admission "
                "must reserve COW headroom (one block per sequence)")
        self.deref(block)
        self._m_cow.inc()
        self._update_gauges()
        return fresh[0], True

    # -- teardown ---------------------------------------------------------
    def release_all(self) -> None:
        """Drop every hold and every cached block (elastic reinit /
        executor teardown): the pool returns to fully free."""
        for b in range(self.num_blocks):
            self._ref[b] = 0
            self._unpublish(b)
        self._lru.clear()
        self._free = deque(range(self.num_blocks))
        self._update_gauges()

    def close(self) -> None:
        """hvdlife HVD702 release verb: the pool's blocks index HBM
        regions in the model cache — an executor that drops its pool
        without closing it leaks the residency accounting across
        reinit_world cycles (HVD704)."""
        if self._closed:
            return
        self.release_all()
        self._closed = True
