"""Prefill-to-decode KV-block streaming (disaggregated serving,
ISSUE 14 tentpole piece 3).

Under ``HOROVOD_SERVE_PREFILL_RANKS`` the highest N ranks of the serving
world run prompt prefill ONLY: they compute a prompt's KV blocks into a
local scratch pool and stream the finished blocks to the decode
replica's ranks over a dedicated :class:`~..runner.network.PeerMesh` —
never over the collective planes, so the BatchPlan broadcast stays the
single schedule source and the fingerprint stream is identical on every
rank.  Decode ranks keep decoding their in-flight slots while the
transfer runs; a long prompt therefore never occupies a decode step
(the compute-into-communication overlap of arXiv:2305.06942, applied to
inference).

Wire format is the ``STATE_MAGIC`` mold from statesync: magic-prefixed
frames, JSON meta, **addressed CRC'd chunks** so a half-arrived
transfer is detectable and every chunk self-describes its offset::

    KVS_MAGIC | u8 kind | u32 meta_len | meta json | payload

    KVS_DATA  {rid, o, n, crc, total}   one chunk of the block image
    KVS_DONE  {rid, total, first, plen, cursor, shape, dtype}  trailer

The payload image is the prompt's K/V pool rows for every layer,
serialized by the replica (one contiguous ndarray); ``shape``/``dtype``
in the trailer let the decode rank reinterpret it without trusting the
sender's layout implicitly.

Every receive wait is bounded by a :class:`KVStreamGuard` poll slice
(the StreamGuard discipline from statesync/stream.py): ``close()`` sets
the stop flag and the drain threads exit within one slice — the wakeup
half of hvdlife HVD705.
"""
from __future__ import annotations

import json
import struct
import threading
import zlib

from ..common import config
from ..common.logging import logger

__all__ = ["KVS_DATA", "KVS_DONE", "KVS_MAGIC", "KVStreamGuard",
           "KVStreamMesh", "PrefilledImage", "pack_kv_frame",
           "unpack_kv_frame", "kvstream_scope"]

KVS_MAGIC = b"\xffHVDKVS\xff"
_KVS_HDR = struct.Struct(">BI")

KVS_DATA = 1     # prefill -> decode: one addressed, CRC'd chunk
KVS_DONE = 2     # prefill -> decode: transfer trailer (shape/dtype/...)


def pack_kv_frame(kind: int, meta: dict, payload=b"") -> bytes:
    meta_raw = json.dumps(meta, separators=(",", ":")).encode()
    head = KVS_MAGIC + _KVS_HDR.pack(kind, len(meta_raw)) + meta_raw
    if not payload:
        return head
    return head + bytes(payload)


def unpack_kv_frame(raw) -> tuple[int, dict, memoryview]:
    view = memoryview(raw)
    n_magic = len(KVS_MAGIC)
    if bytes(view[:n_magic]) != KVS_MAGIC:
        raise ValueError("kvstream channel received a non-KVS frame — "
                         "the prefill mesh carries only KVS_MAGIC "
                         "frames")
    kind, meta_len = _KVS_HDR.unpack_from(view, n_magic)
    meta_start = n_magic + _KVS_HDR.size
    meta = json.loads(bytes(view[meta_start:meta_start + meta_len]))
    return kind, meta, view[meta_start + meta_len:]


def kvstream_scope(epoch: str, gen: int) -> str:
    """The dedicated mesh scope of one serving generation's prefill
    streams (epoch-scoped like statesync's sync meshes, so a rebuilt
    world never collides with a dying one's sockets)."""
    return f"kvserve.{epoch}.{gen}"


class KVStreamStopped(ConnectionError):
    """The guard aborted a wait because the mesh is closing."""


class KVStreamGuard:
    """Deadline/stop policy for kvstream channel waits (duck-typed like
    statesync's StreamGuard): every wait polls in short slices and
    aborts as soon as ``stop`` is set — a drain thread parked on an
    idle channel wakes within one slice of ``close()``.  Sends are
    additionally silence-bounded: ``timeout`` seconds without a byte of
    progress raises instead of wedging the serve loop behind a dead
    decode peer (receives stay stop-only — a drain thread idling
    between transfers is the normal state, and a peer that dies
    mid-transfer closes the socket, which raises on its own)."""

    def __init__(self, stop: threading.Event,
                 poll_interval: float = 0.1,
                 timeout: float = 30.0) -> None:
        self._stop = stop
        self.poll_interval = poll_interval
        self.timeout = float(timeout)

    def check(self, peer: int, waited: float, phase: str) -> None:
        if self._stop.is_set():
            raise KVStreamStopped(
                f"kvstream mesh closing (peer {peer}, {phase})")
        if phase != "recv" and waited >= self.timeout:
            raise ConnectionError(
                f"kvstream peer {peer}: no progress for {waited:.1f}s "
                f"in {phase} — abandoning the transfer")

    def peer_connection_lost(self, peer: int, phase: str,
                             detail: str) -> ConnectionError:
        return ConnectionError(
            f"kvstream peer {peer} lost in {phase}: {detail}")


class PrefilledImage:
    """One fully received prefill transfer, ready for pool insertion."""

    __slots__ = ("rid", "data", "first", "plen", "cursor", "shape",
                 "dtype")

    def __init__(self, rid: int, data: bytearray, meta: dict) -> None:
        self.rid = rid
        self.data = data
        self.first = int(meta["first"])       # first generated token
        self.plen = int(meta["plen"])         # true prompt length
        self.cursor = int(meta["cursor"])     # decode resumes here
        self.shape = tuple(meta["shape"])
        self.dtype = str(meta["dtype"])


def _stream_bytes_counter(role: str):
    from ..telemetry import metrics

    return metrics().counter(
        "horovod_serve_prefill_stream_bytes_total",
        "KV-block payload bytes streamed from prefill ranks to decode "
        "replicas, by role",
        labels={"role": role})


class KVStreamMesh:
    """One rank's half of the prefill/decode streaming plane.

    Formed collectively (every serving rank constructs it with the same
    scope) so PeerMesh's pairwise bootstrap completes; decode ranks then
    run one named drain thread per prefill peer, prefill ranks just
    send.  The collective planes never see a byte of this traffic."""

    def __init__(self, kv, scope: str, rank: int, size: int,
                 prefill_ranks: list[int], *,
                 chunk_bytes: int | None = None,
                 timeout: float = 30.0) -> None:
        from ..runner.network import PeerMesh

        self.rank = rank
        self.prefill_ranks = list(prefill_ranks)
        self.chunk_bytes = chunk_bytes or \
            config.SERVE_KVSTREAM_CHUNK_BYTES.get()
        self._stop = threading.Event()
        self._guard = KVStreamGuard(self._stop)
        self.mesh = PeerMesh(rank, size, kv, scope=scope,
                             timeout=timeout, resilience=self._guard)
        self._lock = threading.Lock()
        self._partial: dict[int, tuple[bytearray, int]] = {}
        self._ready: dict[int, PrefilledImage] = {}
        self._threads: list[threading.Thread] = []
        self._sent = _stream_bytes_counter("sent")
        self._received = _stream_bytes_counter("received")
        if rank not in self.prefill_ranks:
            for peer in self.prefill_ranks:
                t = threading.Thread(
                    target=self._drain, args=(peer,), daemon=True,
                    name=f"hvd-serve-kvstream-{peer}")
                t.start()
                self._threads.append(t)

    # -- prefill side ------------------------------------------------------
    def send_image(self, rid: int, dests: list[int], image: bytes,
                   *, first: int, plen: int, cursor: int,
                   shape: tuple, dtype: str) -> None:
        """Stream one prompt's serialized KV-block image to every rank
        of the decode replica group: addressed CRC'd chunks, then the
        trailer that makes the transfer interpretable."""
        view = memoryview(image)
        total = view.nbytes
        trailer = pack_kv_frame(KVS_DONE, {
            "rid": rid, "total": total, "first": first, "plen": plen,
            "cursor": cursor, "shape": list(shape), "dtype": dtype})
        for dest in dests:
            for o in range(0, total, self.chunk_bytes):
                n = min(self.chunk_bytes, total - o)
                chunk = view[o:o + n]
                self.mesh.send(dest, pack_kv_frame(
                    KVS_DATA, {"rid": rid, "o": o, "n": n,
                               "crc": zlib.crc32(chunk),
                               "total": total}, chunk))
                self._sent.inc(n)
            self.mesh.send(dest, trailer)

    # -- decode side -------------------------------------------------------
    def _drain(self, peer: int) -> None:
        try:
            while not self._stop.is_set():
                kind, meta, payload = unpack_kv_frame(
                    self.mesh.recv(peer))
                self._ingest(kind, meta, payload)
        except KVStreamStopped:
            return
        except (ConnectionError, OSError, ValueError) as exc:
            if not self._stop.is_set():
                # A dead prefill rank mid-transfer: the replica's
                # pending-prefill fallback re-prefills locally, so this
                # is degradation, not failure.
                logger.warning("kvstream: drain from prefill rank %d "
                               "ended: %s", peer, exc)

    def _ingest(self, kind: int, meta: dict, payload) -> None:
        rid = int(meta["rid"])
        with self._lock:
            if kind == KVS_DATA:
                o, n = int(meta["o"]), int(meta["n"])
                if zlib.crc32(payload) != int(meta["crc"]):
                    # Corrupt chunk: drop the transfer — the decode
                    # side's fallback re-prefills locally rather than
                    # ever interpreting unverified bytes.
                    logger.warning("kvstream: chunk CRC mismatch for "
                                   "rid %d at offset %d; dropping the "
                                   "transfer", rid, o)
                    self._partial.pop(rid, None)
                    return
                buf, got = self._partial.get(
                    rid, (bytearray(int(meta["total"])), 0))
                buf[o:o + n] = payload
                self._partial[rid] = (buf, got + n)
                self._received.inc(n)
            elif kind == KVS_DONE:
                buf, got = self._partial.pop(rid, (bytearray(0), 0))
                if got != int(meta["total"]):
                    logger.warning("kvstream: transfer for rid %d ended "
                                   "with %d/%d bytes; dropping", rid,
                                   got, int(meta["total"]))
                    return
                self._ready[rid] = PrefilledImage(rid, buf, meta)

    def pop_ready(self, rid: int) -> PrefilledImage | None:
        """Non-blocking: the fully received transfer for ``rid``, or
        None while it is still in flight (the serve step never waits on
        a stream — pending slots simply skip decode)."""
        with self._lock:
            return self._ready.pop(rid, None)

    def ready_rids(self) -> list[int]:
        with self._lock:
            return list(self._ready)

    def discard(self, rid: int) -> None:
        """Drop any state for ``rid`` (locally admitted via a full
        prefix-cache hit, or resolved by the fallback prefill)."""
        with self._lock:
            self._partial.pop(rid, None)
            self._ready.pop(rid, None)

    # -- teardown ----------------------------------------------------------
    def close(self) -> None:
        """Stop the drain threads (guard flip = their wakeup), then
        close the mesh."""
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5.0)
        self._threads.clear()
        self.mesh.close()
        with self._lock:
            self._partial.clear()
            self._ready.clear()
