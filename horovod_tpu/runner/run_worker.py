"""Remote worker bootstrap for :func:`horovod_tpu.run`.

Reference: horovod/runner/launch.py:528-618 `_run_static` runs a pickled
``run_func`` on remote hosts and collects results through a KV server; here
the bootstrap reads the pickled ``(func, args, kwargs)`` from stdin (argv
is world-readable on the remote host; stdin is not), executes it with the
slot environment the parent exported, and ships the pickled outcome back
to the parent's rendezvous KV store under the ``runfunc`` scope.
"""
from __future__ import annotations

import os
import pickle
import sys
import traceback


def main() -> int:
    payload = sys.stdin.buffer.read()
    rank = os.environ["HOROVOD_RANK"]
    from .network import RendezvousClient
    kv = RendezvousClient(
        os.environ["HOROVOD_GLOO_RENDEZVOUS_ADDR"],
        int(os.environ["HOROVOD_GLOO_RENDEZVOUS_PORT"]))
    try:
        # Unpickling inside the try: the most common remote failure is the
        # function's module not being importable on this host, and that
        # diagnostic must reach the parent, not vanish into a timeout.
        func, args, kwargs = pickle.loads(payload)
        result = func(*args, **kwargs)
        outcome = (True, result)
        rc = 0
    except BaseException:  # noqa: BLE001 - ship the traceback to the parent
        outcome = (False, traceback.format_exc())
        rc = 1
    kv.put("runfunc", rank, pickle.dumps(outcome))
    return rc


if __name__ == "__main__":
    sys.exit(main())
