"""jsrun/LSF launcher for Spectrum-LSF clusters.

Reference: horovod/runner/js_run.py + horovod/runner/util/lsf.py — on LSF
clusters ``horovodrun`` discovers the allocation and launches one worker
per slot through ``jsrun`` with an explicit-resource (ERF) rankfile
instead of ssh.

TPU-native redesign: the reference queries IBM CSM daemons for the node
inventory and relies on MPI for rank identity.  Neither exists on TPU
pods, so here (a) the allocation is read from LSF's own environment
(``LSB_MCPU_HOSTS`` / ``LSB_DJOB_HOSTFILE``), (b) ``jsrun`` is used purely
as the *process starter* — the control plane stays this framework's
rendezvous/TCP stack, exactly like the ssh launcher — and (c) each worker
adopts its rank from the JSM/PMIx environment (``JSM_NAMESPACE_RANK`` et
al.) and maps it onto the ``HOROVOD_*`` env contract at ``init()``.
"""
from __future__ import annotations

import os
import shutil

from .hosts import SlotInfo

#: Override knob: explicit compute-host list ("h1:4,h2:4") taking
#: precedence over env parsing, for allocations whose batch node cannot be
#: told apart heuristically.
COMPUTE_HOSTS_ENV = "HOROVOD_LSF_COMPUTE_HOSTS"
#: Override knob: cores bound per slot in the generated ERF rankfile.
CPU_PER_SLOT_ENV = "HOROVOD_JSRUN_CPU_PER_SLOT"
#: Set by launch_jsrun for every rank: the full "h1:4,h2:2" layout, so
#: workers can compute local/cross ranks with the same host-assignment
#: math as the ssh launcher (jsrun cannot hand out per-rank env).
JSRUN_HOSTS_ENV = "HOROVOD_JSRUN_HOSTS"


def using_lsf(env: dict | None = None) -> bool:
    """True when running inside an LSF job (reference: lsf.py:35-37)."""
    return "LSB_JOBID" in (env if env is not None else os.environ)


def jsrun_available(env: dict | None = None) -> bool:
    """True if the ``jsrun`` starter is on PATH (reference:
    js_run.py:27-29)."""
    path = (env if env is not None else os.environ).get("PATH")
    return shutil.which("jsrun", path=path) is not None


def lsf_hosts_string(env: dict | None = None, *,
                     include_launch_node: bool = False) -> str | None:
    """Derive "h1:4,h2:4" from the LSF environment.

    Sources, in order: the :data:`COMPUTE_HOSTS_ENV` override,
    ``LSB_DJOB_HOSTFILE`` (one line per slot), ``LSB_MCPU_HOSTS``
    ("host slots host slots ..."), ``LSB_HOSTS`` (one name per slot).

    LSF prepends the batch/launch node to the allocation; the reference
    filters it out via CSM's compute-node inventory (lsf.py:72-75).
    Without CSM the heuristic is: when several distinct hosts are present
    and the FIRST carries exactly one slot while every other carries more,
    it is the launch node and is dropped (override with
    ``include_launch_node=True`` or the env knob).

    Known limitation: one-task-per-node allocations (``span[ptile=1]``)
    make every host carry one slot, so the batch node is indistinguishable
    from the env alone and is kept — pass ``-H`` explicitly or set
    :data:`COMPUTE_HOSTS_ENV` for such jobs.
    """
    env = env if env is not None else os.environ
    override = env.get(COMPUTE_HOSTS_ENV)
    if override:
        return override

    # Aggregate total slots per hostname, preserving first-seen order —
    # cyclic task distributions repeat hostnames non-consecutively.
    counts: dict[str, int] = {}

    def _add(name: str, slots: int = 1) -> None:
        counts[name] = counts.get(name, 0) + slots

    hostfile = env.get("LSB_DJOB_HOSTFILE")
    if hostfile and os.path.exists(hostfile):
        with open(hostfile) as f:
            for ln in f:
                if ln.strip():
                    _add(ln.strip())
    elif env.get("LSB_MCPU_HOSTS"):
        toks = env["LSB_MCPU_HOSTS"].split()
        for i in range(0, len(toks), 2):
            _add(toks[i], int(toks[i + 1]))
    elif env.get("LSB_HOSTS"):
        for name in env["LSB_HOSTS"].split():
            _add(name)
    if not counts:
        return None
    pairs = list(counts.items())

    if (not include_launch_node and len(pairs) > 1
            and pairs[0][1] == 1
            and all(slots > 1 for _, slots in pairs[1:])):
        pairs = pairs[1:]
    return ",".join(f"{name}:{slots}" for name, slots in pairs)


def generate_jsrun_rankfile(slots: list[SlotInfo], *,
                            cores_per_slot: int | None = None,
                            path: str) -> str:
    """Write an explicit-resource (ERF) rankfile binding each rank to a
    disjoint logical-CPU range on its host (reference: js_run.py:96-146,
    which splits cores evenly per experiment).

    The reference derives cores-per-slot from CSM + remote lscpu; neither
    exists on TPU pods and the *launch* node's cpu_count says nothing
    about the compute nodes, so the count must come from the caller or
    :data:`CPU_PER_SLOT_ENV` — guessing would mis-pin every rank.  No
    accelerator resources are declared: TPU chips are not scheduled by
    jsrun; chip assignment happens per local rank at runtime.
    """
    if cores_per_slot is None:
        env_val = os.environ.get(CPU_PER_SLOT_ENV)
        if not env_val:
            raise ValueError(
                "ERF rankfile generation needs the compute-node cores per "
                f"slot: set {CPU_PER_SLOT_ENV} (the launch node's CPU "
                "count is not a usable proxy for the compute nodes).")
        cores_per_slot = int(env_val)
    with open(path, "w") as f:
        f.write("overlapping_rs: allow\ncpu_index_using: logical\n\n")
        for s in slots:
            start = s.local_rank * cores_per_slot
            f.write(f"rank: {s.rank}: {{ hostname: {s.hostname}; "
                    f"cpu: {{{start}-{start + cores_per_slot - 1}}} }}\n")
    return path


def build_jsrun_command(command: list[str], *,
                        np: int | None = None,
                        rs_per_host: int | None = None,
                        rankfile: str | None = None,
                        env_overrides: dict[str, str] | None = None,
                        output_filename: str | None = None) -> list[str]:
    """Build the ``jsrun`` argv (reference: js_run.py:72-82, minus the
    MPI --smpiargs plumbing — the data plane here is not MPI).

    Two placement modes: an ERF ``rankfile`` (explicit CPU pinning, needs
    the compute-node core count), or resource-set flags ``np`` +
    ``rs_per_host`` (one task per resource set; jsrun divides each host's
    CPUs evenly, no core-count knowledge needed — the default).
    """
    cmd = ["jsrun"]
    if rankfile is not None:
        cmd += ["--erf_input", rankfile]
    else:
        # --bind none: jsrun's default gives each resource set ONE CPU;
        # unbound tasks match the ssh launcher's unpinned behavior.
        # --launch_distribution packed: consecutive ranks fill each host
        # in turn — the same host-major order get_host_assignments uses,
        # so rank adoption from JSRUN_HOSTS_ENV matches real placement.
        cmd += ["--nrs", str(np), "--tasks_per_rs", "1",
                "--rs_per_host", str(rs_per_host), "--bind", "none",
                "--launch_distribution", "packed"]
    if output_filename:
        cmd += ["--stdio_stdout", output_filename,
                "--stdio_stderr", output_filename]
    for name in sorted(env_overrides or {}):
        cmd += ["-E", f"{name}={env_overrides[name]}"]
    return cmd + list(command)


def adopt_jsm_env(env: dict | None = None) -> bool:
    """Map JSM/PMIx rank identity onto the ``HOROVOD_*`` env contract.

    jsrun cannot hand each rank a distinct environment the way the ssh
    launcher does (hosts.py SlotInfo.to_env); instead JSM exports
    ``JSM_NAMESPACE_{RANK,SIZE}`` (PMIx fallbacks: ``PMIX_RANK``,
    OMPI_COMM_WORLD_*) per task, and :func:`launch_jsrun` exports the full
    host layout in :data:`JSRUN_HOSTS_ENV` — so every worker derives its
    local/cross ranks from the SAME ``get_host_assignments`` math the ssh
    launcher uses, which stays correct for non-uniform slot counts.

    Called at ``init()``; a no-op unless the JSM identity is present and
    ``HOROVOD_RANK`` is not already set.  Returns True when the contract
    was populated.
    """
    env = env if env is not None else os.environ
    if "HOROVOD_RANK" in env:
        return False
    def _first(*names):
        for name in names:
            if name in env:
                return env[name]
        return None

    # JSM (jsrun), OpenMPI, PMIx, and Hydra/PMI (MPICH, Intel MPI).
    rank = _first("JSM_NAMESPACE_RANK", "OMPI_COMM_WORLD_RANK",
                  "PMIX_RANK", "PMI_RANK")
    size = _first("JSM_NAMESPACE_SIZE", "OMPI_COMM_WORLD_SIZE",
                  "PMI_SIZE")
    if rank is None or size is None:
        return False
    if JSRUN_HOSTS_ENV not in env \
            and "HOROVOD_GLOO_RENDEZVOUS_ADDR" not in env:
        # JSM/OMPI/PMIx identity WITHOUT one of our launchers'
        # control-plane env: a bare `mpirun`/`jsrun` of a script where
        # each process expects an independent size-1 world — adopting a
        # multi-rank world with no rendezvous to form it would only turn
        # working scripts into init-time failures.
        return False
    rank, size = int(rank), int(size)
    hosts_string = env.get(JSRUN_HOSTS_ENV)
    if hosts_string:
        from .hosts import get_host_assignments, host_ids_env, parse_hosts
        assignments = get_host_assignments(parse_hosts(hosts_string), size)
        slot = assignments[rank]
        jsm_local = env.get("JSM_NAMESPACE_LOCAL_RANK")
        if jsm_local is not None and int(jsm_local) != slot.local_rank:
            # jsrun placed this task somewhere other than the host-major
            # order the layout math assumes — wrong local ranks would
            # double-bind TPU chips. Fail loudly with the escape hatch.
            raise RuntimeError(
                f"jsrun placement mismatch: rank {rank} has JSM local "
                f"rank {jsm_local} but host-major layout expects "
                f"{slot.local_rank}; launch with {CPU_PER_SLOT_ENV} set "
                "(ERF rankfile pins placement explicitly).")
        env.update(slot.to_env())
        env["HOROVOD_HOST_IDS"] = host_ids_env(assignments)
        return True
    # Bare JSM/PMIx launch (no layout exported): rank/size and the local
    # identity are per-rank facts JSM provides directly.  The cross
    # topology is NOT derivable here — dividing size by a per-rank
    # local_size gives different answers on hosts with different slot
    # counts, and ranks disagreeing on cross_size hangs hierarchical
    # collectives.  Leaving cross unset (init defaults: 0 of 1) is
    # consistent from every rank's view and simply keeps hierarchical
    # paths off.
    local_rank = int(env.get("JSM_NAMESPACE_LOCAL_RANK",
                             env.get("OMPI_COMM_WORLD_LOCAL_RANK", rank)))
    local_size = int(env.get("JSM_NAMESPACE_LOCAL_SIZE",
                             env.get("OMPI_COMM_WORLD_LOCAL_SIZE", 0)) or 0)
    if local_size <= 0:
        local_size = size
    env.update({
        "HOROVOD_RANK": str(rank),
        "HOROVOD_SIZE": str(size),
        "HOROVOD_LOCAL_RANK": str(local_rank),
        "HOROVOD_LOCAL_SIZE": str(local_size),
    })
    return True


def launch_jsrun(args, command: list[str]) -> int:
    """Static launch through jsrun: start the rendezvous server on the
    launch node, emit the ERF rankfile, and exec ONE jsrun covering every
    rank (reference: js_run.py:32-93)."""
    import tempfile

    from . import safe_shell_exec
    from .hosts import get_host_assignments, parse_hosts
    from .launch import control_plane_env
    from .network import RendezvousServer

    hosts = parse_hosts(args.hosts)
    np = args.num_proc or sum(h.slots for h in hosts)
    slots = get_host_assignments(hosts, np)

    server = RendezvousServer()
    port = server.start()
    overrides = control_plane_env(args, hosts, port, layout=args.hosts)
    # Placement: ERF pinning only when the compute-node core count is
    # known (the env knob); otherwise resource-set flags, where jsrun
    # itself splits each host's CPUs — requires uniform slots per host.
    rankfile = None
    slot_counts = {h.slots for h in hosts}
    try:
        if os.environ.get(CPU_PER_SLOT_ENV):
            fd, rankfile = tempfile.mkstemp(suffix=".erf")
            os.close(fd)
            generate_jsrun_rankfile(slots, path=rankfile)
            cmd = build_jsrun_command(
                command, rankfile=rankfile, env_overrides=overrides,
                output_filename=getattr(args, "output_filename", None))
        elif len(slot_counts) == 1:
            cmd = build_jsrun_command(
                command, np=np, rs_per_host=slot_counts.pop(),
                env_overrides=overrides,
                output_filename=getattr(args, "output_filename", None))
        else:
            raise RuntimeError(
                "jsrun launch with non-uniform slots per host needs an "
                f"ERF rankfile: set {CPU_PER_SLOT_ENV} to the "
                "compute-node cores per slot.")
        if args.verbose:
            print(" ".join(cmd))
        return safe_shell_exec.execute(cmd, env=dict(os.environ))
    finally:
        server.stop()
        if rankfile:
            try:
                os.unlink(rankfile)
            except OSError:
                pass
