"""Coordinator-fault-tolerant control plane for the rendezvous KV.

Before this module the rendezvous KV (`network.RendezvousServer`) was a
single in-memory HTTP server whose death orphaned heartbeats, membership
watchers, autoscale and every elastic rejoin path (ROADMAP item 5; the
original Horovod elastic design punts on coordinator death entirely).
Three pieces close the gap:

- **Write-ahead log** (:class:`WalWriter` / :func:`replay`): every
  mutating KV verb (``put`` / ``claim`` / ``delete``) appends one
  epoch-stamped, CRC-framed record to an append-only log and is acked
  only after the group-commit fsync — a restarted or promoted server
  replays the log and loses nothing that was ever acked.  Claim records
  carry the *assigned* index, so replay never re-runs the counter and a
  retried claim stays idempotent by construction.

- **Lease-based leader election, epoch-fenced, stored in the log
  itself** (:class:`ControlPlane`): the primary renews a ``lease``
  record every third of ``HOROVOD_RENDEZVOUS_LEASE_MS``; standbys tail
  the primary's log over HTTP (``/.ctl/wal``) and promote when the
  lease lapses by appending a ``leader`` record with ``epoch + 1``.
  The log is the arbiter: after appending, the candidate re-reads it
  and the FIRST ``leader`` record at the new epoch wins — a duelling
  candidate demotes itself.  A primary whose lease lapsed (SIGSTOP, GC
  pause, partition) re-verifies the log tail before accepting another
  write: a higher-epoch ``leader`` record fences it out (it demotes and
  answers 409 with the winner's endpoint), so a resumed stale primary
  can never ack a write the replayed state would drop.

- **Client failover** lives in ``network.RendezvousClient``: a
  multi-endpoint seed list, transparent retry of idempotent verbs, and
  409-redirect handling converge every client on the current leader.

The election protocol is model-checked (``runner/specs.py``
rendezvous-failover + ``analysis/hvdmc/machines.py`` FailoverModel):
no two leaders in one epoch, no committed write lost by promotion,
clients converge — and the seeded ``accept-stale-lease`` mutation
(skip the re-verify) is caught with a two-leaders counterexample.

``python -m horovod_tpu.runner.controlplane`` runs one replica as its
own process (the shape the chaos ``coordkill:`` action kills).
"""
from __future__ import annotations

import os
import queue
import struct
import threading
import time
import zlib

from ..common import config
from ..common.logging import logger
from ..common.wire import Decoder, Encoder

__all__ = ["ControlPlane", "WalWriter", "apply_record", "fold_digest",
           "replay", "replay_state", "wal_path"]

_REC_HDR = struct.Struct(">I")       # payload length; trailer is crc32
_WAL_NAME = "rendezvous.wal"

# WAL record kinds (the rendezvous-failover spec's KV verb vocabulary).
KIND_PUT = "put"
KIND_CLAIM = "claim"
KIND_DELETE = "delete"
KIND_LEASE = "lease"
KIND_LEADER = "leader"

_FNV_OFFSET = 0xCBF29CE484222325
_FNV_PRIME = 0x100000001B3
_MASK64 = (1 << 64) - 1


def wal_path(wal_dir: str) -> str:
    return os.path.join(wal_dir, _WAL_NAME)


def _encode_record(epoch: int, kind: str, scope: str, key: str,
                   value: bytes) -> bytes:
    enc = Encoder()
    enc.uvarint(epoch).string(kind).string(scope).string(key).blob(value)
    payload = enc.getvalue()
    return (_REC_HDR.pack(len(payload)) + payload
            + _REC_HDR.pack(zlib.crc32(payload)))


def _decode_payload(payload: bytes) -> tuple:
    dec = Decoder(payload)
    return (dec.uvarint(), dec.string(), dec.string(), dec.string(),
            dec.blob())


def replay(path: str, offset: int = 0):
    """Yield ``(epoch, kind, scope, key, value)`` records from `path`
    starting at byte `offset`.  A torn tail (partial record or CRC
    mismatch — the writer died mid-append) ends the stream: everything
    before it was fsync'd and acked, everything after was never acked."""
    try:
        with open(path, "rb") as f:
            if offset:
                f.seek(offset)
            while True:
                hdr = f.read(_REC_HDR.size)
                if len(hdr) < _REC_HDR.size:
                    return
                (n,) = _REC_HDR.unpack(hdr)
                payload = f.read(n)
                trailer = f.read(_REC_HDR.size)
                if len(payload) < n or len(trailer) < _REC_HDR.size:
                    return
                if _REC_HDR.unpack(trailer)[0] != zlib.crc32(payload):
                    return
                yield _decode_payload(payload)
    except FileNotFoundError:
        return


def fold_digest(digest: int, kind: str, scope: str, key: str,
                value: bytes) -> int:
    """FNV-1a fold of one applied record into a rolling 64-bit digest
    (the WAL-replay digest the failover battery checks)."""
    for chunk in (kind.encode(), scope.encode(), key.encode(), value):
        for b in chunk:
            digest = ((digest ^ b) * _FNV_PRIME) & _MASK64
        digest = ((digest ^ 0x1F) * _FNV_PRIME) & _MASK64
    return digest


def apply_record(state: dict, kind: str, scope: str, key: str,
                 value: bytes) -> None:
    """Apply one data record to a KV state dict (``kv`` / ``counters``
    / ``claims`` / ``digest`` keys — the same shape the live server
    mutates, so replayed and live state share one code path)."""
    if kind == KIND_PUT:
        state["kv"].setdefault(scope, {})[key] = value
    elif kind == KIND_DELETE:
        if key:
            state["kv"].get(scope, {}).pop(key, None)
        else:
            state["kv"].pop(scope, None)
    elif kind == KIND_CLAIM:
        # value = b"claimant|index": replay applies the index assigned
        # at commit time instead of re-running the counter (claim order
        # in the log therefore never matters).
        claimant, _, idx = value.decode().rpartition("|")
        n = int(idx)
        ckey = f"{scope}/{key}"
        state["counters"][ckey] = max(state["counters"].get(ckey, 0),
                                      n + 1)
        if claimant:
            state["claims"].setdefault(ckey, {})[claimant] = n
    else:
        return
    state["digest"] = fold_digest(state.get("digest", _FNV_OFFSET),
                                  kind, scope, key, value)


def replay_state(path: str) -> dict:
    """Replay a whole log into ``{kv, counters, claims, digest, epoch,
    lease_expiry, leader_id}``.  Epoch fencing happens HERE: a
    ``leader`` record advances the current epoch, and any data record
    stamped with an older epoch that appears after it is dropped — the
    write a fenced-out stale primary appended was never committed."""
    state = {"kv": {}, "counters": {}, "claims": {},
             "digest": _FNV_OFFSET, "epoch": 0, "lease_expiry": 0.0,
             "leader_id": -1}
    for epoch, kind, scope, key, value in replay(path):
        if kind == KIND_LEADER:
            if epoch > state["epoch"]:
                state["epoch"] = epoch
                state["leader_id"] = int(key or -1)
                state["lease_expiry"] = _lease_expiry_of(value)
            continue
        if epoch < state["epoch"]:
            continue                       # fenced: stale-primary record
        if kind == KIND_LEASE:
            state["lease_expiry"] = max(state["lease_expiry"],
                                        _lease_expiry_of(value))
            continue
        apply_record(state, kind, scope, key, value)
    return state


def _lease_expiry_of(value: bytes) -> float:
    try:
        return float(value.decode().rpartition("|")[2])
    except ValueError:
        return 0.0


class WalWriter:
    """Append-only log writer with a group-commit fsync lane.

    Appends enqueue ``(record bytes, committed event)`` on an internal
    queue drained by ONE daemon thread (``hvd-rdzv-wal-<id>``) that
    writes every queued record and issues a single fsync per batch —
    callers wait on their record's event, so an ack always means
    on-disk.  Records are written with ``O_APPEND`` in one ``os.write``
    each, so concurrent writers (a duelling election across processes)
    can interleave records but never tear one.
    """

    def __init__(self, path: str, writer_id: int = 0) -> None:
        self.path = path
        self._fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_APPEND,
                           0o644)
        self._queue: queue.Queue = queue.Queue(maxsize=256)
        # Group-commit observability: records vs fsync batches is the
        # coalescing ratio the fleetsim fan-in test asserts on.
        from ..telemetry import metrics
        tm = metrics()
        self._m_batches = tm.counter(
            "horovod_rendezvous_wal_commit_batches_total",
            "WAL group-commit fsync batches flushed by this writer")
        self._m_records = tm.counter(
            "horovod_rendezvous_wal_records_total",
            "WAL records committed by this writer")
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"hvd-rdzv-wal-{writer_id}")
        self._thread.start()

    def append_async(self, epoch: int, kind: str, scope: str, key: str,
                     value: bytes) -> threading.Event:
        """Enqueue one record; the returned event is set once the
        record (and its batch) is fsync'd.  Enqueue order is commit
        order — callers serialize enqueues under the KV lock so the
        log order matches the in-memory apply order."""
        done = threading.Event()
        self._queue.put((_encode_record(epoch, kind, scope, key, value),
                         done))
        return done

    def append(self, epoch: int, kind: str, scope: str, key: str,
               value: bytes, timeout: float = 10.0) -> bool:
        """Append + wait for the fsync (bounded)."""
        return self.append_async(epoch, kind, scope, key,
                                 value).wait(timeout)

    def _run(self) -> None:
        while True:
            item = self._queue.get()
            if item is None:
                return
            batch = [item]
            while True:               # group commit: drain what's queued
                try:
                    nxt = self._queue.get_nowait()
                except queue.Empty:
                    break
                if nxt is None:
                    self._flush(batch)
                    return
                batch.append(nxt)
            self._flush(batch)

    def _flush(self, batch) -> None:
        for record, _done in batch:
            os.write(self._fd, record)
        os.fsync(self._fd)
        self._m_batches.inc()
        self._m_records.inc(len(batch))
        for _record, done in batch:
            done.set()

    def size(self) -> int:
        return os.fstat(self._fd).st_size

    def close(self) -> None:
        # Poison first, then join (the wedged-sender close contract):
        # the lane always reaches the sentinel because every append
        # before close() already has its bytes queued.
        self._queue.put(None)
        self._thread.join(timeout=10.0)
        if self._thread.is_alive():
            logger.warning("controlplane: WAL writer thread for %s "
                           "survived poison; leaking it as daemon",
                           self.path)
        try:
            os.close(self._fd)
        except OSError:
            pass


class Replicator:
    """Standby half: tail the primary's log over HTTP and mirror it.

    One thread (``hvd-rdzv-tail-<id>``) long-polls ``/.ctl/wal`` on the
    current primary and applies fetched records to the owning server's
    KV state; every fetched byte also refreshes the lease-observation
    stamp the monitor thread judges lapse by.  The tail is warm-standby
    state only — promotion re-reads the durable log, so a standby that
    lagged the tail still loses nothing committed.
    """

    def __init__(self, plane: "ControlPlane") -> None:
        self._plane = plane
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"hvd-rdzv-tail-{plane.replica_id}")
        self._thread.start()

    def _run(self) -> None:
        plane = self._plane
        poll = max(0.05, plane.lease_s / 4.0)
        while not self._stop.wait(poll):
            if plane.role != "standby":
                continue
            try:
                got = plane._tail_once()
            except Exception:  # noqa: BLE001 - primary may be dying
                continue
            if got:
                plane.note_leader_activity()

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=10.0)


class ControlPlane:
    """Role, epoch, lease and WAL state of one rendezvous replica.

    Attached to a ``network.RendezvousServer`` when
    ``HOROVOD_RENDEZVOUS_WAL_DIR`` (or the ``wal_dir=`` argument) is
    set.  The server's handler consults :meth:`check_write` before
    every mutating verb and :meth:`record` to commit it; reads are
    answered only by the primary too (409 + leader hint otherwise), so
    clients never observe a stale mirror.
    """

    def __init__(self, server, wal_dir: str, replica_id: int = 0,
                 endpoints=None, lease_ms: float | None = None,
                 standby: bool = False) -> None:
        self.server = server
        self.wal_dir = wal_dir
        self.replica_id = int(replica_id)
        # Ordered seed list ["host:port", ...]; index = replica id.
        self.endpoints = list(endpoints or [])
        lease_ms = config.RENDEZVOUS_LEASE_MS.get() \
            if lease_ms is None else float(lease_ms)
        self.lease_s = max(0.05, lease_ms / 1e3)
        self.role = "standby" if standby else "primary"
        self.epoch = 0
        self.failovers = 0
        self._lease_expiry = 0.0          # wall clock, primary only
        self._observed = time.monotonic()  # standby: last leader sign
        self._tail_offset = 0
        self._wal: WalWriter | None = None
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._replicator: Replicator | None = None
        self._lease_thread: threading.Thread | None = None
        os.makedirs(wal_dir, exist_ok=True)
        from ..telemetry import metrics
        tm = metrics()
        self._m_role = tm.gauge(
            "horovod_rendezvous_role",
            "1 while this replica is the rendezvous primary, 0 as "
            "standby", labels={"replica": str(self.replica_id)})
        self._m_failovers = tm.counter(
            "horovod_rendezvous_failovers_total",
            "Leader promotions this replica performed (lease lapse or "
            "primary death)")

    # -- lifecycle -------------------------------------------------------
    def start(self) -> None:
        state = replay_state(wal_path(self.wal_dir))
        if self.role == "primary":
            # Fresh primary: claim epoch 0 -> 1 (or succeed the log's
            # last leader) so every later record is fenced to our reign.
            self.epoch = state["epoch"] + 1
            self._append_leader()
            self._load(replay_state(wal_path(self.wal_dir)))
            self._renew_lease()
        else:
            self.epoch = state["epoch"]
            self._load(state)
            self.note_leader_activity()
            self._replicator = Replicator(self)
        self._m_role.set(1 if self.role == "primary" else 0)
        self._lease_thread = threading.Thread(
            target=self._lease_loop, daemon=True,
            name=f"hvd-rdzv-lease-{self.replica_id}")
        self._lease_thread.start()

    def close(self) -> None:
        self._stop.set()
        if self._lease_thread is not None:
            self._lease_thread.join(timeout=10.0)
            self._lease_thread = None
        if self._replicator is not None:
            self._replicator.close()
            self._replicator = None
        if self._wal is not None:
            self._wal.close()
            self._wal = None

    # -- state loading ---------------------------------------------------
    def _load(self, state: dict) -> None:
        httpd = self.server._httpd
        with httpd.kv_lock:
            httpd.kv = state["kv"]
            httpd.counters = state["counters"]
            httpd.claims = state["claims"]
            httpd.kv_digest = state["digest"]
            httpd.kv_cond.notify_all()

    # -- WAL plumbing ----------------------------------------------------
    def _writer(self) -> WalWriter:
        if self._wal is None:
            self._wal = WalWriter(wal_path(self.wal_dir),
                                  self.replica_id)
        return self._wal

    def record(self, kind: str, scope: str, key: str,
               value: bytes) -> threading.Event:
        """Commit one data record at the current epoch.  Called with
        the server's KV lock HELD (enqueue only — the fsync wait
        happens on the returned event after the lock is released), so
        log order equals in-memory apply order."""
        return self._writer().append_async(self.epoch, kind, scope,
                                           key, value)

    def _append_leader(self) -> None:
        expiry = time.time() + self.lease_s
        self._writer().append(
            self.epoch, KIND_LEADER, "", str(self.replica_id),
            f"{self.replica_id}|{expiry}".encode())
        self._lease_expiry = expiry

    def _renew_lease(self) -> None:
        expiry = time.time() + self.lease_s
        if self._writer().append(
                self.epoch, KIND_LEASE, "", str(self.replica_id),
                f"{self.replica_id}|{expiry}".encode()):
            self._lease_expiry = expiry

    # -- primary write fence ---------------------------------------------
    def check_write(self) -> tuple[bool, str]:
        """May this replica accept a mutating (or any) KV request RIGHT
        NOW?  Returns ``(ok, leader_hint)``.  The lease check is the
        split-brain fence: a primary that overslept its lease (SIGSTOP,
        the ``coordpause:`` chaos shape) must re-read the log before
        touching state — a higher-epoch ``leader`` record means a
        standby was promoted during the pause, and accepting the write
        would ack bytes the replayed state drops."""
        if self.role == "primary":
            if time.time() <= self._lease_expiry:
                return True, ""
            return self._reverify_lease()
        return False, self.leader_hint()

    def _reverify_lease(self) -> tuple[bool, str]:
        with self._lock:
            if self.role != "primary":
                return False, self.leader_hint()
            state = replay_state(wal_path(self.wal_dir))
            if state["epoch"] > self.epoch:
                self._demote(state)
                return False, self.leader_hint()
            # Lease lapsed but nobody contested YET: self-succeed under
            # a fresh epoch.  A standby candidate may race us through
            # the same bytes — re-read and let the log arbitrate (first
            # leader record at the epoch wins), exactly like a
            # promotion duel.
            candidate_epoch = state["epoch"] + 1
            self.epoch = candidate_epoch
            self._append_leader()
            winner = self._election_winner(candidate_epoch)
            if winner != self.replica_id:
                self._demote(replay_state(wal_path(self.wal_dir)))
                return False, self.leader_hint()
            return True, ""

    def _demote(self, state: dict) -> None:
        logger.warning(
            "controlplane: replica %d fenced out by leader epoch %d "
            "(held epoch %d); demoting to standby",
            self.replica_id, state["epoch"], self.epoch)
        self.epoch = state["epoch"]
        self._load(state)
        self.role = "standby"
        self._m_role.set(0)
        self.note_leader_activity()
        if self._replicator is None:
            self._replicator = Replicator(self)

    # -- standby: lease watch + promotion --------------------------------
    def note_leader_activity(self) -> None:
        self._observed = time.monotonic()

    def _lapse_after(self) -> float:
        """Silence a standby tolerates before attempting promotion.
        Staggered by replica id so the lowest standby wins elections
        unopposed on the common path (duels resolve through the log)."""
        return self.lease_s * (2.0 + max(0, self.replica_id - 1))

    def _lease_loop(self) -> None:
        interval = max(0.02, self.lease_s / 3.0)
        while not self._stop.wait(interval):
            if self.role == "primary":
                if time.time() > self._lease_expiry:
                    # The loop overslept its own lease (SIGSTOP, GC
                    # pause): re-verify the log BEFORE renewing so a
                    # promotion that happened during the gap demotes us
                    # proactively — not only when the next request
                    # trips the write fence.
                    self._reverify_lease()
                    continue
                self._renew_lease()
            else:
                silence = time.monotonic() - self._observed
                if silence > self._lapse_after():
                    self._try_promote()

    def _try_promote(self) -> None:
        with self._lock:
            if self.role != "standby":
                return
            state = replay_state(wal_path(self.wal_dir))
            now = time.time()
            if state["lease_expiry"] > now or \
                    state["epoch"] > self.epoch:
                # Someone renewed or a peer already won a newer epoch:
                # adopt what the log says and keep standing by.
                self.epoch = state["epoch"]
                self.note_leader_activity()
                return
            candidate_epoch = state["epoch"] + 1
            self._writer().append(
                candidate_epoch, KIND_LEADER, "",
                str(self.replica_id),
                f"{self.replica_id}|{now + self.lease_s}".encode())
            winner = self._election_winner(candidate_epoch)
            if winner != self.replica_id:
                logger.warning(
                    "controlplane: replica %d lost election for epoch "
                    "%d to replica %d", self.replica_id,
                    candidate_epoch, winner)
                self.epoch = candidate_epoch
                self.note_leader_activity()
                return
            self.epoch = candidate_epoch
            self._load(replay_state(wal_path(self.wal_dir)))
            self.role = "primary"
            self.failovers += 1
            self._m_role.set(1)
            self._m_failovers.inc()
            self._renew_lease()
            logger.warning(
                "controlplane: replica %d promoted to rendezvous "
                "primary (epoch %d)", self.replica_id, self.epoch)

    def _election_winner(self, epoch: int) -> int:
        """The log is the arbiter: the FIRST leader record at `epoch`
        wins; everyone else demotes.  Reads the durable file, not the
        tail mirror — candidates race through the same bytes."""
        for rec_epoch, kind, _scope, key, _value in replay(
                wal_path(self.wal_dir)):
            if kind == KIND_LEADER and rec_epoch == epoch:
                return int(key or -1)
        return -1

    # -- tail fetch (standby) --------------------------------------------
    def leader_hint(self) -> str:
        """Best-known leader endpoint for the 409 redirect header."""
        state = replay_state(wal_path(self.wal_dir))
        leader = state["leader_id"]
        if 0 <= leader < len(self.endpoints):
            return self.endpoints[leader]
        return ""

    def _tail_once(self) -> bool:
        """Fetch new log bytes from the current leader's ``/.ctl/wal``
        endpoint and apply them to the mirror.  Returns True when any
        byte arrived (leader liveness evidence)."""
        from urllib import request as urlrequest
        hint = self.leader_hint()
        if not hint:
            return False
        url = f"http://{hint}/.ctl/wal?from={self._tail_offset}"
        with urlrequest.urlopen(url, timeout=self.lease_s) as resp:
            raw = resp.read()
            end = int(resp.headers.get("X-Hvd-Wal-End",
                                       self._tail_offset))
        if not raw:
            return True                    # reachable, nothing new
        self._apply_tail(raw)
        self._tail_offset = end
        return True

    def _apply_tail(self, raw: bytes) -> None:
        httpd = self.server._httpd
        pos = 0
        with httpd.kv_lock:
            state = {"kv": httpd.kv, "counters": httpd.counters,
                     "claims": httpd.claims,
                     "digest": getattr(httpd, "kv_digest",
                                       _FNV_OFFSET)}
            while pos + _REC_HDR.size <= len(raw):
                (n,) = _REC_HDR.unpack_from(raw, pos)
                end = pos + _REC_HDR.size + n + _REC_HDR.size
                if end > len(raw):
                    break
                payload = raw[pos + _REC_HDR.size:pos + _REC_HDR.size
                              + n]
                epoch, kind, scope, key, value = \
                    _decode_payload(payload)
                if kind == KIND_LEADER and epoch > self.epoch:
                    self.epoch = epoch
                elif kind not in (KIND_LEASE, KIND_LEADER) and \
                        epoch >= self.epoch:
                    apply_record(state, kind, scope, key, value)
                pos = end
            httpd.kv_digest = state["digest"]
            httpd.kv_cond.notify_all()

    # -- introspection (/.ctl handlers) ----------------------------------
    def describe(self) -> str:
        return f"{self.role}|{self.epoch}|{self.leader_hint()}"

    def wal_bytes_from(self, offset: int) -> tuple[bytes, int]:
        path = wal_path(self.wal_dir)
        try:
            with open(path, "rb") as f:
                f.seek(offset)
                raw = f.read()
                return raw, offset + len(raw)
        except FileNotFoundError:
            return b"", offset


def start_replica_set(n_standbys: int, wal_dir: str,
                      lease_ms: float | None = None,
                      host: str = "127.0.0.1"):
    """Convenience used by launchers and tests: one primary plus
    ``n_standbys`` standby replicas in this process, sharing `wal_dir`.
    Returns ``(servers, endpoints)`` — index 0 is the primary; the
    seed list goes into ``HOROVOD_GLOO_RENDEZVOUS_ADDR`` verbatim."""
    from .network import RendezvousServer, free_port

    ports = [free_port() for _ in range(n_standbys + 1)]
    endpoints = [f"{host}:{p}" for p in ports]
    servers = []
    for i, port in enumerate(ports):
        srv = RendezvousServer(port=port, wal_dir=wal_dir, replica_id=i,
                               endpoints=endpoints, lease_ms=lease_ms,
                               standby=(i > 0))
        srv.start()
        servers.append(srv)
    return servers, endpoints


def _main(argv=None) -> int:
    """Run ONE replica as its own process until SIGTERM — the unit the
    chaos ``coordkill:``/``coordpause:`` actions target."""
    import argparse
    import signal
    import sys

    from .network import RendezvousServer

    parser = argparse.ArgumentParser(
        prog="python -m horovod_tpu.runner.controlplane")
    parser.add_argument("--port", type=int, required=True)
    parser.add_argument("--wal-dir", required=True)
    parser.add_argument("--replica-id", type=int, default=0)
    parser.add_argument("--endpoints", default="",
                        help="comma-separated host:port seed list")
    parser.add_argument("--lease-ms", type=float, default=None)
    parser.add_argument("--standby", action="store_true")
    args = parser.parse_args(argv)
    endpoints = [e for e in args.endpoints.split(",") if e]
    server = RendezvousServer(
        port=args.port, wal_dir=args.wal_dir,
        replica_id=args.replica_id, endpoints=endpoints,
        lease_ms=args.lease_ms, standby=args.standby)
    server.start()
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_a: stop.set())
    print(f"READY {server.port} {os.getpid()}", flush=True)
    try:
        while not stop.wait(0.5):
            pass
    except KeyboardInterrupt:
        pass
    server.stop()
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(_main())
