"""Host parsing and rank assignment.

Reference: horovod/runner/common/util/hosts.py — `parse_hosts` turns
"h1:2,h2:4" into host/slot records and `get_host_assignments` hands out
ranks round-robin host-major, producing for every slot its global rank,
local rank (within host) and cross rank (host index among hosts that hold
that local rank).
"""
from __future__ import annotations

import dataclasses


@dataclasses.dataclass
class HostInfo:
    hostname: str
    slots: int

    @staticmethod
    def from_string(host_string: str) -> "HostInfo":
        if ":" in host_string:
            name, slots = host_string.rsplit(":", 1)
            return HostInfo(name.strip(), int(slots))
        return HostInfo(host_string.strip(), 1)


@dataclasses.dataclass
class SlotInfo:
    hostname: str
    rank: int
    local_rank: int
    cross_rank: int
    size: int
    local_size: int
    cross_size: int

    def to_env(self) -> dict[str, str]:
        """Env block consumed at init (reference: gloo_run.py:187-198)."""
        return {
            "HOROVOD_HOSTNAME": self.hostname,
            "HOROVOD_RANK": str(self.rank),
            "HOROVOD_SIZE": str(self.size),
            "HOROVOD_LOCAL_RANK": str(self.local_rank),
            "HOROVOD_LOCAL_SIZE": str(self.local_size),
            "HOROVOD_CROSS_RANK": str(self.cross_rank),
            "HOROVOD_CROSS_SIZE": str(self.cross_size),
        }


def parse_hosts(hosts_string: str) -> list[HostInfo]:
    """Parse "host1:2,host2:4" (reference: hosts.py parse_hosts)."""
    return [HostInfo.from_string(x) for x in hosts_string.split(",") if x]


def parse_host_files(filename: str) -> str:
    """Read a hostfile with "hostname slots=N" per line into the
    "h1:n1,h2:n2" form (reference: launch.py parse_host_files)."""
    hosts = []
    with open(filename) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            parts = line.split()
            name = parts[0]
            slots = 1
            for p in parts[1:]:
                if p.startswith("slots="):
                    slots = int(p[len("slots="):])
            hosts.append(f"{name}:{slots}")
    return ",".join(hosts)


def get_host_assignments(hosts: list[HostInfo], min_np: int,
                         max_np: int | None = None) -> list[SlotInfo]:
    """Assign ranks host-major (reference: hosts.py:155
    get_host_assignments): fill each host's slots in order, stop at
    max_np; error if fewer than min_np slots exist."""
    max_np = max_np or min_np
    slots: list[tuple[str, int]] = []          # (hostname, local_rank)
    for h in hosts:
        for lr in range(h.slots):
            if len(slots) >= max_np:
                break
            slots.append((h.hostname, lr))
    if len(slots) < min_np:
        raise ValueError(
            f"requested {min_np} processes but only {len(slots)} slots "
            f"available on {','.join(h.hostname for h in hosts)}")

    size = len(slots)
    local_sizes: dict[str, int] = {}
    for hostname, _ in slots:
        local_sizes[hostname] = local_sizes.get(hostname, 0) + 1
    # cross world for local_rank L = hosts that have a slot with that L;
    # cross_rank = this host's position within that per-L host list.
    hosts_with_lr: dict[int, list[str]] = {}
    for hostname, lr in slots:
        hosts_with_lr.setdefault(lr, []).append(hostname)

    assignments = []
    for rank, (hostname, lr) in enumerate(slots):
        peers = hosts_with_lr[lr]
        assignments.append(SlotInfo(
            hostname=hostname, rank=rank, local_rank=lr,
            cross_rank=peers.index(hostname), size=size,
            local_size=local_sizes[hostname], cross_size=len(peers)))
    return assignments


def host_ids_env(assignments: list[SlotInfo]) -> str:
    """World-wide rank→host-index map ("0,0,1,1") for the slot layout.

    Per-slot env (``SlotInfo.to_env``) tells each rank only its OWN host;
    topology-aware collectives need the whole map to group ring orders by
    host when the layout is not the homogeneous host-major shape that
    local_size/cross_size auto-detection covers (elastic re-assignments,
    uneven slots-per-host).  The string is identical for every rank —
    launcher-uniform, so algo/ring-order decisions derived from it stay
    rank-symmetric.
    """
    by_rank = sorted(assignments, key=lambda s: s.rank)
    order: dict[str, int] = {}
    for slot in by_rank:
        order.setdefault(slot.hostname, len(order))
    return ",".join(str(order[s.hostname]) for s in by_rank)


def is_local_host(hostname: str) -> bool:
    """True for localhost and any 127/8 loopback alias.  Loopback aliases
    count as local everywhere (launcher AND programmatic run) so the
    multi-host-without-a-cluster trick (SURVEY §4: distinct loopback IPs
    act as distinct "hosts" with their own host hashes) behaves the same
    from every entry point."""
    import re
    return hostname in ("localhost", "127.0.0.1") or \
        bool(re.fullmatch(r"127(\.\d{1,3}){3}", hostname))


def ssh_argv(hostname: str, script: str) -> list[str]:
    """The shared remote-exec command shape (one place to keep ssh options
    in sync across the launcher and hvd.run)."""
    import shlex
    return ["ssh", "-o", "StrictHostKeyChecking=no", hostname,
            f"/bin/sh -c {shlex.quote(script)}"]
