"""Programmatic launch: ``horovod_tpu.run(func, np=N, ...)``.

Reference: horovod/runner/__init__.py:92-210 — run a Python function on N
worker processes (instead of shelling out to a training script) and return
the per-rank results.  Workers are forked locally (or ssh'd for remote
hosts via the same slot plumbing as the CLI), the function and its results
travel as pickles.
"""
from __future__ import annotations

import multiprocessing as mp
import os
import sys
import traceback
from typing import Any, Callable, Sequence

from .hosts import get_host_assignments, parse_hosts
from .network import RendezvousServer


def _worker_main(fn_payload, slot_env: dict, conn) -> None:
    try:
        import pickle
        os.environ.update(slot_env)
        func, args, kwargs = pickle.loads(fn_payload)
        result = func(*args, **kwargs)
        conn.send((True, result))
    except BaseException:  # noqa: BLE001 - ship traceback to the parent
        conn.send((False, traceback.format_exc()))
    finally:
        conn.close()


def run(func: Callable, args: Sequence = (), kwargs: dict | None = None,
        np: int | None = None, hosts: str | None = None,
        env: dict | None = None, use_gloo: bool = True,
        start_timeout: float = 120.0) -> list[Any]:
    """Run ``func(*args, **kwargs)`` on ``np`` local worker processes with
    the full eager runtime initialized (rendezvous, controller, data
    plane); returns results ordered by rank.

    The reference's remote-host path (ssh per slot) applies only to its CLI
    here; programmatic multi-host launches should use the CLI or the
    elastic driver.
    """
    import pickle

    kwargs = kwargs or {}
    host_list = parse_hosts(hosts) if hosts else None
    world = np or (sum(h.slots for h in host_list) if host_list else 1)
    if host_list is None:
        host_list = parse_hosts(f"localhost:{world}")
    slots = get_host_assignments(host_list, world)
    if any(s.hostname not in ("localhost", "127.0.0.1") for s in slots):
        raise NotImplementedError(
            "horovod_tpu.run() launches local workers; use the "
            "horovodrun-tpu CLI for multi-host jobs")

    server = RendezvousServer()
    port = server.start()
    payload = pickle.dumps((func, tuple(args), dict(kwargs)))

    ctx = mp.get_context("spawn")
    procs, conns = [], []
    try:
        for slot in slots:
            parent, child = ctx.Pipe()
            slot_env = dict(env or {})
            slot_env.update(slot.to_env())
            slot_env.update({
                "HOROVOD_GLOO_RENDEZVOUS_ADDR": "127.0.0.1",
                "HOROVOD_GLOO_RENDEZVOUS_PORT": str(port),
                "HOROVOD_CONTROLLER": "tcp",
                "HOROVOD_GLOO_TIMEOUT_SECONDS": str(start_timeout),
            })
            p = ctx.Process(target=_worker_main,
                            args=(payload, slot_env, child), daemon=True)
            p.start()
            child.close()
            procs.append(p)
            conns.append(parent)

        results: list[Any] = [None] * len(slots)
        errors: list[str] = []
        for rank, (p, conn) in enumerate(zip(procs, conns)):
            if conn.poll(start_timeout + 600):
                ok, value = conn.recv()
                if ok:
                    results[rank] = value
                else:
                    errors.append(f"rank {rank}:\n{value}")
            else:
                errors.append(f"rank {rank}: no result (timeout)")
        for p in procs:
            p.join(timeout=30)
        if errors:
            raise RuntimeError("horovod_tpu.run() worker failures:\n"
                               + "\n".join(errors))
        return results
    finally:
        for p in procs:
            if p.is_alive():
                p.terminate()
        server.stop()
