"""Programmatic launch: ``horovod_tpu.run(func, np=N, ...)``.

Reference: horovod/runner/__init__.py:92-210 — run a Python function on N
worker processes (instead of shelling out to a training script) and return
the per-rank results.  Local slots fork worker processes; remote slots run
the same pickled function over ssh through the
:mod:`horovod_tpu.runner.run_worker` bootstrap, with results returning via
the rendezvous KV store (the reference's run_func KV server,
runner/launch.py:528-618).
"""
from __future__ import annotations

import multiprocessing as mp
import os
import shlex
import socket
import subprocess
import sys
import threading
import traceback
from typing import Any, Callable, Sequence

from .hosts import (get_host_assignments, host_ids_env, is_local_host,
                    parse_hosts,
                    ssh_argv)
from .launch import rendezvous_env
from .network import RendezvousClient, RendezvousServer

# Module alias so tests can substitute a local shell for the ssh binary.
_ssh_argv = ssh_argv


def _run_elastic(func: Callable, args: Sequence, kwargs: dict, *,
                 np: int | None, hosts: str | None, env: dict | None,
                 min_np: int | None,
                 max_np: int | None, host_discovery_script: str | None,
                 reset_limit: int | None, elastic_timeout: float,
                 start_timeout: float, slots: int | None
                 ) -> dict[int, Any]:
    """Programmatic elastic launch: the pickled fn is seeded into the
    rendezvous KV; elastic_run_worker bootstraps fetch + execute it under
    the driver (reference: runner/__init__.py elastic branch)."""
    import pickle

    from ..elastic.launcher import launch_elastic
    from .launch import parse_args

    # Full CLI-default namespace (args_to_env reads every tuning attr),
    # then overlay the programmatic params.
    launch_args = parse_args(["placeholder-command"])
    for attr, value in (("num_proc", np), ("hosts", hosts),
                        ("min_np", min_np), ("max_np", max_np),
                        ("host_discovery_script", host_discovery_script),
                        ("reset_limit", reset_limit),
                        ("elastic_timeout", elastic_timeout),
                        ("start_timeout", start_timeout),
                        ("slots", slots)):
        setattr(launch_args, attr, value)
    command = [sys.executable, "-m",
               "horovod_tpu.runner.elastic_run_worker"]
    payload = pickle.dumps((func, tuple(args), dict(kwargs)))
    rc, outcomes, world = launch_elastic(
        launch_args, command, payload=payload, collect_results=True,
        extra_env=env)
    failures = {rank: value for rank, (ok, value) in outcomes.items()
                if not ok}
    if failures:
        raise RuntimeError(
            "elastic run(func) worker failures:\n" + "\n".join(
                f"[rank {r}] {tb}" for r, tb in sorted(failures.items())))
    if rc != 0:
        raise RuntimeError(f"elastic run(func) failed with rc={rc}")
    missing = sorted(set(range(world)) - set(outcomes))
    if missing:
        # A worker that died without publishing (e.g. SIGKILL) must not
        # silently vanish from the result dict.
        raise RuntimeError(
            f"elastic run(func): ranks {missing} of the final "
            f"{world}-rank world returned no result (worker died before "
            "publishing?)")
    return {rank: value for rank, (ok, value) in outcomes.items()}


def _worker_main(fn_payload, slot_env: dict, conn) -> None:
    try:
        import pickle
        os.environ.update(slot_env)
        func, args, kwargs = pickle.loads(fn_payload)
        result = func(*args, **kwargs)
        conn.send((True, result))
    except BaseException:  # noqa: BLE001 - ship traceback to the parent
        conn.send((False, traceback.format_exc()))
    finally:
        conn.close()


def _launch_remote(slot_env: dict, hostname: str, payload: bytes,
                   procs: dict, rank: int) -> int:
    """Run the bootstrap on a remote host: env rides the command line,
    the pickled function rides stdin.  The Popen registers in ``procs``
    so the caller can kill it on error paths."""
    exports = " ".join(f"{k}={shlex.quote(str(v))}"
                       for k, v in slot_env.items())
    script = (f"env {exports} {shlex.quote(sys.executable)} "
              f"-m horovod_tpu.runner.run_worker")
    proc = subprocess.Popen(_ssh_argv(hostname, script),
                            stdin=subprocess.PIPE,
                            stdout=sys.stdout.fileno(),
                            stderr=sys.stderr.fileno())
    procs[rank] = proc
    proc.communicate(payload)
    return proc.returncode


def run(func: Callable, args: Sequence = (), kwargs: dict | None = None,
        np: int | None = None, hosts: str | None = None,
        env: dict | None = None, use_gloo: bool = True,
        start_timeout: float = 120.0,
        min_np: int | None = None, max_np: int | None = None,
        host_discovery_script: str | None = None,
        reset_limit: int | None = None,
        elastic_timeout: float | None = None,
        slots: int | None = None) -> list[Any] | dict[int, Any]:
    """Run ``func(*args, **kwargs)`` on every slot of ``hosts`` (default:
    ``np`` local processes) with the full eager runtime initialized
    (rendezvous, controller, data plane); returns results ordered by rank.
    Remote hosts need this package importable and ssh reachability, the
    same contract as the reference's ``horovod.run``.

    Elastic mode (reference: runner/__init__.py:92-210): pass ``min_np``/
    ``max_np``/``host_discovery_script`` to run under the elastic driver —
    workers are respawned across membership changes and ``func`` decides
    its own fault-tolerance via ``hvd.elastic.run``. Returns
    {final_rank: result} (the world can end a different size than it
    started)."""
    import pickle

    kwargs = kwargs or {}
    if min_np is not None or max_np is not None \
            or host_discovery_script is not None:
        return _run_elastic(func, args, kwargs, np=np, hosts=hosts,
                            env=env, min_np=min_np, max_np=max_np,
                            host_discovery_script=host_discovery_script,
                            reset_limit=reset_limit,
                            elastic_timeout=(600.0 if elastic_timeout
                                             is None else elastic_timeout),
                            start_timeout=start_timeout, slots=slots)
    stray = {name: value for name, value in
             (("reset_limit", reset_limit),
              ("elastic_timeout", elastic_timeout),
              ("slots", slots)) if value is not None}
    if stray:
        raise ValueError(
            f"{sorted(stray)} only apply to elastic mode — also pass "
            "min_np/max_np or host_discovery_script, or drop them.")
    host_list = parse_hosts(hosts) if hosts else None
    world = np or (sum(h.slots for h in host_list) if host_list else 1)
    if host_list is None:
        host_list = parse_hosts(f"localhost:{world}")
    slot_infos = get_host_assignments(host_list, world)
    any_remote = any(not is_local_host(s.hostname) for s in slot_infos)

    server = RendezvousServer()
    port = server.start()
    # Remote workers must reach the rendezvous/KV server over the network;
    # local-only runs stay on loopback.
    addr = socket.gethostbyname(socket.gethostname()) if any_remote \
        else "127.0.0.1"
    payload = pickle.dumps((func, tuple(args), dict(kwargs)))

    ctx = mp.get_context("spawn")
    procs, conns = [], []          # local slots
    remote_threads, remote_rcs = [], {}
    remote_procs: dict[int, subprocess.Popen] = {}
    remote_ranks: list[int] = []
    try:
        host_ids = host_ids_env(slot_infos)
        for slot in slot_infos:
            slot_env = dict(env or {})
            slot_env.update(slot.to_env())
            slot_env["HOROVOD_HOST_IDS"] = host_ids
            slot_env.update(rendezvous_env(addr, port, start_timeout))
            if is_local_host(slot.hostname):
                parent, child = ctx.Pipe()
                p = ctx.Process(target=_worker_main,
                                args=(payload, slot_env, child),
                                daemon=True)
                p.start()
                child.close()
                procs.append((slot.rank, p))
                conns.append((slot.rank, parent))
            else:
                remote_ranks.append(slot.rank)

                def _remote(slot_env=slot_env, hostname=slot.hostname,
                            rank=slot.rank):
                    try:
                        remote_rcs[rank] = _launch_remote(
                            slot_env, hostname, payload, remote_procs,
                            rank)
                    except Exception:  # noqa: BLE001
                        remote_rcs[rank] = -1
                        traceback.print_exc()

                t = threading.Thread(target=_remote, daemon=True,
                                     name="hvd-remote-launch")
                t.start()
                remote_threads.append(t)

        results: list[Any] = [None] * len(slot_infos)
        errors: list[str] = []
        for rank, conn in conns:
            if conn.poll(start_timeout + 600):
                ok, value = conn.recv()
                if ok:
                    results[rank] = value
                else:
                    errors.append(f"rank {rank}:\n{value}")
            else:
                errors.append(f"rank {rank}: no result (timeout)")
        kv = RendezvousClient("127.0.0.1", port, timeout=30.0) \
            if remote_ranks else None
        for rank in remote_ranks:
            # Poll the KV for the result, but fail FAST when the remote
            # launch already died without posting one (ssh exit 255, bad
            # python, import failure before the bootstrap's try block).
            import time as _time
            deadline = _time.time() + start_timeout + 600
            blob = None
            while _time.time() < deadline:
                blob = kv.get("runfunc", str(rank))
                if blob is not None:
                    break
                rc = remote_rcs.get(rank)
                if rc is not None and rc != 0:
                    errors.append(f"rank {rank} (remote): launch exited "
                                  f"rc={rc} with no result")
                    break
                _time.sleep(0.25)
            else:
                errors.append(f"rank {rank} (remote): no result (timeout)")
            if blob is not None:
                ok, value = pickle.loads(blob)
                if ok:
                    results[rank] = value
                else:
                    errors.append(f"rank {rank} (remote):\n{value}")
        for _, p in procs:
            p.join(timeout=30)
        for t in remote_threads:
            t.join(timeout=30)
        if errors:
            raise RuntimeError("horovod_tpu.run() worker failures:\n"
                               + "\n".join(errors))
        return results
    finally:
        for _, p in procs:
            if p.is_alive():
                p.terminate()
        for proc in remote_procs.values():
            if proc.poll() is None:
                proc.terminate()
        server.stop()
