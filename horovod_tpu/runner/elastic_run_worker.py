"""Worker bootstrap for programmatic *elastic* :func:`horovod_tpu.run`.

Reference: horovod/runner/__init__.py:92-210 — `horovod.run(func,
min_np=..., max_np=...)` launches the elastic driver over a pickled
function.  Unlike the static bootstrap (run_worker.py, payload over
stdin), elastic workers are (re)spawned by the driver on membership
changes — possibly on hosts that did not exist at submit time — so the
payload is fetched from the rendezvous KV store every worker can already
reach via the exported env.

The function runs once per worker lifetime; on success its result is
published under the worker's FINAL rank (elastic rounds may have
re-ranked it).  The function itself decides how to use
``hvd.elastic.run`` / State for mid-run fault tolerance, exactly as with
the CLI launcher.
"""
from __future__ import annotations

import os
import pickle
import sys
import traceback

PAYLOAD_SCOPE = "elastic_runfunc"
RESULT_SCOPE = "elastic_runfunc_result"


def main() -> int:
    from ..elastic.run import _apply_assignment
    from ..elastic.worker import notification_manager
    from .network import RendezvousClient

    kv = RendezvousClient(
        os.environ["HOROVOD_GLOO_RENDEZVOUS_ADDR"],
        int(os.environ["HOROVOD_GLOO_RENDEZVOUS_PORT"]))
    assigned = False
    try:
        # Pull this worker's rank assignment from the elastic driver (the
        # role hvd.elastic.run's _rendezvous plays for CLI workers): the
        # launcher hands out only hostname+local_rank; global rank/size
        # come from the driver's round formation. The launcher strips any
        # inherited epoch/rank env, so round formation starts at 0.
        notification_manager.init()
        if notification_manager.has_driver:
            assignment = notification_manager.get_assignment(0)
            if assignment is None:
                return 0   # dropped from the new world; exit quietly
            _apply_assignment(assignment)
            assigned = True
        payload = kv.wait(PAYLOAD_SCOPE, "blob", timeout=60.0)
        func, args, kwargs = pickle.loads(payload)
        result = func(*args, **kwargs)
        outcome, rc = (True, result), 0
    except BaseException:  # noqa: BLE001 — ship the traceback to the parent
        outcome, rc = (False, traceback.format_exc()), 1
    # HOROVOD_RANK/RENDEZVOUS_EPOCH reflect the latest elastic assignment
    # (elastic/run.py _apply_assignment re-exports them each round). A
    # worker that failed BEFORE receiving any assignment must not publish
    # — a fallback key would clobber/misattribute the real rank 0's
    # outcome; its nonzero exit reaches the driver's results instead.
    # The key carries the epoch so a result published by an EARLIER
    # round's incarnation of rank r (killed before the final round) can
    # never masquerade as the final round's rank-r outcome.
    if assigned or "HOROVOD_RANK" in os.environ:
        epoch = os.environ.get("HOROVOD_RENDEZVOUS_EPOCH", "0")
        # The payload carries the publishing SLOT so the launcher can
        # accept an earlier-epoch result only when it provably belongs to
        # the final round's incarnation of the rank (a success can race
        # the final round forming, landing one epoch behind).
        slot = (f"{os.environ.get('HOROVOD_HOSTNAME', '')}"
                f"[{os.environ.get('HOROVOD_LOCAL_RANK', '')}]")
        kv.put(RESULT_SCOPE,
               f"{epoch}:{os.environ['HOROVOD_RANK']}",
               pickle.dumps((outcome, slot)))
    return rc


if __name__ == "__main__":
    sys.exit(main())
