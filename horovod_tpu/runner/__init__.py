"""Launcher, rendezvous server, and cluster plumbing (horovodrun analogue)."""
