"""Child-process execution with signal forwarding and output prefixing.

Reference: horovod/runner/common/util/safe_shell_exec.py — run a worker
command, stream its stdout/stderr line-by-line through a prefixing filter
(`[1]<stdout>: ...`), forward SIGINT/SIGTERM to the whole process group,
and make sure orphans die with the launcher.
"""
from __future__ import annotations

import os
import signal
import subprocess
import sys
import threading

GRACEFUL_TERMINATION_TIME_S = 5


def _tail(stream, prefix: str, sink, buffer: list[str] | None) -> None:
    for raw in iter(stream.readline, b""):
        line = raw.decode(errors="replace")
        if buffer is not None:
            buffer.append(line)
        if prefix:
            sink.write(f"{prefix}{line}")
        else:
            sink.write(line)
        sink.flush()
    stream.close()


def execute(command, env: dict | None = None, index: int | None = None,
            stdout=None, stderr=None, prefix_output: bool = True,
            capture: list[str] | None = None,
            events: list[threading.Event] | None = None,
            stdin_data: bytes | None = None) -> int:
    """Run `command` (list or shell string); returns its exit code.

    `events`: optional termination events — a watcher thread kills the
    child when any is set (used by the elastic driver to stop slots whose
    host was blacklisted).
    `stdin_data`: bytes written to the child's stdin then closed — used to
    hand secrets to remote shells without exposing them in argv."""
    stdout = stdout or sys.stdout
    stderr = stderr or sys.stderr
    shell = isinstance(command, str)
    proc = subprocess.Popen(
        command, shell=shell, env=env,
        stdin=subprocess.PIPE if stdin_data is not None else None,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        start_new_session=True)
    if stdin_data is not None:
        try:
            proc.stdin.write(stdin_data)
            proc.stdin.close()
        except (BrokenPipeError, OSError):
            pass

    out_prefix = f"[{index}]<stdout>: " if prefix_output and index is not None \
        else ""
    err_prefix = f"[{index}]<stderr>: " if prefix_output and index is not None \
        else ""
    threads = [
        threading.Thread(target=_tail, name="hvd-tail",
                         args=(proc.stdout, out_prefix, stdout, capture),
                         daemon=True),
        threading.Thread(target=_tail, name="hvd-tail",
                         args=(proc.stderr, err_prefix, stderr, capture),
                         daemon=True),
    ]
    for t in threads:
        t.start()

    def _kill_group(sig):
        try:
            os.killpg(os.getpgid(proc.pid), sig)
        except (ProcessLookupError, PermissionError):
            pass

    stop_watch = threading.Event()
    if events:
        def _watch():
            while not stop_watch.is_set():
                if any(e.is_set() for e in events):
                    _kill_group(signal.SIGTERM)
                    if proc.poll() is None:
                        stop_watch.wait(GRACEFUL_TERMINATION_TIME_S)
                        _kill_group(signal.SIGKILL)
                    return
                stop_watch.wait(0.1)
        threading.Thread(target=_watch, daemon=True,
                         name="hvd-exec-watch").start()

    prev_handlers = {}
    if threading.current_thread() is threading.main_thread():
        def _forward(sig, _frame):
            _kill_group(sig)
        for sig in (signal.SIGINT, signal.SIGTERM):
            prev_handlers[sig] = signal.signal(sig, _forward)
    try:
        proc.wait()
    finally:
        stop_watch.set()
        for sig, h in prev_handlers.items():
            signal.signal(sig, h)
        for t in threads:
            t.join(timeout=1)
    return proc.returncode
