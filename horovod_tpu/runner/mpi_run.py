"""mpirun command construction for MPI-controller clusters.

Reference: horovod/runner/mpi_run.py — detect the installed MPI flavor and
build one big ``mpirun`` invocation carrying host slots, process binding,
and the HOROVOD_* environment.  On TPU pods the data plane is XLA over
ICI, but MPI remains a valid *process launcher + control plane* on clusters
where ssh is not available and mpirun is; the built command execs one
worker per slot with the same env contract as the TCP launcher.
"""
from __future__ import annotations

import os
import shutil
import subprocess

_OMPI_FLAGS = ["-mca", "pml", "ob1", "-mca", "btl", "^openib"]
_SMPI_FLAGS = ["-tcp"]
_MPICH_FLAGS: list[str] = []
_INTEL_FLAGS: list[str] = []
_NO_BINDING_ARGS = ["-bind-to", "none", "-map-by", "slot"]


def mpi_available(env: dict | None = None) -> bool:
    return _mpirun_path(env) is not None


def _mpirun_path(env: dict | None = None) -> str | None:
    path = (env or os.environ).get("PATH")
    return shutil.which("mpirun", path=path)


def flavor(env: dict | None = None,
           version_text: str | None = None) -> str:
    """Detect openmpi / spectrum / mpich / intel / unknown
    (reference: mpi_run.py:24-120)."""
    if version_text is None:
        mpirun = _mpirun_path(env)
        if mpirun is None:
            return "none"
        try:
            version_text = subprocess.run(
                [mpirun, "--version"], capture_output=True, timeout=10,
                text=True).stdout
        except (subprocess.SubprocessError, OSError):
            return "unknown"
    text = version_text.lower()
    if "open mpi" in text or "openrte" in text:
        return "openmpi"
    if "ibm spectrum mpi" in text:
        return "spectrum"
    if "mpich" in text or "hydra" in text:
        return "mpich"
    if "intel(r) mpi" in text:
        return "intel"
    return "unknown"


def build_mpi_command(command: list[str], *, np: int,
                      hosts: str | None = None,
                      env: dict | None = None,
                      mpi_flavor: str | None = None,
                      ssh_port: int | None = None,
                      extra_mpi_args: str | None = None) -> list[str]:
    """Build the mpirun argv (reference: mpi_run.py:210-254)."""
    env = dict(env if env is not None else os.environ)
    mpi_flavor = mpi_flavor or flavor(env)
    impl_flags = {
        "openmpi": _OMPI_FLAGS,
        "spectrum": _SMPI_FLAGS,
        "mpich": _MPICH_FLAGS,
        "intel": _INTEL_FLAGS,
    }.get(mpi_flavor, _OMPI_FLAGS)

    # 'unknown' (version probe failed/unparseable) keeps the OpenMPI
    # treatment throughout — matching the impl_flags fallback above.
    ompi_style = mpi_flavor not in ("mpich", "intel")
    cmd = ["mpirun"]
    if ompi_style:
        # OpenMPI-only flag: mpich/intel Hydra mpirun rejects it and
        # would fail at launch (advisor finding).
        cmd.append("--allow-run-as-root")
    cmd += ["-np", str(np)]
    if hosts:
        # OpenMPI takes -H host:slots; Hydra (mpich/intel) spells the
        # same list -hosts and rejects -H outright.
        cmd += ["-H" if ompi_style else "-hosts", hosts]
    if ompi_style:
        cmd += _NO_BINDING_ARGS
        cmd += impl_flags
        if ssh_port:
            cmd += ["-mca", "plm_rsh_args", f"-p {ssh_port}"]
        for name in sorted(env):
            if name.startswith("HOROVOD_") or name in ("PATH", "PYTHONPATH",
                                                       "LD_LIBRARY_PATH"):
                cmd += ["-x", name]
    else:
        cmd += impl_flags
        exported = [n for n in sorted(env)
                    if n.startswith("HOROVOD_")
                    or n in ("PATH", "PYTHONPATH", "LD_LIBRARY_PATH")]
        if exported:
            cmd += ["-genvlist", ",".join(exported)]
    if extra_mpi_args:
        cmd += extra_mpi_args.split()
    return cmd + list(command)
