"""`horovodrun`-equivalent CLI launcher.

Reference: horovod/runner/launch.py — parse flags, map the perf/debug
knobs onto `HOROVOD_*` env vars (reference: common/util/config_parser.py),
compute rank assignments from the host list, start the rendezvous KV
server, and exec one worker per slot with its env block (reference:
runner/gloo_run.py:133-272). Remote hosts go through ssh; localhost slots
exec directly. `--min-np/--max-np/--host-discovery-script` switches to the
elastic driver.

Usage:
    python -m horovod_tpu.runner.launch -np 4 python train.py
    horovodrun-tpu -np 8 -H host1:4,host2:4 python train.py
"""
from __future__ import annotations

import argparse
import os
import shlex
import sys
import threading

from ..common.logging import logger
from . import safe_shell_exec
from .hosts import (get_host_assignments, host_ids_env, parse_host_files,
                    parse_hosts, SlotInfo)
from .network import RendezvousServer, free_port

LOCAL_HOSTS = ("localhost", "127.0.0.1", "0.0.0.0")


def _is_local(hostname: str) -> bool:
    from .hosts import is_local_host
    return hostname in LOCAL_HOSTS or is_local_host(hostname)


def parse_args(argv=None) -> argparse.Namespace:
    parser = argparse.ArgumentParser(
        prog="horovodrun-tpu",
        description="Launch a horovod_tpu distributed training job.")
    parser.add_argument("-v", "--version", action="version",
                        version=_version())
    parser.add_argument("-np", "--num-proc", type=int, default=None,
                        help="Total number of training processes.")
    parser.add_argument("-H", "--hosts", default=None,
                        help='Host list, e.g. "host1:4,host2:4".')
    parser.add_argument("--hostfile", default=None,
                        help='Hostfile with "hostname slots=N" lines.')
    parser.add_argument("--network-interface", default=None,
                        help="NIC(s) for the control plane (sets "
                        "HOROVOD_GLOO_IFACE).")
    parser.add_argument("--ssh-port", type=int, default=None)
    parser.add_argument("--ssh-identity-file", default=None)
    starter = parser.add_mutually_exclusive_group()
    starter.add_argument("--use-gloo", action="store_true",
                         help="Force the rendezvous/ssh process starter "
                         "(the default; disables jsrun auto-detection).")
    starter.add_argument("--use-mpi", action="store_true",
                         help="Start workers through mpirun; ranks adopt "
                         "their identity from the OMPI/PMIx env.")
    starter.add_argument("--use-jsrun", action="store_true",
                         help="Start workers through jsrun (LSF).")
    parser.add_argument("--verbose", action="store_true")
    parser.add_argument("--disable-cache", action="store_true",
                        help="Disable the response cache.")
    parser.add_argument("--start-timeout", type=float, default=600.0)
    parser.add_argument("--check-build", action="store_true",
                        help="Print the build/backend matrix and exit.")

    elastic = parser.add_argument_group("elastic")
    elastic.add_argument("--min-np", type=int, default=None)
    elastic.add_argument("--max-np", type=int, default=None)
    elastic.add_argument("--host-discovery-script", default=None,
                         help="Script printing 'host:slots' lines; polled "
                         "for membership changes.")
    elastic.add_argument("--reset-limit", type=int, default=None)
    elastic.add_argument("--slots", type=int, default=None,
                         help="Default slots per host for discovery-script "
                         "lines without an explicit :slots suffix.")
    elastic.add_argument("--elastic-timeout", type=float, default=600.0,
                         help="Seconds to wait for min-np slots / a new "
                         "rendezvous round.")

    tuning = parser.add_argument_group("tuning")
    tuning.add_argument("--fusion-threshold-mb", type=int, default=None)
    tuning.add_argument("--cycle-time-ms", type=float, default=None)
    tuning.add_argument("--cache-capacity", type=int, default=None)
    tuning.add_argument("--hierarchical-allreduce", action="store_true")
    tuning.add_argument("--hierarchical-allgather", action="store_true")
    tuning.add_argument("--autotune", action="store_true")
    tuning.add_argument("--autotune-log-file", default=None)

    fleet = parser.add_argument_group("fleet")
    fleet.add_argument("--fleet", action="store_true",
                       help="Run the unified train+serve fleet "
                       "controller on rank 0 (traffic-driven rank "
                       "rebalancing; docs/fleet.md).")
    fleet.add_argument("--fleet-publish-steps", type=int, default=None,
                       help="Trainer param-snapshot publish cadence in "
                       "steps (continuous weight deployment; 0 "
                       "disables).")
    fleet.add_argument("--fleet-interval", type=float, default=None,
                       help="Fleet controller gauge-poll/decision "
                       "interval in seconds.")

    debug = parser.add_argument_group("debug")
    debug.add_argument("--timeline-filename", default=None)
    debug.add_argument("--timeline-mark-cycles", action="store_true")
    debug.add_argument("--no-stall-check", action="store_true")
    debug.add_argument("--stall-check-warning-time-seconds", type=float,
                       default=None)
    debug.add_argument("--stall-check-shutdown-time-seconds", type=float,
                       default=None)
    debug.add_argument("--log-level", default=None,
                       choices=["trace", "debug", "info", "warning",
                                "error", "fatal"])
    debug.add_argument("--config-file", default=None,
                       help="YAML file with the above options.")

    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="Training command to run on every slot.")
    args = parser.parse_args(argv)
    if args.config_file:
        _apply_config_file(args, parser)
    return args


def _version() -> str:
    from .. import __version__
    return f"horovodrun-tpu {__version__}"


def _apply_config_file(args, parser) -> None:
    """YAML config support (reference: launch.py:513-517 +
    config_parser.py). A file value applies unless the CLI flag was
    explicitly given (i.e. the arg still holds its parser default)."""
    import yaml
    with open(args.config_file) as f:
        cfg = yaml.safe_load(f) or {}
    for key, value in cfg.items():
        attr = key.replace("-", "_")
        if hasattr(args, attr) and \
                getattr(args, attr) == parser.get_default(attr):
            setattr(args, attr, value)


def args_to_env(args) -> dict[str, str]:
    """Map CLI flags → HOROVOD_* env (reference: config_parser.py)."""
    env: dict[str, str] = {}

    def set_if(cond, name, value):
        if cond:
            env[name] = str(value)

    set_if(args.fusion_threshold_mb is not None, "HOROVOD_FUSION_THRESHOLD",
           (args.fusion_threshold_mb or 0) * 1024 * 1024)
    set_if(args.cycle_time_ms is not None, "HOROVOD_CYCLE_TIME",
           args.cycle_time_ms)
    set_if(args.cache_capacity is not None, "HOROVOD_CACHE_CAPACITY",
           args.cache_capacity)
    set_if(args.disable_cache, "HOROVOD_CACHE_CAPACITY", 0)
    set_if(args.hierarchical_allreduce, "HOROVOD_HIERARCHICAL_ALLREDUCE", 1)
    set_if(args.hierarchical_allgather, "HOROVOD_HIERARCHICAL_ALLGATHER", 1)
    set_if(args.autotune, "HOROVOD_AUTOTUNE", 1)
    set_if(args.autotune_log_file is not None, "HOROVOD_AUTOTUNE_LOG",
           args.autotune_log_file)
    set_if(args.timeline_filename is not None, "HOROVOD_TIMELINE",
           args.timeline_filename)
    set_if(args.timeline_mark_cycles, "HOROVOD_TIMELINE_MARK_CYCLES", 1)
    set_if(args.no_stall_check, "HOROVOD_STALL_CHECK_DISABLE", 1)
    set_if(args.stall_check_warning_time_seconds is not None,
           "HOROVOD_STALL_CHECK_TIME_SECONDS",
           args.stall_check_warning_time_seconds)
    set_if(args.stall_check_shutdown_time_seconds is not None,
           "HOROVOD_STALL_SHUTDOWN_TIME_SECONDS",
           args.stall_check_shutdown_time_seconds)
    set_if(args.log_level is not None, "HOROVOD_LOG_LEVEL", args.log_level)
    set_if(args.network_interface is not None, "HOROVOD_GLOO_IFACE",
           args.network_interface)
    # getattr: programmatic callers (elastic driver, run_api) build the
    # Namespace by hand and may predate the fleet group.
    set_if(getattr(args, "fleet", False), "HOROVOD_FLEET", 1)
    fleet_publish = getattr(args, "fleet_publish_steps", None)
    set_if(fleet_publish is not None,
           "HOROVOD_FLEET_PUBLISH_STEPS", fleet_publish)
    fleet_interval = getattr(args, "fleet_interval", None)
    set_if(fleet_interval is not None, "HOROVOD_FLEET_INTERVAL_S",
           fleet_interval)
    return env


def check_build(out=sys.stdout) -> None:
    """Print the build matrix (reference: launch.py:522-523,
    util.py:137-186)."""
    import horovod_tpu as hvd
    rows = [
        ("XLA/TPU data plane", hvd.xla_built()),
        ("TCP data plane", hvd.tcp_built()),
        ("Gloo-compatible control plane", hvd.gloo_built()),
        ("MPI", hvd.mpi_built()),
        ("NCCL", hvd.nccl_built()),
    ]
    frameworks = []
    for name, mod in (("PyTorch", "horovod_tpu.torch"),
                      ("JAX", "horovod_tpu.training")):
        try:
            __import__(mod)
            frameworks.append((name, True))
        except ImportError:
            frameworks.append((name, False))
    out.write(f"{_version()}\n\nAvailable frameworks:\n")
    for name, ok in frameworks:
        out.write(f"    [{'X' if ok else ' '}] {name}\n")
    out.write("\nAvailable backends:\n")
    for name, ok in rows:
        out.write(f"    [{'X' if ok else ' '}] {name}\n")


def rendezvous_env(addr: str, port: int,
                   start_timeout: float) -> dict[str, str]:
    """The env block every worker needs to reach the control plane —
    shared by the ssh and jsrun launch paths so the contract can't
    drift between them.  ``addr`` may be a single host or a comma-
    separated ``host:port`` seed list (replicated control plane):
    ``RendezvousClient`` parses both."""
    return {
        "HOROVOD_GLOO_RENDEZVOUS_ADDR": addr,
        "HOROVOD_GLOO_RENDEZVOUS_PORT": str(port),
        "HOROVOD_CONTROLLER": "tcp",
        "HOROVOD_GLOO_TIMEOUT_SECONDS": str(start_timeout),
    }


def start_rendezvous(advertised_addr: str):
    """Start the rendezvous control plane: one plain in-memory server
    by default, or — under ``HOROVOD_RENDEZVOUS_REPLICAS`` > 0 with
    ``HOROVOD_RENDEZVOUS_WAL_DIR`` set — a WAL-backed primary plus N
    standby replicas that survive coordinator death (standby promotion
    on lease lapse, docs/controlplane.md).  Returns ``(servers,
    addr_spec, port)``; pass ``addr_spec`` (a seed list when
    replicated) to :func:`rendezvous_env` and stop every server at
    teardown."""
    from ..common import config as _config

    replicas = _config.RENDEZVOUS_REPLICAS.get()
    wal_dir = _config.RENDEZVOUS_WAL_DIR.get()
    if replicas > 0 and wal_dir:
        from .controlplane import start_replica_set
        servers, endpoints = start_replica_set(
            replicas, wal_dir, host=advertised_addr)
        return servers, ",".join(endpoints), servers[0].port
    if replicas > 0:
        logger.warning(
            "HOROVOD_RENDEZVOUS_REPLICAS=%d needs "
            "HOROVOD_RENDEZVOUS_WAL_DIR (the replica set shares the "
            "durable log); starting a single un-replicated server",
            replicas)
    server = RendezvousServer()
    port = server.start()
    return [server], advertised_addr, port


def _ssh_command(slot: SlotInfo, command: list[str], env: dict[str, str],
                 args) -> str:
    exports = " ".join(f"{k}={shlex.quote(v)}" for k, v in env.items())
    inner = f"cd {shlex.quote(os.getcwd())} > /dev/null 2>&1 ; " \
            f"env {exports} {' '.join(shlex.quote(c) for c in command)}"
    ssh = ["ssh", "-o", "PasswordAuthentication=no",
           "-o", "StrictHostKeyChecking=no"]
    if args.ssh_port:
        ssh += ["-p", str(args.ssh_port)]
    if args.ssh_identity_file:
        ssh += ["-i", args.ssh_identity_file]
    ssh += [slot.hostname, inner]
    return " ".join(shlex.quote(s) if i >= len(ssh) - 1 else s
                    for i, s in enumerate(ssh))


def launch_static(args, command: list[str]) -> int:
    """Static (non-elastic) launch (reference: gloo_run.py launch_gloo)."""
    from . import js_run
    if args.hostfile:
        args.hosts = parse_host_files(args.hostfile)
    if args.hosts is None and js_run.using_lsf():
        # Inside an LSF job the allocation IS the host list (reference:
        # launch.py _check_all_hosts_ssh_successful / lsf default hosts).
        args.hosts = js_run.lsf_hosts_string()
    hosts = parse_hosts(args.hosts) if args.hosts else None
    if getattr(args, "use_mpi", False):
        return launch_mpi(args, command)
    if getattr(args, "use_jsrun", False):
        if hosts is None:
            sys.stderr.write("horovodrun-tpu: --use-jsrun needs -H or an "
                             "LSF allocation\n")
            return 2
        return js_run.launch_jsrun(args, command)
    if hosts is not None and not getattr(args, "use_gloo", False) and \
            js_run.using_lsf() and js_run.jsrun_available() and \
            not all(_is_local(h.hostname) for h in hosts):
        # jsrun is the process starter on LSF clusters (ssh is usually
        # disabled between compute nodes there); control plane unchanged.
        return js_run.launch_jsrun(args, command)
    np = args.num_proc or (sum(h.slots for h in hosts) if hosts else 1)
    if hosts is None:
        hosts = parse_hosts(f"localhost:{np}")
    slots = get_host_assignments(hosts, np)

    rendezvous_addr = _advertised_address(
        hosts, getattr(args, "network_interface", None))
    servers, addr_spec, port = start_rendezvous(rendezvous_addr)

    base_env = dict(os.environ)
    base_env.update(args_to_env(args))
    base_env.update(rendezvous_env(addr_spec, port,
                                   args.start_timeout))
    # Full rank→host map for topology-aware ring orders (hosts.py).
    base_env["HOROVOD_HOST_IDS"] = host_ids_env(slots)

    exit_codes = [None] * len(slots)
    # Workers run from launcher threads, so signal forwarding must go
    # through a termination event watched inside execute() — the main
    # thread's handler can't reach children started off-main-thread.
    terminate = threading.Event()

    def _run_slot(i: int, slot: SlotInfo) -> None:
        env = dict(base_env)
        env.update(slot.to_env())
        if _is_local(slot.hostname):
            exit_codes[i] = safe_shell_exec.execute(
                command, env=env, index=slot.rank, events=[terminate])
        else:
            remote = _ssh_command(slot, command, env, args)
            exit_codes[i] = safe_shell_exec.execute(
                remote, env=base_env, index=slot.rank, events=[terminate])

    threads = [threading.Thread(target=_run_slot, args=(i, s),
                                daemon=True, name=f"hvd-slot-{i}")
               for i, s in enumerate(slots)]
    prev_handlers = {}
    if threading.current_thread() is threading.main_thread():
        import signal

        def _on_signal(sig, _frame):
            terminate.set()
        for sig in (signal.SIGINT, signal.SIGTERM):
            prev_handlers[sig] = signal.signal(sig, _on_signal)
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    except KeyboardInterrupt:
        terminate.set()
        for t in threads:
            t.join(timeout=2 * safe_shell_exec.GRACEFUL_TERMINATION_TIME_S)
        raise
    finally:
        import signal
        for sig, h in prev_handlers.items():
            signal.signal(sig, h)
        for srv in servers:
            srv.stop()
    failures = [(s.rank, c) for s, c in zip(slots, exit_codes) if c != 0]
    if failures:
        sys.stderr.write(f"horovodrun-tpu: ranks failed: {failures}\n")
        return 1
    return 0


def control_plane_env(args, hosts, port: int,
                      layout: str | None = None) -> dict[str, str]:
    """Worker env block for starters that launch all ranks in one shot
    (mpirun, jsrun): tuning knobs + rendezvous coordinates, plus the host
    layout for rank adoption when the starter cannot hand out per-rank
    env. One definition so the contract can't drift between starters."""
    env = args_to_env(args)
    env.update(rendezvous_env(
        _advertised_address(hosts, getattr(args, "network_interface",
                                           None)),
        port, args.start_timeout))
    if layout:
        from .js_run import JSRUN_HOSTS_ENV
        env[JSRUN_HOSTS_ENV] = layout
    return env


def launch_mpi(args, command: list[str]) -> int:
    """Static launch through mpirun (reference: mpi_run.py / launch.py
    --use-mpi): ONE mpirun invocation starts every rank; mpirun cannot
    hand out per-rank env, so workers adopt their identity from the
    OMPI/PMIx vars plus the exported host layout (the same adoption path
    jsrun uses, runner/js_run.py adopt_jsm_env) and dial back to the
    rendezvous server started here. MPI is the process starter only —
    the control plane stays TCP and the data plane XLA."""
    from . import safe_shell_exec
    from .mpi_run import build_mpi_command, mpi_available

    if not mpi_available():
        sys.stderr.write("horovodrun-tpu: --use-mpi but mpirun is not on "
                         "PATH\n")
        return 2
    hosts_str = args.hosts or f"localhost:{args.num_proc or 1}"
    hosts = parse_hosts(hosts_str)
    np = args.num_proc or sum(h.slots for h in hosts)

    server = RendezvousServer()
    port = server.start()
    env = dict(os.environ)
    env.update(control_plane_env(args, hosts, port, layout=hosts_str))
    cmd = build_mpi_command(command, np=np, hosts=hosts_str, env=env,
                            ssh_port=args.ssh_port)
    if args.verbose:
        print(" ".join(cmd))
    try:
        return safe_shell_exec.execute(cmd, env=env)
    finally:
        server.stop()


def _advertised_address(hosts, network_interface: str | None = None) -> str:
    """Address the workers should dial for rendezvous: loopback for pure
    local runs; the pinned NIC's address when ``--network-interface`` is
    given (reference: driver_service NIC selection); else this host's
    primary address."""
    if all(_is_local(h.hostname) for h in hosts):
        return "127.0.0.1"
    if network_interface:
        from .driver_service import candidate_addresses
        return candidate_addresses(network_interface)[0]
    import socket
    return socket.getfqdn()


def launch_elastic(args, command: list[str]) -> int:
    try:
        from ..elastic.launcher import launch_elastic as _launch
    except ImportError as exc:
        sys.stderr.write(
            f"horovodrun-tpu: elastic launch unavailable: {exc}\n")
        return 2
    return _launch(args, command)


def main(argv=None) -> int:
    args = parse_args(argv)
    if args.check_build:
        check_build()
        return 0
    command = list(args.command)
    if command and command[0] == "--":
        command = command[1:]
    if not command:
        sys.stderr.write("horovodrun-tpu: no training command given\n")
        return 2
    if args.host_discovery_script or args.min_np is not None:
        return launch_elastic(args, command)
    return launch_static(args, command)


if __name__ == "__main__":
    sys.exit(main())
