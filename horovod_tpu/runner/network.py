"""Socket primitives for the DCN control/data planes.

Reference analogues: horovod/common/gloo/http_store.cc (KV client),
horovod/runner/http/http_server.py:35-241 (rendezvous KV server), and the
point-to-point plumbing under runner/common/service/.  Framing is a 4-byte
big-endian length prefix; payloads are opaque bytes (wire.py messages or raw
numpy buffers).

Bulk transfers ride persistent per-peer duplex channels (`_PeerChannel`):
one long-lived sender thread + bounded queue per neighbor drains
scatter-gather `sendmsg` frames, and receives land in a reusable per-peer
scratch pool via `recv_into` — no per-step thread spawn, no bytes copies
on either direction (the reference keeps Gloo's persistent pair
connections alive the same way).
"""
from __future__ import annotations

import os
import queue
import random
import selectors
import socket
import struct
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib import error as urlerror
from urllib import parse as urlparse
from urllib import request as urlrequest

from ..common import config, wire
from ..common.logging import logger
from .controlplane import _FNV_OFFSET, ControlPlane, apply_record

_LEN = struct.Struct(">I")

# Grace for a sender lane to drain after its queue is poisoned at close;
# past it the socket is shut down under the thread (unblocking a sendmsg
# wedged on a dead peer) and a structured warning names the peer.
_CLOSE_JOIN_GRACE = 10.0


def _resilience_state():
    """The process ResilienceState, or None (zero-overhead off mode).
    Late import: resilience/ sits above the transport layer."""
    from ..resilience import active_state
    return active_state()


def _chaos_engine():
    from ..resilience import chaos
    return chaos.active()

# Depth of a channel's outbound queue.  Collective schedules keep at most
# one or two sends in flight per peer; the bound only exists so a runaway
# producer backpressures instead of buffering unbounded payload refs.
_SEND_QUEUE_DEPTH = 8


def send_msg(sock: socket.socket, payload: bytes) -> None:
    if len(payload) < (1 << 16):
        # Small control messages: one syscall, concat is cheap.
        sock.sendall(_LEN.pack(len(payload)) + payload)
    else:
        # Bulk payloads: never materialize header+payload (a full copy of
        # a multi-MB gradient buffer per send).
        sock.sendall(_LEN.pack(len(payload)))
        sock.sendall(payload)


def send_msg_gather(sock: socket.socket, view: memoryview) -> None:
    """Frame + send in one scatter-gather syscall (`sendmsg`): the header
    never gets concatenated onto a multi-MB payload, and the payload is
    consumed straight from the caller's buffer (numpy slice, bytes, ...).
    Handles partial sends — sendmsg may stop at any byte boundary."""
    n = view.nbytes
    hdr = _LEN.pack(n)
    sent = sock.sendmsg([hdr, view])
    while sent < 4 + n:
        if sent < 4:
            sent += sock.send(memoryview(hdr)[sent:])
        else:
            sent += sock.send(view[sent - 4:])


def _as_byte_view(payload) -> memoryview:
    """A flat uint8 memoryview over bytes/bytearray/memoryview/ndarray
    without copying (C-contiguous buffers only — all our payloads are)."""
    view = payload if isinstance(payload, memoryview) else memoryview(payload)
    if view.format != "B" or view.ndim != 1:
        view = view.cast("B")
    return view


def recv_exact(sock: socket.socket, n: int) -> bytearray:
    # Single preallocated buffer + recv_into: no per-chunk allocations,
    # no final join copy (numpy consumes the bytearray zero-copy via
    # frombuffer).
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)  # hvdlint: disable=unbounded-blocking-wait,unbounded-serve-wait -- mesh-bootstrap rank-id/HELLO exchange only; dialed sockets carry the formation connect timeout as their socket timeout and the acceptor thread is joined under the same bound
        if r == 0:
            raise ConnectionError("socket closed mid-message")
        got += r
    return buf


def recv_msg(sock: socket.socket) -> bytearray:
    (length,) = _LEN.unpack(recv_exact(sock, 4))
    return recv_exact(sock, length)


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


# ---------------------------------------------------------------------------
# Rendezvous KV store (HTTP, like the reference's RendezvousServer/HTTPStore)
# ---------------------------------------------------------------------------
def _kv_apply(httpd, kind: str, scope: str, key: str, value: bytes):
    """Commit (WAL, when a control plane is attached) + apply one
    mutating KV verb.  Enqueue and apply happen under the KV lock so
    log order equals in-memory apply order; the fsync wait happens on
    the returned event AFTER the lock is released (the caller acks the
    client only once it is set).  Returns ``(commit_event|None,
    claim_index|None)``."""
    cp = httpd.controlplane
    with httpd.kv_lock:
        result = None
        if kind == "claim":
            claimant = value.decode()
            ckey = f"{scope}/{key}"
            assigned = httpd.claims.setdefault(ckey, {})
            if claimant and claimant in assigned:
                # Idempotent re-present: nothing new to commit.
                return None, assigned[claimant]
            result = httpd.counters.get(ckey, 0)
            # The record carries the ASSIGNED index so replay never
            # re-runs the counter (claim order in the log is free).
            value = f"{claimant}|{result}".encode()
        commit = cp.record(kind, scope, key, value) \
            if cp is not None else None
        state = {"kv": httpd.kv, "counters": httpd.counters,
                 "claims": httpd.claims, "digest": httpd.kv_digest}
        apply_record(state, kind, scope, key, value)
        httpd.kv_digest = state["digest"]
        httpd.kv_cond.notify_all()
    return commit, result


def _kv_apply_many(httpd, records):
    """Apply a batch of put records under ONE KV-lock hold: every WAL
    record is enqueued back-to-back so the group-commit lane drains them
    in one (or very few) fsync batches instead of interleaving with
    other writers.  Returns the LAST commit event only — the WAL queue
    is FIFO and the writer sets commit events in batch order, so the
    last record's durability implies every earlier record's."""
    cp = httpd.controlplane
    last = None
    with httpd.kv_lock:
        state = {"kv": httpd.kv, "counters": httpd.counters,
                 "claims": httpd.claims, "digest": httpd.kv_digest}
        for scope, key, value in records:
            if cp is not None:
                last = cp.record("put", scope, key, value)
            apply_record(state, "put", scope, key, value)
        httpd.kv_digest = state["digest"]
        httpd.kv_cond.notify_all()
    return last


def encode_batch(records) -> bytes:
    """Frame ``[(scope, key, value), ...]`` put records for the
    ``PUT /.batch/`` fan-in verb (wire.py varint framing)."""
    enc = wire.Encoder()
    records = list(records)
    enc.uvarint(len(records))
    for scope, key, value in records:
        enc.string(scope).string(key).blob(value)
    return enc.getvalue()


def decode_batch(raw: bytes) -> list[tuple[str, str, bytes]]:
    dec = wire.Decoder(bytes(raw))
    return [(dec.string(), dec.string(), dec.blob())
            for _ in range(dec.uvarint())]


def encode_scope(entries: dict) -> bytes:
    """Frame one scope's key->value dict for the empty-key GET (scope
    dump) response."""
    enc = wire.Encoder()
    enc.uvarint(len(entries))
    for key, value in entries.items():
        enc.string(key).blob(value)
    return enc.getvalue()


def decode_scope(raw: bytes) -> dict[str, bytes]:
    dec = wire.Decoder(bytes(raw))
    return {dec.string(): dec.blob() for _ in range(dec.uvarint())}


# Reserved scope name carrying batched put records (PUT body is a
# wire-framed record list, not a single value).
BATCH_SCOPE = ".batch"


class _KVHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # silence default stderr logging
        pass

    def _split(self) -> tuple[str, str]:
        parts = urlparse.urlsplit(self.path).path.lstrip("/") \
            .split("/", 1)
        scope = parts[0] if parts else ""
        key = parts[1] if len(parts) > 1 else ""
        return scope, key

    def _query(self) -> dict:
        return urlparse.parse_qs(urlparse.urlsplit(self.path).query)

    def _reply(self, code: int, body: bytes = b"",
               headers=()) -> None:
        self.send_response(code)
        for name, val in headers:
            self.send_header(name, val)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        if body:
            self.wfile.write(body)

    def _gate(self) -> bool:
        """Leader fence: with a control plane attached, only the
        current primary answers KV verbs (reads included — clients must
        never observe a stale standby mirror).  A refused request gets
        409 + the best-known leader endpoint so clients converge."""
        cp = self.server.controlplane
        if cp is None:
            return True
        ok, hint = cp.check_write()
        if ok:
            return True
        self._reply(409, headers=((("X-Hvd-Leader", hint),)
                                  if hint else ()))
        return False

    def _commit_or_fail(self, commit) -> bool:
        """Wait for the WAL group-commit fsync before acking; a write
        that never reached disk answers 503 instead of lying."""
        if commit is None or commit.wait(timeout=10.0):
            return True
        self._reply(503)
        return False

    def do_PUT(self):
        if not self._gate():
            return
        scope, key = self._split()
        length = int(self.headers.get("Content-Length", 0))
        value = self.rfile.read(length)
        if scope == BATCH_SCOPE:
            # Fan-in verb: one request carries a host-group's worth of
            # put records (fleetsim heartbeat stamps), applied under a
            # single lock hold so WAL group-commit coalesces them.
            try:
                records = decode_batch(value)
            except (ValueError, IndexError):
                return self._reply(400)
            commit = _kv_apply_many(self.server, records)
            if self._commit_or_fail(commit):
                self._reply(200, str(len(records)).encode())
            return
        commit, _ = _kv_apply(self.server, "put", scope, key, value)
        if self._commit_or_fail(commit):
            self._reply(200)

    def do_GET(self):
        scope, key = self._split()
        if scope == ".ctl":
            return self._ctl(key)
        if not self._gate():
            return
        if key == "":
            # Scope dump: one request returns every key in the scope
            # (fleetsim host groups refresh their heartbeat snapshot
            # with ONE read instead of size-many gets per window).
            with self.server.kv_lock:
                entries = dict(self.server.kv.get(scope, {}))
            return self._reply(200, encode_scope(entries))
        wait_q = self._query().get("wait", ["0"])[0]
        try:
            wait_s = max(0.0, min(float(wait_q) / 1e3, 60.0))
        except ValueError:
            wait_s = 0.0
        deadline = time.monotonic() + wait_s
        with self.server.kv_lock:
            value = self.server.kv.get(scope, {}).get(key)
            while value is None:
                # Server-side long-poll (?wait=<ms>): a steady-state
                # watcher costs one outstanding request instead of a
                # 100 req/s busy-poll.  Bounded by the client's wait
                # budget; wakeups ride every committed mutation.
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self.server.kv_cond.wait(timeout=remaining)
                value = self.server.kv.get(scope, {}).get(key)
        if value is None:
            self._reply(404)
        else:
            self._reply(200, value)

    def _ctl(self, key: str) -> None:
        """Introspection endpoints under ``/.ctl/``: replica role/epoch
        (``role``), process id (``pid`` — the chaos ``coordkill:``
        target), live KV digest (``digest``) and the raw log tail
        (``wal?from=<offset>``) standbys replicate from."""
        cp = self.server.controlplane
        if key == "pid":
            return self._reply(200, str(os.getpid()).encode())
        if key == "role":
            desc = cp.describe() if cp is not None else "primary|0|"
            return self._reply(200, desc.encode())
        if key == "digest":
            with self.server.kv_lock:
                digest = self.server.kv_digest
            return self._reply(200, str(digest).encode())
        if key.startswith("wal"):
            if cp is None:
                return self._reply(404)
            try:
                offset = int(self._query().get("from", ["0"])[0])
            except ValueError:
                offset = 0
            raw, end = cp.wal_bytes_from(offset)
            return self._reply(200, raw,
                               headers=(("X-Hvd-Wal-End", str(end)),))
        self._reply(404)

    def do_POST(self):
        """Atomic fetch-and-increment counter per (scope, key) — used for
        per-host slot claims (reference: the spark driver service's
        task-registration counter, spark/runner.py:47-426). A non-empty
        body names the logical claimant: re-presenting the same body
        returns the original index (idempotent under task retries)."""
        if not self._gate():
            return
        scope, key = self._split()
        length = int(self.headers.get("Content-Length", 0))
        claimant = self.rfile.read(length)
        commit, n = _kv_apply(self.server, "claim", scope, key, claimant)
        if self._commit_or_fail(commit):
            self._reply(200, str(n).encode())

    def do_DELETE(self):
        if not self._gate():
            return
        scope, key = self._split()
        commit, _ = _kv_apply(self.server, "delete", scope, key, b"")
        if self._commit_or_fail(commit):
            self._reply(200)


class RendezvousServer:
    """Threaded HTTP KV store (reference: runner/http/http_server.py).

    With ``wal_dir`` (or ``HOROVOD_RENDEZVOUS_WAL_DIR``) set, a
    :class:`~.controlplane.ControlPlane` is attached: every mutating
    verb is WAL-committed before it is acked, standby replicas tail the
    log and promote on lease lapse, and the handler fences every verb
    on the current leadership (docs/controlplane.md)."""

    def __init__(self, port: int = 0, wal_dir: str | None = None,
                 replica_id: int = 0, endpoints=None,
                 lease_ms: float | None = None,
                 standby: bool = False) -> None:
        self._httpd = ThreadingHTTPServer(("", port), _KVHandler)
        self._httpd.kv = {}
        self._httpd.counters = {}
        self._httpd.claims = {}
        self._httpd.kv_digest = _FNV_OFFSET
        self._httpd.kv_lock = threading.Lock()
        self._httpd.kv_cond = threading.Condition(self._httpd.kv_lock)
        wal_dir = wal_dir or (config.RENDEZVOUS_WAL_DIR.get() or None)
        self._httpd.controlplane = None if wal_dir is None else \
            ControlPlane(self, wal_dir, replica_id=replica_id,
                         endpoints=endpoints, lease_ms=lease_ms,
                         standby=standby)
        self._thread: threading.Thread | None = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def controlplane(self) -> ControlPlane | None:
        return self._httpd.controlplane

    def start(self) -> int:
        if self._httpd.controlplane is not None:
            self._httpd.controlplane.start()
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True,
                                        name="hvd-rendezvous")
        self._thread.start()
        return self.port

    def put(self, scope: str, key: str, value: bytes) -> None:
        commit, _ = _kv_apply(self._httpd, "put", scope, key, value)
        if commit is not None:
            commit.wait(timeout=10.0)

    def put_many(self, records) -> None:
        """Batched puts (``[(scope, key, value), ...]``) applied under
        one lock hold — the in-proc mirror of ``PUT /.batch/``."""
        commit = _kv_apply_many(self._httpd, list(records))
        if commit is not None:
            commit.wait(timeout=10.0)

    def get(self, scope: str, key: str) -> bytes | None:
        with self._httpd.kv_lock:
            return self._httpd.kv.get(scope, {}).get(key)

    def get_scope(self, scope: str) -> dict[str, bytes]:
        with self._httpd.kv_lock:
            return dict(self._httpd.kv.get(scope, {}))

    def kv_digest(self) -> int:
        """Rolling FNV digest of every applied mutation (matches the
        digest a WAL replay of the same history computes)."""
        with self._httpd.kv_lock:
            return self._httpd.kv_digest

    def stop(self) -> None:
        if self._httpd.controlplane is not None:
            self._httpd.controlplane.close()
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            # Reap the serve thread (hvdlife HVD701): shutdown() above
            # is its wakeup, so the join is prompt.
            self._thread.join(timeout=5.0)
            self._thread = None


# Long-poll chunk a single wait() request asks the server to hold for;
# short enough that endpoint failover is never stalled behind one
# outstanding request for long.
_LONG_POLL_CHUNK_S = 5.0
# Jittered exponential retry backoff between endpoint attempts.
_BACKOFF_FLOOR_S = 0.01
_BACKOFF_CAP_S = 0.25
# Per-attempt HTTP timeout: one stalled endpoint (SIGSTOP'd primary, a
# half-open socket) must never eat the whole retry deadline — the next
# seed gets its turn after this bound.
_ATTEMPT_TIMEOUT_S = 5.0


class RendezvousClient:
    """HTTP KV client with blocking get (reference: gloo/http_store.cc
    wait) and multi-endpoint failover: ``addr`` may be a single host
    (paired with ``port``) or a comma-separated ``host:port`` seed list
    (every replica of a fault-tolerant control plane).  Idempotent
    verbs — get/wait/delete/put/claim-with-``task_key`` — retry across
    endpoints with jittered exponential backoff inside one deadline,
    riding out a coordinator restart or failover window; a bare claim
    (no ``task_key``) still fails fast, since a retry could double-
    allocate its index."""

    def __init__(self, addr: str, port: int | None = None,
                 timeout: float = 30.0, endpoints=None) -> None:
        if endpoints is not None:
            self._endpoints = list(endpoints)
        else:
            self._endpoints = self.parse_endpoints(addr, port)
        self._active = 0
        self.timeout = timeout
        # Per-verb latency histograms, bound lazily to the live registry
        # (telemetry may be configured after the client is built).
        self._lat: dict[str, object] = {}
        self._lat_reg = None

    def _observe_latency(self, verb: str, start: float) -> None:
        """Record one verb's wall time (retries + failover included) on
        ``horovod_rendezvous_kv_latency_ms{verb}`` — the fleet-scale
        control-plane latency SLO the 256-rank battery asserts on."""
        from ..telemetry import metrics
        tm = metrics()
        if not tm.enabled:
            return
        if self._lat_reg is not tm:
            self._lat = {}
            self._lat_reg = tm
        hist = self._lat.get(verb)
        if hist is None:
            hist = tm.histogram(
                "horovod_rendezvous_kv_latency_ms",
                "Client-observed rendezvous KV verb latency, failover "
                "retries included", labels={"verb": verb})
            self._lat[verb] = hist
        hist.observe((time.monotonic() - start) * 1e3)

    @staticmethod
    def parse_endpoints(addr: str, port: int | None) -> list[str]:
        """``"h1:p1,h2:p2"`` (seed list) or ``("host", port)``."""
        eps = []
        for part in str(addr).split(","):
            part = part.strip()
            if not part:
                continue
            if ":" not in part and port is None:
                raise ValueError(
                    f"rendezvous endpoint {part!r} has no port and no "
                    f"default port was given")
            eps.append(part if ":" in part else f"{part}:{port}")
        if not eps:
            raise ValueError("rendezvous client needs at least one "
                             "endpoint")
        return eps

    @property
    def endpoint(self) -> str:
        return self._endpoints[self._active]

    @property
    def _base(self) -> str:
        return f"http://{self.endpoint}"

    def _failover(self, failed: str, why, hint: str = "") -> None:
        """Move to the hinted leader (409 redirect) or the next seed;
        one structured warning names the endpoint per transition."""
        if hint:
            if hint not in self._endpoints:
                self._endpoints.append(hint)
            nxt = self._endpoints.index(hint)
        else:
            nxt = (self._active + 1) % len(self._endpoints)
        if nxt != self._active:
            logger.warning(
                "rendezvous: endpoint %s unavailable (%s); failing "
                "over to %s", failed, why, self._endpoints[nxt])
        self._active = nxt

    def _call(self, method: str, scope: str, key: str,
              data: bytes | None = None, query: str = "",
              idempotent: bool = True,
              deadline: float | None = None,
              attempt_timeout: float | None = None,
              verb: str | None = None) -> bytes | None:
        """One verb with bounded endpoint failover.  Returns the body,
        or None on 404.  Non-idempotent calls never retry a transport
        error (the request may have committed server-side); 409 leader
        redirects are always safe to follow — a refused request was
        never applied."""
        if deadline is None:
            deadline = time.monotonic() + self.timeout
        if attempt_timeout is None:
            attempt_timeout = min(self.timeout, _ATTEMPT_TIMEOUT_S)
        verb = verb or method.lower()
        start = time.monotonic()
        attempt = 0
        last_exc: Exception | None = None
        while True:
            endpoint = self.endpoint
            req = urlrequest.Request(
                f"http://{endpoint}/{scope}/{key}{query}",
                data=data, method=method)
            try:
                with urlrequest.urlopen(
                        req, timeout=attempt_timeout) as resp:
                    body = resp.read()
                self._observe_latency(verb, start)
                return body
            except urlerror.HTTPError as e:
                if e.code == 404:
                    self._observe_latency(verb, start)
                    return None
                if e.code not in (409, 503):
                    raise
                last_exc = e
                self._failover(endpoint, f"HTTP {e.code}",
                               e.headers.get("X-Hvd-Leader", ""))
            except (urlerror.URLError, ConnectionError, TimeoutError,
                    OSError) as e:
                if not idempotent:
                    raise
                last_exc = e
                reason = getattr(e, "reason", e)
                self._failover(endpoint, reason)
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"rendezvous {method} {scope}/{key} failed against "
                    f"every endpoint {self._endpoints} within the "
                    f"deadline") from last_exc
            delay = min(_BACKOFF_FLOOR_S * (2 ** attempt),
                        _BACKOFF_CAP_S)
            time.sleep(delay * random.uniform(0.5, 1.0))
            attempt += 1

    def put(self, scope: str, key: str, value: bytes) -> None:
        # A put is a blind last-write-wins set: retrying a possibly-
        # committed put re-applies the same value (idempotent).
        self._call("PUT", scope, key, data=value)

    def put_many(self, records) -> None:
        """Batched puts: ``[(scope, key, value), ...]`` in ONE request
        (``PUT /.batch/``), applied server-side under a single lock
        hold so the WAL group-commits them in one fsync lane pass.
        Idempotent — every record is a last-write-wins put."""
        records = list(records)
        if not records:
            return
        self._call("PUT", BATCH_SCOPE, "", data=encode_batch(records),
                   verb="put_many")

    def get_scope(self, scope: str) -> dict[str, bytes]:
        """One request returning the scope's full key->value dict (the
        empty-key GET): what a fleetsim host group polls instead of
        size-many per-peer gets."""
        raw = self._call("GET", scope, "", verb="get_scope")
        return {} if raw is None else decode_scope(raw)

    def claim(self, scope: str, key: str, task_key: str = "") -> int:
        """Atomic fetch-and-increment of the (scope, key) counter.
        A non-empty ``task_key`` makes the claim idempotent: retries
        with the same key get the originally assigned index back (and
        may therefore safely ride endpoint failover)."""
        raw = self._call("POST", scope, key, data=task_key.encode(),
                         idempotent=bool(task_key))
        return int(raw)

    def get(self, scope: str, key: str) -> bytes | None:
        return self._call("GET", scope, key)

    def delete(self, scope: str, key: str = "") -> None:
        """Delete one key (or a whole scope when ``key`` is empty) —
        statesync consumes its join/ready/donation marks so a later
        epoch's watcher never replays a resolved event."""
        self._call("DELETE", scope, key)

    def probe(self) -> str | None:
        """The active endpoint's ``/.ctl/role`` descriptor, or None
        when no endpoint answers (control-plane health check)."""
        try:
            raw = self._call("GET", ".ctl", "role")
        except (TimeoutError, urlerror.URLError, OSError):
            return None
        return raw.decode() if raw is not None else None

    def find_primary(self) -> str | None:
        """Probe every seed DIRECTLY (each replica answers ``/.ctl``
        for itself) and return the endpoint currently acting as
        primary, retargeting the client at it.  None while no replica
        leads (mid-election)."""
        for i, endpoint in enumerate(list(self._endpoints)):
            try:
                with urlrequest.urlopen(
                        f"http://{endpoint}/.ctl/role",
                        timeout=2.0) as resp:
                    role = resp.read().decode()
            except OSError:
                continue
            if role.startswith("primary"):
                self._active = i
                return endpoint
        return None

    def wait(self, scope: str, key: str,
             timeout: float | None = None) -> bytes:
        """Block until the key exists.  Each request long-polls
        server-side (``?wait=<ms>``) so a steady-state watcher keeps
        ONE outstanding request instead of busy-polling at 100 req/s;
        between failed attempts the retry backs off with jitter
        (10 ms -> 250 ms cap)."""
        total = timeout or self.timeout
        deadline = time.monotonic() + total
        delay = _BACKOFF_FLOOR_S
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError(
                    f"Rendezvous key {scope}/{key} not available after "
                    f"{total}s")
            chunk_ms = int(min(remaining, _LONG_POLL_CHUNK_S) * 1e3)
            try:
                # The server legitimately holds the request for the
                # whole chunk: the per-attempt bound must exceed it.
                value = self._call("GET", scope, key,
                                   query=f"?wait={chunk_ms}",
                                   deadline=deadline,
                                   attempt_timeout=chunk_ms / 1e3 + 5.0,
                                   verb="wait")
            except TimeoutError:
                raise TimeoutError(
                    f"Rendezvous key {scope}/{key} not available after "
                    f"{total}s (endpoints {self._endpoints})") from None
            if value is not None:
                return value
            time.sleep(delay * random.uniform(0.5, 1.0))
            delay = min(delay * 2, _BACKOFF_CAP_S)


def advertised_hello() -> tuple[int, int]:
    """The wire proto version + feature bits this process offers at
    channel establishment.  ``HOROVOD_PROTO_COMPAT=<N>`` pins the
    advertisement to version N (masking newer feature bits) so a world
    can roll from framework version N to N+1 rank-by-rank: the still-
    old ranks negotiate every peer down to the min common schema."""
    compat = config.PROTO_COMPAT.get()
    proto = wire.PROTO_VERSION if compat <= 0 \
        else min(compat, wire.PROTO_VERSION)
    return proto, wire.proto_features(proto)


# ---------------------------------------------------------------------------
# Persistent duplex channel to one peer
# ---------------------------------------------------------------------------
class _PeerChannel:
    """One long-lived socket to a peer with a persistent sender lane.

    Sends enqueue onto a bounded queue drained by ONE daemon thread that
    lives as long as the channel (spawned lazily on the first async send,
    so control-plane meshes that never bulk-send cost zero threads).
    Receives go through `recv_begin` (framing) + `recv_exact_into`
    (straight into the caller's buffer) or the reusable scratch pool —
    the zero-copy replacement for the old alloc-per-message recv.
    """

    __slots__ = ("sock", "peer", "_queue", "_sender", "_error",
                 "_scratch", "_hdr", "_on_sent", "_res")

    def __init__(self, sock: socket.socket, peer: int, on_sent,
                 resilience=None) -> None:
        self.sock = sock
        self.peer = peer
        self._queue: queue.Queue | None = None
        self._sender: threading.Thread | None = None
        self._error: BaseException | None = None
        self._scratch = bytearray(0)
        self._hdr = bytearray(4)
        self._on_sent = on_sent    # bytes counter callback (mesh-level)
        # Resilience (HOROVOD_FAULT_TOLERANCE): a non-None state installs
        # a short socket timeout so every blocking wait on this channel
        # becomes a deadline-bounded poll loop — between slices the state
        # raises RanksFailedError on peer death or per-op deadline expiry
        # instead of blocking forever.  None = the exact pre-resilience
        # syscall pattern (zero-overhead off mode).
        self._res = resilience
        if resilience is not None:
            self.sock.settimeout(resilience.poll_interval)

    def _dead(self, exc: BaseException) -> BaseException:
        """Latch a failure on the channel: later sends/recvs raise it
        immediately instead of re-waiting out a deadline on a stream
        that is already known broken (and possibly desynced)."""
        if self._error is None:
            self._error = exc
        return exc

    # -- sending ----------------------------------------------------------
    def send_async(self, payload) -> None:
        """Enqueue one framed message on the persistent sender lane.  The
        caller must not mutate `payload`'s buffer until the channel is
        flushed (collectives flush before returning results)."""
        if self._error is not None:
            raise self._error
        if self._sender is None:
            self._queue = queue.Queue(maxsize=_SEND_QUEUE_DEPTH)
            self._sender = threading.Thread(
                target=self._send_loop, daemon=True,
                name=f"hvd-send-{self.peer}")
            self._sender.start()
        self._queue.put(_as_byte_view(payload))

    def send_sync(self, payload) -> int:
        """Blocking framed send; routed through the sender lane when one
        exists so sync and async frames never interleave on the wire.
        Returns the bytes to account (0 when the lane already counted
        them through its completion callback)."""
        view = _as_byte_view(payload)
        if self._sender is not None:
            self.send_async(view)
            self.flush()
            return 0
        self._send_gather(view)
        return view.nbytes

    def _send_gather(self, view: memoryview) -> None:
        """Framed scatter-gather send, deadline-bounded when resilience
        is on: a sendmsg stalled on a wedged peer's zero-window socket
        polls in slices and raises RanksFailedError at the op deadline
        instead of blocking the lane forever (progress resets the clock —
        the deadline bounds silence, not transfer time)."""
        if self._res is None:
            send_msg_gather(self.sock, view)
            return
        n = view.nbytes
        hdr = _LEN.pack(n)
        sent = 0
        start = time.monotonic()
        while sent < 4 + n:
            try:
                if sent == 0:
                    sent += self.sock.sendmsg([hdr, view])
                elif sent < 4:
                    sent += self.sock.send(memoryview(hdr)[sent:])
                else:
                    sent += self.sock.send(view[sent - 4:])
            except TimeoutError:
                self._res.check(self.peer, time.monotonic() - start,
                                "send")
                continue
            except (ConnectionResetError, BrokenPipeError) as e:
                raise self._dead(self._res.peer_connection_lost(
                    self.peer, "send", str(e))) from e
            start = time.monotonic()

    def _send_loop(self) -> None:
        while True:
            view = self._queue.get()
            try:
                if view is None:
                    return
                self._send_gather(view)
                self._on_sent(view.nbytes)
            except BaseException as e:  # noqa: BLE001 - surfaced to caller
                if self._error is None:
                    self._error = e
                # Wake a peer blocked in recv on the dead channel.
                try:
                    self.sock.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
            finally:
                self._queue.task_done()

    def flush(self) -> None:
        """Block until every queued frame has been handed to the kernel
        (the pre-channel code's per-step join gave the same guarantee).
        Bounded indirectly: under fault tolerance every send the lane
        drains is itself deadline-bounded, so the join below terminates
        within one op deadline of a peer failure."""
        if self._queue is not None:
            self._queue.join()  # hvdlint: disable=unbounded-blocking-wait -- each queued send is deadline-bounded (see _send_gather); the lane always reaches task_done
        if self._error is not None:
            raise self._error

    # -- receiving --------------------------------------------------------
    def recv_exact_into(self, view: memoryview) -> None:
        got, n = 0, view.nbytes
        if self._res is None:   # zero-overhead off mode: original loop
            while got < n:
                r = self.sock.recv_into(view[got:], n - got)  # hvdlint: disable=unbounded-blocking-wait -- intentional pre-resilience behavior when HOROVOD_FAULT_TOLERANCE is off
                if r == 0:
                    raise ConnectionError("socket closed mid-message")
                got += r
            return
        start = time.monotonic()
        while got < n:
            try:
                r = self.sock.recv_into(view[got:], n - got)  # hvdlint: disable=unbounded-blocking-wait -- bounded by the socket poll timeout installed at channel construction; the except arm enforces the op deadline
            except TimeoutError:
                # check() raises RanksFailedError on peer death or op-
                # deadline expiry; otherwise keep polling.
                self._res.check(self.peer, time.monotonic() - start,
                                "recv")
                continue
            except (ConnectionResetError, BrokenPipeError) as e:
                raise self._dead(self._res.peer_connection_lost(
                    self.peer, "recv", str(e))) from e
            if r == 0:
                raise self._dead(self._res.peer_connection_lost(
                    self.peer, "recv", "socket closed mid-message"))
            got += r
            start = time.monotonic()   # progress: deadline bounds silence

    def recv_begin(self) -> int:
        """Read one frame header; the next `nbytes` on the wire are the
        payload, consumed by the caller via recv_exact_into/scratch."""
        if self._error is not None:
            raise self._error
        hv = memoryview(self._hdr)
        self.recv_exact_into(hv)
        return _LEN.unpack(self._hdr)[0]

    def scratch(self, nbytes: int) -> memoryview:
        """A reusable receive buffer of at least `nbytes` (grown
        geometrically, never shrunk): steady-state receives allocate
        nothing.  Contents are valid until the next scratch recv on this
        channel — consume before receiving again."""
        if len(self._scratch) < nbytes:
            self._scratch = bytearray(max(nbytes, 2 * len(self._scratch)))
        return memoryview(self._scratch)[:nbytes]

    def close(self) -> None:
        """Shutdown-leak fix (mirrors the Timeline writer fix): poison
        the queue FIRST, then join.  The old order (bounded join with no
        poison-first guarantee) could time out silently and leak the
        sender thread plus its bounded queue — every payload it
        referenced stayed pinned for the process lifetime.  A sender
        wedged in sendmsg on a dead peer is woken by shutting the socket
        down under it; if it STILL survives, a structured warning names
        the peer instead of hiding the leak."""
        if self._sender is not None:
            try:
                self.flush()
            except BaseException:  # noqa: BLE001 - already torn down
                pass
            self._queue.put(None)                      # poison first
            self._sender.join(timeout=_CLOSE_JOIN_GRACE)
            if self._sender.is_alive():
                # Unblock a send wedged on a dead/zero-window peer, then
                # give the lane one more chance to observe the poison.
                try:
                    self.sock.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                self._sender.join(timeout=1.0)
            if self._sender.is_alive():
                logger.warning(
                    "peer-channel close: sender thread for peer %d "
                    "survived poison + socket shutdown (queue depth %d); "
                    "leaking it as daemon", self.peer,
                    self._queue.qsize() if self._queue is not None else -1)
            self._sender = None
        try:
            self.sock.close()
        except OSError:
            pass


# ---------------------------------------------------------------------------
# Full-mesh point-to-point connections between ranks
# ---------------------------------------------------------------------------
class PeerMesh:
    """Connect every pair of ranks once; expose send/recv by peer rank.

    Bootstraps peer addresses through the rendezvous KV store, then lower
    rank listens / higher rank connects (the reference's gloo
    connectFullMesh does the same through its HTTPStore).
    """

    def __init__(self, rank: int, size: int, kv: RendezvousClient,
                 scope: str = "mesh", timeout: float = 30.0,
                 resilience=None) -> None:
        self.rank = rank
        self.size = size
        self.scope = scope
        self._socks: dict[int, socket.socket] = {}
        self._channels: dict[int, _PeerChannel] = {}
        self._lock = threading.Lock()
        # Resilience (HOROVOD_FAULT_TOLERANCE) + chaos (HOROVOD_CHAOS):
        # captured at formation.  Both None in the default off mode, so
        # the per-call cost is one attribute test; tests may inject a
        # private ResilienceState (the process default is rank-global).
        self._resilience = resilience if resilience is not None \
            else _resilience_state()
        self._chaos = _chaos_engine()
        # Payload byte counters (framing excluded): the observability the
        # compression subsystem's bandwidth claims are asserted against
        # (tests/test_compress.py) and PERFORMANCE.md numbers come from.
        self.bytes_sent = 0
        self.bytes_received = 0
        # Telemetry (HOROVOD_METRICS): per-peer wire counters + send-queue
        # depth, labelled by mesh scope so control/data/stream meshes stay
        # distinguishable.  Null registry when off — per-call cost is one
        # attribute test on _tm_on.
        from ..telemetry import metrics as _tm_metrics
        self._tm = _tm_metrics()
        self._tm_on = self._tm.enabled
        self._tm_sent: dict[int, object] = {}
        self._tm_recv: dict[int, object] = {}
        self._tm_qdepth = self._tm.histogram(
            "horovod_tcp_send_queue_depth",
            "Outbound frames queued on a peer's persistent sender lane "
            "at enqueue time", labels={"mesh": scope}) if self._tm_on \
            else None
        # Versioned wire handshake (HELLO{proto_version, feature_bits},
        # exchanged on every pair socket at formation): the mesh-wide
        # negotiated schema is the min proto / AND of feature bits over
        # every peer — identical on all ranks by construction, so one
        # encode per broadcast serves the whole world and optional
        # field groups (fp_*/tm_*/trace_*) are gated symmetrically.
        self.proto_version, self.features = advertised_hello()
        self.peer_protos: dict[int, int] = {}
        self.negotiated_proto = self.proto_version
        self.negotiated_features = self.features
        if size == 1:
            return

        listener = socket.socket()
        listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        listener.bind(("", 0))
        listener.listen(size)
        port = listener.getsockname()[1]
        host = self._advertised_host()
        kv.put(scope, f"addr:{rank}", f"{host}:{port}".encode())

        expected_inbound = size - 1 - rank   # peers with higher rank dial in
        accepted: dict[int, socket.socket] = {}

        def _tune(sock: socket.socket) -> None:
            # Bulk data plane: large kernel buffers keep the ring's
            # concurrent 1-8 MB chunk exchanges streaming instead of
            # ping-ponging on default (~200 KB) windows.
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            for opt in (socket.SO_SNDBUF, socket.SO_RCVBUF):
                try:
                    sock.setsockopt(socket.SOL_SOCKET, opt, 4 << 20)
                except OSError:
                    pass

        hello = wire.pack_hello(self.proto_version, self.features)
        peer_hellos: dict[int, tuple[int, int]] = {}

        def _accept():
            for _ in range(expected_inbound):
                conn, _ = listener.accept()
                peer = int.from_bytes(recv_exact(conn, 4), "big")
                peer_hellos[peer] = wire.unpack_hello(
                    recv_exact(conn, wire.HELLO_LEN))
                conn.sendall(hello)
                _tune(conn)
                accepted[peer] = conn

        acceptor = threading.Thread(target=_accept, daemon=True,
                                    name="hvd-mesh-accept")
        acceptor.start()

        for peer in range(rank):   # dial every lower-ranked peer
            raw = kv.wait(scope, f"addr:{peer}", timeout).decode()
            peer_host, peer_port = raw.rsplit(":", 1)
            deadline = time.monotonic() + timeout
            while True:
                try:
                    sock = socket.create_connection(
                        (peer_host, int(peer_port)), timeout=timeout)
                    break
                except OSError:
                    if time.monotonic() > deadline:
                        raise
                    time.sleep(0.05)
            _tune(sock)
            sock.sendall(self.rank.to_bytes(4, "big") + hello)
            peer_hellos[peer] = wire.unpack_hello(
                recv_exact(sock, wire.HELLO_LEN))
            self._socks[peer] = sock

        acceptor.join(timeout)
        if len(accepted) != expected_inbound:
            raise TimeoutError(
                f"rank {rank}: only {len(accepted)}/{expected_inbound} "
                f"inbound peers connected")
        self._socks.update(accepted)
        listener.close()
        self._negotiate_wire(peer_hellos)
        for peer, sock in self._socks.items():
            self._channels[peer] = _PeerChannel(sock, peer,
                                                self._count_sent,
                                                resilience=self._resilience)

    def _negotiate_wire(self, peer_hellos: dict) -> None:
        """Fold every peer's HELLO into the mesh-wide negotiated wire
        schema and export the per-peer proto gauge.  The fold is
        order-free (min / AND), so every rank lands on the identical
        (proto, features) pair without an extra exchange."""
        proto, feats = self.proto_version, self.features
        for peer_proto, peer_feats in peer_hellos.values():
            proto, feats = wire.negotiate(proto, feats, peer_proto,
                                          peer_feats)
        self.negotiated_proto = proto
        self.negotiated_features = feats
        self.peer_protos = {p: h[0] for p, h in peer_hellos.items()}
        if self._tm_on:
            for peer, (peer_proto, _pf) in sorted(peer_hellos.items()):
                self._tm.gauge(
                    "horovod_wire_proto_version",
                    "Wire protocol version the peer advertised at "
                    "channel establishment",
                    labels={"mesh": self.scope,
                            "peer": str(peer)}).set(peer_proto)

    @staticmethod
    def _advertised_host() -> str:
        """Address peers dial: HOROVOD_GLOO_IFACE pins the NIC when set
        (reference: gloo_context.cc reads the same variable to select the
        Gloo transport device); otherwise the hostname's address."""
        iface = os.environ.get("HOROVOD_GLOO_IFACE")
        if iface:
            from .driver_service import candidate_addresses
            return candidate_addresses(iface)[0]
        return socket.gethostbyname(socket.gethostname())

    def _count_sent(self, nbytes: int) -> None:
        with self._lock:   # sender lanes run concurrently with the ring
            self.bytes_sent += nbytes

    def _count_received(self, nbytes: int) -> None:
        with self._lock:
            self.bytes_received += nbytes

    # -- per-peer telemetry counters (lazily created per peer) ----------
    def _tm_peer(self, table: dict, name: str, peer: int):
        c = table.get(peer)
        if c is None:
            c = self._tm.counter(
                name, "Payload bytes on the wire by peer rank "
                "(framing excluded)",
                labels={"mesh": self.scope, "peer": str(peer)})
            table[peer] = c
        return c

    def _tm_count_sent(self, peer: int, nbytes: int) -> None:
        self._tm_peer(self._tm_sent,
                      "horovod_tcp_bytes_sent_total", peer).inc(nbytes)

    def _tm_count_recv(self, peer: int, nbytes: int) -> None:
        self._tm_peer(self._tm_recv,
                      "horovod_tcp_bytes_received_total", peer).inc(nbytes)

    def send(self, peer: int, payload: bytes) -> None:
        if self._chaos is not None:
            act = self._chaos.on_send(self.scope, peer)
            if act == "drop":
                return
            if act == "dup":
                self._count_sent(self._channels[peer].send_sync(payload))
        self._count_sent(self._channels[peer].send_sync(payload))
        if self._tm_on:
            self._tm_count_sent(peer, len(payload))

    def send_async(self, peer: int, payload) -> None:
        """Enqueue a framed message on the peer's persistent sender lane
        (counted by the lane on completion).  Zero-copy: the payload
        buffer must stay unmutated until `flush()`."""
        ch = self._channels[peer]
        if self._chaos is not None:
            act = self._chaos.on_send(self.scope, peer)
            if act == "drop":
                return
            if act == "dup":
                ch.send_async(payload)
        ch.send_async(payload)
        if self._tm_on:
            # Depth AFTER the put: what's now waiting on the lane.
            if ch._queue is not None:
                self._tm_qdepth.observe(ch._queue.qsize())
            self._tm_count_sent(peer, _as_byte_view(payload).nbytes)

    def recv(self, peer: int) -> bytearray:
        """Receive one framed message, allocated fresh.  Routed through
        the peer channel so the wait is deadline-bounded under fault
        tolerance (the channel falls back to the original blocking loop
        when resilience is off)."""
        ch = self._channels.get(peer)
        if ch is None:   # size-1 mesh / pre-channel peer: legacy path
            data = recv_msg(self._socks[peer])
        else:
            n = ch.recv_begin()
            data = bytearray(n)
            if n:
                ch.recv_exact_into(memoryview(data))
        self._count_received(len(data))
        if self._tm_on:
            self._tm_count_recv(peer, len(data))
        return data

    # -- zero-copy receive surface (bulk data plane) --------------------
    def recv_begin(self, peer: int) -> int:
        """Read one frame header from `peer`; returns the payload length
        the caller must now consume via recv_raw_into/scratch."""
        n = self._channels[peer].recv_begin()
        self._count_received(n)
        if self._tm_on:
            self._tm_count_recv(peer, n)
        return n

    def recv_raw_into(self, peer: int, view: memoryview) -> None:
        """Receive exactly len(view) payload bytes straight into the
        caller's buffer (no staging copy)."""
        self._channels[peer].recv_exact_into(view)

    def scratch(self, peer: int, nbytes: int) -> memoryview:
        """The peer channel's reusable receive scratch (see
        _PeerChannel.scratch for the validity contract)."""
        return self._channels[peer].scratch(nbytes)

    def recv_in_arrival_order(self, peers):
        """Yield (peer, message) for one framed message from each of
        `peers`, draining whichever peer's bytes arrive first (selectors)
        instead of fixed rank order — one slow rank no longer serializes
        the drain behind the sockets after it."""
        remaining = set(peers)
        if not remaining:
            return
        res = self._resilience
        with selectors.DefaultSelector() as sel:
            for p in remaining:
                sel.register(self._socks[p], selectors.EVENT_READ, p)
            start = time.monotonic()
            while remaining:
                events = sel.select(None if res is None
                                    else res.poll_interval)
                if not events:
                    if res is not None:
                        # Deadline-bounded drain: a silent slice checks
                        # the liveness table and the op deadline,
                        # attributed to the still-missing peers.
                        res.check(min(remaining),
                                  time.monotonic() - start, "gather")
                    continue
                for key, _ in events:
                    peer = key.data
                    sel.unregister(key.fileobj)
                    remaining.discard(peer)
                    yield peer, self.recv(peer)  # hvdlint: disable=unbounded-blocking-wait -- bounded inside the peer channel (socket poll timeout + op deadline)
                start = time.monotonic()

    def flush(self, peer: int | None = None) -> None:
        """Wait until queued sends (to `peer`, or everyone) reached the
        kernel.  Collectives flush before returning so callers may mutate
        result buffers; direct-fd paths (native ring) flush first so raw
        writes never interleave with queued frames."""
        channels = [self._channels[peer]] if peer is not None \
            else self._channels.values()
        for ch in channels:
            ch.flush()

    def close(self) -> None:
        for ch in self._channels.values():
            ch.close()
        for sock in self._socks.values():   # size-1 meshes have no channels
            try:
                sock.close()
            except OSError:
                pass
        self._channels.clear()
        self._socks.clear()
